"""Noisy-neighbor QoS benchmark (ISSUE 20 round 20).

PR 15 proved routing is label-shape-invariant; this round proves the
QoS plane makes tenancy a SCHEDULING dimension. The fleet is the
`noisy_neighbor` tenant regime from `benchmarks.scenarios`: one whale
tenant owns NOISY_FACTOR x every quiet tenant's share of services, and
during the measured phase it floods the REAL ingest receiver far past
its byte-rate envelope. Three claims, asserted in-run:

  * **isolation** — the quiet tenants' anomaly injections (pushed
    through the same receiver, judged by the same worker) keep the
    push→verdict latency and F1 they had in a SOLO control run with no
    whale at all: p99 within 1.5x (+250 ms grace) of control and F1
    byte-equal. Weighted-fair claim ordering (dirty-set drain + sweep
    pool, equal weights — fairness, not hand-tuned throttling) plus
    ring-byte envelopes are what hold the line.
  * **targeted backpressure** — every 429 + Retry-After lands on the
    whale's pushes; the quiet tenants' POSTs all answer 200 and their
    shed counter stays zero. The whale's series evictions are charged
    to the whale; the quiet tenants' warm series stay resident.
  * **attribution** — the run's per-tenant ledger (sheds, evictions,
    claims, resident ring bytes) is visible in GET /debug/state's
    `tenants` section and exported as `foremast_tenant_*`; the bench
    pins the end-state snapshot into BENCH_rNN.json (`tenants` key).

A fourth phase pins the PARITY contract: with zero or one tenant
configured, statuses/reasons/anomaly payloads on the sliced warm path
are byte-identical between an untenanted worker and a single-tenant
registry — the QoS plane reorders claims and redirects pressure, it
never changes a verdict.

Usage: python -m benchmarks.noisy_bench [--services N] [--inject K]
       [--small]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from benchmarks.latency_bench import (
    CUR_LEN,
    HIST_LEN,
    STEP,
    _await_status,
    mk_worker,
)
from benchmarks.scenarios import WHALE_TENANT, tenant_fleet, tenant_weighted_specs
from foremast_tpu.ingest import (
    RingStore,
    canonical_series,
    start_ingest_server,
    stop_ingest_server,
)
from foremast_tpu.jobs.models import (
    STATUS_COMPLETED_UNHEALTH,
    Document,
)
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.reactive import DirtySet
from foremast_tpu.tenant import (
    TenantRegistry,
    TenantSpec,
    accounting_for,
    set_tenancy,
)

TENANTS = 4
# quiet-tenant QoS bars (full shape): p99 within this factor of the
# solo control (plus an absolute grace for scheduler jitter at small
# sample counts), F1 exactly equal
P99_FACTOR = 1.5
P99_GRACE_S = 0.25
# uniform per-tenant envelopes: rate low enough that the whale's flood
# trips admission within one batch, ring slice big enough that the
# quiet tenants' warm series never evict
INGEST_BYTES_PER_S = 64 * 1024
RING_BYTES_PER_TENANT = 8 << 20


def _expr(s: int, tenant: str) -> str:
    return (
        f'latency{{app="app{s}",namespace="bench",tenant="{tenant}"}}'
    )


def build_fleet(indices, assignments, t_now: int, tenancy=None):
    """The latency bench's push fleet, tenant-labeled: series selectors
    and doc query configs carry `tenant="<t>"`, so registry resolution
    sees the same label on both the push path and the claim path.
    `indices` picks which service indices exist (the control run builds
    only the quiet ones — SAME ids, keys and data as the treatment
    run's quiet subset)."""
    rng = np.random.default_rng(7)
    store = InMemoryStore()
    ring = RingStore(
        shards=8, budget_bytes=1 << 30, stale_seconds=3600.0,
        tenancy=tenancy,
    )
    ht = t_now - 86_400 * 7 + STEP * np.arange(HIST_LEN, dtype=np.int64)
    ct = t_now - STEP * CUR_LEN + STEP * np.arange(CUR_LEN, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 7200)
    )
    keys = {}
    for s in indices:
        expr = _expr(s, assignments[s])
        key = canonical_series(expr)
        keys[s] = key
        hv = rng.normal(1.0, 0.1, HIST_LEN).astype(np.float32)
        cv = np.ones(CUR_LEN, np.float32)
        ring.push(
            key,
            np.concatenate([ht, ct]),
            np.concatenate([hv, cv]),
            start=float(ht[0]),
            now=float(t_now),
        )
        cur_url = prometheus_url(
            {"endpoint": "http://p/api/v1/", "query": expr,
             "start": int(ct[0]), "end": int(t_now + 7200), "step": STEP}
        )
        hist_url = prometheus_url(
            {"endpoint": "http://p/api/v1/", "query": expr,
             "start": int(ht[0]), "end": int(ht[-1]), "step": STEP}
        )
        store.create(
            Document(
                id=f"job-{s}",
                app_name=f"app{s}",
                end_time=end_time,
                current_config=f"latency== {cur_url}",
                historical_config=f"latency== {hist_url}",
                strategy="continuous",
            )
        )
    return store, ring, keys, ht, ct


def _post(port: int, payload: dict):
    """POST a push; returns (status, headers) — 429 is an ANSWER here
    (the admission verdict under test), not an error."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/write",
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()
            return resp.status, dict(resp.headers)
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, dict(e.headers)


def _push_payload(key: str, ts, vs) -> dict:
    return {
        "timeseries": [
            {
                "alias": key,
                "times": [int(t) for t in ts],
                "values": [float(v) for v in vs],
            }
        ]
    }


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def run_parity(services: int, t_now: int) -> None:
    """The ISSUE 20 parity pin: zero-vs-one-tenant byte-identical
    statuses on identical fleets through the SLICED warm path (cold
    judgment, a warm re-check, a spiked re-check)."""
    assignments = ["default"] * services
    indices = list(range(services))
    arms = []
    try:
        for reg in (
            None,
            TenantRegistry({"default": TenantSpec(name="default")}),
        ):
            set_tenancy(reg)
            store, ring, keys, ht, ct = build_fleet(
                indices, assignments, t_now
            )
            w = mk_worker(store, ring, services)
            w.sweep_slice_docs = 32
            now = float(t_now)
            assert w.tick(now=now) == services
            assert w.tick(now=now + 60) == services  # warm sliced
            spike_t = ct[-3:]
            spike_v = np.full(3, 40.0, np.float32)
            ring.push(keys[1], spike_t, spike_v, now=now)
            assert w.tick(now=now + 120) == services
            arms.append(_statuses(store))
            w.close()
    finally:
        set_tenancy(None)
    assert arms[0] == arms[1], "zero-vs-one-tenant parity broke"
    assert arms[0]["job-1"][0] == STATUS_COMPLETED_UNHEALTH


def run_phase(
    indices,
    assignments,
    inject_at,
    t_now: int,
    tenancy,
    whale_keys=None,
    small: bool = False,
) -> dict:
    """One measured arm: fleet up, worker run loop + receiver, anomaly
    injections into the quiet services at `inject_at`, optional whale
    flood against the same receiver. Returns latencies, F1 inputs, the
    flood's answer codes, and the end-state /debug/state tenants
    section."""
    set_tenancy(tenancy)
    try:
        store, ring, keys, ht, ct = build_fleet(
            indices, assignments, t_now, tenancy=tenancy
        )
        services = len(indices)
        dirty = DirtySet(max_keys=max(8192, 4 * services), tenancy=tenancy)
        worker = mk_worker(store, ring, services, dirty=dirty)
        srv, _ = start_ingest_server(
            0, ring, host="127.0.0.1", dirty=dirty, tenancy=tenancy
        )
        port = srv.server_address[1]
        t0 = time.perf_counter()
        assert worker.tick(now=float(t_now)) == services
        warm_seconds = time.perf_counter() - t0
        stop = threading.Event()
        loop = threading.Thread(
            target=worker.run,
            kwargs={"poll_seconds": 5.0, "stop": stop.is_set},
            daemon=True,
        )
        loop.start()

        flood_codes: dict[int, int] = {}
        flood_stop = threading.Event()
        flood_thread = None
        if whale_keys:
            # the whale: large batches of fresh samples over its whole
            # series population, as fast as the socket allows. Each
            # batch decodes to ~60 KB of columns — the burst bucket
            # (2 x INGEST_BYTES_PER_S = 128 KB) drains within two
            # batches, so admission MUST shed the flood for the rest
            # of the phase
            def flood():
                i = 0
                per_batch = min(64, len(whale_keys))
                n_samples = 60
                while not flood_stop.is_set():
                    stamp = int(time.time())
                    times = [
                        int(t)
                        for t in stamp - STEP * (n_samples - 1)
                        + STEP * np.arange(n_samples)
                    ]
                    body = {
                        "timeseries": [
                            {
                                "alias": whale_keys[
                                    (i + j) % len(whale_keys)
                                ],
                                "times": times,
                                "values": [1.0] * n_samples,
                            }
                            for j in range(per_batch)
                        ]
                    }
                    i += per_batch
                    code, _hdrs = _post(port, body)
                    flood_codes[code] = flood_codes.get(code, 0) + 1
                    if code == 429:
                        # a real pusher honors Retry-After; the bench
                        # keeps hammering on a short leash so the
                        # governor stays saturated for the whole phase
                        flood_stop.wait(0.02)

            flood_thread = threading.Thread(target=flood, daemon=True)
            flood_thread.start()
            time.sleep(0.3)  # let the flood reach steady state first

        latencies = []
        timeouts = 0
        quiet_codes: dict[int, int] = {}
        for s in inject_at:
            stamp = int(time.time())
            ts = stamp - STEP * 2 + STEP * np.arange(3)
            t0 = time.monotonic()
            code, _hdrs = _post(
                port,
                _push_payload(keys[s], ts, np.full(3, 40.0, np.float32)),
            )
            quiet_codes[code] = quiet_codes.get(code, 0) + 1
            elapsed = _await_status(
                store, f"job-{s}", (STATUS_COMPLETED_UNHEALTH,), 20.0
            )
            if elapsed is None:
                timeouts += 1
            else:
                latencies.append(time.monotonic() - t0)

        if flood_thread is not None:
            flood_stop.set()
            flood_thread.join(timeout=5)
        stop.set()
        loop.join(timeout=30)

        # F1 over the QUIET services: injected spikes are the positive
        # class, every other quiet service must stay healthy
        spiked = set(inject_at)
        tp = fp = fn = 0
        whale_set = {
            s for s in indices if assignments[s] == WHALE_TENANT
        }
        for s in indices:
            if s in whale_set:
                continue
            doc = store.get(f"job-{s}")
            unhealthy = (
                doc is not None
                and doc.status == STATUS_COMPLETED_UNHEALTH
            )
            if s in spiked:
                tp += unhealthy
                fn += not unhealthy
            else:
                fp += unhealthy
        f1 = (
            2 * tp / (2 * tp + fp + fn) if (2 * tp + fp + fn) else 1.0
        )

        # quiet residency: the whale's flood must not have evicted the
        # quiet tenants' warm series out of the ring
        resident = sum(
            1
            for s in indices
            if s not in whale_set
            and ring.query(
                keys[s], float(ht[0]), float(ct[-1]), now=time.time()
            )
            is not None
        )
        tenants_dbg = None
        if tenancy is not None:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/state", timeout=10
            ) as resp:
                tenants_dbg = json.load(resp).get("tenants")
        stop_ingest_server(srv)
        worker.close()
        lat = np.asarray(sorted(latencies), np.float64)
        return {
            "latencies": latencies,
            "p50": float(np.percentile(lat, 50)) if len(lat) else None,
            "p99": float(np.percentile(lat, 99)) if len(lat) else None,
            "timeouts": timeouts,
            "f1": round(f1, 4),
            "quiet_codes": quiet_codes,
            "flood_codes": flood_codes,
            "quiet_resident": resident,
            "quiet_total": len(indices) - len(whale_set),
            "fleet_warm_seconds": round(warm_seconds, 3),
            "tenants": tenants_dbg,
        }
    finally:
        set_tenancy(None)


def run(services: int, inject: int, small: bool) -> dict:
    t_now = int(time.time())
    run_parity(min(96, services), t_now)

    assignments = tenant_fleet("noisy_neighbor", services, TENANTS)
    quiet = [
        s for s in range(services) if assignments[s] != WHALE_TENANT
    ]
    whale = [
        s for s in range(services) if assignments[s] == WHALE_TENANT
    ]
    inject_at = quiet[-min(inject, len(quiet)):]

    # solo-tenant CONTROL: the quiet services alone, untenanted
    control = run_phase(
        quiet, assignments, inject_at, t_now, tenancy=None, small=small
    )

    # TREATMENT: full fleet, whale flooding, equal-weight registry with
    # uniform envelopes — fairness and budgets, not hand-tuned throttles
    spec_map = tenant_weighted_specs(
        TENANTS,
        ring_bytes=RING_BYTES_PER_TENANT,
        ingest_bytes_per_s=INGEST_BYTES_PER_S,
    )
    reg = TenantRegistry(
        {n: TenantSpec.from_json(n, d) for n, d in spec_map.items()}
    )
    whale_keys = [
        canonical_series(_expr(s, WHALE_TENANT)) for s in whale
    ]
    treatment = run_phase(
        list(range(services)),
        assignments,
        inject_at,
        t_now,
        tenancy=reg,
        whale_keys=whale_keys,
        small=small,
    )
    acct = accounting_for(reg).snapshot()

    result = {
        "bench": "noisy",
        "services": services,
        "tenants": TENANTS,
        "whale_services": len(whale),
        "quiet_services": len(quiet),
        "inject": len(inject_at),
        "small": small,
        "control": {
            k: control[k]
            for k in ("p50", "p99", "f1", "timeouts", "fleet_warm_seconds")
        },
        "treatment": {
            k: treatment[k]
            for k in ("p50", "p99", "f1", "timeouts", "fleet_warm_seconds")
        },
        "quiet_push_codes": treatment["quiet_codes"],
        "whale_flood_codes": treatment["flood_codes"],
        "quiet_resident": (
            f"{treatment['quiet_resident']}/{treatment['quiet_total']}"
        ),
        "accounting": acct,
        "debug_state_tenants": treatment["tenants"] is not None,
        "parity": "zero-vs-one-tenant byte-identical (asserted)",
    }

    # -- in-run asserts (the acceptance criteria) -----------------------
    assert control["timeouts"] == 0 and treatment["timeouts"] == 0, (
        control["timeouts"], treatment["timeouts"],
    )
    # targeted backpressure: every quiet POST answered 200; the whale
    # was shed, and ONLY the whale carries shed charges
    assert set(treatment["quiet_codes"]) == {200}, treatment["quiet_codes"]
    assert treatment["flood_codes"].get(429, 0) > 0, (
        f"whale flood never shed: {treatment['flood_codes']}"
    )
    for name, row in acct.items():
        if name != WHALE_TENANT:
            assert row["shed"] == 0, (name, row)
    assert acct.get(WHALE_TENANT, {}).get("shed", 0) > 0, acct
    # isolation: quiet residency intact, F1 unchanged vs control
    assert treatment["quiet_resident"] == treatment["quiet_total"], (
        result["quiet_resident"]
    )
    assert treatment["f1"] == control["f1"], (
        f"quiet F1 moved: control {control['f1']} vs "
        f"treatment {treatment['f1']}"
    )
    # attribution visible end to end
    assert treatment["tenants"] is not None, "/debug/state tenants missing"
    assert WHALE_TENANT in treatment["tenants"].get("accounting", {}), (
        treatment["tenants"]
    )
    if not small:
        bar = control["p99"] * P99_FACTOR + P99_GRACE_S
        assert treatment["p99"] <= bar, (
            f"quiet p99 {treatment['p99']:.3f}s past the noisy bar "
            f"{bar:.3f}s (control {control['p99']:.3f}s)"
        )
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=2048)
    ap.add_argument("--inject", type=int, default=32)
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = ap.parse_args(argv)
    services = 96 if args.small else args.services
    inject = 4 if args.small else args.inject
    result = run(services, inject, args.small)
    print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary(
        "noisy",
        result,
        small=args.small,
        tenants=result["accounting"],
    )


if __name__ == "__main__":
    main()
