"""Reactive-plane latency benchmark (ISSUE 12 round 14; ISSUE 15
round 17 — sliced, preemptible sweeps).

Every plane before this one is tick-paced: a pushed anomaly sits in the
ring until the next full sweep. This benchmark measures the reactive
plane end to end, with the REAL moving parts on both halves:

  * **deploy** — a Deployment PATCHed into the fake kube server (real
    HTTP, real chunked ``watch=true`` stream) dispatches through
    `StreamingInformer` to a handler that creates the analysis job
    (the barrelman→analyst chain collapsed to `store.create`, as in a
    single-binary deployment) and marks its app dirty; the reactive
    worker's micro-tick judges it. Measured: PATCH-sent →
    first-verdict-written. Bar (full shape): **≤ 1 s**.
  * **anomaly** — at the 16k-service fleet (warm, continuous
    background pushes keeping micro-ticks honestly busy, full sweeps
    interleaving on the poll cadence), K anomaly injections arrive
    through the REAL ingest receiver (HTTP POST, receiver-clock
    arrival stamps); each measures POST-sent →
    ``completed_unhealth``-written. HALF the injections deliberately
    fire while a sweep is IN FLIGHT (the sweep-preemption phase,
    ISSUE 15) — under monolithic sweeps those samples tracked sweep
    wall clock (round 14's 1.34 s max); sliced sweeps bound them by
    slice wall clock. Bar (full shape): **p99 ≤ 0.5 s INCLUDING the
    collision samples**.
  * **warm throughput** — the round-16 canary-heavy fleet (16,384
    services, 50% baseline docs) re-measured through the SLICED sweep:
    slicing must not regress the warm fleet rate. Bar (full shape):
    **≥ 108k windows/s** (round 16's number), warm-pipeline overlap
    ratio reported.
  * **parity** — the acceptance pins: a doc judged by a micro-tick is
    byte-identical (status, reason, anomaly payload) to the same doc
    judged by a full tick on an identical fleet; and a SLICED sweep's
    statuses are byte-identical to a monolithic sweep's on identical
    fleets — including a sharded-mesh arm (8 forced virtual devices,
    full runs) re-executed in a child process. Asserted in-run at
    every shape.

Usage: python -m benchmarks.latency_bench [--services N] [--inject K]
       [--small]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.ingest import (
    RingSource,
    RingStore,
    canonical_series,
    start_ingest_server,
    stop_ingest_server,
)
from foremast_tpu.jobs.models import (
    STATUS_COMPLETED_UNHEALTH,
    STATUS_PREPROCESS_COMPLETED,
    Document,
)
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.reactive import DirtySet

HIST_LEN = 256
CUR_LEN = 30
STEP = 60
# ISSUE 15 acceptance bars (full shape): anomaly push→unhealthy p99
# INCLUDING sweep-collision samples, and the round-16 warm canary
# fleet rate the sliced sweep must not regress
ANOMALY_P99_BAR = 0.5
WARM_WPS_BAR = 108_000


def _expr(s: int) -> str:
    return f'latency{{namespace="bench",app="app{s}"}}'


def build_fleet(services: int, t_now: int):
    """Pure-push fleet anchored to the REAL clock (latency measurement
    needs wall time): 7-day-old history heads, current windows open
    another hour — every doc re-checks until the bench ends."""
    rng = np.random.default_rng(7)
    store = InMemoryStore()
    ring = RingStore(
        shards=8, budget_bytes=1 << 30, stale_seconds=3600.0
    )
    ht = t_now - 86_400 * 7 + STEP * np.arange(HIST_LEN, dtype=np.int64)
    ct = t_now - STEP * CUR_LEN + STEP * np.arange(CUR_LEN, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 7200)
    )
    keys = []
    for s in range(services):
        key = canonical_series(_expr(s))
        keys.append(key)
        hv = rng.normal(1.0, 0.1, HIST_LEN).astype(np.float32)
        cv = np.ones(CUR_LEN, np.float32)
        ring.push(
            key,
            np.concatenate([ht, ct]),
            np.concatenate([hv, cv]),
            start=float(ht[0]),
            now=float(t_now),
        )
        cur_url = prometheus_url(
            {"endpoint": "http://p/api/v1/", "query": _expr(s),
             "start": int(ct[0]), "end": int(t_now + 7200), "step": STEP}
        )
        hist_url = prometheus_url(
            {"endpoint": "http://p/api/v1/", "query": _expr(s),
             "start": int(ht[0]), "end": int(ht[-1]), "step": STEP}
        )
        store.create(
            Document(
                id=f"job-{s}",
                app_name=f"app{s}",
                end_time=end_time,
                current_config=f"latency== {cur_url}",
                historical_config=f"latency== {hist_url}",
                strategy="continuous",
            )
        )
    return store, ring, keys, ht, ct


def mk_worker(store, ring, services, dirty=None, microtick_seconds=0.05):
    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_cache_size=services + 64,
    )
    w = BrainWorker(
        store,
        RingSource(ring, fallback=None),
        config=cfg,
        # headroom over the fleet: a sweep that claims the WHOLE fleet
        # must read as unsaturated, or the run loop would never leave
        # the busy-sweep branch on a store where re-check docs are
        # immediately re-claimable
        claim_limit=services + 16,
        worker_id="latency-bench",
        dirty=dirty,
    )
    w.microtick_seconds = microtick_seconds
    w.microtick_docs = 512
    return w


def _post_push(port: int, key: str, ts, vs) -> None:
    body = json.dumps(
        {
            "timeseries": [
                {
                    "alias": key,
                    "times": [int(t) for t in ts],
                    "values": [float(v) for v in vs],
                }
            ]
        }
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/write",
        data=body,
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        resp.read()


def _await_status(store, doc_id, statuses, deadline_s: float):
    """Poll until the doc reaches one of `statuses`; returns elapsed
    monotonic seconds since call start, or None on timeout."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        doc = store.get(doc_id)
        if doc is not None and doc.status in statuses:
            return time.monotonic() - t0
        time.sleep(0.005)
    return None


def run_parity(services: int, t_now: int) -> None:
    """The acceptance pin: micro-tick vs full-tick byte-identical
    statuses on identical fleets (cold judgment AND a spiked
    re-check)."""
    store_a, ring_a, keys_a, ht, ct = build_fleet(services, t_now)
    store_b, ring_b, keys_b, _, _ = build_fleet(services, t_now)
    wa = mk_worker(store_a, ring_a, services)
    db = DirtySet(max_keys=services + 8)
    wb = mk_worker(store_b, ring_b, services, dirty=db)
    now = float(t_now)
    assert wa.tick(now=now) == services
    for k in keys_b:
        db.mark_series(k, now=now)
    assert wb.micro_tick(now=now) == services

    def statuses(store):
        return {
            d.id: (d.status, d.reason, d.anomaly_info)
            for d in store._docs.values()
        }

    assert statuses(store_a) == statuses(store_b), "cold parity broke"
    spike_t = ct[-3:]
    spike_v = np.full(3, 40.0, np.float32)
    for ring, keys in ((ring_a, keys_a), (ring_b, keys_b)):
        ring.push(keys[1], spike_t, spike_v, now=now)
    assert wa.tick(now=now + 60) == services
    db.mark_series(keys_b[1], now=now)
    assert wb.micro_tick(now=now + 60) == 1
    a, b = statuses(store_a), statuses(store_b)
    assert a["job-1"] == b["job-1"], "spiked re-check parity broke"
    assert a["job-1"][0] == STATUS_COMPLETED_UNHEALTH


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def run_sliced_parity(
    services: int, t_now: int, slice_docs: int = 32,
    expect_sharded: bool = False,
) -> None:
    """The ISSUE 15 acceptance pin: a SLICED sweep's statuses are
    byte-identical to a monolithic sweep's on identical fleets — cold
    judgment, a warm re-check, and a spiked re-check. With
    `expect_sharded`, the workers' univariate judges must be mesh-
    sharded (the child-process arm under 8 forced virtual devices),
    proving slicing composes with the ISSUE-13 device mesh."""
    store_a, ring_a, keys_a, ht, ct = build_fleet(services, t_now)
    store_b, ring_b, keys_b, _, _ = build_fleet(services, t_now)
    wa = mk_worker(store_a, ring_a, services)
    wa.sweep_slice_docs = 0  # the monolithic arm
    wb = mk_worker(store_b, ring_b, services)
    wb.sweep_slice_docs = slice_docs
    assert not wa._sweep_sliceable() and wb._sweep_sliceable()
    if expect_sharded:
        for w in (wa, wb):
            uni = w._uni
            assert hasattr(uni, "mesh_debug"), "judge is not sharded"
            assert uni.mesh_debug()["devices"] > 1
    now = float(t_now)
    assert wa.tick(now=now) == services  # cold: slow path both arms
    assert wb.tick(now=now) == services
    assert _statuses(store_a) == _statuses(store_b), "cold parity broke"
    assert wa.tick(now=now + 60) == services  # warm columnar re-check
    assert wb.tick(now=now + 60) == services
    assert _statuses(store_a) == _statuses(store_b), "warm parity broke"
    assert (wb._last_sweep or {}).get("slices", 0) > 1, wb._last_sweep
    spike_t = ct[-3:]
    spike_v = np.full(3, 40.0, np.float32)
    for ring, keys in ((ring_a, keys_a), (ring_b, keys_b)):
        for s in (1, services - 1):
            ring.push(keys[s], spike_t, spike_v, now=now)
    assert wa.tick(now=now + 120) == services
    assert wb.tick(now=now + 120) == services
    a, b = _statuses(store_a), _statuses(store_b)
    assert a == b, "spiked parity broke"
    assert a["job-1"][0] == STATUS_COMPLETED_UNHEALTH
    wa.close()
    wb.close()


_SHARDED_CHILD = """
import sys, time
sys.path.insert(0, {repo!r})
from benchmarks.latency_bench import run_sliced_parity
run_sliced_parity(128, int(time.time()), slice_docs=32, expect_sharded=True)
print("SHARDED_PARITY_OK")
"""


def run_sharded_parity_child() -> None:
    """Re-exec the sliced-vs-monolithic parity under 8 forced virtual
    devices + FOREMAST_DEVICE_MESH=auto: the sharded-mesh arm of the
    acceptance pin (a parent process that already initialized JAX
    cannot re-shape its device count)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu") or "cpu"
    env["FOREMAST_DEVICE_MESH"] = "auto"
    env.pop("FOREMAST_SWEEP_SLICE_DOCS", None)
    out = subprocess.run(
        [sys.executable, "-c", _SHARDED_CHILD.format(repo=repo)],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "SHARDED_PARITY_OK" in out.stdout, (out.stdout, out.stderr)


def run_warm_throughput(
    small: bool, services: int = 16_384, ticks: int = 3
) -> dict:
    """The no-pipelining-regression phase: the round-16 canary-heavy
    fleet (50% baseline docs) measured warm through the SLICED sweep.
    Bar at the full shape: >= 108k windows/s (round 16's monolithic
    number) — slicing + the warm pipeline must not give back the
    canary-columnar win; the warm overlap ratio is the proof the
    pipeline actually overlaps."""
    from benchmarks.worker_bench import build_mixed_fleet
    from foremast_tpu.jobs.worker import BrainWorker as _BW

    n = 128 if small else services
    hist = 256 if small else 10_080
    now = float(int(time.time()))
    store, source, windows_by_doc = build_mixed_fleet(
        n, hist, CUR_LEN, now, baseline_frac=0.5
    )
    windows = sum(windows_by_doc.values())
    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_cache_size=4 * n + 64,
    )
    worker = _BW(
        store, source, config=cfg, claim_limit=n,
        worker_id="latency-warm",
    )
    if small:
        worker.sweep_slice_docs = 32  # slices engage at smoke shape too
    # backend-compile witness: the cold tick owns every compile; a warm
    # tick that recompiles has a dispatch cache-key leak (the static
    # recompile-hazard rule's runtime twin, docs/static-analysis.md)
    from foremast_tpu.analysis.recompile_witness import RecompileWitness

    wit = RecompileWitness()
    wit.install()
    try:
        with wit.phase("cold"):
            t0 = time.perf_counter()
            assert worker.tick(now=now + 150) == n
            cold_s = time.perf_counter() - t0
        rates = []
        # the FIRST warm tick owns the pipelined warm path's one-time
        # compiles (the cold sweep runs the monolithic program, so its
        # tick cannot warm them); every tick after it must run entirely
        # from the dispatch cache
        with wit.phase("pipeline_warmup"):
            t0 = time.perf_counter()
            assert worker.tick(now=now + 160) == n
            rates.append(windows / (time.perf_counter() - t0))
        with wit.phase("warm"):
            for k in range(1, ticks):
                t0 = time.perf_counter()
                assert worker.tick(now=now + 160 + 10 * k) == n
                rates.append(windows / (time.perf_counter() - t0))
        wit.assert_zero("warm")
    finally:
        wit.uninstall()
    wps = float(np.median(rates))
    sweep = dict(worker._last_sweep or {})
    pipe = sweep.get("pipeline") or {}
    worker.close()
    result = {
        "services": n,
        "windows": windows,
        "slice_docs": worker.sweep_slice_docs,
        "slices": sweep.get("slices"),
        "cold_sweep_seconds": round(cold_s, 3),
        "warm_windows_per_sec": round(wps, 1),
        "warm_overlap_ratio": pipe.get("overlap_ratio"),
        "warm_device_idle_seconds": pipe.get("device_idle_seconds"),
        "warm_write_queue_peak": pipe.get("write_queue_peak"),
        "recompiles": wit.snapshot(),
    }
    assert sweep.get("slices", 0) > 1, sweep  # the sliced path ran
    if not small:
        assert wps >= WARM_WPS_BAR, (
            f"sliced warm throughput {wps:.0f} w/s under the "
            f"{WARM_WPS_BAR} bar (round-16 regression)"
        )
    return result


def run_deploy_phase(
    store, ring, dirty, keys, t_now, worker=None, deadline_s=5.0
):
    """Deploy-to-first-verdict through the fake kube server's real
    watch stream. Returns measured seconds (None on timeout).

    The PATCH fires right after a sweep boundary (when `worker` is
    given): this phase measures the reactive chain — watch event →
    job create → dirty mark → micro-tick → verdict — not the tail of
    a colliding 16k full sweep; sweep collision cost is exactly what
    the anomaly phase's p99 already charges for."""
    import sys
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
    from fake_kube_server import FakeKubeServer
    from foremast_tpu.reactive.watchstream import StreamingInformer
    from foremast_tpu.watch.kubeapi import HttpKube

    doc_tpl = store.get("job-0")

    def on_deploy(event, dep, old):
        name = dep.get("metadata", {}).get("name", "")
        if name != "bench-deploy" or event not in ("add", "update"):
            return
        # the barrelman→service chain collapsed to one in-process
        # create (LocalAnalyst-style): a NEW analysis job for the
        # already-monitored app0, same warm series + history
        store.create(
            Document(
                id="job-deploy",
                app_name="app0",
                end_time=doc_tpl.end_time,
                current_config=doc_tpl.current_config,
                historical_config=doc_tpl.historical_config,
                strategy="continuous",
            )
        )
        # the deploy event is an arrival too: mark the app dirty so
        # the very next micro-tick claims the new job
        dirty.mark("app0", time.time())

    with FakeKubeServer() as srv:
        kube = HttpKube(base_url=srv.url, token="t")
        informer = StreamingInformer(kube, on_deploy)
        informer.resync()
        stop = threading.Event()

        def stream_loop():
            while not stop.is_set():
                informer.consume(1.0, stall_margin=2.0)

        t = threading.Thread(target=stream_loop, daemon=True)
        t.start()
        time.sleep(0.1)  # let the first watch window open
        if worker is not None:
            last = worker._last_tick["at"]
            wait_until = time.monotonic() + 10.0
            while (
                worker._last_tick["at"] == last
                and time.monotonic() < wait_until
            ):
                time.sleep(0.01)
        t0 = time.monotonic()
        srv.state.put(
            "deployments",
            "bench",
            {"metadata": {"name": "bench-deploy", "namespace": "bench",
                          "uid": "uid-bench-deploy"}},
        )
        elapsed = _await_status(
            store, "job-deploy",
            (STATUS_PREPROCESS_COMPLETED, STATUS_COMPLETED_UNHEALTH),
            deadline_s,
        )
        done_at = time.monotonic()
        stop.set()
        t.join(timeout=5)
        return None if elapsed is None else done_at - t0


def run(services: int, inject: int, small: bool) -> dict:
    t_now = int(time.time())
    run_parity(min(64, services), t_now)
    # sliced-vs-monolithic byte parity (ISSUE 15): in-process arm at
    # every shape; the sharded-mesh arm re-execs under 8 virtual
    # devices on full runs (tier-1 covers sharded parity separately)
    run_sliced_parity(min(128, services), t_now)
    if not small:
        run_sharded_parity_child()
    warm = run_warm_throughput(small)

    store, ring, keys, ht, ct = build_fleet(services, t_now)
    dirty = DirtySet(max_keys=max(8192, services))
    worker = mk_worker(store, ring, services, dirty=dirty)

    # receiver: the REAL arrival path for injections
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1", dirty=dirty)
    port = srv.server_address[1]

    # fleet-warm: one cold sweep fits everything
    t0 = time.perf_counter()
    assert worker.tick(now=float(t_now)) == services
    warm_seconds = time.perf_counter() - t0

    # the reactive loop: real run() with micro drains + poll sweeps
    stop = threading.Event()
    loop = threading.Thread(
        target=worker.run,
        kwargs={"poll_seconds": 5.0, "stop": stop.is_set},
        daemon=True,
    )
    loop.start()

    # background pushers keep the dirty set honestly busy: every
    # second, benign fresh samples for ~1/30 of the fleet (direct ring
    # pushes + marks — the receiver handles the measured injections)
    bg_stop = threading.Event()

    def background():
        i = 0
        batch = max(1, services // 30)
        while not bg_stop.is_set():
            stamp = int(time.time())
            for _ in range(batch):
                s = i % services
                i += 1
                ring.push(
                    keys[s], [stamp], [1.0], now=float(stamp)
                )
                dirty.mark_series(keys[s], now=float(stamp))
            bg_stop.wait(1.0)

    bg = threading.Thread(target=background, daemon=True)
    bg.start()

    # measured deploy-to-first-verdict through the fake kube server
    deploy_seconds = run_deploy_phase(
        store, ring, dirty, keys, t_now, worker=worker
    )

    # anomaly injections through the REAL receiver, one app each
    # (starting high so the background pusher never overwrites them).
    # EVEN injections fire whenever; ODD injections are the SWEEP-
    # PREEMPTION phase (ISSUE 15): they wait for a sweep to be in
    # flight and post INTO it, so the sample set provably contains
    # sweep collisions — the p99 bar covers both arms pooled.
    latencies = []
    collision_latencies = []
    first_failures = 0
    for j in range(inject):
        s = services - 1 - j
        want_collision = (j % 2 == 1) and not small
        if want_collision:
            wait_until = time.monotonic() + 15.0
            while (
                not worker._sweep_active
                and time.monotonic() < wait_until
            ):
                time.sleep(0.002)
        stamp = int(time.time())
        ts = stamp - STEP * 2 + STEP * np.arange(3)
        t0 = time.monotonic()
        _post_push(port, keys[s], ts, np.full(3, 40.0, np.float32))
        # a sample only counts as a COLLISION if a sweep was verifiably
        # in flight when the push landed — a timed-out wait (or a sweep
        # that finished under the POST) must not launder a non-collision
        # sample into the collision arm's evidence
        collided = want_collision and worker._sweep_active
        elapsed = _await_status(
            store, f"job-{s}", (STATUS_COMPLETED_UNHEALTH,), 20.0
        )
        if elapsed is None:
            first_failures += 1
        else:
            sample = time.monotonic() - t0
            latencies.append(sample)
            if collided:
                collision_latencies.append(sample)

    bg_stop.set()
    bg.join(timeout=5)
    stop.set()
    loop.join(timeout=30)
    stop_ingest_server(srv)
    worker.close()

    lat = np.asarray(sorted(latencies), np.float64)
    clat = np.asarray(sorted(collision_latencies), np.float64)
    p50 = float(np.percentile(lat, 50)) if len(lat) else None
    p99 = float(np.percentile(lat, 99)) if len(lat) else None
    sweep_state = dict(worker._last_sweep or {})
    sweep_state.pop("pipeline", None)
    result = {
        "bench": "latency",
        "services": services,
        "inject": inject,
        "small": small,
        "fleet_warm_seconds": round(warm_seconds, 3),
        "sweep_seconds": round(worker._last_tick["seconds"], 3),
        "sweep": sweep_state,
        "warm_throughput": warm,
        "deploy_to_first_verdict_seconds": (
            None if deploy_seconds is None else round(deploy_seconds, 4)
        ),
        "anomaly_latency_p50_seconds": (
            None if p50 is None else round(p50, 4)
        ),
        "anomaly_latency_p99_seconds": (
            None if p99 is None else round(p99, 4)
        ),
        "anomaly_latency_max_seconds": (
            round(float(lat[-1]), 4) if len(lat) else None
        ),
        "sweep_collision_samples": len(clat),
        "sweep_collision_max_seconds": (
            round(float(clat[-1]), 4) if len(clat) else None
        ),
        "injections_timed_out": first_failures,
        "dirty": dirty.counts(),
        "parity": "byte-identical (asserted)",
        "sliced_parity": (
            "byte-identical (asserted"
            + ("" if small else ", incl. sharded-mesh arm")
            + ")"
        ),
    }

    # in-run assertions — every injection must land, and the reactive
    # bars hold at the full shape (reported informationally at smoke
    # shapes, same policy as the other benches)
    assert first_failures == 0, f"{first_failures} injections timed out"
    assert deploy_seconds is not None, "deploy never produced a verdict"
    if not small:
        assert deploy_seconds <= 1.0, (
            f"deploy-to-first-verdict {deploy_seconds:.3f}s > 1s bar"
        )
        # the sliced sweep actually ran sliced at the fleet shape, and
        # injections really collided with in-flight sweeps
        assert sweep_state.get("slices", 0) > 1, sweep_state
        assert len(clat) > 0, "no sweep-collision samples collected"
        assert p99 is not None and p99 <= ANOMALY_P99_BAR, (
            f"anomaly p99 {p99}s > {ANOMALY_P99_BAR}s bar "
            f"(incl. {len(clat)} sweep-collision samples, max "
            f"{result['sweep_collision_max_seconds']}s)"
        )
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=16_384)
    ap.add_argument("--inject", type=int, default=64)
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = ap.parse_args(argv)
    services = 64 if args.small else args.services
    inject = 4 if args.small else args.inject
    result = run(services, inject, args.small)
    print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary(
        "latency",
        result,
        small=args.small,
        recompiles=result["warm_throughput"].get("recompiles"),
    )


if __name__ == "__main__":
    main()
