"""Machine-readable bench summaries: ``BENCH_rNN.json`` (ISSUE 15).

BENCHMARKS.md pins each round's numbers as prose; CI cannot diff prose.
Every ``make bench-*`` entry point now ALSO folds its headline result
into one JSON artifact per benchmark round at the repo root:

    BENCH_r17.json
    {
      "round": 17,
      "generated_by": "benchmarks.report",
      "results": {
        "latency": {"asserts_passed": true, ...headline numbers...},
        "mixed":   {...},
        ...
      }
    }

so the perf trajectory (throughput, p50/p99, in-run asserts) is
diffable across PRs. One file per round, one key per bench — re-running
a bench inside the same round overwrites only its own key.

Round resolution: ``FOREMAST_BENCH_ROUND`` when set (re-running a bench
for an already-pinned round), else the highest ``## Round N`` heading
in BENCHMARKS.md **plus one** — a bench run is, by definition, the
round being measured for the NEXT BENCHMARKS.md entry.

``--small`` smoke runs never write (tier-1 tests must not dirty the
tree); pass ``path`` to redirect (tests use a tmpdir).
"""

from __future__ import annotations

import json
import os
import re

# BENCHMARKS.md headings carry the round as "## <title> (round N, ...)"
# (a plain "## Round N" also counts, future-proofing)
_ROUND_RE = re.compile(
    r"^## (?:Round (\d+)|[^\n]*\(round (\d+))", re.MULTILINE | re.IGNORECASE
)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def current_round(root: str | None = None) -> int:
    """The round this bench run measures (see module docstring)."""
    env = os.environ.get("FOREMAST_BENCH_ROUND", "")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    root = _repo_root() if root is None else root
    try:
        with open(os.path.join(root, "BENCHMARKS.md")) as f:
            rounds = [
                int(a or b) for a, b in _ROUND_RE.findall(f.read())
            ]
    except OSError:
        rounds = []
    return (max(rounds) + 1) if rounds else 1


def write_summary(
    bench: str,
    result: dict,
    small: bool = False,
    asserts_passed: bool = True,
    path: str | None = None,
    recompiles: dict | None = None,
    tenants: dict | None = None,
) -> str | None:
    """Fold one bench's headline result into the round's JSON artifact.

    Returns the file path written, or None for smoke runs. `result`
    must already be JSON-serializable (every bench prints it as a JSON
    line — this is the same dict). `recompiles` is the bench's
    RecompileWitness snapshot ({"total": N, "<phase>": n, ...}) when it
    ran one — benches assert zero WARM-phase backend compiles in-run
    (docs/static-analysis.md, rule recompile-hazard); the artifact pins
    the counts so a cache-key leak shows up as a diff even where no
    phase asserts. `tenants` is the run's end-state per-tenant
    accounting snapshot ({tenant: {shed, evictions, claims,
    ring_bytes}}, ISSUE 20) when the bench ran tenanted — pinned so a
    QoS regression (sheds landing on quiet tenants, evictions charged
    to the wrong tenant) is a JSON diff, not just an in-run assert.
    Failures to write are raised: a CI lane asking for the artifact
    must not silently get prose only."""
    if small:
        return None
    if path is None:
        rnd = current_round()
        path = os.path.join(_repo_root(), f"BENCH_r{rnd:02d}.json")
    else:
        rnd = current_round(os.path.dirname(path) or ".")
    doc = {"round": rnd, "generated_by": "benchmarks.report", "results": {}}
    try:
        with open(path) as f:
            existing = json.load(f)
    except OSError:
        existing = None  # absent: start fresh
    except ValueError as e:
        raise ValueError(
            f"{path} exists but is not JSON; refusing to overwrite a "
            "foreign artifact — set FOREMAST_BENCH_ROUND"
        ) from e
    if existing is not None:
        if not (
            isinstance(existing, dict)
            and existing.get("generated_by") == "benchmarks.report"
        ):
            # a file we did not write (e.g. a driver artifact from an
            # early round) must never be clobbered — fail loudly, the
            # round resolution is misconfigured
            raise ValueError(
                f"{path} exists with a foreign schema; refusing to "
                "overwrite — set FOREMAST_BENCH_ROUND to the intended "
                "round"
            )
        doc = existing
        doc["round"] = rnd
        if not isinstance(doc.get("results"), dict):
            doc["results"] = {}
    entry = dict(result, asserts_passed=asserts_passed)
    if recompiles is not None:
        entry["recompiles"] = recompiles
    if tenants is not None:
        entry["tenants"] = tenants
    doc["results"][bench] = entry
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path
