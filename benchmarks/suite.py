"""Benchmark suite — the five BASELINE.md configs plus golden-trace F1.

`bench.py` at the repo root prints the single headline line the driver
records; this suite measures every BASELINE config individually:

  1. single-metric pairwise health check (latency)
  2. 4-metric joint score (latency + error4xx + error5xx + tps, Mann-Whitney)
  3. Holt-Winters seasonal forecaster anomaly bounds (fitted per series)
  4. LSTM-autoencoder multivariate detector (train + score)
  5. cluster-wide batch: 10k services x 4 metrics x 30-min windows
  F1. anomaly F1 on the spring-boot-demo canary trace (quality gate —
      the reference's CPU brain flags exactly the data2.txt spikes, so
      parity means F1 = 1.0 on this trace)

Usage: python -m benchmarks.suite [--small] [--config N]
Prints one JSON line per config. --small shrinks shapes for CPU smoke
runs (CI); full shapes target a single TPU chip — the v5e-8 north star
(100k windows/sec) divides to 12.5k windows/sec/chip, reported as
`vs_target_per_chip` where windows/sec is the metric.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks import prf1

PER_CHIP_TARGET = 100_000 / 8


def _bench(fn, *args, iters=5):
    """Compile, warm, then time `iters` dispatches (block at the end).

    For cheap-per-iteration programs pass a high `iters`: the axon
    tunnel charges a ~100 ms fixed sync per timed sequence (bench.py
    rationale), which must amortize for the number to reflect steady
    state rather than harness overhead."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


_EMITTED: list[dict] = []  # this process's rows, for the JSON summary


def _emit(config, metric, value, unit, **extra):
    line = {"config": config, "metric": metric, "value": round(value, 2), "unit": unit}
    line.update(extra)
    _EMITTED.append(line)
    print(json.dumps(line), flush=True)


def _score_batch(b, th, tc, seed=0):
    from foremast_tpu.parallel.batch import throughput_batch

    return jax.device_put(throughput_batch(b, th, tc, seed=seed))


# ---------------------------------------------------------------------------


def config1_single_metric_pairwise(small: bool):
    """Canary check on one metric per service: pairwise + MA bounds."""
    from foremast_tpu.engine import scoring

    b = 1024 if small else 8192
    batch = _score_batch(b, 512 if small else 10080, 10)
    dt = _bench(lambda x: scoring.score(x), batch, iters=5 if small else 100)
    wps = b / dt
    _emit(
        "1-single-metric-pairwise",
        "windows_per_sec",
        wps,
        "windows/s",
        vs_target_per_chip=round(wps / PER_CHIP_TARGET, 3),
    )


def config2_four_metric_joint(small: bool):
    """4 metrics per service, Mann-Whitney joint verdict."""
    from foremast_tpu.engine import scoring
    from foremast_tpu.config import PAIRWISE_MANN_WHITE

    services = 512 if small else 4096
    b = services * 4
    batch = _score_batch(b, 512 if small else 10080, 30)
    dt = _bench(
        lambda x: scoring.score(x, pairwise_algorithm=PAIRWISE_MANN_WHITE),
        batch,
        iters=5 if small else 100,
    )
    _emit(
        "2-four-metric-mann-whitney",
        "services_per_sec",
        services / dt,
        "services/s",
        windows_per_sec=round(b / dt, 1),
    )


def config3_holt_winters(small: bool):
    """Fitted Holt-Winters bounds (grid-search fit per series).

    Fit time tracks the sequential scan chain (T/m season steps) almost
    independently of batch width, so the fleet batch size is the lever:
    B=8192 windows amortize one scan the way a worker tick batching
    thousands of claimed jobs does (B=1024 measured ~60-84k w/s; B=8192
    ~275k on the same chip)."""
    from foremast_tpu.engine import scoring

    b = 128 if small else 8192
    th = 512 if small else 2016  # 7 d at 5-min resample: the scan length
    batch = _score_batch(b, th, 30)
    dt = _bench(lambda x: scoring.score(x, algorithm="holt_winters"), batch)
    wps = b / dt
    _emit(
        "3-holt-winters-bounds",
        "windows_per_sec",
        wps,
        "windows/s",
        scan_length=th,
        batch=b,
    )

    # re-check tick (SURVEY hard part (d)): warm fit cache -> no history
    # packing/upload/scan, only the judgment tail on the current window.
    # Measured through the SHIPPED path (HealthJudge.judge over MetricTasks,
    # host packing + decode included), not a device-resident shortcut.
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.engine.judge import HealthJudge, MetricTask
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(0)
    hist_v = np.asarray(rng.normal(1.0, 0.2, (b, th)), np.float32)
    cur_v = np.asarray(rng.normal(1.0, 0.2, (b, 30)), np.float32)
    ht = 1_700_000_000 + 60 * np.arange(th, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(30, dtype=np.int64)
    tasks = [
        MetricTask(
            job_id=f"j{i}", alias="m", metric_type=None,
            hist_times=ht, hist_values=hist_v[i],
            cur_times=ct, cur_values=cur_v[i],
            fit_key=f"app{i}|m|u{i}",
        )
        for i in range(b)
    ]
    # season_steps pinned to the classic 24-step shape this config has
    # tracked since round 1 (config 3d measures the daily m=1440 path)
    judge = HealthJudge(BrainConfig(algorithm="holt_winters", season_steps=24))
    judge.judge(tasks[:8])  # compile
    t0 = time.perf_counter()
    judge.judge(tasks)  # cold shipped tick: pack + upload + fit + decode
    cold_dt = time.perf_counter() - t0
    judge.fit_cache = ModelCache(b + 1)
    judge.judge(tasks)  # fill the cache
    t0 = time.perf_counter()
    iters = 2
    for _ in range(iters):
        judge.judge(tasks)
    dt = (time.perf_counter() - t0) / iters
    _emit(
        "3-holt-winters-recheck",
        "windows_per_sec",
        b / dt,
        "windows/s",
        batch=b,
        cold_shipped_windows_per_sec=round(b / cold_dt, 1),
        engine_only_windows_per_sec=round(wps, 1),
    )


def config3d_daily_season(small: bool):
    """Daily-season scoring (ML_SEASON_STEPS=1440): the auto screen —
    global mean + Holt-Winters(1440) rolled scan + trend/Fourier seasonal
    — over full 7-day 10,080-pt histories (the reference's canonical
    workload, `metricsquery.go:43,75-77`). Small mode keeps the same code
    path (rolled HW: m > _HW_UNROLL_MAX) on CPU-feasible shapes."""
    from foremast_tpu.engine import scoring

    b = 64 if small else 2048
    th = 720 if small else 10_080
    m = 288 if small else 1440
    batch = _score_batch(b, th, 30)
    dt = _bench(
        lambda x: scoring.score(x, algorithm="auto_univariate", season_length=m),
        batch,
        iters=3 if small else 20,
    )
    wps = b / dt
    _emit(
        "3d-daily-season-auto",
        "windows_per_sec",
        wps,
        "windows/s",
        scan_length=th,
        season=m,
        batch=b,
    )


def config4_lstm_ae(small: bool):
    """LSTM-autoencoder fleet: train S per-service models, then score."""
    from foremast_tpu.models.lstm_ae import LSTMAEConfig, fit_many, score_many

    s = 32 if small else 256  # services (one model each)
    n_win, t_len, f = 8, 30, 4
    steps = 20 if small else 100
    cfg = LSTMAEConfig(features=f, hidden=16 if small else 32)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0.5, 0.1, size=(s, n_win, t_len, f)).astype(np.float32))
    mask = jnp.ones((s, n_win, t_len), bool)

    t0 = time.perf_counter()
    params, mu, sd, _ = fit_many(jax.random.key(0), x, mask, cfg, steps=steps)
    jax.block_until_ready(mu)
    train_s = time.perf_counter() - t0

    dt = _bench(lambda *a: score_many(*a), params, x, mask, mu, sd, 3.0)
    wps = s * n_win / dt
    _emit(
        "4-lstm-autoencoder",
        "windows_scored_per_sec",
        wps,
        "windows/s",
        models_trained=s,
        train_steps=steps,
        train_seconds=round(train_s, 2),
    )

    # the hybrid judgment's closed-form companion (models/residual_mvn.py):
    # per-job HW fit + residual covariance over [S, F, Th], then causal
    # continuation + Mahalanobis over the current windows
    from foremast_tpu.models.residual_mvn import (
        chi2_quantile,
        fit_residual_mvn,
        score_residual_mvn,
    )

    th = 256 if small else 1024
    hist = jnp.asarray(
        rng.normal(0.5, 0.1, size=(s, f, th)).astype(np.float32)
    )
    cur = jnp.asarray(rng.normal(0.5, 0.1, size=(s, f, t_len)).astype(np.float32))
    t0 = time.perf_counter()
    state = fit_residual_mvn(hist)
    jax.block_until_ready(state.cov)
    fit_s = time.perf_counter() - t0
    cut = chi2_quantile(4.0, f)
    dt = _bench(lambda st, c: score_residual_mvn(st, c, cut), state, cur)
    _emit(
        "4b-residual-mvn",
        "windows_scored_per_sec",
        s / dt,
        "windows/s",
        jobs=s,
        hist_len=th,
        fit_seconds=round(fit_s, 2),
    )


def config5_cluster_batch(small: bool):
    """BASELINE config 5: 10k services x 4 metrics x 30-min windows.

    On one chip this is the per-chip share of the fleet; the driver's
    dryrun exercises the same program sharded over an 8-device mesh."""
    from foremast_tpu.engine import scoring

    services = 1250 if small else 10_000
    b = services * 4
    batch = _score_batch(b, 256 if small else 1440, 30)  # 1-day hist/window
    dt = _bench(lambda x: scoring.score(x), batch, iters=3 if small else 50)
    wps = b / dt
    _emit(
        "5-cluster-batch",
        "windows_per_sec",
        wps,
        "windows/s",
        services=services,
        vs_target_per_chip=round(wps / PER_CHIP_TARGET, 3),
    )


def config_f1_golden_trace(small: bool):
    """Quality gate: F1 on the demo canary traces (BASELINE 'CPU-parity
    anomaly F1'). data2.txt carries the injected spikes; every spike point
    must flag and nothing else (the reference demo's pass criterion —
    docs/guides/installation.md:84-143 runbook)."""
    import csv
    import os
    from datetime import datetime, timezone

    from foremast_tpu.engine.judge import HealthJudge, MetricTask

    data = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "tests", "data")

    def load(name):
        # rows are "YYYY-mm-dd HH:MM:SS,value" (the reference demo's
        # FileErrorGenerator trace format)
        ts, vs = [], []
        with open(os.path.join(data, name)) as f:
            for row in csv.reader(f):
                if row:
                    dt = datetime.strptime(row[0], "%Y-%m-%d %H:%M:%S")
                    ts.append(int(dt.replace(tzinfo=timezone.utc).timestamp()))
                    vs.append(float(row[1]))
        return np.asarray(ts, np.int64), np.asarray(vs, np.float32)

    nt, nv = load("demo_canary_normal.csv")
    st, sv = load("demo_canary_spike.csv")
    hist_t = np.concatenate([nt - 86400 * (i + 1) for i in range(6)])
    hist_v = np.tile(nv, 6)

    task = MetricTask(
        job_id="golden", alias="error5xx", metric_type="error5xx",
        hist_times=hist_t, hist_values=hist_v,
        cur_times=st, cur_values=sv,
        base_times=nt, base_values=nv,
    )
    (verdict,) = HealthJudge().judge([task])
    flagged = set(verdict.anomaly_pairs[0::2])
    truth = {float(t) for t, v in zip(st, sv) if v > 10.0}  # the 40.x spikes
    tp = len(flagged & truth)
    fp = len(flagged - truth)
    fn = len(truth - flagged)
    precision, recall, f1 = prf1(tp, fp, fn)
    _emit(
        "f1-golden-trace",
        "anomaly_f1",
        f1,
        "f1",
        precision=round(precision, 3),
        recall=round(recall, 3),
        spikes=len(truth),
    )


CONFIGS = {
    "1": config1_single_metric_pairwise,
    "2": config2_four_metric_joint,
    "3": config3_holt_winters,
    "3d": config3d_daily_season,
    "4": config4_lstm_ae,
    "5": config5_cluster_batch,
    "f1": config_f1_golden_trace,
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--small", action="store_true", help="CPU smoke shapes")
    ap.add_argument(
        "--config",
        default=None,
        choices=list(CONFIGS),
        help="run one config (1-5, f1)",
    )
    args = ap.parse_args(argv)
    keys = [args.config] if args.config else list(CONFIGS)
    for k in keys:
        CONFIGS[k](args.small)
    from benchmarks.report import write_summary

    write_summary("suite", {"configs": list(_EMITTED)}, small=args.small)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
