"""Shipped cold-tick PIPELINE benchmark: serial vs overlapped chunk loop.

`worker_bench` measures the steady-state re-check loop against an
in-memory source (zero fetch latency — exactly the regime where the
chunk pipeline has nothing to hide). This benchmark measures the other
production regime: a FLEET-COLD tick whose metric windows come from a
latency-injected fake Prometheus, where the serial chunk loop leaves
the device idle for every chunk's fetch+write round trips. Same fleet,
same seed, two runs:

  * serial    — `pipeline_depth = 1` (the pre-pipeline worker);
  * pipelined — `FOREMAST_PIPELINE_DEPTH` (default 2): chunk N+1's
    windows prefetch while chunk N judges and chunk N-1's verdicts
    drain on the writer thread.

A throwaway warm-up run (discarded) pays the XLA compiles first so both
measured phases see hot jit caches, and the two runs' final document
statuses are compared — the benchmark itself asserts write-equivalence
(the full contract is pinned in tests/test_worker_pipeline.py).

Usage: python -m benchmarks.pipeline_bench [--services N] [--latency-ms L]
       [--depth D] [--chunk-docs C] [--small]
Prints one JSON line: both cold-tick times, the speedup, and the
pipeline's occupancy stats (device-idle seconds, overlap ratio).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from benchmarks.worker_bench import _add_service
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.source import MetricSource

ALIASES_PER_DOC = 4  # worker_bench's reference 4-metric monitor shape


class LatencySource(MetricSource):
    """Exact-match URL->series map with an injected per-fetch sleep —
    the fake-Prometheus floor plus the one thing ArraySource elides:
    the HTTP round trip the pipeline exists to hide. Declares
    `concurrent_fetch = True` (like the real PrometheusSource) so the
    worker fans fetches over its pool and engages the pipeline."""

    concurrent_fetch = True

    def __init__(self, latency_s: float):
        self.data: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self.latency_s = latency_s

    def fetch(self, url: str):
        if self.latency_s > 0:
            time.sleep(self.latency_s)
        return self.data[url]


def build_fleet(
    services: int,
    hist_len: int,
    cur_len: int,
    now: float,
    latency_s: float,
    seed: int = 0,
):
    """One document per service x 4 aliases (worker_bench shapes), all
    cold: no tick has run, so every fit is new."""
    rng = np.random.default_rng(seed)
    store = InMemoryStore()
    source = LatencySource(latency_s)
    t_now = int(now)
    ht = t_now - 86_400 * 7 + 60 * np.arange(hist_len, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    for s in range(services):
        _add_service(
            store, source, str(s), ht, ct, hist_len, cur_len, end_time, rng
        )
    return store, source


def run_phase(
    depth: int,
    services: int,
    chunk_docs: int,
    hist_len: int,
    cur_len: int,
    latency_s: float,
    algorithm: str,
    now: float,
    fetch_workers: int = 16,
):
    """One fleet-cold tick at the given pipeline depth; returns
    (cold_seconds, pipeline_stats, statuses)."""
    store, source = build_fleet(services, hist_len, cur_len, now, latency_s)
    cfg = BrainConfig(algorithm=algorithm, season_steps=24,
                      max_cache_size=4 * services + 64)
    worker = BrainWorker(
        store,
        source,
        config=cfg,
        claim_limit=services,
        worker_id=f"pipe-bench-d{depth}",
    )
    worker.cold_chunk_docs = chunk_docs
    worker.pipeline_depth = depth
    worker.fetch_workers = fetch_workers
    t0 = time.perf_counter()
    n = worker.tick(now=now + 150)
    cold_s = time.perf_counter() - t0
    assert n == services, f"claimed {n} != {services}"
    stats = dict(worker._last_pipeline or {})
    statuses = {d.id: (d.status, d.reason) for d in store._docs.values()}
    worker.close()
    return cold_s, stats, statuses


def run(
    services: int,
    latency_ms: float,
    depth: int,
    chunk_docs: int,
    hist_len: int,
    cur_len: int,
    algorithm: str,
    fetch_workers: int = 16,
) -> dict:
    now = 1_760_000_000.0
    latency_s = latency_ms / 1000.0
    args = (services, chunk_docs, hist_len, cur_len, latency_s,
            algorithm, now, fetch_workers)
    # throwaway run: pays the XLA compiles so both measured phases are
    # hot (zero injected latency — this phase only exists to compile)
    run_phase(1, services, chunk_docs, hist_len, cur_len, 0.0,
              algorithm, now)
    serial_s, serial_stats, serial_out = run_phase(1, *args)
    piped_s, piped_stats, piped_out = run_phase(depth, *args)
    assert serial_out == piped_out, (
        "pipelined tick diverged from the serial path"
    )
    return {
        "config": "p-pipelined-cold-tick",
        "services": services,
        "windows": services * ALIASES_PER_DOC,
        "latency_ms": latency_ms,
        "depth": depth,
        "fetch_workers": fetch_workers,
        "chunk_docs": chunk_docs,
        "chunks": piped_stats.get("chunks"),
        "algorithm": algorithm,
        "serial_cold_tick_seconds": round(serial_s, 3),
        "pipelined_cold_tick_seconds": round(piped_s, 3),
        "serial_stage_seconds": {
            k: serial_stats.get(k)
            for k in ("fetch_seconds", "judge_seconds", "write_seconds")
        },
        "device_idle_seconds": piped_stats.get("device_idle_seconds"),
        "overlap_ratio": piped_stats.get("overlap_ratio"),
        "write_queue_peak": piped_stats.get("write_queue_peak"),
        "equivalent": True,  # asserted above
        "metric": "cold_tick_speedup",
        "value": round(serial_s / piped_s, 3) if piped_s > 0 else None,
        "unit": "x",
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=4096)
    ap.add_argument("--latency-ms", type=float, default=3.0,
                    help="injected per-fetch latency (fake Prometheus "
                    "round trip)")
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--fetch-workers", type=int, default=16,
                    help="persistent fetch-pool size "
                    "(FOREMAST_FETCH_WORKERS equivalent)")
    ap.add_argument("--chunk-docs", type=int, default=512)
    ap.add_argument("--hist-len", type=int, default=512)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument("--algorithm", default="moving_average_all")
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = ap.parse_args(argv)
    if args.small:
        args.services = min(args.services, 48)
        args.hist_len = min(args.hist_len, 128)
        args.chunk_docs = min(args.chunk_docs, 16)
        args.latency_ms = min(args.latency_ms, 1.0)
    result = run(
        args.services,
        args.latency_ms,
        args.depth,
        args.chunk_docs,
        args.hist_len,
        args.cur_len,
        args.algorithm,
        fetch_workers=args.fetch_workers,
    )
    print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary("pipeline", result, small=args.small)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
