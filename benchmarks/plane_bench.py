"""Watch-plane scale micro-benchmark (VERDICT r5 #7).

The reference runs workqueue-ratelimited informer handlers with 2
workers (`Barrelman.go:112-119,940-993`); this framework's plane is a
single-threaded loop (`watch/plane.py`): Deployments list+diff resync
every 30 s, DeploymentMonitor poll every 10 s. That is fine until
N monitors x 10 s poll says otherwise — this benchmark says.

Drives `DeploymentInformer.resync` and `MonitorController.tick` against
an `InMemoryKube` seeded with N deployments + N RUNNING monitors and a
zero-latency analyst stub, so the measured time is the PLANE's own host
work (list, diff, dispatch, poll bookkeeping) with every external round
trip at its floor. Budget: one controller poll tick and one steady
resync must each stay well under the 10 s poll period at 10k monitors —
the done-bar is ~1 s per tick.

Usage: python -m benchmarks.plane_bench [--monitors N] [--small]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import time

from foremast_tpu.watch.controller import MonitorController
from foremast_tpu.watch.crds import DeploymentMonitor, MonitorPhase, MonitorStatus
from foremast_tpu.watch.kubeapi import InMemoryKube
from foremast_tpu.watch.plane import DeploymentInformer


class _StubAnalyst:
    """Zero-latency analyst: every job stays Running (the steady state
    of a fleet mid-window — no status write-back, the poll's floor)."""

    class _Status:
        phase = MonitorPhase.RUNNING
        reason = ""
        anomaly: dict = {}

    def __init__(self, endpoint: str = ""):
        pass

    def get_status(self, job_id: str):
        return self._Status()


def build(n: int) -> InMemoryKube:
    kube = InMemoryKube()
    kube.add_namespace("bench", annotations={"foremast.ai/monitoring": "enabled"})
    for i in range(n):
        name = f"svc-{i}"
        kube.deployments[("bench", name)] = {
            "metadata": {
                "namespace": "bench",
                "name": name,
                "uid": f"uid-{i}",
                "resourceVersion": "1",
                "labels": {"app": name},
            },
            "spec": {
                "selector": {"matchLabels": {"app": name}},
                "template": {"metadata": {"labels": {"app": name}}},
            },
        }
        kube.monitors[("bench", f"{name}-monitor")] = DeploymentMonitor(
            name=f"{name}-monitor",
            namespace="bench",
            selector={"app": name},
            analyst_endpoint="http://analyst.invalid/v1/healthcheck/",
            wait_until="2100-01-01T00:00:00Z",  # far future: no expiry
            status=MonitorStatus(job_id=f"job-{i}", phase=MonitorPhase.RUNNING),
        )
    return kube


def run(monitors: int, ticks: int = 3) -> dict:
    kube = build(monitors)
    handled = [0]

    def handler(event, dep, old):  # count-only: isolates informer cost
        handled[0] += 1

    informer = DeploymentInformer(kube, handler)
    controller = MonitorController(kube, analyst_factory=_StubAnalyst)

    t0 = time.perf_counter()
    informer.resync()  # prime: emits one add per deployment
    prime_s = time.perf_counter() - t0

    steady = []
    for _ in range(ticks):
        t0 = time.perf_counter()
        informer.resync()  # no changes: pure list + diff
        steady.append(time.perf_counter() - t0)

    polls = []
    for _ in range(ticks):
        t0 = time.perf_counter()
        controller.tick()
        polls.append(time.perf_counter() - t0)

    steady_s = sorted(steady)[len(steady) // 2]
    poll_s = sorted(polls)[len(polls) // 2]
    return {
        "monitors": monitors,
        "deployments": monitors,
        "informer_prime_seconds": round(prime_s, 4),
        "informer_resync_seconds": round(steady_s, 4),
        "poll_tick_seconds": round(poll_s, 4),
        "poll_us_per_monitor": round(poll_s / monitors * 1e6, 2),
        "events_handled": handled[0],
        "within_budget": bool(steady_s < 1.0 and poll_s < 1.0),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--monitors", type=int, default=10_000)
    ap.add_argument("--ticks", type=int, default=3)
    ap.add_argument("--small", action="store_true", help="CI smoke shapes")
    args = ap.parse_args(argv)
    if args.small:
        args.monitors = min(args.monitors, 256)
    result = run(args.monitors, args.ticks)
    result["config"] = "wp-watch-plane-scale"
    result["metric"] = "poll_tick_seconds"
    result["value"] = result["poll_tick_seconds"]
    result["unit"] = "seconds"
    print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary("plane", result, small=args.small)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
