"""Cold-start benchmark: ring-resident cold fits, short-history
admission, background refinement (ISSUE 10, BENCHMARKS.md round 12).

Rounds 5/8 left the cold/churn path as the last order-of-magnitude
bound: a 16k daily-season COLD tick paid a full 7-day history
fetch+upload per doc (271 s), and a 10%-churn tick re-paid the churned
fraction's share every tick (13.1 s). The ingest ring already holds
that history resident — this benchmark measures the tentpole that lets
cold fits read it from there:

  * **pull-cold** — the round-5 baseline: PrometheusSource against a
    real localhost query_range server, fleet-cold tick (HTTP fetch +
    pack + upload + fit per doc);
  * **ring-cold** — same fleet, same samples, ring-resident: the cold
    tick's historical windows come straight off ring columns
    (`RingSource.hist_columns`), ZERO HTTP — asserted in-run against
    the fake Prometheus's request counter, along with byte-identical
    statuses vs the pull worker;
  * **churn** — 10% of services retired and replaced before a warm
    tick (their series already pushed, the ingest-plane steady state):
    the cold fits ride ring columns, zero HTTP — asserted;
  * **newcomers** — services with only ~2 days of pushed coverage get
    verdict-capable PROVISIONAL fits in their first tick
    (short-history admission, `FOREMAST_ADMIT_MIN_COVERAGE_SECONDS`) —
    non-UNKNOWN verdicts asserted via the on_verdict hook;
  * **refinement** — coverage then closes the newcomers' windows and
    steady ticks drain the provisional book in bounded batches; the
    refined fits are asserted BYTE-IDENTICAL to a fresh worker's
    from-scratch fits on the same ring (band parity).

Acceptance bars (asserted in-run at the full 16k daily-season shape;
reported informationally at smaller shapes): ring-cold tick <= 120 s,
churn tick <= 8 s, first verdict <= 10 s.

Usage: python -m benchmarks.cold_bench [--services N] [--hist-len H]
       [--algorithm A] [--season M] [--newcomers K] [--small]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from benchmarks.ingest_bench import FakePrometheus, build_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import UNKNOWN
from foremast_tpu.ingest import RingSource, RingStore
from foremast_tpu.ingest.wire import canonical_series
from foremast_tpu.jobs.models import Document, TERMINAL_STATUSES
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.metrics.source import PrometheusSource

NOW = 1_760_000_000.0
ALIASES = 4
# full-shape acceptance bars (ISSUE 10 / BENCHMARKS.md round 12)
FULL_SERVICES = 16_384
FULL_HIST = 10_080
BAR_COLD_SECONDS = 120.0
BAR_CHURN_SECONDS = 8.0
BAR_FIRST_VERDICT_SECONDS = 10.0


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def _mk_worker(store, source, services, cfg, hook=None):
    return BrainWorker(
        store, source, config=cfg, claim_limit=services,
        worker_id="cold-bench", on_verdict=hook,
    )


def _first_write_probe(store):
    """Wrap the store's write path to timestamp the first persisted
    judgment (time-to-first-verdict, VERDICT r4 #7)."""
    first = [None]
    orig_update, orig_many = store.update, store.update_many

    def _u(doc):
        if first[0] is None:
            first[0] = time.perf_counter()
        return orig_update(doc)

    def _um(docs):
        if first[0] is None and docs:
            first[0] = time.perf_counter()
        return orig_many(docs)

    store.update, store.update_many = _u, _um

    def unwrap():
        store.update, store.update_many = orig_update, orig_many
        return first[0]

    return unwrap


def _push_fake_into_ring(ring, fake, start):
    """The pusher's steady state: every series the fleet monitors is
    resident with full coverage (direct push API — the receiver wire
    path is priced by `make bench-ingest`)."""
    for key, (t, v) in fake.data.items():
        ring.push(key, t, v, start=float(start), now=NOW)


def _add_churn_services(store, fake, ring, endpoint, count, hist_len,
                        cur_len, seed):
    """Retire the oldest `count` open docs and admit `count` fresh
    services whose series are already pushed (ring + fake agree)."""
    rng = np.random.default_rng(seed)
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(hist_len, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    with store._lock:
        open_ids = [
            d.id for d in store._docs.values()
            if d.status not in TERMINAL_STATUSES
        ][:count]
        for did in open_ids:
            store._docs.pop(did, None)
    names = ("latency", "error5xx", "tps", "cpu")[:ALIASES]
    for k in range(count):
        app = f"churn{seed}-{k}"
        cur_parts, hist_parts = [], []
        for a in names:
            expr = (
                f"namespace_app_per_pod:{a}"
                f'{{namespace="bench",app="{app}"}}'
            )
            key = canonical_series(expr)
            hv = rng.normal(1.0, 0.1, hist_len).astype(np.float32)
            cv = (
                1.0 + 0.05 * np.sin(np.arange(cur_len) / 3.0)
            ).astype(np.float32)
            t_all = np.concatenate([ht, ct])
            v_all = np.concatenate([hv, cv])
            fake.data[key] = (t_all, v_all)
            ring.push(key, t_all, v_all, start=float(ht[0]), now=NOW)
            cur_parts.append(
                f"{a}== " + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ct[0]), "end": int(ct[-1]), "step": 60}
                )
            )
            hist_parts.append(
                f"{a}== " + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ht[0]), "end": int(ht[-1]), "step": 60}
                )
            )
        store.create(
            Document(
                id=f"churn-{seed}-{k}",
                app_name=app,
                end_time=end_time,
                current_config=" ||".join(cur_parts),
                historical_config=" ||".join(hist_parts),
                strategy="continuous",
            )
        )
    return count


def _newcomer_docs(ring, count, coverage_seconds, seed=11):
    """Newcomer services: docs request the full 7-day history, the
    ring holds only `coverage_seconds` of live pushes (pure-push
    world: the fallback has nothing more for a true newcomer)."""
    rng = np.random.default_rng(seed)
    store = InMemoryStore()
    base = int(NOW)
    t1 = base - 1000
    t0 = t1 - 7 * 86_400
    # pushes stop SHORT of the requested window's head (within the
    # staleness slack), so the admitted fit is genuinely PROVISIONAL —
    # in-window data can still arrive and refinement has work to do
    push_end = t1 - 200
    push0 = push_end - int(coverage_seconds)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(base + 3600)
    )
    endpoint = "http://prom/api/v1/"
    keys = []
    for s in range(count):
        expr = (
            f'namespace_app_per_pod:latency{{namespace="bench",app="nc{s}"}}'
        )
        key = canonical_series(expr)
        keys.append(key)
        pt = np.arange(push0, push_end + 1, 60, dtype=np.int64)
        pv = rng.normal(1.0, 0.1, len(pt)).astype(np.float32)
        ring.push(key, pt, pv, now=NOW)
        cur_t1 = push_end - 60
        cur_t0 = cur_t1 - 28 * 60
        cur_url = prometheus_url(
            {"endpoint": endpoint, "query": expr, "start": int(cur_t0),
             "end": int(cur_t1), "step": 60}
        )
        hist_url = prometheus_url(
            {"endpoint": endpoint, "query": expr, "start": int(t0),
             "end": int(t1), "step": 60}
        )
        store.create(
            Document(
                id=f"nc-{s}",
                app_name=f"nc{s}",
                end_time=end_time,
                current_config=f"latency== {cur_url}",
                historical_config=f"latency== {hist_url}",
                strategy="continuous",
            )
        )
    return store, keys, t1


def run(services, hist_len, cur_len, algorithm, season, newcomers,
        churn_frac=0.1, full_bars=False) -> dict:
    fake = FakePrometheus()
    endpoint = fake.start()
    cfg = BrainConfig(
        algorithm=algorithm,
        season_steps=season,
        max_cache_size=ALIASES * services + newcomers + 64,
    )
    try:
        # -- phase 1: pull-cold baseline (the round-5 regime) ----------
        pull_store = build_fleet(
            services, ALIASES, hist_len, cur_len, endpoint, fake
        )
        pull_worker = _mk_worker(
            pull_store, PrometheusSource(), services, cfg
        )
        t0 = time.perf_counter()
        n = pull_worker.tick(now=NOW + 150)
        pull_cold_s = time.perf_counter() - t0
        assert n == services, f"pull cold claimed {n} != {services}"
        pull_statuses = _statuses(pull_store)
        pull_worker.close()

        # -- phase 2: ring-cold (tentpole) -----------------------------
        # size the ring to the fleet (docs/operations.md "Ingest
        # plane" sizing rule: 12 B/pt at pow2 capacities — residency
        # is a host-RAM budget, and an under-budgeted ring evicts the
        # very histories this benchmark measures reading)
        pow2_pts = 256
        while pow2_pts < hist_len + cur_len:
            pow2_pts *= 2
        n_series = ALIASES * (services + services // 10) + ALIASES
        # 3x: the budget is a CAP (no allocation behind it), and crc32
        # shard skew at small fleets needs slack per shard slice
        budget = 3 * n_series * pow2_pts * 12
        ring = RingStore(budget_bytes=budget, max_points=pow2_pts)
        t_hist0 = int(NOW) - 86_400 * 7
        _push_fake_into_ring(ring, fake, start=t_hist0)
        ring_store = build_fleet(
            services, ALIASES, hist_len, cur_len, endpoint, fake
        )
        reqs_before = fake.requests
        source = RingSource(ring, fallback=PrometheusSource())
        ring_worker = _mk_worker(ring_store, source, services, cfg)
        unwrap = _first_write_probe(ring_store)
        t0 = time.perf_counter()
        n = ring_worker.tick(now=NOW + 150)
        ring_cold_s = time.perf_counter() - t0
        first_w = unwrap()
        first_verdict_s = (first_w - t0) if first_w else ring_cold_s
        assert n == services, f"ring cold claimed {n} != {services}"
        zero_http_cold = fake.requests == reqs_before
        assert zero_http_cold, (
            f"ring-cold tick touched HTTP: {fake.requests - reqs_before} "
            "fetches (the ring covers every window — the bar is zero)"
        )
        assert _statuses(ring_store) == pull_statuses, (
            "ring-cold judgments diverged from the pull path"
        )
        cold_reads = ring_worker.debug_state()["cold_start"]["hist_reads"]
        assert cold_reads["ring_full"] >= services * ALIASES, cold_reads

        # -- phase 3: churn tick (10% cold fits from the ring) ---------
        n_churn = max(1, int(services * churn_frac))
        _add_churn_services(
            ring_store, fake, ring, endpoint, n_churn, hist_len,
            cur_len, seed=1,
        )
        reqs_before = fake.requests
        t0 = time.perf_counter()
        n = ring_worker.tick(now=NOW + 300)
        churn_s = time.perf_counter() - t0
        assert n == services, f"churn tick claimed {n} != {services}"
        zero_http_churn = fake.requests == reqs_before
        assert zero_http_churn, "churn tick touched HTTP"
        ring_worker.close()

        # -- phase 4: short-history newcomer admission -----------------
        nc_ring = RingStore.from_env()
        coverage = 2 * 86_400 if hist_len >= 2880 else 7_200.0
        floor = min(86_400.0, coverage / 2)
        nc_store, nc_keys, nc_t1 = _newcomer_docs(
            nc_ring, newcomers, coverage
        )
        nc_source = RingSource(nc_ring, fallback=None, admit_floor=floor)
        verdicts = {}
        nc_worker = _mk_worker(
            nc_store, nc_source, newcomers, cfg,
            hook=lambda d, vs: verdicts.setdefault(d.id, []).extend(vs),
        )
        t0 = time.perf_counter()
        n = nc_worker.tick(now=NOW + 150)
        nc_tick_s = time.perf_counter() - t0
        assert n == newcomers
        unknown = sum(
            1 for vs in verdicts.values()
            if all(v.verdict == UNKNOWN for v in vs)
        )
        assert unknown == 0, (
            f"{unknown}/{newcomers} newcomers UNKNOWN on their first "
            "tick — short-history admission did not engage"
        )
        pending = len(nc_worker._refine_book)
        assert pending == newcomers, (pending, newcomers)

        # -- phase 5: background refinement + band parity --------------
        rng = np.random.default_rng(12)
        for key in nc_keys:
            # the window head fills in: coverage closes the window
            tail = np.arange(
                nc_t1 - 140, nc_t1 + 121, 60, dtype=np.int64
            )
            nc_ring.push(
                key, tail,
                rng.normal(1.0, 0.1, len(tail)).astype(np.float32),
                now=NOW,
            )
        budget = max(1, newcomers // 4)
        nc_worker.refine_docs_per_tick = budget
        refine_ticks = 0
        k = 0
        while len(nc_worker._refine_book) and refine_ticks < 64:
            k += 1
            nc_worker.tick(now=NOW + 150 + 10 * k)
            refine_ticks += 1
        assert not len(nc_worker._refine_book), "refine book never drained"
        k += 1
        nc_worker.tick(now=NOW + 150 + 10 * k)  # terminal refits land

        fresh_store, _, _ = _newcomer_docs(nc_ring, newcomers, coverage)
        fresh = _mk_worker(fresh_store, nc_source, newcomers, cfg)
        fresh.tick(now=NOW + 150 + 10 * k)
        mismatched = 0
        compared = 0
        for fkey, entry in list(nc_worker._fit_cache._d.items()):
            other = fresh._fit_cache.peek(fkey)
            if other is None:
                mismatched += 1
                continue
            compared += 1
            for a, b in zip(entry, other):
                if not np.array_equal(np.asarray(a), np.asarray(b)):
                    mismatched += 1
                    break
        band_parity = mismatched == 0 and compared >= newcomers
        assert band_parity, (
            f"refined fits diverged from from-scratch fits "
            f"({mismatched} mismatched / {compared} compared)"
        )
        refine_counts = nc_worker._refine_book.debug_state()
        nc_worker.close()
        fresh.close()

        if full_bars:
            assert ring_cold_s <= BAR_COLD_SECONDS, ring_cold_s
            assert churn_s <= BAR_CHURN_SECONDS, churn_s
            assert first_verdict_s <= BAR_FIRST_VERDICT_SECONDS, (
                first_verdict_s
            )

        return {
            "config": "c-cold-ring-tick",
            "services": services,
            "windows": services * ALIASES,
            "hist_len": hist_len,
            "algorithm": algorithm,
            "season": season,
            "pull_cold_tick_seconds": round(pull_cold_s, 2),
            "ring_cold_tick_seconds": round(ring_cold_s, 2),
            "cold_speedup": round(pull_cold_s / ring_cold_s, 2),
            "first_verdict_seconds": round(first_verdict_s, 3),
            "churn_docs": n_churn,
            "churn_tick_seconds": round(churn_s, 2),
            "zero_http_cold": zero_http_cold,
            "zero_http_churn": zero_http_churn,
            "newcomers": newcomers,
            "newcomer_tick_seconds": round(nc_tick_s, 3),
            "newcomer_unknown": unknown,
            "refine_ticks_to_drain": refine_ticks,
            "refine_counts": refine_counts,
            "band_parity": band_parity,
            "bars_asserted": full_bars,
            "metric": "ring_cold_tick_seconds",
            "value": round(ring_cold_s, 2),
            "unit": "s",
        }
    finally:
        fake.stop()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=FULL_SERVICES)
    ap.add_argument("--hist-len", type=int, default=FULL_HIST)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument("--algorithm", default="phase_means")
    ap.add_argument("--season", type=int, default=1440)
    ap.add_argument("--newcomers", type=int, default=512)
    ap.add_argument("--churn", type=float, default=0.1)
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = ap.parse_args(argv)
    if args.small:
        args.services = min(args.services, 24)
        args.hist_len = min(args.hist_len, 512)
        args.season = min(args.season, 24)
        args.newcomers = min(args.newcomers, 4)
        if args.algorithm == "phase_means":
            args.algorithm = "moving_average_all"
    full_bars = (
        args.services >= FULL_SERVICES and args.hist_len >= FULL_HIST
    )
    result = run(
        args.services, args.hist_len, args.cur_len, args.algorithm,
        args.season, args.newcomers, churn_frac=args.churn,
        full_bars=full_bars,
    )
    print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary("cold", result, small=args.small)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
