"""Mixed-fleet benchmark suite — `make bench-mixed` (ISSUE 4 + ISSUE 14).

Four phases, one JSON line each:

  1. **joint** — the round-7 condition: 15% joint (bivariate/LSTM-
     hybrid) docs under the `auto` selector, warm throughput with the
     joint docs on the columnar path (worker_bench --joint-frac).
  2. **canary** — the ISSUE 14 headline: a canary-HEAVY fleet (>= 50%
     baseline-carrying docs) judged twice on identical fleets — the
     columnar canary bucket (default) vs the object path
     (FOREMAST_CANARY_COLUMNAR=0 semantics) — with IN-RUN asserts:
     statuses byte-identical after every tick, warm throughput >= 3x
     the object arm, and >= 12.5k windows/s/chip (full shapes only;
     CPU-host proxy for the per-chip bar, like rounds 7-15).
  3. **scenario matrix** — strategy x regime point-F1 sweep
     (benchmarks/scenarios.py), floors asserted in-run; extends the
     `fleet_mix` table in BENCHMARKS.md with the strategy dimension.
  4. **fan-in** — the canary fleet fed PURE-PUSH through the real
     ingest receiver by 1 vs 8 concurrent pushers (scenarios.
     FAN_IN_SHAPES): per-shape receiver apply rate, a warm tick judged
     entirely from the ring (zero HTTP by construction — the source has
     no fallback), and statuses asserted IDENTICAL across fan-in shapes
     (fan-in is a wire topology, never a semantics).

Usage: python -m benchmarks.mixed_bench [--services N] [--ticks K]
       [--small] [--skip-joint]
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.request

import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.worker import BrainWorker

NOW = 1_760_000_000.0

# in-run bars (full shapes only): the ISSUE 14 acceptance criteria
CANARY_SPEEDUP_BAR = 3.0
CANARY_WPS_PER_CHIP_BAR = 12_500.0
# scenario-matrix F1 floors (seeded draws, so these are exact pins at
# the bench shape): the stair regime's recall is priced separately —
# spikes near a freshly-learned step hide inside the widened band
F1_FLOOR = 0.95
F1_FLOOR_STAIR = 0.85


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def run_canary(
    services: int,
    ticks: int,
    hist_len: int,
    cur_len: int,
    baseline_frac: float = 0.5,
    assert_bars: bool = True,
) -> dict:
    """Phase 2: canary-heavy fleet, three arms on identical fleets with
    byte parity asserted between every pair:

      * columnar   — the default: canary docs on the pairwise-active
        columnar bucket, baseline-less docs on the PAIRWISE_NONE one;
      * canary_off — FOREMAST_CANARY_COLUMNAR=0 semantics (the pre-
        round-16 default: canary docs object, the rest columnar);
      * object     — the whole fleet on the per-task object path (the
        ~10k w/s path VERDICT r5 #9 pinned — the acceptance bar's
        denominator: "warm throughput >= 3x the object-path baseline
        on the same fleet").
    """
    from benchmarks.worker_bench import build_mixed_fleet

    def mk(arm: str):
        store, source, windows = build_mixed_fleet(
            services, hist_len, cur_len, NOW,
            baseline_frac=baseline_frac,
        )
        cfg = BrainConfig(
            algorithm="moving_average_all",
            season_steps=24,
            max_cache_size=4 * services + 64,
        )
        worker = BrainWorker(
            store, source, config=cfg, claim_limit=services,
            worker_id="canary-bench",
        )
        if arm == "canary_off":
            # FOREMAST_CANARY_COLUMNAR=0 semantics (the knob itself is
            # read at construction and pinned by tests/test_fast_tick;
            # the bench flips the worker's resolved flag so one process
            # measures all arms)
            worker._canary_fast = False
        elif arm == "object":
            worker._fast_tick = lambda docs, now: (0, docs)
        return worker, store, sum(windows.values())

    # backend-compile witness over all three arms: each arm's cold tick
    # may compile (fresh shapes for that routing), its warm ticks must
    # not — a warm recompile is a dispatch cache-key leak (the static
    # recompile-hazard rule's runtime twin, docs/static-analysis.md)
    from foremast_tpu.analysis.recompile_witness import RecompileWitness

    wit = RecompileWitness()
    wit.install()
    arms = ("columnar", "canary_off", "object")
    results = {}
    stores = {}
    fast_kinds = None
    windows = 0
    try:
        for name in arms:
            worker, store, windows = mk(name)
            with wit.phase(f"{name}_cold"):
                t0 = time.perf_counter()
                n = worker.tick(now=NOW + 150)
                cold_s = time.perf_counter() - t0
            assert n == services, f"{name}: claimed {n} != {services}"
            rates = []
            # first warm tick per arm: the arm's pipelined warm path
            # compiles once here (process-global dispatch cache, so a
            # later arm may inherit an earlier arm's programs); the
            # remaining ticks must be pure cache hits
            with wit.phase(f"{name}_warmup"):
                t0 = time.perf_counter()
                n = worker.tick(now=NOW + 160)
                rates.append(windows / (time.perf_counter() - t0))
            assert n == services, f"{name}: claimed {n} != {services}"
            with wit.phase(f"{name}_warm"):
                for k in range(1, ticks):
                    t0 = time.perf_counter()
                    n = worker.tick(now=NOW + 160 + 10 * k)
                    dt = time.perf_counter() - t0
                    assert n == services, (
                        f"{name}: claimed {n} != {services}"
                    )
                    rates.append(windows / dt)
            wit.assert_zero(f"{name}_warm")
            results[name] = {
                "cold_tick_seconds": round(cold_s, 3),
                "warm_windows_per_sec": round(float(np.median(rates)), 1),
            }
            stores[name] = store
            if name == "columnar":
                fast_kinds = dict(worker._fast_kinds)
            worker.close()
    finally:
        wit.uninstall()

    # byte parity across every arm — the opt-out knob's contract AND
    # the columnar path's: same fleet, same verdicts, bit for bit
    ref = _statuses(stores["columnar"])
    for name in arms[1:]:
        other = _statuses(stores[name])
        assert other == ref, {
            k: (ref[k], other[k]) for k in ref if ref[k] != other[k]
        }
    n_canary = int(round(services * baseline_frac))
    assert fast_kinds["baseline"] > 0, fast_kinds
    speedup = (
        results["columnar"]["warm_windows_per_sec"]
        / results["object"]["warm_windows_per_sec"]
    )
    out = {
        "config": "w-canary-fleet-tick",
        "services": services,
        "windows": windows,
        "canary_services": n_canary,
        "baseline_frac": baseline_frac,
        "columnar": results["columnar"],
        "canary_columnar_off": results["canary_off"],
        "object_path": results["object"],
        "vs_canary_off": round(
            results["columnar"]["warm_windows_per_sec"]
            / results["canary_off"]["warm_windows_per_sec"],
            2,
        ),
        "fast_path_docs": fast_kinds,
        "equivalent": True,  # asserted above, all three arms
        "metric": "canary_warm_speedup_vs_object",
        "value": round(speedup, 2),
        "unit": "x",
        "recompiles": wit.snapshot(),
    }
    if assert_bars:
        assert speedup >= CANARY_SPEEDUP_BAR, (
            f"canary warm speedup {speedup:.2f}x under the "
            f"{CANARY_SPEEDUP_BAR}x bar: {results}"
        )
        wps = results["columnar"]["warm_windows_per_sec"]
        assert wps >= CANARY_WPS_PER_CHIP_BAR, (
            f"canary-heavy warm throughput {wps} w/s under the "
            f"{CANARY_WPS_PER_CHIP_BAR} w/s/chip bar"
        )
        out["bars"] = {
            "speedup_3x_vs_object": True,
            "wps_per_chip_12500": True,
        }
    return out


def run_scenarios(b: int, th: int, tc: int, assert_floors: bool = True):
    """Phase 3: the strategy x regime F1 matrix with in-run floors."""
    from benchmarks.scenarios import scenario_matrix

    rows = scenario_matrix(b, th, tc)
    if assert_floors:
        for row in rows:
            floor = F1_FLOOR_STAIR if row["regime"] == "stair" else F1_FLOOR
            assert row["f1"] >= floor, (row, floor)
    return rows


# -- phase 4: pusher fan-in over the real receiver -----------------------


def _build_push_fleet(services, hist_len, cur_len, baseline_frac, endpoint):
    """Canary fleet whose URLs are query_range-shaped (resolvable to
    ring series keys); returns (store, series) where series maps
    key -> (times, values) covering history + current + baseline."""
    from foremast_tpu.ingest.wire import canonical_series
    from foremast_tpu.jobs.models import Document
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.metrics.promql import prometheus_url

    rng = np.random.default_rng(0)
    store = InMemoryStore()
    series: dict[str, tuple] = {}
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(hist_len, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
    bt = ct - 3600
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    n_canary = int(round(services * baseline_frac))
    for s in range(services):
        cur_parts, hist_parts, base_parts = [], [], []
        for a in ("latency", "error5xx"):
            expr = f'job:{a}{{app="app{s}"}}'
            hv = rng.normal(1.0, 0.1, hist_len).astype(np.float32)
            cv = (
                1.0 + 0.05 * np.sin(np.arange(cur_len) / 3.0)
            ).astype(np.float32)
            series[canonical_series(expr)] = (
                np.concatenate([ht, ct]),
                np.concatenate([hv, cv]),
            )
            cur_parts.append(
                f"{a}== "
                + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ct[0]), "end": int(ct[-1]), "step": 60}
                )
            )
            hist_parts.append(
                f"{a}== "
                + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ht[0]), "end": int(ht[-1]), "step": 60}
                )
            )
            if s < n_canary:
                # baseline pods are their OWN series (different label
                # set), pushed like any other
                bexpr = f'job:{a}{{app="app{s}",track="baseline"}}'
                bv = (
                    1.0
                    + 0.05 * np.sin(np.arange(cur_len) / 3.0)
                    + rng.normal(0, 0.01, cur_len)
                ).astype(np.float32)
                series[canonical_series(bexpr)] = (bt, bv)
                base_parts.append(
                    f"{a}== "
                    + prometheus_url(
                        {"endpoint": endpoint, "query": bexpr,
                         "start": int(bt[0]), "end": int(bt[-1]),
                         "step": 60}
                    )
                )
        store.create(
            Document(
                id=f"job-{s}",
                app_name=f"app{s}",
                end_time=end_time,
                current_config=" ||".join(cur_parts),
                historical_config=" ||".join(hist_parts),
                baseline_config=" ||".join(base_parts),
                strategy="canary" if s < n_canary else "continuous",
            )
        )
    return store, series


def run_fanin(services, hist_len, cur_len, fan_in_shapes):
    """Phase 4: the canary fleet PURE-PUSH — series pushed through the
    real receiver by N concurrent pushers, judged from the ring with no
    fallback configured. Statuses must be identical across fan-in
    shapes (wire topology, not semantics); per-shape apply rate and the
    canary fast-path engagement are reported."""
    from foremast_tpu.ingest import RingSource, RingStore, start_ingest_server

    rows = []
    status_sets = []
    for fan_in in fan_in_shapes:
        store, series = _build_push_fleet(
            services, hist_len, cur_len, 0.5, "http://prom/api/v1/"
        )
        ring = RingStore.from_env()
        srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
        port = srv.server_address[1]
        items = list(series.items())
        samples = sum(len(t) for t, _ in series.values())

        def push(worklist):
            batch = 64
            for i in range(0, len(worklist), batch):
                body = json.dumps(
                    {
                        "timeseries": [
                            {
                                "alias": key,
                                "times": t.tolist(),
                                "values": [float(x) for x in v],
                                "start": float(t[0]),
                            }
                            for key, (t, v) in worklist[i : i + batch]
                        ]
                    }
                ).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/api/v1/write",
                    data=body,
                    method="POST",
                )
                resp = urllib.request.urlopen(req)
                assert resp.status == 200
        t0 = time.perf_counter()
        try:
            if fan_in == 1:
                push(items)
            else:
                # collect per-thread failures and re-raise: a swallowed
                # push error would otherwise surface far away as a
                # status-parity assert, misattributing an ingest-push
                # failure to a judgment-semantics bug
                errors: list[BaseException] = []

                def worker(worklist):
                    try:
                        push(worklist)
                    except BaseException as e:  # noqa: BLE001 — re-raised below
                        errors.append(e)

                threads = [
                    threading.Thread(target=worker, args=(items[j::fan_in],))
                    for j in range(fan_in)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                if errors:
                    raise RuntimeError(
                        f"{len(errors)} of {fan_in} pushers failed"
                    ) from errors[0]
            push_s = time.perf_counter() - t0
        finally:
            srv.shutdown()
        source = RingSource(ring)  # NO fallback: pure push, zero HTTP
        cfg = BrainConfig(
            algorithm="moving_average_all",
            season_steps=24,
            max_cache_size=4 * services + 64,
        )
        worker = BrainWorker(
            store, source, config=cfg, claim_limit=services,
            worker_id=f"fanin-{fan_in}",
        )
        assert worker.tick(now=NOW + 150) == services
        t0 = time.perf_counter()
        assert worker.tick(now=NOW + 200) == services
        warm_s = time.perf_counter() - t0
        assert worker._fast_kinds["baseline"] > 0, worker._fast_kinds
        worker.close()
        status_sets.append(_statuses(store))
        rows.append(
            {
                "config": "w-canary-fanin",
                "fan_in": fan_in,
                "services": services,
                "series": len(series),
                "samples": samples,
                "push_seconds": round(push_s, 3),
                "push_samples_per_sec": round(samples / push_s, 1),
                "warm_tick_seconds": round(warm_s, 3),
                "pure_push": True,
            }
        )
    first = status_sets[0]
    for shape_statuses in status_sets[1:]:
        assert shape_statuses == first, (
            "fan-in shape changed judgments — wire topology leaked "
            "into semantics"
        )
    for row in rows:
        row["equivalent_across_shapes"] = True
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=16_384)
    ap.add_argument("--ticks", type=int, default=5)
    ap.add_argument("--hist-len", type=int, default=10_080)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument(
        "--skip-joint", action="store_true",
        help="skip the round-7 joint phase (canary/scenario focus)",
    )
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = ap.parse_args(argv)
    small = args.small
    if small:
        args.services = min(args.services, 64)
        args.hist_len = min(args.hist_len, 256)
        args.ticks = min(args.ticks, 2)

    # phase 1: joint mixed fleet (round 7's condition, unchanged)
    if not args.skip_joint:
        from benchmarks.worker_bench import run as run_joint

        joint = run_joint(
            max(args.services // 4, 16) if small else args.services,
            args.ticks,
            "auto",
            24,
            args.hist_len,
            args.cur_len,
            joint_frac=0.15,
        )
        joint["config"] = "w-mixed-fleet-tick"
        print(json.dumps(joint), flush=True)

    # phase 2: canary-heavy fleet, columnar vs object, bars in-run
    canary = run_canary(
        args.services,
        args.ticks,
        args.hist_len,
        args.cur_len,
        assert_bars=not small,
    )
    print(json.dumps(canary), flush=True)

    # phase 3: scenario matrix (floors in-run at every shape — the
    # seeded draws make them exact pins)
    b = 16 if small else 128
    th = 240 if small else 1008
    scenario_rows = []
    for row in run_scenarios(b, th, 30):
        row["config"] = "q-scenario-matrix"
        scenario_rows.append(row)
        print(json.dumps(row), flush=True)

    # phase 3b: label-shape routing/ownership cells (ISSUE 15
    # satellite — ROADMAP item 4's multi-cluster / multi-tenant
    # generator gap): doc↔series co-location and ownership spread must
    # be invariant across label shapes, asserted inside the cell
    from benchmarks.scenarios import LABEL_SHAPES, label_shape_routing_cell

    label_rows = []
    for shape in LABEL_SHAPES:
        row = label_shape_routing_cell(
            shape, services=64 if small else 1024
        )
        label_rows.append(row)
        print(json.dumps(row), flush=True)

    # phase 4: pusher fan-in shapes over the real receiver
    from benchmarks.scenarios import FAN_IN_SHAPES

    fan_services = 16 if small else 1024
    fan_hist = min(args.hist_len, 256) if small else 2048
    fanin_rows = run_fanin(
        fan_services, fan_hist, args.cur_len, FAN_IN_SHAPES
    )
    for row in fanin_rows:
        print(json.dumps(row), flush=True)
    from benchmarks.report import write_summary

    write_summary(
        "mixed",
        {
            "canary": canary,
            "scenario_matrix": scenario_rows,
            "label_shapes": label_rows,
            "fan_in": fanin_rows,
        },
        small=small,
        recompiles=canary.get("recompiles"),
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
