"""Elastic mesh bench: 2 → 4 → 2 workers under live load (ISSUE 11).

Every earlier membership change paid a cold refit (round 9's heal wall
was ~82 s of survivors cold-fitting inherited partitions). This bench
PROVES rebalance is now a state TRANSFER: an autoscale-driven fleet of
shipped-stack workers (BrainWorker + MeshNode + HandoffManager + ring
receiver, judging entirely from pushed samples over a real HTTP store)
scales up and back down under continuous load, and the planned moves
cost nothing.

Phases (one JSON row each, plus a summary row):

  load       2 workers under a rolling document load; the autoscale
             driver watches MEASURED tick occupancy + ring pressure and
             must verdict `scale_up` (hysteresis: consecutive breaches)
  scale_up   w3/w4 register FENCED (`joining`) mid-load; the owners
             stream them the moving ring series + fit entries; both
             activate on `done` markers (never the deadline), each
             sender finishing inside ≤ 2 ticks — and the first batch
             the joiners judge costs ZERO cold refits and ZERO fallback
             fetches (the state ARRIVED, nothing reconstructs)
  scale_down idle occupancy drives a `scale_down` verdict; w3/w4 drain
             (state `draining`: stream their partitions to survivors,
             then leave) — the survivors judge the next batch with zero
             cold refits and zero fallback fetches for the partitions
             they inherited
  fault      a chaos-plan window blackholes the peer→peer `transfer`
             edge while w5 joins: every send fails (counted), w5
             activates at its DEADLINE instead of wedging, and its
             partition cold-refits through the fallback path — the
             fleet still converges with exactly-once verdicts

In-run asserts (the bench FAILS, not just reports): one terminal
ledger write per doc per phase (zero lost or duplicated verdicts), no
`completed_unknown` regression anywhere, planned handoff inside 2
ticks, zero cold refits + zero fallback fetches on every PLANNED move,
pusher redirect convergence after each membership change, and the
runtime lock witness clean against the committed static graph.

Usage: python -m benchmarks.elastic_bench [--small]
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.parse

from benchmarks.chaos_bench import (
    SynthSession,
    assert_exactly_once,
    wait_all_terminal,
)
from benchmarks.scaleout_bench import ALIAS_EXPR, HttpFleetStore, StoreServer

# the lease must comfortably outlive a BUSY tick: renewal happens at
# tick boundaries, so a lease under the tick duration makes a sender
# mid-judgment look dead to a fenced joiner — which then (by design)
# discounts its handoff and cold-refits. docs/operations.md "Elastic
# scaling" carries this sizing rule.
LEASE_SECONDS = 6.0
POLL_SECONDS = 0.05
ROUTER_REFRESH_SECONDS = 0.25
HANDOFF_DEADLINE = 5.0
PUSH_PERIOD = 0.2
OBSERVE_PERIOD = 0.15
OCCUPANCY_WINDOW = 0.6

# the chaos-plan window that blackholes the transfer edge (plan-clock
# seconds; the driver moves the injected clock)
FAULT_WINDOW = (100.0, 200.0)


class CountingSynthSession(SynthSession):
    """The chaos bench's query_range synthesizer, counting every GET —
    the bench's 'fallback fetch' meter. Planned phases must leave it at
    ZERO; the fault phase must move it (cold refit via fallback)."""

    def __init__(self):
        super().__init__()
        self.calls = 0
        self.urls: list[str] = []
        self._lock = threading.Lock()

    def get(self, url, timeout=None):
        with self._lock:
            self.calls += 1
            if len(self.urls) < 32:
                self.urls.append(url)
        return super().get(url, timeout=timeout)


class ElasticWorker:
    """One elastic seat: the shipped stack judging from its ring, with
    the planned-handoff plane mounted on its receiver."""

    def __init__(self, wid: str, store_url: str, plan, fault_edges=True):
        from foremast_tpu.chaos import BreakerRegistry, Degradation
        from foremast_tpu.chaos.degrade import DegradeStats
        from foremast_tpu.config import BrainConfig
        from foremast_tpu.ingest import (
            RingSource,
            RingStore,
            start_ingest_server,
        )
        from foremast_tpu.jobs.worker import BrainWorker
        from foremast_tpu.mesh import (
            HandoffManager,
            Membership,
            MeshNode,
            MeshRouter,
        )
        from foremast_tpu.metrics.source import PrometheusSource

        self.wid = wid
        stats = DegradeStats()
        self.degrade = Degradation(
            stats=stats,
            breakers=BreakerRegistry(
                failure_threshold=2, open_seconds=0.5
            ),
        )
        self.fleet = HttpFleetStore(store_url, wid)
        self.ring = RingStore(
            budget_bytes=8 << 20, shards=2, stale_seconds=3600.0
        )
        self.handoff = HandoffManager(
            ring_store=self.ring,
            deadline_seconds=HANDOFF_DEADLINE,
            retries=1,
            backoff_seconds=0.05,
            timeout=2.0,
            chaos=plan.edge("transfer") if fault_edges else None,
            breaker=self.degrade.breakers.get("transfer"),
        )
        self.session = CountingSynthSession()
        fallback = PrometheusSource(
            session=self.session, retries=0, backoff_seconds=0.01
        )
        fallback.concurrent_fetch = False  # GIL-bound synth fetches
        self.source = RingSource(self.ring, fallback=fallback)
        membership = Membership(
            self.fleet, wid, lease_seconds=LEASE_SECONDS
        )
        router = MeshRouter(
            membership, refresh_seconds=ROUTER_REFRESH_SECONDS
        )
        self.receiver, _ = start_ingest_server(
            0, self.ring, host="127.0.0.1", router=router,
            handoff=self.handoff, degrade_stats=stats,
        )
        membership.ingest_address = (
            "127.0.0.1:%d" % self.receiver.server_address[1]
        )
        self.node = MeshNode(
            membership, router, ring_store=self.ring, handoff=self.handoff
        )
        config = BrainConfig(
            algorithm="moving_average_all",
            max_stuck_seconds=30.0,
            max_cache_size=8192,
        )
        self.worker = BrainWorker(
            self.fleet, self.source, config=config, claim_limit=32,
            worker_id=wid, mesh=self.node, degrade=self.degrade,
        )
        self.tick_log: list[tuple[float, float, int]] = []
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._loop, name=f"elastic-{wid}", daemon=True
        )

    # -- loop -----------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                n = self.worker.tick()
            except Exception:  # pragma: no cover — the bench fails below
                import logging

                logging.getLogger("elastic_bench").exception(
                    "worker %s tick crashed", self.wid
                )
                self.tick_log.append((t0, time.monotonic(), -1))
                return
            self.tick_log.append((t0, time.monotonic(), n))
            if n == 0:
                time.sleep(POLL_SECONDS)

    def start(self):
        self.thread.start()

    def stop_loop(self, timeout=30.0):
        self._stop.set()
        self.thread.join(timeout=timeout)
        assert not self.thread.is_alive(), f"{self.wid} tick loop stuck"

    def crashed(self) -> bool:
        return any(n < 0 for _, _, n in self.tick_log)

    # -- signals ---------------------------------------------------------

    def occupancy(self, window: float = OCCUPANCY_WINDOW) -> float:
        """Busy fraction of the trailing window — the bench-side read
        of the tick-occupancy signal the autoscale driver consumes."""
        now = time.monotonic()
        lo = now - window
        busy = 0.0
        for t0, t1, n in reversed(self.tick_log):
            if t1 < lo:
                break
            if n > 0:
                busy += min(t1, now) - max(t0, lo)
        # a tick in flight right now counts as busy from its start
        if self.tick_log:
            pass
        return min(1.0, busy / window)

    def ring_pressure(self) -> float:
        s = self.ring.stats()
        return s["bytes"] / float(8 << 20)

    def busy_ticks_between(self, t0: float, t1: float) -> int:
        return sum(
            1 for a, _, n in self.tick_log if t0 <= a <= t1 and n > 0
        )

    def cold_reads(self) -> dict:
        return self.worker._cold_snapshot()

    def close(self):
        from foremast_tpu.ingest import stop_ingest_server

        self.worker.close()
        stop_ingest_server(self.receiver, drain_seconds=1.0)


# ---------------------------------------------------------------------------
# load + push plumbing
# ---------------------------------------------------------------------------


def seed_batch(server, phase: str, apps, hist_len, cur_len, anchor):
    """One finalize-on-first-judgment doc per app, windows ANCHORED so
    every phase reuses the same fit-cache keys (the warm state planned
    handoff moves). Returns the doc ids."""
    from foremast_tpu.jobs.models import Document

    cur_t1 = anchor - 60
    cur_t0 = cur_t1 - 60 * (cur_len - 1)
    hist_t1 = cur_t0 - 120
    hist_t0 = hist_t1 - 60 * (hist_len - 1)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(anchor - 30)
    )
    ids = []
    for app in apps:
        sid = app[3:]
        expr = urllib.parse.quote(ALIAS_EXPR.format(a=0, sid=sid), safe="")
        doc_id = f"job-{phase}-{sid}"
        server.store.create(
            _doc(
                Document, doc_id, app, end_time,
                f"m0== http://synth/api/v1/query_range?query={expr}"
                f"&start={cur_t0}&end={cur_t1}&step=60",
                f"m0== http://synth/api/v1/query_range?query={expr}"
                f"&start={hist_t0}&end={hist_t1}&step=60",
            )
        )
        ids.append(doc_id)
    return ids


def _doc(Document, doc_id, app, end_time, cur, hist):
    return Document(
        id=doc_id, app_name=app, end_time=end_time,
        current_config=cur, historical_config=hist,
        strategy="continuous",
    )


class ContinuousPusher:
    """The live push load: every cycle re-pushes each app's CURRENT
    window through a RoutingPusher (full history goes once, up front) —
    so a joining member's ring is receiving live samples the moment the
    receivers hint the pusher at it, exactly like production."""

    def __init__(self, seed_addr, apps, hist_len, cur_len, anchor):
        import numpy as np

        from foremast_tpu.mesh import RoutingPusher

        self.pusher = RoutingPusher(
            [seed_addr], retries=1, backoff_seconds=0.05,
            timeout=5.0, buffer_bytes=8 << 20,
        )
        self.anchor = anchor
        cur_t1 = anchor - 60
        self.cur_t0 = cur_t1 - 60 * (cur_len - 1)
        hist_t1 = self.cur_t0 - 120
        self.hist_t0 = hist_t1 - 60 * (hist_len - 1)
        self._np = np
        self.apps = apps
        self.cycles: list[dict] = []  # (redirects, errors) per cycle
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._loop, name="elastic-pusher", daemon=True
        )

    def _series(self, t0, t1, start):
        from benchmarks.scaleout_bench import synth_values

        np = self._np
        out = []
        for app in self.apps:
            sid = app[3:]
            key = ALIAS_EXPR.format(a=0, sid=sid)
            ts = np.arange(int(t0), int(t1) + 1, 60, np.int64)
            out.append(
                (key, ts.tolist(), synth_values(key, ts).tolist(),
                 float(start))
            )
        return out

    def backfill(self, cycles=4):
        """Full-span push (history + current), repeated until the
        redirect hints converge — every series resident on its owner."""
        series = self._series(
            self.hist_t0, self.anchor - 60, self.hist_t0 - 600
        )
        for i in range(cycles):
            out = self.pusher.push_cycle(series)
            if i > 0 and out["redirects"] == 0 and out["errors"] == 0:
                return out
        raise AssertionError(
            f"pusher never converged during backfill: {out}"
        )

    def _loop(self):
        series = self._series(self.cur_t0, self.anchor - 60, self.cur_t0)
        while not self._stop.is_set():
            out = self.pusher.push_cycle(series)
            self.cycles.append(
                {"redirects": out["redirects"], "errors": out["errors"]}
            )
            self._stop.wait(PUSH_PERIOD)

    def start(self):
        self.thread.start()

    def stop(self):
        self._stop.set()
        self.thread.join(timeout=10)

    def cycles_since(self, idx: int) -> list[dict]:
        return self.cycles[idx:]


def assert_no_unknown(server, ids, phase):
    from foremast_tpu.jobs.models import STATUS_COMPLETED_UNKNOWN

    unknown = [
        i for i in ids
        if server.store.get(i).status == STATUS_COMPLETED_UNKNOWN
    ]
    assert not unknown, (
        f"[{phase}] UNKNOWN regression: {len(unknown)} doc(s) "
        f"completed_unknown: {unknown[:5]}"
    )


def assert_redirects_converged(pusher, mark, phase, settle=3,
                               timeout=10.0):
    """After a membership change, hint traffic must settle: within
    `timeout` the pusher runs `settle` consecutive hint-free cycles
    (each member hints its moved series the first time it sees them
    post-change; ONE learning cycle per hint wave, then quiet)."""
    deadline = time.monotonic() + timeout
    while True:
        cycles = pusher.cycles_since(mark)
        tail = cycles[-settle:]
        if len(tail) == settle and all(
            c["redirects"] == 0 for c in tail
        ):
            return
        assert time.monotonic() < deadline, (
            f"[{phase}] pusher never settled after the membership "
            f"change: {cycles}"
        )
        time.sleep(PUSH_PERIOD)


def _cold_delta(before: dict, after: dict) -> dict:
    return {k: after[k] - before.get(k, 0) for k in after}


def run(small: bool = False) -> list[dict]:
    from foremast_tpu.analysis import witness
    from foremast_tpu.chaos import FaultPlan
    from foremast_tpu.mesh import AutoscaleConfig, AutoscaleDriver

    wit = witness.install()

    apps_n = 24 if small else 64
    hist_len = 48 if small else 192
    cur_len = 8 if small else 16
    max_load_batches = 12
    apps = [f"app{i}" for i in range(apps_n)]
    anchor = int(time.time())

    clock_box = [0.0]
    plan = FaultPlan(
        rules=(
            {"edge": "transfer", "after": FAULT_WINDOW[0],
             "duration": FAULT_WINDOW[1] - FAULT_WINDOW[0],
             "error_rate": 1.0, "kind": "timeout"},
        ),
        seed=4242,
        clock=lambda: clock_box[0],
    ).activate(now=0.0)

    server = StoreServer()
    url = server.start()
    rows: list[dict] = []
    workers: dict[str, ElasticWorker] = {}

    def phase_row(phase, **extra):
        row = {"config": "c-elastic", "phase": phase, **extra}
        rows.append(row)
        print(json.dumps(row), flush=True)

    def actives():
        return [
            w for w in workers.values()
            if w.node.state == "active" and not w._stop.is_set()
        ]

    def total_fallback():
        return sum(w.session.calls for w in workers.values())

    pusher = None
    try:
        # -- boot: 2 active workers, rings warm ------------------------
        for wid in ("w1", "w2"):
            workers[wid] = ElasticWorker(wid, url, plan)
            workers[wid].start()
        deadline = time.monotonic() + 20
        while any(
            len(w.node.router.members()) < 2
            or w.node.state != "active"
            for w in workers.values()
        ):
            assert time.monotonic() < deadline, "mesh never converged"
            time.sleep(0.05)
        pusher = ContinuousPusher(
            workers["w1"].node.membership.ingest_address,
            apps, hist_len, cur_len, anchor,
        )
        pusher.backfill()
        pusher.start()

        # -- phase: load → autoscale verdict ---------------------------
        driver = AutoscaleDriver(
            AutoscaleConfig(
                high_occupancy=0.5, low_occupancy=0.2,
                high_ring_pressure=0.95, high_write_queue=1 << 30,
                breach_ticks=3, cooldown_seconds=2.0,
                min_workers=2, max_workers=4,
            )
        )

        def observe_until(want, deadline_s, label):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                occ = max(w.occupancy() for w in actives())
                pressure = max(w.ring_pressure() for w in actives())
                verdict = driver.observe(
                    occ, len(actives()), ring_pressure=pressure
                )
                if verdict == want:
                    return True
                time.sleep(OBSERVE_PERIOD)
            raise AssertionError(
                f"autoscale driver never verdicted {want!r} during "
                f"{label}: {driver.debug_state()}"
            )

        t0 = time.monotonic()
        fired = threading.Event()
        verdict_thread = threading.Thread(
            target=lambda: (
                observe_until("scale_up", 60.0, "load"), fired.set()
            ),
            daemon=True,
        )
        verdict_thread.start()
        batches = 0
        while not fired.is_set():
            assert batches < max_load_batches, (
                "autoscale never fired scale_up under sustained load: "
                f"{driver.debug_state()}"
            )
            ids = seed_batch(
                server, f"load{batches}", apps, hist_len, cur_len, anchor
            )
            wait_all_terminal(server, ids, timeout=120)
            assert_exactly_once(server, ids, f"load{batches}")
            assert_no_unknown(server, ids, f"load{batches}")
            batches += 1
        verdict_thread.join(timeout=70)
        assert total_fallback() == 0, (
            "the warm 2-worker fleet fell back to HTTP "
            f"({total_fallback()} fetches) — the ring should serve "
            "everything"
        )
        cold0 = {w: workers[w].cold_reads() for w in ("w1", "w2")}
        assert all(c["http"] == 0 for c in cold0.values()), cold0
        phase_row(
            "load", workers=2, batches=batches, docs_per_batch=apps_n,
            occupancy=driver.last_signals["occupancy"],
            scale_up_after_seconds=round(time.monotonic() - t0, 3),
            cold_reads=cold0,
        )

        # -- phase: scale up 2 → 4 under in-flight load ----------------
        inflight = seed_batch(
            server, "up-inflight", apps, hist_len, cur_len, anchor
        )
        cycle_mark = len(pusher.cycles)
        t_join = time.monotonic()
        join_windows = {}
        # sequential joins (the autoscaler's one-verdict-one-worker
        # cadence): each joiner fences against a SETTLED target ring,
        # so its streamed share is exactly the share it activates with.
        # (Simultaneous joiners re-stream on the membership move —
        # pinned by test_simultaneous_joiners_restream_on_target_change
        # — but sequential is the operational recommendation.)
        for wid in ("w3", "w4"):
            t_w = time.monotonic()
            workers[wid] = ElasticWorker(wid, url, plan)
            workers[wid].start()
            # a joiner's `state` reads "active" until its first tick
            # fences it, so "joined" = the handoff recorded a completed
            # wait AND the state settled active
            deadline = time.monotonic() + 30
            while (
                workers[wid].handoff.join_wait_seconds is None
                or workers[wid].node.state != "active"
            ):
                assert time.monotonic() < deadline, (
                    f"{wid} never activated: "
                    + str(workers[wid].handoff.debug_state())
                )
                time.sleep(0.05)
            join_windows[wid] = (t_w, time.monotonic())
        t_active = time.monotonic()
        join_seconds = t_active - t_join
        # activation came from DONE markers, not the deadline (w4 joins
        # a 3-member fleet, so w3 is one of its senders)
        expected_senders = {"w3": ["w1", "w2"], "w4": ["w1", "w2", "w3"]}
        for wid in ("w3", "w4"):
            h = workers[wid].handoff.debug_state()
            assert sorted(h["done_from"]) == expected_senders[wid], (
                f"{wid} activated without every sender's done marker: {h}"
            )
            assert h["join_wait_seconds"] < HANDOFF_DEADLINE, h
        # each sender delivered inside the 2-tick bar, per join
        for jid, (w0, w1_) in join_windows.items():
            for wid in expected_senders[jid]:
                busy = workers[wid].busy_ticks_between(w0, w1_)
                assert busy <= 2, (
                    f"handoff to {jid} took {wid} {busy} busy ticks "
                    "(bar: ≤ 2)"
                )
        sent = {
            w: workers[w].handoff.counters_snapshot() for w in ("w1", "w2")
        }
        moved_series = sum(c["series_sent"] for c in sent.values())
        moved_fits = sum(c["fits_sent"] for c in sent.values())
        assert moved_series > 0 and moved_fits > 0, sent
        assert all(
            c["send"]["failed"] == 0 and c["send"]["rejected"] == 0
            for c in sent.values()
        ), sent
        wait_all_terminal(server, inflight, timeout=120)
        assert_exactly_once(server, inflight, "up-inflight")
        assert_no_unknown(server, inflight, "up-inflight")
        # the first post-activation batch: the joiners judge their
        # partition WARM — zero cold refits, zero fallback fetches
        cold_before = {w: workers[w].cold_reads() for w in ("w3", "w4")}
        ids = seed_batch(server, "up-warm", apps, hist_len, cur_len, anchor)
        wait_all_terminal(server, ids, timeout=120)
        assert_exactly_once(server, ids, "up-warm")
        assert_no_unknown(server, ids, "up-warm")
        ledger = server.ledger_snapshot()
        joiner_writes = sum(
            1
            for i in ids
            for e in ledger.get(i, ())
            if e[0] in ("w3", "w4")
        )
        assert joiner_writes > 0, (
            "the joiners judged nothing post-activation — partition "
            "never moved"
        )
        cold_delta = {
            w: _cold_delta(cold_before[w], workers[w].cold_reads())
            for w in ("w3", "w4")
        }
        for wid, delta in cold_delta.items():
            assert all(v == 0 for v in delta.values()), (
                f"{wid} paid {delta} cold refits on a PLANNED move — "
                "the transferred state should have made it warm"
            )
        for wid in ("w3", "w4"):
            assert workers[wid].session.calls == 0, (
                f"{wid} fell back to HTTP: "
                f"{workers[wid].session.urls}"
            )
        assert_redirects_converged(pusher, cycle_mark, "scale_up")
        phase_row(
            "scale_up", workers=4,
            join_seconds=round(join_seconds, 3),
            moved_series=moved_series, moved_fits=moved_fits,
            joiner_docs=joiner_writes,
            joiner_cold_refits=0, joiner_fallback_fetches=0,
        )

        # -- phase: scale down 4 → 2 (autoscale + drain) ---------------
        observe_until("scale_down", 30.0, "idle fleet")
        cycle_mark = len(pusher.cycles)
        recv_before = {
            w: workers[w].handoff.counters_snapshot() for w in ("w1", "w2")
        }
        cold_before = {w: workers[w].cold_reads() for w in ("w1", "w2")}
        t_drain = time.monotonic()
        for wid in ("w3", "w4"):
            w = workers[wid]
            w.stop_loop()
            out = w.node.drain()
            assert all(r == "ok" for r in out["targets"].values()), (
                f"{wid} drain transfers failed: {out}"
            )
        deadline = time.monotonic() + 20
        while any(
            len(workers[w].node.router.members()) != 2
            for w in ("w1", "w2")
        ):
            assert time.monotonic() < deadline, "drain never healed"
            time.sleep(0.05)
        drain_seconds = time.monotonic() - t_drain
        received = {
            w: _cold_delta(
                {
                    "series": recv_before[w]["series_received"],
                    "fits": recv_before[w]["fits_received"],
                },
                {
                    "series": workers[w].handoff.counters_snapshot()[
                        "series_received"
                    ],
                    "fits": workers[w].handoff.counters_snapshot()[
                        "fits_received"
                    ],
                },
            )
            for w in ("w1", "w2")
        }
        assert sum(r["series"] for r in received.values()) > 0, received
        assert sum(r["fits"] for r in received.values()) > 0, received
        ids = seed_batch(server, "down", apps, hist_len, cur_len, anchor)
        wait_all_terminal(server, ids, timeout=120)
        assert_exactly_once(server, ids, "down")
        assert_no_unknown(server, ids, "down")
        cold_delta = {
            w: _cold_delta(cold_before[w], workers[w].cold_reads())
            for w in ("w1", "w2")
        }
        for wid, delta in cold_delta.items():
            assert all(v == 0 for v in delta.values()), (
                f"{wid} paid {delta} cold refits inheriting a DRAINED "
                "partition — the state should have moved with it"
            )
        assert total_fallback() == 0, (
            f"planned phases cost {total_fallback()} fallback fetches"
        )
        assert_redirects_converged(pusher, cycle_mark, "scale_down")
        phase_row(
            "scale_down", workers=2,
            drain_seconds=round(drain_seconds, 3),
            inherited=received,
            survivor_cold_refits=0, survivor_fallback_fetches=0,
        )

        # -- phase: blackholed transfer degrades, never wedges ---------
        clock_box[0] = FAULT_WINDOW[0] + 1.0
        t_fault_join = time.monotonic()
        workers["w5"] = ElasticWorker("w5", url, plan)
        workers["w5"].start()
        deadline = time.monotonic() + 30
        while (
            workers["w5"].handoff.join_wait_seconds is None
            or workers["w5"].node.state != "active"
        ):
            assert time.monotonic() < deadline, (
                "w5 wedged behind a blackholed transfer: "
                + str(workers["w5"].handoff.debug_state())
            )
            time.sleep(0.05)
        h5 = workers["w5"].handoff.debug_state()
        assert h5["done_from"] == [], (
            f"a blackholed transfer still delivered done markers: {h5}"
        )
        assert h5["join_wait_seconds"] >= HANDOFF_DEADLINE * 0.9, h5
        failed_sends = sum(
            workers[w].handoff.counters_snapshot()["send"]["failed"]
            for w in ("w1", "w2")
        )
        assert failed_sends >= 1, "the fault window injected nothing"
        assert (
            plan.injections_snapshot().get(("transfer", "timeout"), 0) >= 1
        )
        cold_before5 = workers["w5"].cold_reads()
        ids = seed_batch(server, "fault", apps, hist_len, cur_len, anchor)
        wait_all_terminal(server, ids, timeout=120)
        assert_exactly_once(server, ids, "fault")
        # w5 COLD-REFIT its partition (fallback history fetches: its
        # ring never received the blackholed transfer) — the designed
        # degradation, and the fleet still converged exactly-once
        delta5 = _cold_delta(cold_before5, workers["w5"].cold_reads())
        refits5 = sum(delta5.values())
        assert refits5 > 0, (
            "w5 judged its partition with no cold refits despite the "
            f"blackholed transfer: {delta5}"
        )
        assert workers["w5"].session.calls > 0, (
            "w5's cold refits never touched the fallback — where did "
            "its history come from?"
        )
        clock_box[0] = FAULT_WINDOW[1] + 1.0
        phase_row(
            "fault", workers=3,
            join_wait_seconds=round(h5["join_wait_seconds"], 3),
            failed_sends=failed_sends,
            w5_cold_refits=refits5,
            w5_fallback_fetches=workers["w5"].session.calls,
        )

        # -- end state --------------------------------------------------
        for w in workers.values():
            assert not w.crashed(), f"{w.wid} tick loop crashed"
        graph = witness.load_graph()
        assert graph is not None, "analysis_lockgraph.json missing"
        missing = wit.unobserved_edges(graph)
        assert not missing, (
            "lock witness observed edges missing from the static "
            f"graph (run `make lockgraph`): {missing}"
        )
        summary = {
            "config": "c-elastic",
            "phase": "summary",
            "phases": [r["phase"] for r in rows],
            "apps": apps_n,
            "no_lost_or_duplicated_verdicts": True,
            "no_unknown_regression": True,
            "planned_moves_zero_cold_refits": True,
            "planned_moves_zero_fallback_fetches": True,
            "handoff_within_2_ticks": True,
            "fault_degraded_to_cold_refit": True,
            "lock_witness_clean": True,
        }
        rows.append(summary)
        print(json.dumps(summary), flush=True)
        return rows
    finally:
        if pusher is not None:
            pusher.stop()
        for w in workers.values():
            if not w._stop.is_set():
                w._stop.set()
                w.thread.join(timeout=10)
            try:
                w.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        server.stop()
        witness.uninstall()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = parser.parse_args(argv)
    phases = run(small=args.small)
    from benchmarks.report import write_summary

    write_summary(
        "elastic", {"phases": phases}, small=args.small
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
