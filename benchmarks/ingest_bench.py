"""Ingest-plane benchmark: warm RingSource vs PrometheusSource over HTTP.

`pipeline_bench` measures how much of the fetch stage OVERLAP can hide;
this benchmark measures how much of it the push plane ELIMINATES. Same
fleet, same samples, two workers:

  * pull — `PrometheusSource` against a real localhost HTTP server
    speaking the query_range JSON matrix protocol (socket + JSON parse
    per window, the reference brain's per-tick cost floor);
  * push — `RingSource` over a ring warmed through the remote-write
    receiver (the full wire path: JSON POST -> shard push), with the
    SAME PrometheusSource wrapped as cold-miss fallback.

Both run one cold tick (fits) and one measured warm tick; the measured
number is the tick's `metric_fetch` stage seconds from the span
pipeline. The benchmark itself asserts (a) statuses + anomaly payloads
byte-identical between the two stores and (b) the fake Prometheus
served ZERO requests during the push worker's ticks — the ISSUE 5
acceptance bar, alongside the >= 5x fetch-stage speedup.

Usage: python -m benchmarks.ingest_bench [--services N] [--aliases F]
       [--hist-len H] [--small]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.parse

import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.ingest import RingStore, RingSource, start_ingest_server
from foremast_tpu.ingest.wire import canonical_series
from foremast_tpu.jobs.models import Document
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.metrics.source import PrometheusSource

NOW = 1_760_000_000.0


class FakePrometheus:
    """Localhost query_range endpoint over a samples dict — real
    sockets, real JSON, per-request slicing; counts every request."""

    def __init__(self):
        self.data: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self.requests = 0
        self._lock = threading.Lock()
        self._srv = None

    def start(self) -> str:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                with fake._lock:
                    fake.requests += 1
                qs = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                key = canonical_series(qs.get("query", [""])[0])
                t0 = float(qs.get("start", ["0"])[0])
                t1 = float(qs.get("end", ["0"])[0])
                t, v = fake.data.get(
                    key, (np.zeros(0, np.int64), np.zeros(0, np.float32))
                )
                lo = int(np.searchsorted(t, t0, side="left"))
                hi = int(np.searchsorted(t, t1, side="right"))
                body = json.dumps(
                    {
                        "status": "success",
                        "data": {
                            "result": [
                                {
                                    "values": [
                                        [int(ts), str(float(val))]
                                        for ts, val in zip(
                                            t[lo:hi], v[lo:hi]
                                        )
                                    ]
                                }
                            ]
                        },
                    }
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(
            target=self._srv.serve_forever, daemon=True
        ).start()
        return f"http://127.0.0.1:{self._srv.server_address[1]}/api/v1/"

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()


def build_fleet(services, aliases, hist_len, cur_len, endpoint, fake, seed=0):
    """Continuous-strategy docs: current + historical windows over the
    same app series (metricsquery.go shape), one series per alias."""
    rng = np.random.default_rng(seed)
    store = InMemoryStore()
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(hist_len, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    names = ("latency", "error5xx", "tps", "cpu")[:aliases]
    for s in range(services):
        cur_parts, hist_parts = [], []
        for a in names:
            expr = (
                f"namespace_app_per_pod:{a}"
                f'{{namespace="bench",app="app{s}"}}'
            )
            key = canonical_series(expr)
            if key not in fake.data:
                hv = rng.normal(1.0, 0.1, hist_len).astype(np.float32)
                cv = (
                    1.0 + 0.05 * np.sin(np.arange(cur_len) / 3.0)
                ).astype(np.float32)
                fake.data[key] = (
                    np.concatenate([ht, ct]),
                    np.concatenate([hv, cv]),
                )
            cur_parts.append(
                f"{a}== "
                + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ct[0]), "end": int(ct[-1]), "step": 60}
                )
            )
            hist_parts.append(
                f"{a}== "
                + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ht[0]), "end": int(ht[-1]), "step": 60}
                )
            )
        store.create(
            Document(
                id=f"job-{s}",
                app_name=f"app{s}",
                end_time=end_time,
                current_config=" ||".join(cur_parts),
                historical_config=" ||".join(hist_parts),
                strategy="continuous",
            )
        )
    return store


def _warm_ring_via_receiver(fake, batch=256, codec="json"):
    """Ring warmed through the real wire — remote-write POSTs in either
    codec. Returns (ring, responses): the (status, body) list is the
    cross-codec byte-parity witness (ISSUE 18) — same batches, same
    receiver code path, so JSON and binary warming must answer
    byte-identical responses."""
    import urllib.request

    from foremast_tpu.ingest import BINARY_CONTENT_TYPE, encode_frame

    ring = RingStore.from_env()
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    port = srv.server_address[1]
    items = list(fake.data.items())
    responses = []
    try:
        for i in range(0, len(items), batch):
            group = items[i : i + batch]
            if codec == "binary":
                body = encode_frame(
                    [
                        (key, t, v, float(t[0]))
                        for key, (t, v) in group
                    ]
                )
                ctype = BINARY_CONTENT_TYPE
            else:
                body = json.dumps(
                    {
                        "timeseries": [
                            {
                                "alias": key,
                                "times": t.tolist(),
                                "values": [float(x) for x in v],
                                "start": float(t[0]),
                            }
                            for key, (t, v) in group
                        ]
                    }
                ).encode()
                ctype = "application/json"
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/write",
                data=body,
                method="POST",
                headers={"Content-Type": ctype},
            )
            resp = urllib.request.urlopen(req)
            responses.append((resp.status, resp.read()))
            assert resp.status == 200
    finally:
        srv.shutdown()
    return ring, responses


def _mk_worker(store, source, services, aliases, tracer):
    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_cache_size=aliases * services + 64,
    )
    return BrainWorker(
        store, source, config=cfg, claim_limit=services,
        worker_id="ingest-bench", tracer=tracer,
    )


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def _phase(store, source, services, aliases):
    """Cold tick (fits) + measured warm tick; returns (fetch_seconds,
    warm_tick_seconds, statuses)."""
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.spans import Tracer

    tracer = Tracer(service="ingest-bench", registry=CollectorRegistry(),
                    trace_dir=None)
    worker = _mk_worker(store, source, services, aliases, tracer)
    n = worker.tick(now=NOW + 150)
    assert n == services, f"claimed {n} != {services}"
    t0 = time.perf_counter()
    n = worker.tick(now=NOW + 300)
    warm_s = time.perf_counter() - t0
    assert n == services
    fetch_s = tracer.last_stage_seconds.get("metric_fetch", 0.0)
    statuses = _statuses(store)
    worker.close()
    return fetch_s, warm_s, statuses


def run(services: int, aliases: int, hist_len: int, cur_len: int) -> dict:
    fake = FakePrometheus()
    endpoint = fake.start()
    try:
        pull_store = build_fleet(
            services, aliases, hist_len, cur_len, endpoint, fake
        )
        push_store = build_fleet(
            services, aliases, hist_len, cur_len, endpoint, fake
        )
        bin_store = build_fleet(
            services, aliases, hist_len, cur_len, endpoint, fake
        )
        pull_fetch_s, pull_warm_s, pull_out = _phase(
            pull_store, PrometheusSource(), services, aliases
        )
        ring, json_resps = _warm_ring_via_receiver(fake)
        # the same fleet warmed over the BINARY codec: the receiver
        # must answer byte-identical responses batch for batch, and the
        # judged statuses downstream must match too (ISSUE 18 parity)
        ring_bin, bin_resps = _warm_ring_via_receiver(fake, codec="binary")
        assert json_resps == bin_resps, (
            "receiver responses diverged across wire codecs"
        )
        # let pull-phase stragglers (handler threads still draining a
        # late keep-alive connection) finish before snapshotting the
        # request counter the zero-HTTP assertion reads
        time.sleep(1.0)
        reqs_before = fake.requests
        source = RingSource(ring, fallback=PrometheusSource())
        push_fetch_s, push_warm_s, push_out = _phase(
            push_store, source, services, aliases
        )
        zero_http = fake.requests == reqs_before
        assert push_out == pull_out, (
            "push-path judgments diverged from the pull path"
        )
        _, _, bin_out = _phase(
            bin_store,
            RingSource(ring_bin, fallback=PrometheusSource()),
            services,
            aliases,
        )
        assert bin_out == push_out, (
            "binary-warmed ring judgments diverged from the JSON-warmed ring"
        )
        stats = ring.stats()
        return {
            "config": "i-ingest-warm-fetch",
            "services": services,
            "aliases": aliases,
            "windows": services * aliases,
            "hist_len": hist_len,
            "series_resident": stats["series"],
            "ring_bytes": stats["bytes"],
            "pull_fetch_seconds": round(pull_fetch_s, 4),
            "push_fetch_seconds": round(push_fetch_s, 4),
            "pull_warm_tick_seconds": round(pull_warm_s, 4),
            "push_warm_tick_seconds": round(push_warm_s, 4),
            "ring_hit_ratio": stats["hit_ratio"],
            "zero_http_warm_tick": zero_http,
            "equivalent": True,  # asserted above
            "codec_responses_identical": True,  # asserted above
            "codec_statuses_identical": True,  # asserted above
            "metric": "fetch_stage_speedup",
            "value": (
                round(pull_fetch_s / push_fetch_s, 2)
                if push_fetch_s > 0
                else None
            ),
            "unit": "x",
        }
    finally:
        fake.stop()


def _wire_fixture(n_series, samples, batch_series, seed=7):
    """Sorted-time fixture rendered once into BOTH codecs: per-batch
    JSON bodies and FMW1 frames carrying identical series/samples."""
    from foremast_tpu.ingest import encode_frame

    rng = np.random.default_rng(seed)
    base = int(NOW) - samples * 60
    t = base + 60 * np.arange(samples, dtype=np.int64)
    json_bodies, frames, entries_per_batch = [], [], []
    for lo in range(0, n_series, batch_series):
        group = []
        for s in range(lo, min(lo + batch_series, n_series)):
            key = (
                f"namespace_app_per_pod:wire"
                f'{{app="app{s}",namespace="bench"}}'
            )
            v = rng.normal(1.0, 0.1, samples).astype(np.float32)
            group.append((key, t, v, float(t[0])))
        json_bodies.append(
            json.dumps(
                {
                    "timeseries": [
                        {
                            "alias": k,
                            "times": ts.tolist(),
                            "values": [float(x) for x in vs],
                            "start": st,
                        }
                        for k, ts, vs, st in group
                    ]
                }
            ).encode()
        )
        frames.append(encode_frame(group))
        entries_per_batch.append(len(group))
    return json_bodies, frames, entries_per_batch


def _measure_codec(bodies, decode, mk_apply, repeats=2):
    """Single-threaded decode+apply passes: returns (samples, wall
    seconds, cpu seconds, per-stage wall seconds) from the FASTEST pass
    (scheduler noise only ever slows a run down). Single thread IS the
    per-worker number — the receiver scales it by the decode pool."""
    best = None
    for _ in range(repeats):
        apply_batch = mk_apply()
        stages = {"decompress": 0.0, "decode": 0.0, "apply": 0.0}
        total = 0
        c0 = time.process_time()
        w0 = time.perf_counter()
        for body in bodies:
            entries, stage_secs = decode(body)
            for k, v in stage_secs.items():
                stages[k] += v
            t0 = time.perf_counter()
            total += sum(apply_batch(entries))
            stages["apply"] += time.perf_counter() - t0
        wall = time.perf_counter() - w0
        cpu = time.process_time() - c0
        if best is None or wall < best[1]:
            best = (
                total,
                wall,
                cpu,
                {k: round(v, 4) for k, v in stages.items()},
            )
    return best


def _dirty_slo(n_series, samples_per_cycle, seconds, pushers=2):
    """Binary pushers at full rate against the REAL receiver with a
    DirtySet wired; a drain thread plays the micro-tick, popping marks
    every 20 ms. Item-closed latency = drain instant minus the
    receiver's arrival stamp — the dirty half of the push→verdict SLO
    at the binary arrival rate."""
    import urllib.request

    from foremast_tpu.ingest import (
        BINARY_CONTENT_TYPE,
        encode_frame,
        stop_ingest_server,
    )
    from foremast_tpu.reactive.dirty import DirtySet

    ring = RingStore(budget_bytes=1 << 30, shards=16)
    dirty = DirtySet(max_keys=1 << 20)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1", dirty=dirty)
    port = srv.server_address[1]
    stop = threading.Event()
    pushed = [0] * pushers
    base = int(NOW)

    def pusher(idx):
        keys = [
            f"slo:series{{app=\"app{idx}_{s}\",namespace=\"slo\"}}"
            for s in range(n_series)
        ]
        cycle = 0
        while not stop.is_set():
            t0 = base + cycle * samples_per_cycle * 60
            ts = t0 + 60 * np.arange(samples_per_cycle, dtype=np.int64)
            vs = np.full(samples_per_cycle, 1.0 + cycle, np.float32)
            frame = encode_frame([(k, ts, vs, None) for k in keys])
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/write",
                data=frame,
                method="POST",
                headers={"Content-Type": BINARY_CONTENT_TYPE},
            )
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
            pushed[idx] += n_series * samples_per_cycle
            cycle += 1

    threads = [
        threading.Thread(target=pusher, args=(i,), daemon=True)
        for i in range(pushers)
    ]
    latencies = []
    w0 = time.perf_counter()
    for th in threads:
        th.start()
    while time.perf_counter() - w0 < seconds:
        time.sleep(0.02)
        now = time.time()
        for _key, stamp in dirty.take_all():
            latencies.append(now - stamp)
    stop.set()
    for th in threads:
        th.join(timeout=10)
    elapsed = time.perf_counter() - w0
    now = time.time()
    for _key, stamp in dirty.take_all():
        latencies.append(now - stamp)
    stop_ingest_server(srv)
    total = sum(pushed)
    wire = srv._foremast_wire_stats.snapshot()
    return {
        "arrival_samples_per_sec": round(total / elapsed),
        "items_closed": len(latencies),
        "p50_close_seconds": round(float(np.percentile(latencies, 50)), 4),
        "p99_close_seconds": round(float(np.percentile(latencies, 99)), 4),
        "receiver_stage_seconds": {
            codec: c["stage_seconds"] for codec, c in wire.items()
        },
    }


def run_wire(n_series, samples, batch_series, small) -> dict:
    """The wire-protocol phase (ISSUE 18): single-threaded decode+apply
    throughput per codec with the stage breakdown, the equal-CPU
    speedup, and the dirty-set SLO under binary push load."""
    from foremast_tpu.ingest import (
        decode_frame,
        parse_push,
        snappy_compress,
        snappy_decompress,
    )

    json_bodies, frames, _ = _wire_fixture(n_series, samples, batch_series)
    snappy_frames = [snappy_compress(f) for f in frames]
    intern: dict = {}

    def dec_json(body):
        t0 = time.perf_counter()
        entries = parse_push(json.loads(body))
        return entries, {"decode": time.perf_counter() - t0}

    def dec_bin(body):
        t0 = time.perf_counter()
        entries = decode_frame(body, intern, canonicalize=True)
        return entries, {"decode": time.perf_counter() - t0}

    def dec_bin_snappy(body):
        t0 = time.perf_counter()
        raw = snappy_decompress(body)
        t1 = time.perf_counter()
        entries = decode_frame(raw, intern, canonicalize=True)
        return entries, {
            "decompress": t1 - t0,
            "decode": time.perf_counter() - t1,
        }

    def fresh_apply(canonical):
        def mk():
            store = RingStore(budget_bytes=1 << 30, shards=16)
            return lambda entries: store.push_batch(
                entries, record_lag=False, canonical=canonical
            )

        return mk

    # interning warm pass (first frame pays utf-8+canonicalize per key,
    # exactly like a pusher's first frame) is part of the measured loop
    results = {}
    for name, bodies, dec, canonical in (
        ("json", json_bodies, dec_json, False),
        ("binary", frames, dec_bin, True),
        ("binary_snappy", snappy_frames, dec_bin_snappy, True),
    ):
        total, wall, cpu, stages = _measure_codec(
            bodies, dec, fresh_apply(canonical)
        )
        results[name] = {
            "samples": total,
            "wall_seconds": round(wall, 4),
            "cpu_seconds": round(cpu, 4),
            "samples_per_sec": round(total / wall) if wall else None,
            "samples_per_cpu_sec": round(total / cpu) if cpu else None,
            "stage_seconds": stages,
        }
    assert (
        results["json"]["samples"]
        == results["binary"]["samples"]
        == results["binary_snappy"]["samples"]
    ), "codecs accepted different sample counts from the same fixture"
    speedup = round(
        results["binary"]["samples_per_cpu_sec"]
        / results["json"]["samples_per_cpu_sec"],
        2,
    )
    slo = _dirty_slo(
        n_series=64 if small else 1024,
        samples_per_cycle=8 if small else 64,
        seconds=1.0 if small else 4.0,
    )
    out = {
        "config": "i-ingest-wire-codec",
        "series": n_series,
        "samples_per_series": samples,
        "batch_series": batch_series,
        "total_samples": results["binary"]["samples"],
        "codecs": results,
        "codec_speedup_equal_cpu": speedup,
        "dirty_slo": slo,
        "metric": "binary_samples_per_sec_per_worker",
        "value": results["binary"]["samples_per_sec"],
        "unit": "samples/s",
    }
    if not small:
        assert results["binary"]["samples_per_sec"] >= 5_000_000, (
            f"binary path {results['binary']['samples_per_sec']} < 5M "
            "samples/s per worker"
        )
        assert speedup >= 6.0, f"equal-CPU speedup {speedup} < 6x JSON"
        assert slo["p99_close_seconds"] <= 0.5, (
            f"dirty-set item-closed p99 {slo['p99_close_seconds']} > 0.5 s "
            "at the binary arrival rate"
        )
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=4096)
    ap.add_argument("--aliases", type=int, default=4)
    ap.add_argument("--hist-len", type=int, default=512)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = ap.parse_args(argv)
    if args.small:
        args.services = min(args.services, 24)
        args.aliases = min(args.aliases, 2)
        args.hist_len = min(args.hist_len, 128)
    # wire-protocol phase FIRST: the warm-fetch line stays the last
    # line printed (test_ingest_bench_small_smoke reads stdout[-1])
    wire_result = run_wire(
        n_series=256 if args.small else 4096,
        samples=64 if args.small else 512,
        batch_series=64 if args.small else 256,
        small=args.small,
    )
    print(json.dumps(wire_result), flush=True)
    result = run(args.services, args.aliases, args.hist_len, args.cur_len)
    print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary("ingest", result, small=args.small)
    write_summary("ingest_wire", wire_result, small=args.small)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
