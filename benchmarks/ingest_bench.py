"""Ingest-plane benchmark: warm RingSource vs PrometheusSource over HTTP.

`pipeline_bench` measures how much of the fetch stage OVERLAP can hide;
this benchmark measures how much of it the push plane ELIMINATES. Same
fleet, same samples, two workers:

  * pull — `PrometheusSource` against a real localhost HTTP server
    speaking the query_range JSON matrix protocol (socket + JSON parse
    per window, the reference brain's per-tick cost floor);
  * push — `RingSource` over a ring warmed through the remote-write
    receiver (the full wire path: JSON POST -> shard push), with the
    SAME PrometheusSource wrapped as cold-miss fallback.

Both run one cold tick (fits) and one measured warm tick; the measured
number is the tick's `metric_fetch` stage seconds from the span
pipeline. The benchmark itself asserts (a) statuses + anomaly payloads
byte-identical between the two stores and (b) the fake Prometheus
served ZERO requests during the push worker's ticks — the ISSUE 5
acceptance bar, alongside the >= 5x fetch-stage speedup.

Usage: python -m benchmarks.ingest_bench [--services N] [--aliases F]
       [--hist-len H] [--small]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.parse

import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.ingest import RingStore, RingSource, start_ingest_server
from foremast_tpu.ingest.wire import canonical_series
from foremast_tpu.jobs.models import Document
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.metrics.source import PrometheusSource

NOW = 1_760_000_000.0


class FakePrometheus:
    """Localhost query_range endpoint over a samples dict — real
    sockets, real JSON, per-request slicing; counts every request."""

    def __init__(self):
        self.data: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self.requests = 0
        self._lock = threading.Lock()
        self._srv = None

    def start(self) -> str:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                with fake._lock:
                    fake.requests += 1
                qs = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                key = canonical_series(qs.get("query", [""])[0])
                t0 = float(qs.get("start", ["0"])[0])
                t1 = float(qs.get("end", ["0"])[0])
                t, v = fake.data.get(
                    key, (np.zeros(0, np.int64), np.zeros(0, np.float32))
                )
                lo = int(np.searchsorted(t, t0, side="left"))
                hi = int(np.searchsorted(t, t1, side="right"))
                body = json.dumps(
                    {
                        "status": "success",
                        "data": {
                            "result": [
                                {
                                    "values": [
                                        [int(ts), str(float(val))]
                                        for ts, val in zip(
                                            t[lo:hi], v[lo:hi]
                                        )
                                    ]
                                }
                            ]
                        },
                    }
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(
            target=self._srv.serve_forever, daemon=True
        ).start()
        return f"http://127.0.0.1:{self._srv.server_address[1]}/api/v1/"

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()


def build_fleet(services, aliases, hist_len, cur_len, endpoint, fake, seed=0):
    """Continuous-strategy docs: current + historical windows over the
    same app series (metricsquery.go shape), one series per alias."""
    rng = np.random.default_rng(seed)
    store = InMemoryStore()
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(hist_len, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    names = ("latency", "error5xx", "tps", "cpu")[:aliases]
    for s in range(services):
        cur_parts, hist_parts = [], []
        for a in names:
            expr = (
                f"namespace_app_per_pod:{a}"
                f'{{namespace="bench",app="app{s}"}}'
            )
            key = canonical_series(expr)
            if key not in fake.data:
                hv = rng.normal(1.0, 0.1, hist_len).astype(np.float32)
                cv = (
                    1.0 + 0.05 * np.sin(np.arange(cur_len) / 3.0)
                ).astype(np.float32)
                fake.data[key] = (
                    np.concatenate([ht, ct]),
                    np.concatenate([hv, cv]),
                )
            cur_parts.append(
                f"{a}== "
                + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ct[0]), "end": int(ct[-1]), "step": 60}
                )
            )
            hist_parts.append(
                f"{a}== "
                + prometheus_url(
                    {"endpoint": endpoint, "query": expr,
                     "start": int(ht[0]), "end": int(ht[-1]), "step": 60}
                )
            )
        store.create(
            Document(
                id=f"job-{s}",
                app_name=f"app{s}",
                end_time=end_time,
                current_config=" ||".join(cur_parts),
                historical_config=" ||".join(hist_parts),
                strategy="continuous",
            )
        )
    return store


def _warm_ring_via_receiver(fake, batch=256):
    """Ring warmed through the real wire: remote-write JSON POSTs."""
    import urllib.request

    ring = RingStore.from_env()
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    port = srv.server_address[1]
    items = list(fake.data.items())
    try:
        for i in range(0, len(items), batch):
            body = json.dumps(
                {
                    "timeseries": [
                        {
                            "alias": key,
                            "times": t.tolist(),
                            "values": [float(x) for x in v],
                            "start": float(t[0]),
                        }
                        for key, (t, v) in items[i : i + batch]
                    ]
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/write",
                data=body,
                method="POST",
            )
            resp = urllib.request.urlopen(req)
            assert resp.status == 200
    finally:
        srv.shutdown()
    return ring


def _mk_worker(store, source, services, aliases, tracer):
    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_cache_size=aliases * services + 64,
    )
    return BrainWorker(
        store, source, config=cfg, claim_limit=services,
        worker_id="ingest-bench", tracer=tracer,
    )


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def _phase(store, source, services, aliases):
    """Cold tick (fits) + measured warm tick; returns (fetch_seconds,
    warm_tick_seconds, statuses)."""
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.spans import Tracer

    tracer = Tracer(service="ingest-bench", registry=CollectorRegistry(),
                    trace_dir=None)
    worker = _mk_worker(store, source, services, aliases, tracer)
    n = worker.tick(now=NOW + 150)
    assert n == services, f"claimed {n} != {services}"
    t0 = time.perf_counter()
    n = worker.tick(now=NOW + 300)
    warm_s = time.perf_counter() - t0
    assert n == services
    fetch_s = tracer.last_stage_seconds.get("metric_fetch", 0.0)
    statuses = _statuses(store)
    worker.close()
    return fetch_s, warm_s, statuses


def run(services: int, aliases: int, hist_len: int, cur_len: int) -> dict:
    fake = FakePrometheus()
    endpoint = fake.start()
    try:
        pull_store = build_fleet(
            services, aliases, hist_len, cur_len, endpoint, fake
        )
        push_store = build_fleet(
            services, aliases, hist_len, cur_len, endpoint, fake
        )
        pull_fetch_s, pull_warm_s, pull_out = _phase(
            pull_store, PrometheusSource(), services, aliases
        )
        ring = _warm_ring_via_receiver(fake)
        # let pull-phase stragglers (handler threads still draining a
        # late keep-alive connection) finish before snapshotting the
        # request counter the zero-HTTP assertion reads
        time.sleep(1.0)
        reqs_before = fake.requests
        source = RingSource(ring, fallback=PrometheusSource())
        push_fetch_s, push_warm_s, push_out = _phase(
            push_store, source, services, aliases
        )
        zero_http = fake.requests == reqs_before
        assert push_out == pull_out, (
            "push-path judgments diverged from the pull path"
        )
        stats = ring.stats()
        return {
            "config": "i-ingest-warm-fetch",
            "services": services,
            "aliases": aliases,
            "windows": services * aliases,
            "hist_len": hist_len,
            "series_resident": stats["series"],
            "ring_bytes": stats["bytes"],
            "pull_fetch_seconds": round(pull_fetch_s, 4),
            "push_fetch_seconds": round(push_fetch_s, 4),
            "pull_warm_tick_seconds": round(pull_warm_s, 4),
            "push_warm_tick_seconds": round(push_warm_s, 4),
            "ring_hit_ratio": stats["hit_ratio"],
            "zero_http_warm_tick": zero_http,
            "equivalent": True,  # asserted above
            "metric": "fetch_stage_speedup",
            "value": (
                round(pull_fetch_s / push_fetch_s, 2)
                if push_fetch_s > 0
                else None
            ),
            "unit": "x",
        }
    finally:
        fake.stop()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=4096)
    ap.add_argument("--aliases", type=int, default=4)
    ap.add_argument("--hist-len", type=int, default=512)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = ap.parse_args(argv)
    if args.small:
        args.services = min(args.services, 24)
        args.aliases = min(args.aliases, 2)
        args.hist_len = min(args.hist_len, 128)
    result = run(args.services, args.aliases, args.hist_len, args.cur_len)
    print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary("ingest", result, small=args.small)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
