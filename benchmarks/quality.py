"""Detector-quality benchmark: point-level anomaly F1 per algorithm.

Three synthetic scenario families probe where each detector should win:

  * flat    — stationary noise + injected spikes (the golden-trace shape);
              every detector should score well.
  * seasonal— strong daily cycle + spikes; the global-mean band must widen
              to cover the cycle, so moving_average_all loses recall or
              precision while holt_winters / seasonal track the cycle.
  * trend   — steady drift + spikes; trendless models mis-center bounds.

Each scenario builds B windows with known injected anomaly points; F1 is
computed over current-window points against ground truth. Usage:

    python -m benchmarks.quality [--small]

One JSON line per (scenario, algorithm).
"""

from __future__ import annotations

import argparse
import json

import jax.numpy as jnp
import numpy as np

from functools import partial

from benchmarks import prf1
from foremast_tpu.engine import scoring
from foremast_tpu.ops.windows import MetricWindows

ALGORITHMS = (
    "moving_average_all",
    "ewma",
    "double_exponential_smoothing",
    "holt_winters",
    "seasonal_p24",
)

# One cycle of the synthetic season, in time steps. This deliberately
# matches fit_holt_winters' default season_length=24 (ops/forecasters.py)
# — scoring.score calls registry entries as fit(values, mask), so HW can
# only track the cycle its default expects; if that default changes, this
# constant (and the HW rows of the results table) must move with it.
PERIOD = 24


def _register_models() -> None:
    """Register the period-matched seasonal variant (deployment config in
    production — default period is 1440, daily at the 60 s step). Called
    from entry points, NOT at import: a benchmark module must not mutate
    the engine's model registry as an import side effect."""
    from foremast_tpu.models.seasonal import fit_seasonal

    scoring.register_model("seasonal_p24", partial(fit_seasonal, period=PERIOD))

SPIKE_SIGMA = 8.0  # injected spike size in noise-sigmas
NOISE = 0.05
SEASON_AMP = 0.5  # seasonal swing: 10x the noise -> dominates a global band
TREND_PER_STEP = 0.002


def gen(kind: str, b: int, th: int, tc: int, seed: int = 0):
    """(hist [B,Th], cur [B,Tc], truth [B,Tc] bool)."""
    rng = np.random.default_rng(seed)
    t_hist = np.arange(th)[None, :]
    t_cur = (th + np.arange(tc))[None, :]

    def signal(t):
        if kind == "flat":
            return 1.0 + 0.0 * t
        if kind == "seasonal":
            return 1.0 + SEASON_AMP * np.sin(2 * np.pi * t / PERIOD)
        if kind == "trend":
            return 1.0 + TREND_PER_STEP * t
        raise ValueError(kind)

    hist = signal(t_hist) + rng.normal(0, NOISE, (b, th))
    cur = signal(t_cur) + rng.normal(0, NOISE, (b, tc))
    truth = np.zeros((b, tc), bool)
    for i in range(b):
        idx = rng.choice(tc, size=2, replace=False)
        cur[i, idx] += SPIKE_SIGMA * NOISE
        truth[i, idx] = True
    return hist.astype(np.float32), cur.astype(np.float32), truth


def make_batch(hist: np.ndarray, cur: np.ndarray) -> scoring.ScoreBatch:
    b = hist.shape[0]

    def win(v):
        return MetricWindows(
            values=jnp.asarray(v),
            mask=jnp.ones(v.shape, bool),
            times=jnp.zeros(v.shape, jnp.int32),
        )

    return scoring.ScoreBatch(
        historical=win(hist),
        current=win(cur),
        baseline=MetricWindows(
            values=jnp.zeros_like(jnp.asarray(cur)),
            mask=jnp.zeros(cur.shape, bool),
            times=jnp.zeros(cur.shape, jnp.int32),
        ),
        threshold=jnp.full((b,), 4.0, jnp.float32),
        bound=jnp.full((b,), 1, jnp.int32),  # upper: spikes are positive
        min_lower_bound=jnp.zeros((b,), jnp.float32),
        min_points=jnp.full((b,), 10, jnp.int32),
    )


def score_algorithm(batch, truth: np.ndarray, algorithm: str):
    _register_models()  # idempotent: any entry point may call first
    res = scoring.score(batch, algorithm=algorithm)
    flags = np.asarray(res.anomalies)
    tp = int((flags & truth).sum())
    fp = int((flags & ~truth).sum())
    fn = int((~flags & truth).sum())
    precision, recall, f1 = prf1(tp, fp, fn)
    return f1, precision, recall


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args(argv)
    _register_models()
    b = 32 if args.small else 256
    th = 240 if args.small else 1008  # ~10-42 cycles of the 24-step season
    tc = 30
    for kind in ("flat", "seasonal", "trend"):
        # one draw + one batch per scenario: every algorithm judges the
        # exact same arrays
        hist, cur, truth = gen(kind, b, th, tc)
        batch = make_batch(hist, cur)
        for algo in ALGORITHMS:
            f1, p, r = score_algorithm(batch, truth, algo)
            print(
                json.dumps(
                    {
                        "scenario": kind,
                        "algorithm": algo,
                        "f1": round(f1, 3),
                        "precision": round(p, 3),
                        "recall": round(r, 3),
                    }
                ),
                flush=True,
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
