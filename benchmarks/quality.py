"""Detector-quality benchmark: point-level anomaly F1 per algorithm.

Synthetic scenario families probe where each detector should win:

  * flat    — stationary noise + injected spikes (the golden-trace shape);
              every detector should score well.
  * seasonal— strong cycle + spikes; the global-mean band must widen
              to cover the cycle, so moving_average_all loses recall or
              precision while holt_winters / seasonal track the cycle.
  * trend   — steady drift + spikes; trendless models mis-center bounds.
  * shift   — mid-history level step; global-trend fits mis-center the
              band (the changepoint trend localizes it).
  * daily-1440 / daily-1440-sharp — the reference's real workload shape
    (m=1440 at the 60 s step over the 7-day history), smooth and
    cron-burst variants.
  * joint scenarios + clean-window job-level false alarms for the
    multivariate hybrid.

Each scenario builds B windows with known injected anomaly points; F1 is
computed over current-window points against ground truth. Usage:

    python -m benchmarks.quality [--small]

One JSON line per (scenario, algorithm).
"""

from __future__ import annotations

import argparse
import json

import jax.numpy as jnp
import numpy as np

from functools import partial

from benchmarks import prf1
from foremast_tpu.engine import scoring
from foremast_tpu.ops.windows import MetricWindows

ALGORITHMS = (
    "moving_average_all",
    "ewma",
    "double_exponential_smoothing",
    "holt_winters",
    "seasonal_p24",
    "auto_univariate",
)

# One cycle of the compact synthetic season, in time steps — matches
# fit_holt_winters' signature default season_length=24 so the bare
# registry call tracks it. The DAILY scenario measures the reference's
# real workload shape instead: m=1440 at the 60 s PromQL step
# (`metricsquery.go:43`) over the full 7-day 10,080-pt history, threaded
# through scoring.score(..., season_length=1440).
PERIOD = 24
PERIOD_DAILY = 1440
TH_DAILY = 10_080


def _register_models() -> None:
    """Register the period-matched seasonal variant (deployment config in
    production — default period is 1440, daily at the 60 s step). Called
    from entry points, NOT at import: a benchmark module must not mutate
    the engine's model registry as an import side effect."""
    from foremast_tpu.models.seasonal import fit_seasonal

    scoring.register_model("seasonal_p24", partial(fit_seasonal, period=PERIOD))

SPIKE_SIGMA = 8.0  # injected spike size in noise-sigmas
NOISE = 0.05
SEASON_AMP = 0.5  # seasonal swing: 10x the noise -> dominates a global band
TREND_PER_STEP = 0.002
SHIFT_LEVEL = 0.5  # mid-history step (a redeploy / traffic migration)
SHIFT_FRAC = 0.55  # shift position as a fraction of the history


def gen(kind: str, b: int, th: int, tc: int, seed: int = 0, period: int = PERIOD):
    """(hist [B,Th], cur [B,Tc], truth [B,Tc] bool)."""
    rng = np.random.default_rng(seed)
    t_hist = np.arange(th)[None, :]
    t_cur = (th + np.arange(tc))[None, :]

    def signal(t):
        if kind == "flat":
            return 1.0 + 0.0 * t
        if kind == "seasonal":
            return 1.0 + SEASON_AMP * np.sin(2 * np.pi * t / period)
        if kind == "sharp-seasonal":
            # a cron-style burst: 10 steps of every cycle sit 10x the
            # noise above the base — unrepresentable by low-order
            # Fourier, exactly what the pooled phase-means fit carries
            return 1.0 + SEASON_AMP * (
                (t % period) < max(10, period // 144)
            ).astype(float)
        if kind == "trend":
            return 1.0 + TREND_PER_STEP * t
        if kind == "shift":
            # seasonal series with a mid-history LEVEL SHIFT: a global
            # linear trend fits a bogus slope through the step and
            # mis-centers the horizon band; the changepoint trend
            # (models/seasonal.py hinges) localizes it
            return (
                1.0
                + SEASON_AMP * np.sin(2 * np.pi * t / period)
                + SHIFT_LEVEL * (t >= SHIFT_FRAC * th)
            )
        raise ValueError(kind)

    hist = signal(t_hist) + rng.normal(0, NOISE, (b, th))
    cur = signal(t_cur) + rng.normal(0, NOISE, (b, tc))
    truth = np.zeros((b, tc), bool)
    for i in range(b):
        idx = rng.choice(tc, size=2, replace=False)
        cur[i, idx] += SPIKE_SIGMA * NOISE
        truth[i, idx] = True
    return hist.astype(np.float32), cur.astype(np.float32), truth


def make_batch(hist: np.ndarray, cur: np.ndarray) -> scoring.ScoreBatch:
    b = hist.shape[0]

    def win(v):
        return MetricWindows(
            values=jnp.asarray(v),
            mask=jnp.ones(v.shape, bool),
            times=jnp.zeros(v.shape, jnp.int32),
        )

    return scoring.ScoreBatch(
        historical=win(hist),
        current=win(cur),
        baseline=MetricWindows(
            values=jnp.zeros_like(jnp.asarray(cur)),
            mask=jnp.zeros(cur.shape, bool),
            times=jnp.zeros(cur.shape, jnp.int32),
        ),
        threshold=jnp.full((b,), 4.0, jnp.float32),
        bound=jnp.full((b,), 1, jnp.int32),  # upper: spikes are positive
        min_lower_bound=jnp.zeros((b,), jnp.float32),
        min_points=jnp.full((b,), 10, jnp.int32),
    )


def _prf_from_flags(flags: np.ndarray, truth: np.ndarray):
    """(precision, recall, f1) from point flags vs ground truth."""
    tp = int((flags & truth).sum())
    fp = int((flags & ~truth).sum())
    fn = int((~flags & truth).sum())
    return prf1(tp, fp, fn)


def score_algorithm(batch, truth: np.ndarray, algorithm: str, season_length: int = 24):
    _register_models()  # idempotent: any entry point may call first
    res = scoring.score(batch, algorithm=algorithm, season_length=season_length)
    precision, recall, f1 = _prf_from_flags(np.asarray(res.anomalies), truth)
    return f1, precision, recall


# -- joint (multivariate) scenarios -----------------------------------------


def _joint_tasks(metrics: np.ndarray, cur: np.ndarray, job_prefix: str):
    """[B, F, Th] hist + [B, F, Tc] cur -> flat MetricTask list."""
    from foremast_tpu.engine.judge import MetricTask

    b, f, th = metrics.shape
    tc = cur.shape[-1]
    t0 = 1_700_000_000
    ht = t0 + 60 * np.arange(th, dtype=np.int64)
    ct = t0 + 60 * (th + np.arange(tc, dtype=np.int64))
    tasks = []
    for i in range(b):
        for j in range(f):
            tasks.append(
                MetricTask(
                    job_id=f"{job_prefix}{i}",
                    alias=f"m{j}",
                    metric_type=None,
                    hist_times=ht,
                    hist_values=metrics[i, j],
                    cur_times=ct,
                    cur_values=cur[i, j],
                    app=f"{job_prefix}{i}",
                )
            )
    return tasks, ct


def draw_comoving(rng, b: int, f: int, n: int, t_start: int, period: int = PERIOD):
    """[B, F, n] co-moving seasonal metrics: shared latent (sine + noise)
    plus per-metric offset and idiosyncratic noise. Shared between the
    joint benchmark scenarios and the residual-MVN unit tests so both
    always validate the same data family."""
    t = (t_start + np.arange(n))[None, :]
    latent = 0.3 * np.sin(2 * np.pi * t / period) + rng.normal(0, 0.05, (b, n))
    return np.stack(
        [1.0 + 0.5 * j + latent + rng.normal(0, 0.05, (b, n)) for j in range(f)],
        axis=1,
    ).astype(np.float32)


def gen_correlated_pair(b: int, th: int, tc: int, seed: int = 0):
    """2 tightly-correlated metrics; anomalies are OFF-RIDGE points whose
    marginals stay in range — only a joint model can see them."""
    rng = np.random.default_rng(seed)
    rho = 0.95

    def draw(n):
        x = rng.normal(0, 1, (b, n))
        y = rho * x + np.sqrt(1 - rho * rho) * rng.normal(0, 1, (b, n))
        return 1.0 + 0.2 * x, 2.0 + 0.3 * y

    hx, hy = draw(th)
    cx, cy = draw(tc)
    truth = np.zeros((b, tc), bool)
    for i in range(b):
        idx = rng.choice(tc, size=2, replace=False)
        # break the ridge: push x up, y down by ~2.5 marginal sigmas
        cx[i, idx] += 2.5 * 0.2
        cy[i, idx] -= 2.5 * 0.3
        truth[i, idx] = True
    hist = np.stack([hx, hy], axis=1).astype(np.float32)
    cur = np.stack([cx, cy], axis=1).astype(np.float32)
    return hist, cur, truth


def gen_joint_lstm(b: int, f: int, th: int, tc: int, seed: int = 0, kind="all"):
    """f co-moving seasonal metrics (shared latent + idiosyncratic noise).

    kind="all":   simultaneous all-metric spikes (+0.6, ~8 idio-sigmas) at
                  random positions INCLUDING seasonal troughs — there the
                  spiked value lands near the marginal mean, so only a
                  phase-aware (contextual) model can see it.
    kind="break": ONE metric deviates +/-0.6 while the others follow the
                  shared latent — a correlation break that is invisible
                  marginally and to per-metric models.
    """
    rng = np.random.default_rng(seed)
    hist = draw_comoving(rng, b, f, th, 0)
    cur = draw_comoving(rng, b, f, tc, th)
    truth = np.zeros((b, tc), bool)
    for i in range(b):
        idx = rng.choice(tc, size=2, replace=False)
        if kind == "all":
            cur[i, :, idx] += 0.6
        else:
            for pos in idx:
                j = rng.integers(0, f)
                cur[i, j, pos] += rng.choice([-1.0, 1.0]) * 0.6
        truth[i, idx] = True
    return hist.astype(np.float32), cur.astype(np.float32), truth


def score_joint(kind: str, b: int, th: int, tc: int):
    """F1 for the joint detectors through MultivariateJudge (the shipped
    dispatch), point-level over aligned current timestamps."""
    import dataclasses

    from foremast_tpu.config import BrainConfig
    from foremast_tpu.engine.multivariate import MultivariateJudge

    if kind == "bivariate":
        hist, cur, truth = gen_correlated_pair(b, th, tc)
        algo = "bivariate_normal"
    else:
        hist, cur, truth = gen_joint_lstm(
            b, 4, th, tc, kind="break" if kind == "lstm-break" else "all"
        )
        algo = "lstm_autoencoder"
    tasks, ct = _joint_tasks(hist, cur, kind)
    # season_steps pinned to the synthetic cycle these scenarios draw
    # (draw_comoving, period=PERIOD); the deployed default is daily 1440
    cfg = BrainConfig(algorithm=algo, season_steps=PERIOD)
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0, rules=())
    )
    judge = MultivariateJudge(cfg)
    verdicts = judge.judge(tasks)
    flagged: dict[str, set] = {}
    for v in verdicts:
        flagged.setdefault(v.job_id, set()).update(v.anomaly_pairs[0::2])
    tp = fp = fn = 0
    for i in range(b):
        got = flagged.get(f"{kind}{i}", set())
        want = {float(t) for t, is_a in zip(ct, truth[i]) if is_a}
        tp += len(got & want)
        fp += len(got - want)
        fn += len(want - got)
    return prf1(tp, fp, fn)


def fleet_mix(b: int, th: int, tc: int, seed: int = 0):
    """ONE batch mixing every univariate shape — the production
    condition: `auto_univariate` must route each series to its model
    inside a single compiled program, with no per-batch tuning. Returns
    (f1, precision, recall) over the whole mixed fleet plus the
    per-kind F1 dict."""
    _register_models()
    kinds = ("flat", "seasonal", "trend", "shift", "sharp-seasonal")
    per = max(b // len(kinds), 1)
    hists, curs, truths = [], [], []
    for j, kind in enumerate(kinds):
        h, c, tr = gen(kind, per, th, tc, seed=seed + j)
        hists.append(h)
        curs.append(c)
        truths.append(tr)
    truth = np.concatenate(truths)
    batch = make_batch(np.concatenate(hists), np.concatenate(curs))
    res = scoring.score(batch, algorithm="auto_univariate", season_length=PERIOD)
    flags = np.asarray(res.anomalies)
    precision, recall, f1 = _prf_from_flags(flags, truth)
    by_kind = {}
    for j, kind in enumerate(kinds):
        sl = slice(j * per, (j + 1) * per)
        _, _, kf1 = _prf_from_flags(flags[sl], truth[sl])
        by_kind[kind] = round(kf1, 3)
    return f1, precision, recall, by_kind


def joint_clean_false_alarms(b: int, th: int, tc: int) -> tuple[int, int]:
    """Job-level false alarms on CLEAN joint windows (no injected
    anomalies): how many of `b` healthy deployments the joint hybrid
    detector would mark Unhealthy. Fail-fast + AutoRollback semantics
    (design.md:43) turn every falsely-flagged job into a potential
    rollback, so this is the metric that prices the detector's tail —
    point precision alone hides it. Returns (false_alarm_jobs, jobs)."""
    import dataclasses

    from foremast_tpu.config import BrainConfig
    from foremast_tpu.engine import scoring as engine_scoring
    from foremast_tpu.engine.multivariate import MultivariateJudge

    rng = np.random.default_rng(7)
    hist = draw_comoving(rng, b, 4, th, 0)
    cur = draw_comoving(rng, b, 4, tc, th)
    tasks, _ = _joint_tasks(hist, cur, "clean")
    cfg = BrainConfig(algorithm="lstm_autoencoder", season_steps=PERIOD)
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0, rules=())
    )
    verdicts = MultivariateJudge(cfg).judge(tasks)
    bad_jobs = {
        v.job_id for v in verdicts if v.verdict == engine_scoring.UNHEALTHY
    }
    return len(bad_jobs), b


JOINT_SCENARIOS = ("bivariate", "lstm", "lstm-break")


# -- mixed univariate + joint WORKER tick (VERDICT r4 #5) --------------------


def _unspike(cur: np.ndarray, truth: np.ndarray, kind: str) -> np.ndarray:
    """Exact clean twin of a generated current window: the injection
    constants are known, so subtracting them at the truth positions
    reconstructs the pre-spike draw bit for bit."""
    clean = cur.copy()
    if kind == "bivariate":
        # gen_correlated_pair: x +2.5*0.2, y -2.5*0.3 at truth
        for i in range(cur.shape[0]):
            clean[i, 0, truth[i]] -= 2.5 * 0.2
            clean[i, 1, truth[i]] += 2.5 * 0.3
    elif kind == "lstm":
        for i in range(cur.shape[0]):
            clean[i, :, truth[i]] -= 0.6
    else:  # univariate kinds ([B, 1, Tc]): SPIKE_SIGMA * NOISE at truth
        view = clean[:, 0, :]
        view[truth] -= SPIKE_SIGMA * NOISE
    return clean


def mixed_fleet_tick(per_uni: int, per_joint: int, th: int, tc: int,
                     seed: int = 0):
    """One WORKER claim set mixing every univariate shape AND joint jobs.

    The production condition no prior round tested: a single
    `BrainWorker.tick` under the `auto` multivariate selector carries
    single-alias docs (routed to the univariate fallback — and, when
    warm, the columnar fast path) NEXT TO 2-alias bivariate and 4-alias
    LSTM-hybrid docs (routed to joint models on the slow path).

    Tick 1 runs CLEAN currents (everything healthy — fits + model
    caches warm up); anomalies are then injected into the current
    windows and tick 2 judges the whole mixed fleet warm. Per-kind
    point F1 is computed from the persisted anomaly_info, and one clean
    doc per kind must stay healthy through both ticks (the
    cross-contamination guard). Returns {kind: (f1, n_docs)} plus the
    false-alarm count."""
    import dataclasses

    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.models import (
        STATUS_COMPLETED_UNHEALTH,
        STATUS_PREPROCESS_COMPLETED,
        Document,
    )
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import MetricSource

    _register_models()

    class _Src(MetricSource):
        concurrent_fetch = False

        def __init__(self):
            self.data = {}

        def fetch(self, url):
            return self.data[url]

    store, source = InMemoryStore(), _Src()
    t0 = 1_700_000_000
    ht = t0 + 60 * np.arange(th, dtype=np.int64)
    ct = t0 + 60 * (th + np.arange(tc, dtype=np.int64))
    now = float(ct[-1]) + 600.0  # hist settled, endTime still ahead
    end_time = str(int(now) + 3600)

    uni_kinds = ("flat", "seasonal", "trend", "shift", "sharp-seasonal")
    fleets = {}  # kind -> (cur_clean [B,F,Tc], cur_spiked, truth [B,Tc])
    for j, kind in enumerate(uni_kinds):
        h, c, tr = gen(kind, per_uni + 1, th, tc, seed=seed + j)
        fleets[kind] = (h[:, None, :], c[:, None, :], tr)
    hb, cb, trb = gen_correlated_pair(per_joint + 1, th, tc, seed=seed + 7)
    fleets["bivariate"] = (hb, cb, trb)
    hl, cl, trl = gen_joint_lstm(per_joint + 1, 4, th, tc, seed=seed + 8)
    fleets["lstm"] = (hl, cl, trl)

    doc_kind = {}
    doc_truth = {}
    clean_docs = set()
    for kind, (hist, cur, truth) in fleets.items():
        b, f, _ = hist.shape
        clean = _unspike(
            cur, truth,
            kind if kind in ("bivariate", "lstm") else "uni",
        )
        for i in range(b):
            doc_id = f"{kind}-{i}"
            cur_parts, hist_parts = [], []
            for m in range(f):
                cu = f"http://prom/cur?q=m{m}:{doc_id}&step=60"
                hu = (
                    f"http://prom/hist?q=m{m}:{doc_id}"
                    f"&end={int(ht[-1]) + 60}&step=60"
                )
                source.data[cu] = (ct, clean[i, m])
                source.data[hu] = (ht, hist[i, m])
                cur_parts.append(f"m{m}== {cu}")
                hist_parts.append(f"m{m}== {hu}")
            store.create(
                Document(
                    id=doc_id,
                    app_name=doc_id,
                    end_time=end_time,
                    current_config=" ||".join(cur_parts),
                    historical_config=" ||".join(hist_parts),
                    strategy="continuous",
                )
            )
            doc_kind[doc_id] = kind
            if i == b - 1:
                clean_docs.add(doc_id)  # stays clean on tick 2
            else:
                doc_truth[doc_id] = truth[i]

    cfg = BrainConfig(algorithm="auto", season_steps=PERIOD)
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0, rules=())
    )
    n_docs = len(doc_kind)
    worker = BrainWorker(
        store, source, config=cfg, claim_limit=n_docs, worker_id="mix-w"
    )
    assert worker.tick(now=now) == n_docs
    healthy_after_1 = sum(
        1 for d in store._docs.values()
        if d.status == STATUS_PREPROCESS_COMPLETED
    )
    assert healthy_after_1 == n_docs, (
        f"tick 1 must be all-healthy, got {healthy_after_1}/{n_docs}"
    )

    # inject the anomalies for the warm mixed tick
    for kind, (hist, cur, truth) in fleets.items():
        b, f, _ = hist.shape
        for i in range(b - 1):  # last doc per kind stays clean
            doc_id = f"{kind}-{i}"
            for m in range(f):
                cu = f"http://prom/cur?q=m{m}:{doc_id}&step=60"
                source.data[cu] = (ct, cur[i, m])
    assert worker.tick(now=now + 60) == n_docs

    tp = {k: 0 for k in fleets}
    fp = dict(tp)
    fn = dict(tp)
    false_alarms = 0
    for doc_id, kind in doc_kind.items():
        doc = store._docs[doc_id]
        if doc_id in clean_docs:
            if doc.status != STATUS_PREPROCESS_COMPLETED:
                false_alarms += 1
            continue
        truth = doc_truth[doc_id]
        want = {float(t) for t, is_a in zip(ct, truth) if is_a}
        got = set()
        if doc.status == STATUS_COMPLETED_UNHEALTH:
            for pairs in doc.anomaly_info["values"].values():
                got.update(pairs[0::2])
        tp[kind] += len(got & want)
        fp[kind] += len(got - want)
        fn[kind] += len(want - got)
    by_kind = {
        k: (prf1(tp[k], fp[k], fn[k])[2], tp[k] + fn[k]) for k in fleets
    }
    return by_kind, false_alarms


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args(argv)
    _register_models()
    b = 32 if args.small else 256
    th = 240 if args.small else 1008  # ~10-42 cycles of the 24-step season
    tc = 30
    for kind in ("flat", "seasonal", "trend", "shift"):
        # one draw + one batch per scenario: every algorithm judges the
        # exact same arrays
        hist, cur, truth = gen(kind, b, th, tc)
        batch = make_batch(hist, cur)
        for algo in ALGORITHMS:
            f1, p, r = score_algorithm(batch, truth, algo)
            print(
                json.dumps(
                    {
                        "scenario": kind,
                        "algorithm": algo,
                        "f1": round(f1, 3),
                        "precision": round(p, 3),
                        "recall": round(r, 3),
                    }
                ),
                flush=True,
            )
    # The reference's real workload shape: a DAILY cycle (m=1440 at the
    # 60 s step) over the full 7-day history. The global-mean default must
    # swallow the whole cycle in its band; the auto screen must route
    # these series to a pooled structured fit (fit_auto_univariate
    # docstring) and keep point F1 >= 0.99.
    db = 8 if args.small else 128
    for daily_kind, label in (
        ("seasonal", "daily-1440"),
        # sharp cron-style bursts: the pooled phase-means candidate's
        # scenario (low-order Fourier cannot represent the shape)
        ("sharp-seasonal", "daily-1440-sharp"),
    ):
        hist, cur, truth = gen(daily_kind, db, TH_DAILY, tc, period=PERIOD_DAILY)
        batch = make_batch(hist, cur)
        for algo in ("moving_average_all", "auto_univariate", "seasonal", "phase_means"):
            f1, p, r = score_algorithm(batch, truth, algo, season_length=PERIOD_DAILY)
            print(
                json.dumps(
                    {
                        "scenario": label,
                        "algorithm": algo,
                        "f1": round(f1, 3),
                        "precision": round(p, 3),
                        "recall": round(r, 3),
                    }
                ),
                flush=True,
            )
    mf1, mp, mr, by_kind = fleet_mix(b, th, tc)
    print(
        json.dumps(
            {
                "scenario": "fleet-mix",
                "algorithm": "auto_univariate",
                "f1": round(mf1, 3),
                "precision": round(mp, 3),
                "recall": round(mr, 3),
                "per_kind_f1": by_kind,
            }
        ),
        flush=True,
    )
    # mixed WORKER tick: every univariate shape + bivariate + LSTM jobs
    # in ONE claim set under the `auto` selector (VERDICT r4 #5)
    mixed_by_kind, mixed_fa = mixed_fleet_tick(
        4 if args.small else 12,
        3 if args.small else 8,
        th,
        tc,
    )
    print(
        json.dumps(
            {
                "scenario": "mixed-worker-tick",
                "algorithm": "auto",
                "per_kind_f1": {
                    k: round(v[0], 3) for k, v in mixed_by_kind.items()
                },
                "clean_doc_false_alarms": mixed_fa,
            }
        ),
        flush=True,
    )
    jb = 16 if args.small else 64  # LSTM trains one model per job
    fa, n_jobs = joint_clean_false_alarms(jb, th, tc)
    print(
        json.dumps(
            {
                "scenario": "joint-clean-windows",
                "algorithm": "lstm_autoencoder",
                "job_false_alarms": fa,
                "jobs": n_jobs,
                "false_alarms_per_10k_jobs": round(fa / n_jobs * 10_000, 1),
            }
        ),
        flush=True,
    )
    for kind in JOINT_SCENARIOS:
        p, r, f1 = score_joint(kind, jb, th, tc)
        print(
            json.dumps(
                {
                    "scenario": f"joint-{kind}",
                    "algorithm": (
                        "bivariate_normal" if kind == "bivariate"
                        else "lstm_autoencoder"
                    ),
                    "f1": round(f1, 3),
                    "precision": round(p, 3),
                    "recall": round(r, 3),
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
