"""Pallas-vs-XLA judgment re-bench on the bf16-delta regime (VERDICT
r5 #5: "settle the Pallas question").

Measures the four variants of the fused moving_average_all judgment on
identical data — XLA f32 (`scoring._score_xla`), XLA bf16-delta
(`scoring.score_bf16_delta`, the shipped steady-state program), Pallas
f32 (`ops.kernels.ma_judgment`), Pallas bf16-delta
(`ops.kernels.ma_judgment_bf16_delta`, added this round so the kernel
finally speaks the default storage layout) — at the headline shape,
steady-state amortized like bench.py. Off-TPU the Pallas rows run in
INTERPRET mode, which measures the Python interpreter, not a kernel;
they are reported with `interpreted: true` and must not be read as
device numbers. The keep-or-cut decision table lives in BENCHMARKS.md.

Usage: python -m benchmarks.kernels_bench [--small] [--iters N]
One JSON line per variant.
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

from foremast_tpu.engine import scoring
from foremast_tpu.ops import kernels
from foremast_tpu.parallel.batch import throughput_batch


def _time(fn, iters: int) -> float:
    res = fn()
    jax.block_until_ready(res)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            res = fn()
        jax.block_until_ready(res)
        times.append(time.perf_counter() - t0)
    return statistics.median(times) / iters


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--iters", type=int, default=None)
    args = ap.parse_args(argv)
    on_tpu = jax.default_backend() == "tpu"
    b = 1024 if args.small or not on_tpu else 32_768
    th = 512 if args.small or not on_tpu else 10_080
    tc = 30
    iters = args.iters or (20 if on_tpu else 3)

    batch = jax.device_put(throughput_batch(b, th, tc))
    slim, anchor, delta = scoring.make_bf16_delta_batch(batch)
    anchor, delta, slim = jax.device_put((anchor, delta, slim))
    lens = jnp.sum(batch.historical.mask, axis=-1).astype(jnp.int32)
    jax.block_until_ready(delta)

    variants = {
        "xla-f32": lambda: scoring._score_xla(batch).verdict,
        "xla-bf16-delta": lambda: scoring.score_bf16_delta(
            slim, anchor, delta
        ).verdict,
        "pallas-f32": lambda: kernels.ma_judgment(
            batch.historical.values,
            batch.historical.mask,
            batch.current.values,
            batch.current.mask,
            batch.threshold,
            batch.bound,
            batch.min_lower_bound,
            batch.min_points,
        )[0],
        "pallas-bf16-delta": lambda: kernels.ma_judgment_bf16_delta(
            anchor,
            delta,
            lens,
            batch.current.values,
            batch.current.mask,
            batch.threshold,
            batch.bound,
            batch.min_lower_bound,
            batch.min_points,
        )[0],
    }
    for name, fn in variants.items():
        interpreted = name.startswith("pallas") and not on_tpu
        if interpreted and b * th > 1024 * 512:
            continue  # interpreter mode at headline shapes never returns
        sec = _time(fn, iters)
        print(
            json.dumps(
                {
                    "config": "k-ma-judgment",
                    "variant": name,
                    "backend": jax.default_backend(),
                    "interpreted": interpreted,
                    "batch": b,
                    "hist_len": th,
                    "metric": "windows_per_sec",
                    "value": round(b / sec, 1),
                    "unit": "windows/s",
                    "seconds_per_iter": round(sec, 6),
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
