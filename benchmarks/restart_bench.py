"""Crash-injection restart benchmark: SIGKILL a durable worker, restart
it, and MEASURE the warm-restart contract (ISSUE 7).

A parent process serves the shared job store over real HTTP (the
scaleout bench's StoreServer) and runs one worker subprocess with the
full durable data plane mounted — RingSource over a journaled/
snapshotted RingStore plus write-through fit journals, all under one
snapshot directory. Phases:

  cold      first tick: fits + ring backfill (journals written through)
  warm      one measured warm tick (must already be 100% fast-path)
  kill      the worker SIGKILLs itself (os.kill SIGKILL — no cleanup,
            no atexit, no final snapshot) MID-TICK: after its claim
            persisted to the store, before any verdict
  recover   a REPLACEMENT process starts against the SAME snapshot
            directory, restores ring + fits, waits out the stuck-claim
            window, and ticks once

In-run assertions (the acceptance bar, enforced here — not eyeballed):

  * the recovery tick is ≥ 90% fast-path,
  * the fallback source served ZERO fetches during it (the pull path —
    Prometheus in production — was never touched),
  * every document was judged exactly once in the recovery round and
    nothing was judged twice across the kill (ledger),
  * torn on-disk state never crashed the restore (discard counters are
    reported, not hidden).

`--mesh` runs the same scenario with 3 mesh workers: the victim
restarts under its persisted worker id, re-takes its seat (ring
unmoved) and re-judges exactly its own partition warm.

Usage: python -m benchmarks.restart_bench [--services N] [--mesh] [--small]
Prints one JSON line per variant.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KILL_EXIT = -signal.SIGKILL  # Popen.returncode for a SIGKILLed child


# ---------------------------------------------------------------------------
# the worker child
# ---------------------------------------------------------------------------


class _CountingSynth:
    """The would-be pull path (Prometheus in production): counts every
    fetch that reaches it so 'zero fallback fetches' is measured."""

    concurrent_fetch = False

    def __init__(self):
        from benchmarks.scaleout_bench import SynthSource

        self.inner = SynthSource()
        self.calls = 0

    def fetch(self, url):
        self.calls += 1
        return self.inner.fetch(url)


class _SuicideRing:
    """Delegates to the ring source until armed, then SIGKILLs this
    process on the 3rd fetch — mid-tick, after the claim persisted,
    before any verdict. A real SIGKILL: no exception handler, no file
    close, no final snapshot."""

    concurrent_fetch = False

    def __init__(self, inner):
        self.inner = inner
        self.armed = False
        self.calls = 0

    def fetch(self, url):
        if self.armed:
            self.calls += 1
            if self.calls >= 3:
                os.kill(os.getpid(), signal.SIGKILL)
        return self.inner.fetch(url)

    # the ring-first cold path is part of the wrapped surface (ISSUE
    # 10): a production worker sees RingSource's hist reads directly
    def hist_columns(self, url, now=None):
        return self.inner.hist_columns(url, now)

    def hist_coverage(self, url, now=None):
        return self.inner.hist_coverage(url, now)

    def ingest_debug_state(self):
        return self.inner.ingest_debug_state()


def run_child(args) -> int:
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.ingest import RingSnapshotter, RingSource, RingStore
    from foremast_tpu.jobs.worker import BrainWorker
    from benchmarks.scaleout_bench import HttpFleetStore

    worker_id = args.worker_id
    store = HttpFleetStore(args.store_url, worker_id)
    ring = RingStore(shards=4, max_points=args.ring_points)
    snap = RingSnapshotter(
        ring, args.snapshot_dir, interval_seconds=3600.0
    )
    restore_stats = snap.restore()
    snap.attach()
    fallback = _CountingSynth()
    source = _SuicideRing(RingSource(ring, fallback=fallback))

    node = None
    if args.mesh:
        import threading

        from foremast_tpu.mesh import Membership, MeshNode, MeshRouter

        membership = Membership(
            store, worker_id, lease_seconds=args.lease_seconds
        )
        router = MeshRouter(
            membership, refresh_seconds=min(1.0, args.lease_seconds / 4)
        )
        node = MeshNode(membership, router, ring_store=ring)
        node.start()
        # heartbeat on its own store client (requests.Session is not
        # thread-safe; the tick thread owns `store`) — dies with the
        # process, which is what makes lease behavior honest
        hb_store = HttpFleetStore(args.store_url, worker_id)
        hb_membership = Membership(
            hb_store, worker_id, lease_seconds=args.lease_seconds
        )
        hb_membership.join()

        def heartbeat():
            while True:
                time.sleep(args.lease_seconds / 3.0)
                hb_membership.renew(force=True)

        threading.Thread(target=heartbeat, daemon=True).start()

    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_stuck_seconds=args.max_stuck,
        max_cache_size=args.services * args.aliases + 64,
    )
    worker = BrainWorker(
        store, source, config=cfg, claim_limit=args.services,
        worker_id=worker_id, mesh=node,
    )
    worker.enable_fit_persistence(args.snapshot_dir)
    worker.attach_ring_snapshotter(snap)

    def tick(tag: str) -> int:
        store.tag = tag
        fallback.calls = 0
        cold0 = worker._cold_snapshot()
        t0 = time.perf_counter()
        n = worker.tick()
        cold1 = worker._cold_snapshot()
        store.report_tick(
            worker=worker_id, tag=tag, docs=n,
            seconds=round(time.perf_counter() - t0, 4),
            fast=worker._last_tick["fast"],
            fallback_fetches=fallback.calls,
            ring_hist_reads=(
                cold1["ring_full"] + cold1["ring_partial"]
                - cold0["ring_full"] - cold0["ring_partial"]
            ),
            http_hist_reads=(
                cold1["http"] + cold1["cache"]
                - cold0["http"] - cold0["cache"]
            ),
            restored_series=restore_stats["restored_series"],
            restored_fits=sum(
                j.counters["restored_entries"]
                for j in worker._fit_journals.values()
            ),
            discards={
                k: v
                for k, v in restore_stats["discards"].items()
                if v
            },
        )
        return n

    done: set[str] = set()

    def arrive(name: str):
        if name not in done:
            done.add(name)
            store.barrier(name)

    store.barrier("ready")
    while True:
        phase = store.phase()
        if phase == "stop":
            break
        if phase == "cold" and "cold" not in done:
            if tick("cold") > 0:
                arrive("cold")
            continue
        if phase == "warm" and "warm" not in done:
            if tick("warm") > 0:
                snap.snapshot()  # mid-life snapshot; logs cover the rest
                arrive("warm")
            continue
        if phase == "kill" and args.victim:
            source.armed = True
            tick("suicide")  # unreachable past fetch #3
            continue
        if (
            phase == "recover"
            and not args.victim
            and args.recovering
            and "recover" not in done
        ):
            # replacement process: wait out the stuck window, then tick
            if tick("recover") > 0:
                arrive("recover")
            else:
                time.sleep(0.5)
            continue
        if (
            phase == "coldfit"
            and args.coldfit
            and "coldfit" not in done
        ):
            # cold-fit recovery (ISSUE 10 satellite): this process
            # started with the fit journals WIPED — every doc re-fits
            # cold, and the restored ring must serve those fits alone
            if tick("coldfit") > 0:
                arrive("coldfit")
            else:
                time.sleep(0.2)
            continue
        if node is not None:
            node.on_tick()
        time.sleep(0.05)
    if node is not None:
        node.close()
    worker.close()
    snap.close()
    return 0


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------


def _spawn(url, snap_dir, worker_id, args, victim=False, recovering=False,
           coldfit=False):
    cmd = [
        sys.executable, "-m", "benchmarks.restart_bench", "--child",
        "--store-url", url, "--snapshot-dir", snap_dir,
        "--worker-id", worker_id,
        "--services", str(args.services), "--aliases", str(args.aliases),
        "--max-stuck", str(args.max_stuck),
        "--lease-seconds", str(args.lease_seconds),
        "--ring-points", str(args.ring_points),
    ]
    if args.mesh:
        cmd.append("--mesh")
    if victim:
        cmd.append("--victim")
    if recovering:
        cmd.append("--recovering")
    if coldfit:
        cmd.append("--coldfit")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("FOREMAST_INGEST", None)
    log_path = os.path.join(
        tempfile.gettempdir(), f"restart_{worker_id}.log"
    )
    log_fh = open(log_path, "w")
    proc = subprocess.Popen(
        cmd, cwd=REPO, env=env, stdout=log_fh, stderr=subprocess.STDOUT,
        text=True,
    )
    log_fh.close()
    return proc


def _wait(predicate, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        time.sleep(0.05)


def _worker_log(worker_id: str) -> str:
    try:
        with open(
            os.path.join(tempfile.gettempdir(), f"restart_{worker_id}.log")
        ) as fh:
            return fh.read()
    except OSError:
        return ""


def run(args, mesh: bool, timeout: float = 900.0) -> dict:
    from benchmarks.scaleout_bench import StoreServer, build_fleet

    args.mesh = mesh
    n_workers = 3 if mesh else 1
    server = StoreServer()
    url = server.start()
    now = int(time.time())
    build_fleet(
        server.store, args.services, args.aliases, args.hist_len,
        args.cur_len, now,
    )
    snap_root = tempfile.mkdtemp(prefix="restart_bench_")
    dirs = {
        f"w{i}": os.path.join(snap_root, f"w{i}") for i in range(n_workers)
    }
    victim_id = f"w{n_workers - 1}"
    procs = {
        wid: _spawn(url, dirs[wid], wid, args, victim=(wid == victim_id))
        for wid in dirs
    }
    replacement = None
    try:
        _wait(
            lambda: server.barrier_count("ready") == n_workers,
            timeout, "workers to join",
        )
        if mesh:
            time.sleep(1.0)  # routers pick up full membership
        server.phase = "cold"
        _wait(
            lambda: server.barrier_count("cold") == n_workers,
            timeout, "cold ticks",
        )
        owners = server.owner_map() if mesh else {}
        server.phase = "warm"
        _wait(
            lambda: server.barrier_count("warm") == n_workers,
            timeout, "warm ticks",
        )
        warm_reports = [
            r for r in server.tick_reports() if r["tag"] == "warm"
        ]
        for r in warm_reports:
            assert r["fallback_fetches"] == 0, r
            assert r["fast"] == r["docs"], r

        # KILL: the victim SIGKILLs itself mid-tick (claim persisted)
        server.phase = "kill"
        _wait(
            lambda: procs[victim_id].poll() is not None,
            timeout, "victim to die",
        )
        assert procs[victim_id].returncode == KILL_EXIT, (
            procs[victim_id].returncode
        )
        from foremast_tpu.jobs.models import STATUS_PREPROCESS_INPROGRESS

        ledger_at_kill = server.ledger_snapshot()
        parked = {
            doc.id
            for doc in server.store.list_open()
            if doc.status == STATUS_PREPROCESS_INPROGRESS
            and not doc.app_name.startswith("__foremast")
        }
        assert parked, "victim died before persisting any claim"
        if mesh:
            orphans = {d for d, o in owners.items() if o == victim_id}
            assert parked == orphans, (len(parked), len(orphans))

        # RECOVER: replacement process, same snapshot dir + worker id
        t_restart = time.perf_counter()
        replacement = _spawn(
            url, dirs[victim_id], victim_id, args, recovering=True
        )
        server.phase = "recover"
        _wait(
            lambda: server.barrier_count("recover") == 1,
            timeout, "recovery tick",
        )
        recover_wall = time.perf_counter() - t_restart
        # the replacement retries empty ticks until the stuck-claim
        # window elapses; the measured tick is the one that claimed
        rec = next(
            r for r in server.tick_reports()
            if r["tag"] == "recover" and r["docs"] > 0
        )

        # ---- the acceptance bar, asserted in-run ----
        fast_frac = rec["fast"] / max(rec["docs"], 1)
        assert fast_frac >= 0.9, (
            f"recovery tick only {fast_frac:.0%} fast-path: {rec}"
        )
        assert rec["fallback_fetches"] == 0, rec
        assert rec["restored_series"] > 0 and rec["restored_fits"] > 0, rec
        # exactly-once: every parked doc judged once in recovery, and
        # no doc judged twice across the kill boundary
        ledger = server.ledger_snapshot()
        for doc_id in parked:
            entries = [
                e for e in ledger.get(doc_id, ())
                if e[1] == "recover"
            ]
            assert len(entries) == 1, (doc_id, entries)
            assert entries[0][0] == victim_id
        lost = [
            doc_id
            for doc_id in ledger_at_kill
            if len(ledger.get(doc_id, ())) < len(ledger_at_kill[doc_id])
        ]
        assert not lost

        # ---- cold-fit recovery (ISSUE 10 satellite, single variant):
        # stop the replacement, WIPE the fit journals (only the ring
        # snapshot/log survives), restart once more — the recovery
        # tick re-fits every doc COLD and the restored ring alone must
        # serve those fits with zero fallback fetches
        coldfit_report = None
        if not mesh:
            server.phase = "stop"
            try:
                replacement.wait(timeout=60)
            except subprocess.TimeoutExpired:
                replacement.kill()
                replacement.wait()
            for name in os.listdir(dirs[victim_id]):
                if name.startswith("fit-"):
                    os.unlink(os.path.join(dirs[victim_id], name))
            coldfit_proc = _spawn(
                url, dirs[victim_id], victim_id, args, coldfit=True
            )
            server.phase = "coldfit"
            try:
                _wait(
                    lambda: server.barrier_count("coldfit") == 1,
                    timeout, "cold-fit recovery tick",
                )
                cf = next(
                    r for r in server.tick_reports()
                    if r["tag"] == "coldfit" and r["docs"] > 0
                )
                assert cf["fast"] == 0, cf  # every doc re-fit cold
                assert cf["fallback_fetches"] == 0, cf
                assert cf["http_hist_reads"] == 0, cf
                assert (
                    cf["ring_hist_reads"]
                    >= args.services * args.aliases
                ), cf
                coldfit_report = cf
            finally:
                server.phase = "stop"
                if coldfit_proc.poll() is None:
                    try:
                        coldfit_proc.wait(timeout=60)
                    except subprocess.TimeoutExpired:
                        coldfit_proc.kill()
                        coldfit_proc.wait()

        server.phase = "stop"
        for p in list(procs.values()) + [replacement]:
            if p.returncode == KILL_EXIT:
                continue
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        return {
            "config": "r-restart-recovery",
            "variant": "mesh-3" if mesh else "single",
            "services": args.services,
            "aliases": args.aliases,
            "windows": args.services * args.aliases,
            "parked_docs_at_kill": len(parked),
            "recover_wall_seconds": round(recover_wall, 3),
            "recovery_tick_seconds": rec["seconds"],
            "recovery_fast_fraction": round(fast_frac, 4),
            "recovery_fallback_fetches": rec["fallback_fetches"],
            "restored_series": rec["restored_series"],
            "restored_fits": rec["restored_fits"],
            "restore_discards": rec.get("discards", {}),
            "exactly_once": True,  # asserted above
            # single variant: the ring-only recovery (fit journals
            # wiped) — cold fits served entirely from restored columns
            "coldfit_recovery": (
                {
                    "tick_seconds": coldfit_report["seconds"],
                    "ring_hist_reads": coldfit_report["ring_hist_reads"],
                    "fallback_fetches": coldfit_report["fallback_fetches"],
                }
                if coldfit_report is not None
                else None
            ),
            "metric": "recovery_fast_fraction",
            "value": round(fast_frac, 4),
            "unit": "fraction",
        }
    except BaseException:
        for wid, p in procs.items():
            if p.poll() is None:
                p.kill()
            out = _worker_log(wid)
            if out:
                sys.stderr.write(f"--- worker {wid} ---\n{out}\n")
        if replacement is not None and replacement.poll() is None:
            replacement.kill()
        raise
    finally:
        server.stop()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=4096)
    ap.add_argument("--aliases", type=int, default=4)
    ap.add_argument("--hist-len", type=int, default=256)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument(
        "--mesh", action="store_true",
        help="3-worker mesh variant only (default runs single AND mesh)",
    )
    ap.add_argument(
        "--single", action="store_true",
        help="single-worker variant only",
    )
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    # child-mode flags (internal)
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--store-url", help=argparse.SUPPRESS)
    ap.add_argument("--snapshot-dir", dest="snapshot_dir", help=argparse.SUPPRESS)
    ap.add_argument("--worker-id", dest="worker_id", help=argparse.SUPPRESS)
    ap.add_argument("--victim", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument(
        "--recovering", action="store_true", help=argparse.SUPPRESS
    )
    ap.add_argument(
        "--coldfit", action="store_true", help=argparse.SUPPRESS
    )
    ap.add_argument(
        "--max-stuck", dest="max_stuck", type=float, default=3.0,
        help=argparse.SUPPRESS,
    )
    ap.add_argument(
        "--lease-seconds", dest="lease_seconds", type=float, default=30.0,
        help=argparse.SUPPRESS,
    )
    ap.add_argument(
        "--ring-points", type=int, default=512, help=argparse.SUPPRESS
    )
    args = ap.parse_args(argv)
    if args.child:
        return run_child(args)
    if args.small:
        args.services = min(args.services, 24)
        args.hist_len = min(args.hist_len, 128)
    variants = []
    if args.single or not args.mesh:
        variants.append(False)
    if args.mesh or not args.single:
        variants.append(True)
    results = {}
    for mesh in variants:
        result = run(args, mesh)
        results["mesh" if mesh else "single"] = result
        print(json.dumps(result), flush=True)
    from benchmarks.report import write_summary

    write_summary("restart", results, small=args.small)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
