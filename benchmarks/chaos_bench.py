"""Chaos soak: a 3-worker mesh under a scheduled fault plan (ISSUE 9).

Every other benchmark measures the system healthy; this one PROVES the
degradation story end to end. Three `BrainWorker`s (the shipped stack:
mesh membership + consistent-hash claims + per-worker ingest receiver)
run against a REAL HTTP store server (`scaleout_bench.StoreServer`,
grown fault hooks) and a real `PrometheusSource` whose session
synthesizes query_range responses, while a seeded `FaultPlan` walks
through the ISSUE's scheduled faults:

  baseline   healthy pass — compiles programs, proves the harness
  brownout   the store answers 503 on every write for a window: write-
             backs buffer locally (write-behind), claims/renews degrade,
             store breakers open; on heal the backlog replays
  blackhole  Prometheus goes dark: fetch faults fail fast once the
             breaker opens, docs RELEASE un-judged instead of failing
  flood      4 concurrent pushers against one latency-injected receiver
             with a small inflight cap: sheds answer 429 + Retry-After,
             pushers retry-then-buffer, the backlog drains post-flood
  skew       one worker's mesh clock runs fast by lease/2 (the pinned
             tolerance's ops guidance): nobody is falsely declared dead
  crash      one worker wedges mid-tick with claims parked (no leave, no
             renew — the SIGKILL effect in-process; `restart_bench` owns
             the real-SIGKILL variant): the ring heals on lease expiry
             and survivors re-judge the orphans via stuck-claim takeover

In-run asserts (the acceptance bar — the bench FAILS, not just reports):
zero lost or duplicated verdicts in every phase (one terminal ledger
entry per doc), every breaker re-closed at the end, recovery ≤ 2 busy
ticks per worker after each fault clears, the runtime lock witness
observes no edge missing from the committed static graph, and every
bounded structure (write-behind, pusher buffer, ring budget) stays
inside its cap.

Usage: python -m benchmarks.chaos_bench [--small]
Prints one JSON line per phase plus a summary line.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.parse

from benchmarks.scaleout_bench import (
    ALIAS_EXPR,
    HttpFleetStore,
    StoreServer,
    synth_values,
)

LEASE_SECONDS = 2.0
# comfortably above the brownout hold + the write-behind replay margin
# (the worker shaves min(store timeout, window/3) off the replay
# window so a slow replay RPC cannot cross the takeover boundary)
MAX_STUCK_SECONDS = 6.0
POLL_SECONDS = 0.05

# fault-plan schedule (plan-clock seconds; the driver moves the clock)
PROM_WINDOW = (100.0, 200.0)
RECEIVER_WINDOW = (300.0, 400.0)
SKEW_WINDOW = (500.0, 600.0)


class _Resp:
    status_code = 200

    def __init__(self, body):
        self._body = body

    def raise_for_status(self):
        pass

    def json(self):
        return self._body


class SynthSession:
    """requests-shaped session synthesizing a query_range JSON matrix
    from the URL alone — the REAL `PrometheusSource` (retries, chaos
    seam, breaker) runs unmodified on top."""

    def __init__(self):
        self.wedged = threading.Event()

    def get(self, url, timeout=None):
        if self.wedged.is_set():
            # crash emulation: this fetch never returns (the worker's
            # tick thread is a daemon — see the crash phase)
            threading.Event().wait()
        import numpy as np

        from foremast_tpu.ingest.wire import resolve_query_range

        key, t0, t1, step = resolve_query_range(url)
        if key is None or t0 is None or t1 is None:
            raise ValueError(f"unresolvable synth url {url!r}")
        ts = np.arange(int(t0), int(t1) + 1, int(step or 60), np.int64)
        vs = synth_values(key, ts)
        return _Resp(
            {
                "status": "success",
                "data": {
                    "result": [
                        {
                            "values": [
                                [int(t), str(float(v))]
                                for t, v in zip(ts, vs)
                            ]
                        }
                    ]
                },
            }
        )


class ChaosWorker:
    """One mesh worker: shipped BrainWorker + MeshNode + receiver, its
    tick loop on a daemon thread, tick log for the recovery asserts."""

    def __init__(self, wid: str, store_url: str, plan, degrade_kw):
        from foremast_tpu.chaos import (
            BreakerRegistry,
            Degradation,
            WriteBehindBuffer,
        )
        from foremast_tpu.chaos.degrade import DegradeStats
        from foremast_tpu.config import BrainConfig
        from foremast_tpu.ingest import RingStore, start_ingest_server
        from foremast_tpu.jobs.worker import BrainWorker
        from foremast_tpu.mesh import Membership, MeshNode, MeshRouter
        from foremast_tpu.metrics.source import PrometheusSource

        self.wid = wid
        stats = DegradeStats()
        self.degrade = Degradation(
            stats=stats,
            breakers=BreakerRegistry(**degrade_kw),
            write_behind=WriteBehindBuffer(
                max_docs=4096, max_age_seconds=MAX_STUCK_SECONDS,
                stats=stats,
            ),
        )
        self.fleet = HttpFleetStore(
            store_url, wid,
            chaos=plan.edge("store"),
            breaker=self.degrade.breakers.get("store"),
        )
        self.session = SynthSession()
        source = PrometheusSource(
            session=self.session, retries=1, backoff_seconds=0.01,
            chaos=plan.edge("prometheus"),
            breaker=self.degrade.breakers.get("prometheus"),
        )
        # serial fetches: 3 in-process workers threading pure-CPU synth
        # fetches would only fight the GIL, and the crash phase wedges
        # the TICK thread (a daemon), never a non-daemon pool thread
        source.concurrent_fetch = False
        membership = Membership(
            self.fleet, wid, lease_seconds=LEASE_SECONDS,
            # the skew phase runs ONE member's clock fast (w2 both
            # stamps its leases and reads peers' by this clock)
            clock=plan.edge("clock").clock() if wid == "w2" else time.time,
        )
        router = MeshRouter(membership, refresh_seconds=0.5)
        self.ring = RingStore(budget_bytes=1 << 20, shards=2)
        self.receiver, _ = start_ingest_server(
            0, self.ring, host="127.0.0.1", router=router,
            max_inflight=2, chaos=plan.edge("receiver"),
            degrade_stats=stats,
        )
        membership.ingest_address = (
            "127.0.0.1:%d" % self.receiver.server_address[1]
        )
        self.node = MeshNode(membership, router, ring_store=self.ring)
        config = BrainConfig(
            algorithm="moving_average_all",
            max_stuck_seconds=MAX_STUCK_SECONDS,
            max_cache_size=4096,
        )
        self.worker = BrainWorker(
            self.fleet, source, config=config, claim_limit=64,
            worker_id=wid, mesh=self.node, degrade=self.degrade,
        )
        self.tick_log: list[tuple[float, float, int]] = []
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._loop, name=f"chaos-{wid}", daemon=True
        )

    def _loop(self):
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                n = self.worker.tick()
            except Exception:  # pragma: no cover — the bench fails below
                import logging

                logging.getLogger("chaos_bench").exception(
                    "worker %s tick crashed", self.wid
                )
                self.tick_log.append((t0, time.monotonic(), -1))
                return
            self.tick_log.append((t0, time.monotonic(), n))
            if n == 0:
                time.sleep(POLL_SECONDS)

    def busy_ticks_after(self, t: float) -> int:
        return sum(1 for t0, _, n in self.tick_log if t0 > t and n > 0)

    def crashed(self) -> bool:
        return any(n < 0 for _, _, n in self.tick_log)

    def stop(self):
        self._stop.set()


def seed_batch(server, tag: str, count: int, hist_len: int, cur_len: int):
    """`count` finalize-on-first-judgment docs (endTime in the past):
    exactly-once then means exactly one terminal ledger entry per doc."""
    from foremast_tpu.jobs.models import Document

    now = int(time.time())
    cur_t1 = now - 60
    cur_t0 = cur_t1 - 60 * (cur_len - 1)
    hist_t1 = cur_t0 - 120
    hist_t0 = hist_t1 - 60 * (hist_len - 1)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(now - 30)
    )
    ids = []
    for i in range(count):
        sid = f"{tag}-{i}"
        expr = urllib.parse.quote(
            ALIAS_EXPR.format(a=0, sid=sid), safe=""
        )
        doc_id = f"job-{sid}"
        server.store.create(
            Document(
                id=doc_id,
                app_name=f"app{sid}",
                end_time=end_time,
                current_config=(
                    f"m0== http://synth/api/v1/query_range?query={expr}"
                    f"&start={cur_t0}&end={cur_t1}&step=60"
                ),
                historical_config=(
                    f"m0== http://synth/api/v1/query_range?query={expr}"
                    f"&start={hist_t0}&end={hist_t1}&step=60"
                ),
                strategy="continuous",
            )
        )
        ids.append(doc_id)
    return ids


def wait_all_terminal(server, ids, timeout: float) -> float:
    """Poll until every doc is terminal; returns the completion wall
    time (monotonic). Raises on timeout — a lost verdict IS the bug
    this bench exists to catch."""
    from foremast_tpu.jobs.models import TERMINAL_STATUSES

    deadline = time.monotonic() + timeout
    while True:
        statuses = [server.store.get(i).status for i in ids]
        if all(s in TERMINAL_STATUSES for s in statuses):
            return time.monotonic()
        if time.monotonic() > deadline:
            pending = [
                (i, s)
                for i, s in zip(ids, statuses)
                if s not in TERMINAL_STATUSES
            ]
            raise AssertionError(
                f"verdicts LOST: {len(pending)} doc(s) never finalized "
                f"within {timeout}s: {pending[:5]}"
            )
        time.sleep(0.05)


def assert_exactly_once(server, ids, phase: str):
    from foremast_tpu.jobs.models import TERMINAL_STATUSES

    ledger = server.ledger_snapshot()
    for doc_id in ids:
        terminal = [
            e for e in ledger.get(doc_id, ())
            if e[2] in TERMINAL_STATUSES
        ]
        assert len(terminal) == 1, (
            f"[{phase}] doc {doc_id} has {len(terminal)} terminal "
            f"writes (expected exactly 1): {terminal}"
        )


BREAKER_OPEN_SECONDS = 0.5
# recovery is measured from the moment the system is ALLOWED to probe
# again: the breaker cooldown after a fault clears is designed
# degradation, not recovery work (plus margin for a tick already in
# flight at the boundary)
RECOVERY_GRACE = BREAKER_OPEN_SECONDS + 0.3


def assert_recovery(workers, t_clear: float, t_done: float, phase: str,
                    exclude=()):
    """Recovery bar: ≤ 2 busy ticks per worker between the fault
    clearing (plus the breaker-cooldown grace) and the batch finishing
    (idle polls don't count — the measure is how many passes over the
    work recovery needed)."""
    start = t_clear + RECOVERY_GRACE
    for cw in workers:
        if cw.wid in exclude:
            continue
        busy = sum(
            1 for t0, _, n in cw.tick_log if start < t0 <= t_done and n > 0
        )
        assert busy <= 2, (
            f"[{phase}] {cw.wid} needed {busy} busy ticks after the "
            "fault cleared (bar: ≤ 2)"
        )


def run(small: bool = False) -> list[dict]:
    from foremast_tpu.analysis import witness
    from foremast_tpu.chaos import FaultPlan

    # the witness wraps every package lock created AFTER this point
    # (workers, rings, receivers, buffers all construct below)
    wit = witness.install()

    batch = 9 if small else 24
    hist_len = 64 if small else 256
    cur_len = 16
    hold = 1.2 if small else 2.5

    clock_box = [0.0]
    plan = FaultPlan(
        rules=(
            {"edge": "prometheus", "after": PROM_WINDOW[0],
             "duration": PROM_WINDOW[1] - PROM_WINDOW[0],
             "blackhole": True},
            {"edge": "receiver", "after": RECEIVER_WINDOW[0],
             "duration": RECEIVER_WINDOW[1] - RECEIVER_WINDOW[0],
             "latency_seconds": 0.25},
            {"edge": "clock", "after": SKEW_WINDOW[0],
             "duration": SKEW_WINDOW[1] - SKEW_WINDOW[0],
             "skew_seconds": LEASE_SECONDS / 2.0},
        ),
        seed=1234,
        clock=lambda: clock_box[0],
    ).activate(now=0.0)

    server = StoreServer()
    url = server.start()
    degrade_kw = dict(
        failure_threshold=2, open_seconds=BREAKER_OPEN_SECONDS
    )
    workers = [
        ChaosWorker(f"w{i}", url, plan, degrade_kw) for i in (1, 2, 3)
    ]
    rows: list[dict] = []
    try:
        for cw in workers:
            cw.thread.start()
        # mesh convergence: every router sees 3 members
        deadline = time.monotonic() + 15
        while any(
            len(cw.node.router.members()) < 3 for cw in workers
        ):
            assert time.monotonic() < deadline, "mesh never converged"
            time.sleep(0.05)

        def phase_row(phase, ids, t_clear, t_done, **extra):
            row = {
                "config": "c-chaos-soak",
                "phase": phase,
                "docs": len(ids),
                "recovery_seconds": (
                    round(t_done - t_clear, 3) if t_clear else None
                ),
                **extra,
            }
            rows.append(row)
            print(json.dumps(row), flush=True)

        # -- baseline ---------------------------------------------------
        ids = seed_batch(server, "base", batch, hist_len, cur_len)
        t0 = time.monotonic()
        t_done = wait_all_terminal(server, ids, timeout=120)
        assert_exactly_once(server, ids, "baseline")
        phase_row("baseline", ids, t0, t_done)

        # -- store brownout --------------------------------------------
        server.add_fault(op="update", status=503)  # update + update_many
        ids = seed_batch(server, "brown", batch, hist_len, cur_len)
        time.sleep(hold)  # workers claim + judge + buffer through this

        def brown_buffered() -> int:
            return sum(
                cw.degrade.stats.docs_snapshot().get("write_buffered", 0)
                for cw in workers
            )

        # deflake (1-CPU CI hosts): the first judge pass can outlast the
        # nominal hold, so no write ever LANDS inside the fault window
        # and the mid-write asserts below would test scheduler luck, not
        # the write-behind. Keep the brownout up — bounded — until a
        # worker demonstrably buffered a write; if even the extended
        # window closes dry, record overlap_observed=False and skip the
        # mid-write asserts (exactly-once + recovery still hold).
        extend = time.monotonic() + (30.0 if small else 20.0)
        while brown_buffered() == 0 and time.monotonic() < extend:
            time.sleep(0.1)
        overlap = brown_buffered() > 0
        server.clear_faults()
        t_clear = time.monotonic()
        t_done = wait_all_terminal(server, ids, timeout=60)
        assert_exactly_once(server, ids, "brownout")
        assert_recovery(workers, t_clear, t_done, "brownout")
        buffered = brown_buffered()
        replayed = sum(
            cw.degrade.stats.docs_snapshot().get("write_replayed", 0)
            for cw in workers
        )
        if overlap:
            assert buffered > 0, "brownout never exercised the write-behind"
            assert replayed > 0, "write-behind backlog never replayed"
        phase_row(
            "brownout", ids, t_clear, t_done,
            buffered=buffered, replayed=replayed,
            overlap_observed=overlap,
        )

        # -- prometheus blackhole --------------------------------------
        clock_box[0] = PROM_WINDOW[0] + 1.0
        ids = seed_batch(server, "dark", batch, hist_len, cur_len)
        time.sleep(hold)
        clock_box[0] = PROM_WINDOW[1] + 1.0
        t_clear = time.monotonic()
        t_done = wait_all_terminal(server, ids, timeout=60)
        assert_exactly_once(server, ids, "blackhole")
        assert_recovery(workers, t_clear, t_done, "blackhole")
        released = sum(
            cw.degrade.stats.docs_snapshot().get("fetch_released", 0)
            for cw in workers
        )
        shorts = sum(
            b.short_circuits
            for cw in workers
            for b in cw.degrade.breakers.all().values()
        )
        assert released > 0, "blackhole never released a doc un-judged"
        assert shorts > 0, "no breaker ever short-circuited"
        phase_row(
            "blackhole", ids, t_clear, t_done,
            released=released, breaker_short_circuits=shorts,
        )

        # -- pusher flood ----------------------------------------------
        from foremast_tpu.mesh.routing import RoutingPusher

        clock_box[0] = RECEIVER_WINDOW[0] + 1.0
        seed_addr = workers[0].node.membership.ingest_address
        pushers = [
            RoutingPusher(
                [seed_addr], retries=0, backoff_seconds=0.01,
                buffer_bytes=1 << 20, timeout=5.0,
            )
            for _ in range(4)
        ]
        t_base = int(time.time()) - 600
        series = [
            [
                (
                    'flood_m{app="appF%d-%d"}' % (p, i),
                    [t_base + 60 * k for k in range(4)],
                    [1.0, 2.0, 3.0, 4.0],
                    None,
                )
                for i in range(6)
            ]
            for p in range(4)
        ]
        flood_threads = [
            threading.Thread(
                target=lambda p=p: pushers[p].push_cycle(series[p]),
                daemon=True,
            )
            for p in range(4)
        ]
        for t in flood_threads:
            t.start()
        for t in flood_threads:
            t.join(timeout=30)
        shed = sum(
            cw.degrade.stats.events_snapshot().get(("receiver", "shed"), 0)
            for cw in workers
        )
        buffered_push = sum(p.counters["buffered_series"] for p in pushers)
        assert shed > 0, "the flood never tripped receiver shedding"
        assert buffered_push > 0, "no pusher ever buffered a shed batch"
        clock_box[0] = RECEIVER_WINDOW[1] + 1.0  # flood over
        t_clear = time.monotonic()
        # backlog drains: one healthy cycle per pusher re-sends it
        for p in pushers:
            out = p.push_cycle([])
            assert out["errors"] == 0, out
            assert p.buffered == 0, "pusher backlog failed to drain"
        assert all(p.counters["dropped_series"] == 0 for p in pushers)
        phase_row(
            "flood", [], t_clear, time.monotonic(),
            sheds=shed, buffered_series=buffered_push,
            resent_series=sum(p.counters["resent_series"] for p in pushers),
        )

        # -- clock skew -------------------------------------------------
        rebalances_before = {
            cw.wid: cw.node.router.counters["rebalances"] for cw in workers
        }
        clock_box[0] = SKEW_WINDOW[0] + 1.0
        ids = seed_batch(server, "skew", batch, hist_len, cur_len)
        time.sleep(max(hold, 3 * LEASE_SECONDS / 3.0))  # several renews
        assert all(
            len(cw.node.router.members()) == 3 for cw in workers
        ), "a lease/2-skewed clock falsely killed a healthy member"
        t_clear = time.monotonic()
        t_done = wait_all_terminal(server, ids, timeout=60)
        assert_exactly_once(server, ids, "skew")
        clock_box[0] = SKEW_WINDOW[1] + 1.0
        for cw in workers:
            assert (
                cw.node.router.counters["rebalances"]
                == rebalances_before[cw.wid]
            ), f"skew phase rebalanced the ring on {cw.wid}"
        phase_row("skew", ids, t_clear, t_done, false_deaths=0)

        # -- worker crash -----------------------------------------------
        # arm the wedge FIRST: the victim's next busy tick claims its
        # partition, then hangs forever on the first fetch — claims
        # parked in-progress, no write-back, no renew, no leave (the
        # in-process SIGKILL effect; restart_bench owns the real one)
        victim = workers[2]
        victim.session.wedged.set()
        ids = seed_batch(server, "crash", batch, hist_len, cur_len)
        # wait until the victim's claims of this batch are parked
        deadline = time.monotonic() + 30
        while True:
            parked = [
                i
                for i in ids
                if server.store.get(i).processing_content == "w3"
                and server.store.get(i).status == "preprocess_inprogress"
            ]
            if parked:
                break
            assert time.monotonic() < deadline, (
                "w3 never claimed any crash-batch doc (partition too "
                "small?) — grow the batch"
            )
            time.sleep(0.01)
        victim.stop()  # loop flag only — its tick thread is wedged
        t_wedge = time.monotonic()
        survivors = workers[:2]
        # ring heals on lease expiry
        deadline = time.monotonic() + 30
        while any(
            len(cw.node.router.members()) != 2 for cw in survivors
        ):
            assert time.monotonic() < deadline, "ring never healed"
            time.sleep(0.05)
        t_heal = time.monotonic()
        t_done = wait_all_terminal(server, ids, timeout=60)
        assert_exactly_once(server, ids, "crash")
        # recovery bar: busy survivor ticks after the docs became
        # claimable again (stuck window past the wedge)
        t_claimable = t_wedge + MAX_STUCK_SECONDS
        assert_recovery(
            survivors, max(t_heal, t_claimable), t_done, "crash"
        )
        phase_row(
            "crash", ids, t_heal, t_done,
            parked_at_wedge=len(parked),
            heal_seconds=round(t_heal - t_wedge, 3),
        )

        # -- end-state asserts ------------------------------------------
        for cw in survivors:
            assert not cw.crashed(), f"{cw.wid} tick loop crashed"
            for edge, br in cw.degrade.breakers.all().items():
                assert br.state == "closed", (
                    f"breaker {cw.wid}/{edge} ended {br.state!r} "
                    "(every breaker must re-close)"
                )
            # bounded memory: every buffer inside its cap
            assert len(cw.degrade.write_behind) == 0
            assert len(cw.worker._judged_status) <= 16384
            assert cw.ring.stats()["bytes"] <= 1 << 20
        graph = witness.load_graph()
        assert graph is not None, "analysis_lockgraph.json missing"
        missing = wit.unobserved_edges(graph)
        assert not missing, (
            f"lock witness observed edges missing from the static "
            f"graph (run `make lockgraph`): {missing}"
        )
        summary = {
            "config": "c-chaos-soak",
            "phase": "summary",
            "phases": [r["phase"] for r in rows],
            "workers": 3,
            "docs_per_phase": batch,
            "no_lost_or_duplicated_verdicts": True,
            "breakers_reclosed": True,
            "recovery_within_2_ticks": True,
            "lock_witness_clean": True,
            "memory_bounded": True,
        }
        rows.append(summary)
        print(json.dumps(summary), flush=True)
        return rows
    finally:
        for cw in workers:
            cw.stop()
        for cw in workers:
            if not cw.session.wedged.is_set():
                cw.thread.join(timeout=10)
                cw.worker.close()
            from foremast_tpu.ingest import stop_ingest_server

            stop_ingest_server(cw.receiver, drain_seconds=1.0)
        server.stop()
        witness.uninstall()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    args = parser.parse_args(argv)
    phases = run(small=args.small)
    from benchmarks.report import write_summary

    write_summary(
        "chaos", {"phases": phases}, small=args.small
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
