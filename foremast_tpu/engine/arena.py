"""Device-resident fitted-state arena: gather-keyed warm-tick scoring.

Round 3 cached stacked terminal state keyed by the ORDERED TUPLE of the
whole claim set's fit keys: any churn — one job finishing, one arriving,
claim-order jitter — missed the tuple key and silently re-paid a full
host restack + upload (~25 MB/tick at the daily season width). The
arena replaces that with one device-resident ROW per fit key:

  * state lives in HBM as [capacity] vectors + a [capacity, m] season
    buffer; a tick's batch is assembled ON DEVICE by `jnp.take` with a
    [B] row-index array inside the scoring program (engine.scoring.
    score_from_arena) — zero host restack for warm rows;
  * a churned claim set re-uploads exactly the changed rows (scatter of
    the fitted entries into their rows), so 10% churn costs 10%;
  * capacity is sized by BYTES, not entries (a row's footprint varies
    360x between m=1 and m=1440) — FOREMAST_ARENA_BYTES, default 256 MB
    — with a row-count ceiling so tiny rows cannot demand a multi-
    million-row index space;
  * hit/miss/eviction counters are exported through the worker's
    self-telemetry (observe.gauges).

The host-side fit cache (models.cache.ModelCache of terminal-state
tuples) stays authoritative — it is what checkpoints and what multihost
workers key — the arena is a device-side acceleration of it. Eviction
safety: every fit-cache miss is refit and force-scattered, so a stale
arena row can never outlive its host entry's eviction.

Reference anchor: this accelerates the brain's model cache semantics
(`foremast-brain/README.md:30` MAX_CACHE_SIZE) for the re-check loop
(`design.md:43`), where the reference refits from the full history.
"""

from __future__ import annotations

import logging
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from foremast_tpu.observe.spans import span

log = logging.getLogger("foremast_tpu.arena")

_DEFAULT_BYTES = 256 * 1024 * 1024
# Hard auto-grow ceiling: a fleet whose working set exceeds the soft
# budget grows the arena rather than silently restacking every tick
# (VERDICT r4: at m=1440 the 256 MB default held ~46k rows, so a daily
# fleet >= ~11.6k services fell off a per-tick re-upload cliff with no
# counter and no log). 2 GB holds the row ceiling even at m=1440
# (262,144 rows x 5,780 B = 1.45 GB) and is ~12% of a v5e chip's HBM.
_DEFAULT_MAX_BYTES = 2 * 1024 * 1024 * 1024
_MAX_ROWS = 262_144
_MIN_ROWS = 8_192


# Explicit overrides beat the env: pod-mode followers adopt the
# leader's broadcast budgets via set_arena_budget() — mutating
# os.environ after worker threads exist is a cross-thread race, and the
# write would only reach code that happens to re-read the env.
_BYTES_OVERRIDE: int | None = None
_MAX_BYTES_OVERRIDE: int | None = None


def set_arena_budget(
    soft_bytes: int | None, max_bytes: int | None
) -> None:
    """Pin the arena byte budgets for this process (None clears an
    override back to env/default). Call BEFORE the first tick: existing
    arenas keep the capacity they were built with."""
    global _BYTES_OVERRIDE, _MAX_BYTES_OVERRIDE
    _BYTES_OVERRIDE = None if soft_bytes is None else int(soft_bytes)
    _MAX_BYTES_OVERRIDE = None if max_bytes is None else int(max_bytes)


def _arena_bytes() -> int:
    if _BYTES_OVERRIDE is not None:
        return _BYTES_OVERRIDE
    return int(os.environ.get("FOREMAST_ARENA_BYTES", _DEFAULT_BYTES))


def _arena_max_bytes() -> int:
    if _MAX_BYTES_OVERRIDE is not None:
        return _MAX_BYTES_OVERRIDE
    return int(
        os.environ.get("FOREMAST_ARENA_MAX_BYTES", _DEFAULT_MAX_BYTES)
    )


def _row_bytes(m: int) -> int:
    # level f32 + trend f32 + phase i32 + scale f32 + n_hist i32 + season
    return 20 + 4 * m


def _is_pad_key(k) -> bool:
    """Batch-padding keys (judge/joint columnar "__pad__*" strings) —
    resident arena machinery that must never read as fleet state in the
    operator counters (models.cache.is_pad_fit_key is the fit-cache
    twin; arena keys that are pads are always plain strings)."""
    return isinstance(k, str) and k.startswith("__pad__")


def _pow2(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


class _ArenaTenancy:
    """Arena-rows budget envelopes + eviction attribution (ISSUE 20).

    Tracks which tenant owns each KEYED resident row (resolved once at
    assignment from the fit key's URL-encoded tenant label, cached by
    the registry) so `assign` can (a) recycle an over-envelope tenant's
    OWN least-recent row instead of evicting a neighbor's, and (b)
    charge every eviction to the tenant whose allocation forced it.
    Pads and transients stay untenanted. Single-threaded like the arena
    itself; the accounting ledger flush at the end of each assign call
    is the only lock it ever touches (tenant.accounting, a leaf)."""

    __slots__ = ("registry", "acct", "envelopes", "rows", "of", "_pending")

    def __init__(self, registry, acct):
        self.registry = registry
        self.acct = acct
        self.envelopes = {
            name: s.arena_rows
            for name, s in registry.specs.items()
            if s.arena_rows > 0
        }
        self.rows: dict = {}  # tenant -> keyed resident row count
        self.of: dict = {}  # fit key -> tenant
        self._pending: dict = {}  # tenant -> evictions this assign call

    @staticmethod
    def build(tenancy=None):
        """A tracker when the process is tenanted and tenancy could
        matter here (>=2 tenants, or any arena_rows envelope), else
        None — the parity pin: an untenanted or single-tenant arena
        keeps today's row placement byte-for-byte."""
        from foremast_tpu.tenant import accounting_for, get_tenancy

        if tenancy is None:
            tenancy = get_tenancy()
        if tenancy is None:
            return None
        if not (
            tenancy.fair
            or any(s.arena_rows > 0 for s in tenancy.specs.values())
        ):
            return None
        return _ArenaTenancy(tenancy, accounting_for(tenancy))

    def tenant_of(self, key):
        """Owning tenant for a REAL fit key (callers skip pads/None)."""
        return self.registry.tenant_of_key(key)

    def note_assign(self, key, tenant) -> None:
        self.of[key] = tenant
        self.rows[tenant] = self.rows.get(tenant, 0) + 1

    def note_drop(self, key) -> None:
        t = self.of.pop(key, None)
        if t is not None:
            left = self.rows.get(t, 0) - 1
            if left > 0:
                self.rows[t] = left
            else:
                self.rows.pop(t, None)

    def over(self, tenant) -> bool:
        env = self.envelopes.get(tenant, 0)
        return env > 0 and self.rows.get(tenant, 0) >= env

    def charge(self, tenant) -> None:
        self._pending[tenant] = self._pending.get(tenant, 0) + 1

    def flush(self) -> None:
        if self._pending:
            for t, n in self._pending.items():
                self.acct.count_eviction(t, n)
            self._pending.clear()

    def clear(self) -> None:
        self.rows.clear()
        self.of.clear()
        self._pending.clear()


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _scatter(level, trend, season, phase, scale, nh, idx, l_n, t_n, s_n, p_n, sc_n, n_n):
    """Functional in-place row update (donated buffers: the arena is the
    sole owner, so XLA reuses the allocation instead of copying)."""
    return (
        level.at[idx].set(l_n),
        trend.at[idx].set(t_n),
        season.at[idx].set(s_n),
        phase.at[idx].set(p_n),
        scale.at[idx].set(sc_n),
        nh.at[idx].set(n_n),
    )


@partial(jax.jit, donate_argnums=(0,))
def _scatter_tree(state, idx, updates):
    """Row scatter for an arbitrary state pytree (TreeArena): every leaf
    is [capacity, ...] and receives its [width, ...] update slab at the
    same row indices. Donated like `_scatter` — the arena owns the sole
    reference, so XLA updates in place."""
    return jax.tree.map(lambda s, u: s.at[idx].set(u), state, updates)


class RowArena:
    """Row-assignment machinery shared by every device state arena:
    byte-budgeted capacity with pow2 auto-grow toward the hard cap,
    approximate-LRU recycling, hit/miss/eviction counters, and the
    per-call transient-row aging. Subclasses own the actual device
    buffer layout via `_alloc` / `_grow` (and their own `scatter`).

    Not thread-safe by design: an arena belongs to a single judge's
    scoring thread (the worker is the only writer, and ModelCache
    remains the concurrent-visible layer)."""

    def __init__(
        self,
        row_bytes: int,
        max_bytes: int | None = None,
        sharding=None,
        shards: int = 1,
    ):
        """`sharding` (optional jax.sharding.Sharding) places the arena's
        device buffers explicitly — a ShardedJudge passes a mesh
        NamedSharding so the warm-tick gather runs locally on every
        device instead of pulling rows from wherever jnp.zeros happened
        to commit them (VERDICT r4 weak #4: the arena's placement under
        GSPMD was inherited by accident).

        `shards` > 1 (ISSUE 19) partitions the row space along the same
        data axis as the batch: the [capacity] leading axis splits into
        `shards` contiguous blocks of `cap_s` rows each (global row
        g = shard * cap_s + local), `sharding` must block-shard the
        leading axis over that axis, and `assign` places position i of a
        B-row batch ONLY in shard i // (B / shards) — the batch's own
        block placement — so the warm gather is device-local by
        construction. Byte budgets (`max_rows` / `hard_rows`) are
        PER-SHARD: each device hosts its block within the same budget,
        so aggregate capacity scales linearly with the mesh
        (`device_bytes()` stays per-device in both modes)."""
        self.row_bytes = max(int(row_bytes), 1)
        self.sharding = sharding
        self.shards = max(int(shards), 1)
        budget = _arena_bytes() if max_bytes is None else max_bytes
        self.max_rows = min(_MAX_ROWS, max(budget // self.row_bytes, 8))
        # soft budget: a batch larger than max_rows auto-grows toward the
        # hard cap (one log per growth) instead of silently thrashing or
        # falling back; only past hard_rows does assign() refuse
        self.hard_rows = min(
            _MAX_ROWS,
            max(_arena_max_bytes() // self.row_bytes, 8),
        )
        self.cap = 0  # TOTAL rows (= shards * cap_s when sharded)
        self.cap_s = 0  # per-shard rows (== cap when shards == 1)
        self.state = None  # layout owned by the subclass
        self.rows: dict = {}  # fit key -> row index
        self.row_key: list = []  # row index -> fit key | None
        # fit key -> the host entry OBJECT its row was scattered from:
        # joint-path refresh detection (an entry replaced under the same
        # key means the device row is stale) compares by identity, the
        # same contract as the worker's admission revalidation. Kept by
        # callers that scatter whole-entry rows (TreeArena users);
        # evictions prune it so it never outgrows the row count.
        self.row_entry: dict = {}
        self.free: list[int] = []  # unassigned row indices (shards == 1)
        # sharded mode: per-shard free lists of LOCAL indices (local
        # indices are stable across growth; global ones renumber)
        self._free_s: list[list[int]] = [[] for _ in range(self.shards)]
        self._transients: list[int] = []  # last call's unkeyed rows
        self.stamp = np.zeros(0, np.int64)  # per-row last-use tick
        self.tick = 0
        self.hits = 0
        self.misses = 0  # rows scattered (new or refreshed)
        self.evictions = 0
        self.shard_moves = 0  # rows migrated between shards (sharded)
        # resident rows held by batch-padding keys ("__pad__*"): arena
        # machinery, not fleet state — subtracted from rows_live so the
        # operator counters report documents, and their hits/misses are
        # never counted (positions >= assign()'s n_real are pads)
        self.pad_live = 0
        # multi-tenant QoS (ISSUE 20): None unless the process is
        # tenanted with >=2 tenants or an arena_rows envelope — the
        # untenanted arena keeps today's placement byte-for-byte
        self._qos = _ArenaTenancy.build()

    # -- layout hooks (subclass-owned) ------------------------------------

    def _alloc(self, cap: int):
        """Fresh all-zero state for `cap` rows."""
        raise NotImplementedError

    def _grow(self, pad: int):
        """`self.state` extended by `pad` zero rows."""
        raise NotImplementedError

    # -- memory ----------------------------------------------------------

    def _ensure_capacity(self, need: int) -> bool:
        """Grow (doubling) to host `need` concurrent rows; False when even
        the hard byte cap cannot fit the batch (caller falls back to a
        one-off stacked dispatch — counted, never silent)."""
        if need > self.max_rows:
            if need > self.hard_rows:
                return False
            # auto-grow past the soft budget: an LRU arena smaller than
            # the fleet's working set thrashes (cyclic access misses every
            # row, re-uploading the whole fleet's state each tick), so the
            # budget is treated as a default, not a wall. Grow to the
            # next power of two (capped at the hard limit) so a fleet
            # that adds a few services per tick amortizes growth instead
            # of reallocating + retracing at every new exact size.
            self.max_rows = min(self.hard_rows, _pow2(need))
            log.warning(
                "arena grown past FOREMAST_ARENA_BYTES soft budget: "
                "%d rows x %d B = %.0f MB; set FOREMAST_ARENA_BYTES>=%d "
                "to silence",
                need,
                self.row_bytes,
                need * self.row_bytes / 1e6,
                need * self.row_bytes,
            )
        if need <= self.cap:
            return True
        new_cap = min(self.max_rows, max(_pow2(need), self._min_rows()))
        pad = new_cap - self.cap
        if self.state is None:
            self.state = self._alloc(new_cap)
        else:
            self.state = self._grow(pad)
        if self.sharding is not None:
            # explicit placement (replicated over the judge's mesh); a
            # handful of device_puts per growth, never per tick
            self.state = jax.device_put(self.state, self.sharding)
        self.row_key.extend([None] * pad)
        self.stamp = np.concatenate(
            [self.stamp, np.full(pad, -1, np.int64)]
        )
        self.free.extend(range(self.cap, new_cap))
        self.cap = new_cap
        self.cap_s = new_cap
        return True

    # Growth IS a sanctioned host round-trip (rare — pow2 doubling,
    # warn-logged): _grow_sharded device_gets every leaf so the row
    # blocks survive the resize (see its docstring), and the index
    # renumbering below is host metadata work on that boundary.
    # foremast: device-boundary
    def _ensure_capacity_sharded(self, need_s: int) -> bool:
        """Sharded-mode `_ensure_capacity`: `need_s` is the PER-SHARD row
        count this call must host. Same soft-budget auto-grow / hard-cap
        refusal rules as the replicated path, applied per shard."""
        if need_s > self.max_rows:
            if need_s > self.hard_rows:
                return False
            self.max_rows = min(self.hard_rows, _pow2(need_s))
            log.warning(
                "sharded arena grown past FOREMAST_ARENA_BYTES soft "
                "budget: %d rows/shard x %d shards x %d B = %.0f MB "
                "aggregate; set FOREMAST_ARENA_BYTES>=%d to silence",
                need_s,
                self.shards,
                self.row_bytes,
                need_s * self.shards * self.row_bytes / 1e6,
                need_s * self.row_bytes,
            )
        if need_s <= self.cap_s:
            return True
        new_s = min(
            self.max_rows,
            max(_pow2(need_s), max(self._min_rows() // self.shards, 8)),
        )
        old_s = self.cap_s
        if self.state is None:
            self.state = self._alloc(self.shards * new_s)
        else:
            self.state = self._grow_sharded(old_s, new_s)
        if self.sharding is not None:
            self.state = jax.device_put(self.state, self.sharding)
        # host-side renumbering: global row g = shard * cap_s + local, so
        # growing cap_s moves every existing global index
        if old_s:
            def remap(g: int) -> int:
                return (g // old_s) * new_s + (g % old_s)

            self.rows = {k: remap(g) for k, g in self.rows.items()}
            new_keys: list = [None] * (self.shards * new_s)
            for g, k in enumerate(self.row_key):
                if k is not None:
                    new_keys[remap(g)] = k
            self.row_key = new_keys
            st = np.full((self.shards, new_s), -1, np.int64)
            st[:, :old_s] = self.stamp.reshape(self.shards, old_s)
            self.stamp = st.ravel()
            self._transients = [remap(g) for g in self._transients]
        else:
            self.row_key = [None] * (self.shards * new_s)
            self.stamp = np.full(self.shards * new_s, -1, np.int64)
        for s in range(self.shards):
            self._free_s[s].extend(range(old_s, new_s))
        self.cap_s = new_s
        self.cap = self.shards * new_s
        return True

    def _grow_sharded(self, old_s: int, new_s: int):
        """Per-shard zero-padding of every state leaf. Growth is RARE
        (pow2 doubling, warn-logged), so it round-trips through the
        host: a plain `jnp.concatenate` on a data-axis-sharded leaf
        would RE-BLOCK the layout under GSPMD — existing rows silently
        migrate devices and their global indices stop matching the block
        rule — while the host reshape keeps every row in its shard."""
        shards = self.shards

        def pad_leaf(x):
            h = np.asarray(jax.device_get(x))
            h = h.reshape(shards, old_s, *h.shape[1:])
            widths = [(0, 0), (0, new_s - old_s)] + [(0, 0)] * (h.ndim - 2)
            h = np.pad(h, widths)
            return h.reshape(shards * new_s, *h.shape[2:])

        return jax.tree.map(pad_leaf, self.state)

    def _min_rows(self) -> int:
        """Initial-allocation floor (subclasses with fat rows lower it:
        pre-allocating 8,192 LSTM rows would burn ~0.5 GB on a 10-job
        fleet)."""
        return _MIN_ROWS

    def clear(self) -> None:
        """Release device buffers and all row assignments."""
        self.cap = 0
        self.cap_s = 0
        self.state = None
        self.rows.clear()
        self.row_entry.clear()
        self.row_key = []
        self.stamp = np.zeros(0, np.int64)
        self.free = []
        self._free_s = [[] for _ in range(self.shards)]
        self._transients = []
        self.pad_live = 0
        if self._qos is not None:
            self._qos.clear()

    # -- assignment ------------------------------------------------------

    def _own_victim(self, order, tenant, base: int = 0) -> int:
        """First evictable row (stamp != this call's tick) OWNED by
        `tenant`, walking `order` (a stamp argsort — LRU first; local
        indices offset by `base` in sharded mode). -1 when every row of
        the tenant is protected this call — the envelope then falls
        through to normal placement, because a budget may reorder row
        recycling but must never block a verdict (ISSUE 20 parity)."""
        qos = self._qos
        for lr in order.tolist():
            r = base + lr
            if self.stamp[r] == self.tick:
                continue
            k = self.row_key[r]
            if k is not None and qos.of.get(k) == tenant:
                return r
        return -1

    def assign(
        self, keys, force, n_real: int | None = None
    ) -> tuple[np.ndarray, list[int]] | None:
        """Map a batch's fit keys onto arena rows.

        keys:  per-task cache keys (None => transient row, scattered and
               immediately recyclable).
        force: positions whose entries were (re)fitted this tick — their
               rows must be scattered even if the key already has a row
               (a fit-cache miss means the host entry was refreshed; the
               old device row is stale).
        n_real: positions >= this are batch-padding keys ("__pad__*"):
               they get rows and scatters like any key (stable pad rows
               keep warm ticks scatter-free) but are excluded from the
               hit/miss/rows_live counters — operators count documents,
               not padding. Default: every position is real.

        Returns (rows [B] int64, scatter_positions) or None when the
        batch cannot fit in the byte budget.

        The warm-tick hit pass is a single C-level dict sweep
        (np.fromiter) plus one fancy-index stamp update — on a fleet
        tick this runs for 40k+ keys with zero scatters, so per-key
        interpreter work is what would dominate. Rows touched this call
        carry stamp == tick and are never eviction candidates; last
        call's transient rows are aged to stamp -1 up front, making them
        the preferred recycling pool.

        Sharded arenas (`shards` > 1) route to `_assign_sharded`: the
        same surface, with rows constrained to each position's data-axis
        block.
        """
        if self.shards > 1:
            return self._assign_sharded(keys, force, n_real)
        # age out the previous call's transient rows (unless a keyed
        # assignment has since claimed the row)
        for r in self._transients:
            if self.row_key[r] is None:
                self.stamp[r] = -1
        self._transients.clear()
        self.tick += 1
        n = len(keys)
        nr = n if n_real is None else n_real
        if not self._ensure_capacity(n):
            return None
        getrow = self.rows.get
        rows = np.fromiter(
            ((getrow(k, -1) if k is not None else -1) for k in keys),
            np.int64,
            count=n,
        )
        hit = rows >= 0
        if hit.any():
            self.stamp[rows[hit]] = self.tick
        nhits = int(hit[:nr].sum())
        scatter: list[int] = []
        if force:
            for i in force:
                if hit[i]:
                    scatter.append(i)
            nhits -= len(scatter)
            self.misses += len(scatter)
        self.hits += nhits
        alloc = np.nonzero(~hit)[0]
        if len(alloc):
            # Working-set growth (ISSUE 14): a warm tick SPLIT across
            # sibling bucket calls (the baseline-less and canary
            # columnar buckets share this arena) has a working set
            # larger than any single batch, but capacity only ever grew
            # to the largest batch — so each bucket would evict the
            # rows its sibling used ONE call ago and the whole fleet
            # state would re-scatter every tick (LRU thrash, the exact
            # failure mode the auto-grow comment in _ensure_capacity
            # describes). Rows touched within the last two calls are
            # treated as resident working set: when the allocation
            # cannot be served from free + genuinely stale rows, grow
            # (same soft-budget warning / hard-cap rules) instead of
            # recycling them.
            # available = the free pool plus assigned rows idle for 3+
            # calls (free rows keep stamp -1 and never re-enter `free`
            # after assignment, so the two sets are disjoint; aged
            # transients undercount here, which at worst grows a little
            # early — never thrashes)
            available = len(self.free) + int(
                ((self.stamp >= 0) & (self.stamp < self.tick - 2)).sum()
            )
            shortfall = len(alloc) - available
            if shortfall > 0 and self.cap + shortfall <= self.hard_rows:
                self._ensure_capacity(self.cap + shortfall)
            qos = self._qos
            order = None
            oi = 0
            for ai, i in enumerate(alloc.tolist()):
                alloc_left = len(alloc) - ai  # incl. this allocation
                k = keys[i]
                if k is not None:
                    r = getrow(k, -1)
                    if r >= 0:
                        # duplicate key later in the same batch: reuse
                        # the row its first occurrence just claimed
                        rows[i] = r
                        continue
                tenant = None
                if qos is not None and k is not None and not _is_pad_key(k):
                    tenant = qos.tenant_of(k)
                if tenant is not None and qos.over(tenant):
                    # arena_rows envelope: an over-budget tenant
                    # recycles its OWN least-recent row — never a
                    # neighbor's, never the free pool, never capacity
                    # growth — and the eviction is charged to it
                    if order is None:
                        order = np.argsort(self.stamp, kind="stable")
                    rv = self._own_victim(order, tenant)
                    if rv >= 0:
                        old = self.row_key[rv]
                        del self.rows[old]
                        self.row_entry.pop(old, None)
                        self.evictions += 1
                        qos.note_drop(old)
                        qos.charge(tenant)
                        self.rows[k] = rv
                        self.row_key[rv] = k
                        qos.note_assign(k, tenant)
                        self.stamp[rv] = self.tick
                        rows[i] = rv
                        scatter.append(i)
                        if i < nr:
                            self.misses += 1
                        continue
                if not self.free:
                    if order is None:
                        order = np.argsort(self.stamp, kind="stable")
                    # In-loop anti-thrash backstop (the pre-loop
                    # estimate's 2-call recency window under-protects
                    # when 3+ assigns share the arena per tick cycle:
                    # uni + canary + several slow-path buckets). Peek
                    # the next eviction candidate without consuming it;
                    # if it was used within the last 8 calls the
                    # working set genuinely exceeds capacity — grow
                    # ONCE for the remaining allocations (same
                    # soft-budget warning / hard-cap rules) instead of
                    # recycling live rows every tick. A row idle for
                    # 8+ assign calls is cold under any real tick shape.
                    pi = oi
                    while pi < len(order) and self.stamp[order[pi]] == self.tick:
                        pi += 1
                    if (
                        pi < len(order)
                        and self.stamp[order[pi]] >= self.tick - 8
                        and self.cap + alloc_left <= self.hard_rows
                    ):
                        self._ensure_capacity(self.cap + alloc_left)
                if self.free:
                    r = self.free.pop()
                else:
                    while True:
                        if oi >= len(order):
                            # Unreachable by construction: _ensure_capacity
                            # guaranteed cap >= n, and at most n rows can
                            # carry this call's stamp, so an evictable row
                            # always exists. Returning None here would
                            # leave rows/row_key/stamp partially mutated
                            # with device state never scattered — a later
                            # tick would gather garbage as a warm hit
                            # (ADVICE r4) — so fail loudly instead.
                            raise RuntimeError(
                                "StateArena.assign invariant violated: "
                                f"no evictable row (need={n}, cap={self.cap})"
                            )
                        r = int(order[oi])
                        oi += 1
                        # current stamp, not the argsort snapshot: rows
                        # touched THIS call (hits and fresh allocs) are
                        # protected
                        if self.stamp[r] != self.tick:
                            break
                    old = self.row_key[r]
                    if old is not None:
                        del self.rows[old]
                        self.row_entry.pop(old, None)
                        self.evictions += 1
                        if _is_pad_key(old):
                            self.pad_live -= 1
                        if qos is not None:
                            qos.note_drop(old)
                            if tenant is not None:
                                qos.charge(tenant)
                if k is not None:
                    self.rows[k] = r
                    self.row_key[r] = k
                    if i >= nr:
                        self.pad_live += 1
                    if tenant is not None:
                        qos.note_assign(k, tenant)
                else:
                    # transient: recyclable at the next assign
                    self.row_key[r] = None
                    self._transients.append(r)
                self.stamp[r] = self.tick
                rows[i] = r
                scatter.append(i)
                if i < nr:
                    self.misses += 1
        if self._qos is not None:
            self._qos.flush()
        return rows, scatter

    def _assign_sharded(
        self, keys, force, n_real: int | None = None
    ) -> tuple[np.ndarray, list[int]] | None:
        """`assign` under the data-axis block placement rule (ISSUE 19):
        position i of a B-row batch lives in shard i // (B / shards) and
        its row must belong to that shard's block (global row
        g = shard * cap_s + local), so the warm gather never crosses a
        device boundary. Differences from the replicated path, all
        bounded and counted:

          * a key whose position moved to a different block since last
            tick MIGRATES — old row freed, fresh row scattered in the
            new shard (`shard_moves` counts these; claim-order jitter is
            self-healing, one re-scatter per moved row);
          * a key already claimed by one position this call but ALSO
            appearing at a position of another shard (duplicate keys —
            shard-qualified pad keys never collide) scores that position
            from a transient row;
          * ALL growth happens before rows are handed out (growing
            renumbers global indices, which would corrupt positions
            already assigned this call), using the same 8-call idle
            window the replicated path's in-loop backstop uses — at
            worst it grows a little earlier, never thrashes.
        """
        n = len(keys)
        nr = n if n_real is None else n_real
        shards = self.shards
        if n % shards:
            log.warning(
                "sharded arena assign: batch of %d rows is not a "
                "multiple of %d shards — stacked fallback", n, shards,
            )
            return None
        for r in self._transients:
            if self.row_key[r] is None:
                self.stamp[r] = -1
        self._transients.clear()
        self.tick += 1
        per = n // shards
        if not self._ensure_capacity_sharded(per):
            return None
        getrow = self.rows.get

        def sweep() -> np.ndarray:
            return np.fromiter(
                ((getrow(k, -1) if k is not None else -1) for k in keys),
                np.int64,
                count=n,
            )

        rows = sweep()
        shard_of = np.repeat(np.arange(shards, dtype=np.int64), per)
        hit = (rows >= 0) & ((rows // self.cap_s) == shard_of)
        miss_shard = shard_of[~hit]
        if len(miss_shard):
            counts = np.bincount(miss_shard, minlength=shards)
            st2 = self.stamp.reshape(shards, self.cap_s)
            idle = ((st2 >= 0) & (st2 < self.tick - 8)).sum(axis=1)
            free_n = np.asarray([len(f) for f in self._free_s])
            short = int((counts - idle - free_n).max())
            if short > 0 and self.cap_s + short <= self.hard_rows:
                self._ensure_capacity_sharded(self.cap_s + short)
                rows = sweep()  # growth renumbered every global index
                hit = (rows >= 0) & ((rows // self.cap_s) == shard_of)
        if hit.any():
            self.stamp[rows[hit]] = self.tick
        nhits = int(hit[:nr].sum())
        scatter: list[int] = []
        if force:
            for i in force:
                if hit[i]:
                    scatter.append(i)
            nhits -= len(scatter)
            self.misses += len(scatter)
        self.hits += nhits
        alloc = np.nonzero(~hit)[0]
        if len(alloc):
            claimed = {
                keys[i] for i in np.nonzero(hit)[0] if keys[i] is not None
            }
            qos = self._qos
            cap_s = self.cap_s
            order_s: list = [None] * shards
            oi_s = [0] * shards
            for i in alloc.tolist():
                k = keys[i]
                s = int(shard_of[i])
                base = s * cap_s
                transient = k is None
                if k is not None:
                    g = getrow(k, -1)
                    if g >= 0:
                        if g // cap_s == s:
                            # duplicate key later in the batch: reuse the
                            # row its first occurrence just claimed
                            rows[i] = g
                            continue
                        if k in claimed:
                            # the key's row legitimately belongs to
                            # another position this call — score this
                            # position from a transient copy
                            transient = True
                        else:
                            # block membership changed since last tick:
                            # migrate the row to this position's shard
                            self.row_key[g] = None
                            self.stamp[g] = -1
                            self._free_s[g // cap_s].append(g % cap_s)
                            del self.rows[k]
                            self.row_entry.pop(k, None)
                            self.shard_moves += 1
                            if _is_pad_key(k):
                                self.pad_live -= 1
                            if qos is not None:
                                # migration, not pressure: residency
                                # moves shards, nobody is charged (the
                                # note_assign below re-registers it)
                                qos.note_drop(k)
                tenant = None
                if qos is not None and not transient and not _is_pad_key(k):
                    tenant = qos.tenant_of(k)
                if tenant is not None and qos.over(tenant):
                    # arena_rows envelope, block-local: recycle the
                    # over-budget tenant's own least-recent row in THIS
                    # position's shard (placement stays device-local),
                    # charged to it; no candidate in the block → fall
                    # through to normal placement
                    if order_s[s] is None:
                        order_s[s] = np.argsort(
                            self.stamp[base : base + cap_s], kind="stable"
                        )
                    rv = self._own_victim(order_s[s], tenant, base)
                    if rv >= 0:
                        old = self.row_key[rv]
                        del self.rows[old]
                        self.row_entry.pop(old, None)
                        self.evictions += 1
                        qos.note_drop(old)
                        qos.charge(tenant)
                        self.rows[k] = rv
                        self.row_key[rv] = k
                        claimed.add(k)
                        qos.note_assign(k, tenant)
                        self.stamp[rv] = self.tick
                        rows[i] = rv
                        scatter.append(i)
                        if i < nr:
                            self.misses += 1
                        continue
                freel = self._free_s[s]
                if freel:
                    r = base + freel.pop()
                else:
                    if order_s[s] is None:
                        order_s[s] = np.argsort(
                            self.stamp[base : base + cap_s], kind="stable"
                        )
                    order = order_s[s]
                    oi = oi_s[s]
                    while True:
                        if oi >= len(order):
                            # mirror of the replicated invariant guard:
                            # cap_s >= per and at most `per` rows of a
                            # shard carry this call's stamp, so an
                            # evictable row always exists — fail loudly
                            # rather than gather garbage later
                            raise RuntimeError(
                                "sharded arena assign invariant "
                                f"violated: no evictable row in shard "
                                f"{s} (per={per}, cap_s={cap_s})"
                            )
                        r = base + int(order[oi])
                        oi += 1
                        if self.stamp[r] != self.tick:
                            break
                    oi_s[s] = oi
                    old = self.row_key[r]
                    if old is not None:
                        del self.rows[old]
                        self.row_entry.pop(old, None)
                        self.evictions += 1
                        if _is_pad_key(old):
                            self.pad_live -= 1
                        if qos is not None:
                            qos.note_drop(old)
                            if tenant is not None:
                                qos.charge(tenant)
                if transient:
                    self.row_key[r] = None
                    self._transients.append(r)
                else:
                    self.rows[k] = r
                    self.row_key[r] = k
                    claimed.add(k)
                    if i >= nr:
                        self.pad_live += 1
                    if tenant is not None:
                        qos.note_assign(k, tenant)
                self.stamp[r] = self.tick
                rows[i] = r
                scatter.append(i)
                if i < nr:
                    self.misses += 1
        if self._qos is not None:
            self._qos.flush()
        return rows, scatter

    def device_bytes(self) -> int:
        """HBM footprint of this arena's buffers on ONE device: the full
        capacity when replicated (total cost = this x device count — the
        worker's device_mesh varz does that multiplication), one shard's
        block when data-axis sharded (so the same multiplication yields
        the SHARD-SUM — ISSUE 19 HBM accounting: adding chips adds
        capacity, not copies)."""
        return (self.cap // self.shards) * self.row_bytes

    def counters(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "rows_live": len(self.rows) - self.pad_live,
            "capacity_rows": self.cap,
            "shard_moves": self.shard_moves,
        }


class StateArena(RowArena):
    """Univariate fitted-forecast rows: [capacity] state vectors plus a
    [capacity, m] season buffer (the layout `scoring.score_from_arena`
    gathers)."""

    def __init__(
        self,
        season_len: int,
        max_bytes: int | None = None,
        sharding=None,
        shards: int = 1,
    ):
        self.m = max(int(season_len), 1)
        super().__init__(
            _row_bytes(self.m),
            max_bytes=max_bytes,
            sharding=sharding,
            shards=shards,
        )

    def _alloc(self, cap: int):
        return (
            jnp.zeros(cap, jnp.float32),
            jnp.zeros(cap, jnp.float32),
            jnp.zeros((cap, self.m), jnp.float32),
            jnp.zeros(cap, jnp.int32),
            jnp.zeros(cap, jnp.float32),
            jnp.zeros(cap, jnp.int32),
        )

    def _grow(self, pad: int):
        lvl, tr, se, ph, sc, nh = self.state
        zf = jnp.zeros(pad, jnp.float32)
        zi = jnp.zeros(pad, jnp.int32)
        return (
            jnp.concatenate([lvl, zf]),
            jnp.concatenate([tr, zf]),
            jnp.concatenate([se, jnp.zeros((pad, self.m), jnp.float32)]),
            jnp.concatenate([ph, zi]),
            jnp.concatenate([sc, zf]),
            jnp.concatenate([nh, zi]),
        )

    # -- data movement ---------------------------------------------------

    def scatter(self, rows: np.ndarray, positions: list[int], entries) -> None:
        """Upload the (re)fitted entries into their rows.

        entries[i] layout: (level, trend, season[np], phase, scale,
        n_hist) — the ModelCache terminal-state tuple. The scatter batch
        is padded to a power of two with duplicates of the first update
        (identical index+value duplicates are deterministic), bounding
        compiled shapes.
        """
        from foremast_tpu.engine import scoring

        k = len(positions)
        if k == 0:
            return
        # child of the judge's arena_assemble stage span: on the trace
        # timeline the scatter upload separates from the assign sweep
        # (churn cost shows as scatter width, not as opaque assemble time)
        with span("arena.scatter", rows=k, season_len=self.m, device=True):
            width = _pow2(k)
            idx = np.empty(width, np.int32)
            lvl = np.empty(width, np.float32)
            tr = np.empty(width, np.float32)
            se = np.empty((width, self.m), np.float32)
            ph = np.empty(width, np.int32)
            sc = np.empty(width, np.float32)
            nh = np.empty(width, np.int32)
            for j, i in enumerate(positions):
                e = entries[i]
                idx[j] = rows[i]
                lvl[j] = e[0]
                tr[j] = e[1]
                se[j] = scoring.tile_season(e[2], self.m)
                ph[j] = e[3]
                sc[j] = e[4]
                nh[j] = e[5]
            if k < width:
                idx[k:] = idx[0]
                lvl[k:] = lvl[0]
                tr[k:] = tr[0]
                se[k:] = se[0]
                ph[k:] = ph[0]
                sc[k:] = sc[0]
                nh[k:] = nh[0]
            self.state = _scatter(*self.state, idx, lvl, tr, se, ph, sc, nh)
            if self.shards > 1:
                # re-pin the block layout: GSPMD is free to solve the
                # global-index scatter by resharding, and the warm
                # gather's shard_map REQUIRES the data-axis blocks.
                # device_put is the identity when the layout survived;
                # scatter is the rare (miss/churn) path either way.
                self.state = jax.device_put(self.state, self.sharding)

    def counters(self) -> dict:
        out = super().counters()
        out["season_len"] = self.m
        return out


class TreeArena(RowArena):
    """Device-resident rows of an arbitrary fixed-shape state PYTREE —
    the joint-detector counterpart of `StateArena` (ISSUE 4 tentpole).

    One row holds everything a joint model needs to score warm: for the
    bivariate detector the fitted Gaussian (mean [2], cov [2, 2], valid);
    for the LSTM-AE hybrid the stacked `AEParams` leaves, the training
    error moments, and the residual-MVN state (per-metric HW terminal
    state, residual mean, covariance). The template fixes every leaf's
    per-row shape/dtype; capacity is the leading axis of every leaf, and
    warm batches are assembled ON DEVICE by `jnp.take` over a [B] row
    index inside the joint scoring programs
    (`multivariate.lstm_joint_score_from_rows`,
    `models.bivariate.detect_bivariate_from_rows`). Byte budgeting,
    pow2 auto-grow, LRU recycling and counters are inherited unchanged
    from `RowArena`."""

    def __init__(
        self,
        template,
        max_bytes: int | None = None,
        sharding=None,
        shards: int = 1,
    ):
        """`template`: pytree of `jax.ShapeDtypeStruct` (or anything with
        .shape/.dtype) describing ONE row, without the capacity axis."""
        self.template = template
        leaves = jax.tree.leaves(template)
        row_bytes = sum(
            int(np.prod(leaf.shape, dtype=np.int64))
            * np.dtype(leaf.dtype).itemsize
            for leaf in leaves
        ) or 1
        super().__init__(
            row_bytes,
            max_bytes=max_bytes,
            sharding=sharding,
            shards=shards,
        )

    def _min_rows(self) -> int:
        # joint rows are fat (an f=4 LSTM-AE row is ~60 KB vs the
        # univariate daily row's ~5.8 KB); pre-allocating StateArena's
        # 8,192-row floor would burn ~0.5 GB of HBM on a 10-job fleet
        return 64

    def _alloc(self, cap: int):
        return jax.tree.map(
            lambda leaf: jnp.zeros((cap, *leaf.shape), leaf.dtype),
            self.template,
        )

    def _grow(self, pad: int):
        return jax.tree.map(
            lambda s, leaf: jnp.concatenate(
                [s, jnp.zeros((pad, *leaf.shape), leaf.dtype)]
            ),
            self.state,
            self.template,
        )

    # -- data movement ---------------------------------------------------

    def scatter(self, rows: np.ndarray, positions: list[int], entries) -> None:
        """Upload (re)fitted row pytrees into their rows.

        entries[i]: a pytree of HOST numpy leaves structurally matching
        the template (each leaf exactly the template's per-row shape —
        callers tile/pad season buffers beforehand). Same pow2
        width-padding discipline as `StateArena.scatter`."""
        k = len(positions)
        if k == 0:
            return
        with span("arena.scatter", rows=k, device=True):
            width = _pow2(k)
            idx = np.empty(width, np.int32)
            idx[:k] = [rows[i] for i in positions]
            idx[k:] = idx[0]
            picked = [entries[i] for i in positions]
            if k < width:
                picked.extend([picked[0]] * (width - k))
            updates = jax.tree.map(
                lambda *leaves: np.stack(leaves), *picked
            )
            self.state = _scatter_tree(self.state, idx, updates)
            if self.shards > 1:
                # same block-layout re-pin as StateArena.scatter
                self.state = jax.device_put(self.state, self.sharding)
