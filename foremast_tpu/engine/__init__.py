"""Batched TPU health-judgment engine (the reference brain's core)."""

from foremast_tpu.engine.scoring import (
    AI_MODEL,
    HEALTHY,
    UNHEALTHY,
    UNKNOWN,
    ScoreBatch,
    ScoreResult,
    pairwise_decision,
    register_model,
    score,
)
from foremast_tpu.engine.judge import (
    HealthJudge,
    MetricTask,
    MetricVerdict,
    bucket_length,
    combine_verdicts,
)

__all__ = [
    "AI_MODEL",
    "HEALTHY",
    "UNHEALTHY",
    "UNKNOWN",
    "ScoreBatch",
    "ScoreResult",
    "pairwise_decision",
    "register_model",
    "score",
    "HealthJudge",
    "MetricTask",
    "MetricVerdict",
    "bucket_length",
    "combine_verdicts",
]
