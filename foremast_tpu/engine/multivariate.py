"""Multivariate job judgment — the reference's metric-count model rule.

Reference model zoo (`docs/guides/design.md:57-93`): 1 metric -> the
univariate forecasters; 2 metrics -> Bivariate Normal Distribution; 3+
metrics -> Deep Learning (LSTM). The brain selects via its AI_MODEL
registry; here the same selection is explicit:

  * `ML_ALGORITHM=auto`             -> by metric count (the design.md rule)
  * `ML_ALGORITHM=bivariate_normal` -> joint 2-metric judgment (pairs only)
  * `ML_ALGORITHM=lstm_autoencoder` -> joint judgment for 2+ metrics
  * anything else                   -> univariate per-metric (HealthJudge)

Joint detectors align the job's metrics on common timestamps (a joint
observation needs every coordinate), judge the joint series, and
attribute flagged timestamps back to every alias in the job (the wire
format is per-alias anomaly pairs, `Barrelman.go:593-620`). Per-alias
gauge bounds stay meaningful via marginal mean +/- threshold * sigma.

Canary pairwise semantics (`docs/guides/design.md:31-33`,
`foremast-brain/README.md:5-11`) apply to joint jobs exactly as to
univariate ones: every metric's current window is tested against its
baseline window (Mann-Whitney / Wilcoxon / Kruskal / Friedman per
ML_PAIRWISE_ALGORITHM), and if ANY metric's distributions differ the
job's joint detection threshold is lowered by
`scoring.DIFF_THRESHOLD_FACTOR` — a suspicious canary gets tighter
bounds. Per-alias p-values and differ flags ride the verdicts so the
wire format carries the same evidence as the univariate path.

LSTM-AE fleets are trained per (app, alias-set) with a bounded
`ModelCache` (`MAX_CACHE_SIZE`, `foremast-brain/README.md:30`) so repeat
judgments of the same service skip training.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import scoring
from foremast_tpu.engine.judge import (
    HealthJudge,
    MetricTask,
    MetricVerdict,
    bucket_length,
    infer_step,
)
from foremast_tpu.models.bivariate import (
    detect_bivariate,
    detect_bivariate_from_rows,
    detect_bivariate_from_rows_sharded,
    fit_bivariate,
    fit_bivariate_bf16_delta,
)
from foremast_tpu.models.cache import ModelCache
from foremast_tpu.models.lstm_ae import (
    AEParams,
    LSTMAEConfig,
    LSTMParams,
    ae_cutoff,
    fit_many,
    score_many_cutoff,
    score_rows_cutoff,
)
from foremast_tpu.models.residual_mvn import (
    MVNState,
    chi2_quantile,
    fit_residual_mvn,
    fit_residual_mvn_bf16_delta,
    residual_mvn_d2_robust,
)
from foremast_tpu.observe.spans import span
from foremast_tpu.ops.forecasters import Forecast
from foremast_tpu.ops.windows import MetricWindows

log = logging.getLogger("foremast_tpu.engine.multivariate")

ALGO_BIVARIATE = "bivariate_normal"
ALGO_LSTM = "lstm_autoencoder"
ALGO_AUTO = "auto"
MULTIVARIATE_ALGOS = frozenset({ALGO_BIVARIATE, ALGO_LSTM, ALGO_AUTO})

# Sigmas ABOVE the configured threshold at which residual-MVN evidence is
# strong enough to flag alone; below it (but above the configured cutoff)
# a point needs corroboration (AE agreement or a neighboring exceedance).
# Measured on the quality scenarios (th=240..1008, F=4, thr=4): clean
# points top out 1.1-1.5x the base chi^2 cutoff while true joint
# anomalies — including single-metric correlation breaks, the weakest
# family — clear the +1-sigma quantile; +2 demoted real breaks into the
# band and cost recall. See the confirmation-band comment in
# _judge_lstm_group.
MVN_CONFIRM_MARGIN = 1.0

# Univariate fallbacks when a multivariate algorithm is configured but the
# job's metric count doesn't fit. `auto` means "pick the best model for
# the job's shape", so its univariate branch uses the structure screen
# (flat -> global mean, seasonal/trend -> fitted Holt-Winters; quality
# table in BENCHMARKS.md). Explicitly-configured bivariate/lstm keep the
# reference's deployed default for their misfit jobs — the operator chose
# a specific algorithm, not "best available" (`foremast-brain.yaml:24-25`).
FALLBACK_UNIVARIATE = "moving_average_all"
FALLBACK_AUTO = "auto_univariate"


def select_mode(algorithm: str, n_metrics: int) -> str:
    """'univariate' | 'bivariate' | 'lstm' for a job with n_metrics."""
    if algorithm == ALGO_AUTO:
        if n_metrics <= 1:
            return "univariate"
        return "bivariate" if n_metrics == 2 else "lstm"
    if algorithm == ALGO_BIVARIATE:
        return "bivariate" if n_metrics == 2 else "univariate"
    if algorithm == ALGO_LSTM:
        return "lstm" if n_metrics >= 2 else "univariate"
    return "univariate"


def align_series(
    times: list[np.ndarray], vals: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    """Common timestamps + stacked values [F, n] for one job's window set.

    Joint observations exist only where every metric has a sample. The
    one alignment routine for BOTH the object path (`_align`) and the
    worker's joint columnar path — the two must never diverge on how a
    ragged alias set intersects."""
    times = [np.asarray(t, np.int64) for t in times]
    vals = [np.asarray(v, np.float32) for v in vals]
    common = times[0]
    for t in times[1:]:
        common = np.intersect1d(common, t, assume_unique=False)
    if len(common) == 0:
        return common, np.zeros((len(times), 0), np.float32)
    cols = []
    for t, v in zip(times, vals):
        # first occurrence per timestamp (times may repeat in raw traces)
        order = np.argsort(t, kind="stable")
        ts = t[order]
        idx = np.searchsorted(ts, common)
        cols.append(v[order][idx])
    return common, np.stack(cols, axis=0)


def _align(tasks: list[MetricTask], which: str) -> tuple[np.ndarray, np.ndarray]:
    """`align_series` over one job's task windows (which: 'hist'/'cur')."""
    return align_series(
        [getattr(t, f"{which}_times") for t in tasks],
        [getattr(t, f"{which}_values") for t in tasks],
    )


def _marginal_bounds(hist: np.ndarray, threshold: float, tc: int):
    """Per-metric constant gauge bounds from historical moments.

    hist [F, n] -> (upper [F, tc], lower [F, tc]) — mean +/- thr*sigma,
    the same semantics every univariate detector publishes."""
    if hist.shape[1] == 0:
        z = np.zeros((hist.shape[0], tc), np.float32)
        return z, z
    mu = hist.mean(axis=1)
    sd = hist.std(axis=1)
    up = np.repeat((mu + threshold * sd)[:, None], tc, axis=1).astype(np.float32)
    lo = np.repeat(
        np.maximum(mu - threshold * sd, 0.0)[:, None], tc, axis=1
    ).astype(np.float32)
    return up, lo


def _pack_np(rows: list[np.ndarray], length: int) -> tuple[np.ndarray, np.ndarray]:
    """Ragged rows -> host ([B, length] values, [B, length] mask)."""
    b = len(rows)
    out = np.zeros((b, length), np.float32)
    mask = np.zeros((b, length), bool)
    for i, r in enumerate(rows):
        n = min(len(r), length)
        out[i, :n] = r[:n]
        mask[i, :n] = True
    return out, mask


def _pack(rows: list[np.ndarray], length: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Ragged rows -> ([B, length] values, [B, length] mask) on device."""
    out, mask = _pack_np(rows, length)
    return jnp.asarray(out), jnp.asarray(mask)


# Checkpoint-blob coercion: rebuilds device params (jnp) and host MVN
# arrays from whatever layout Orbax restored; the H2D uploads and scalar
# reads here are the rehydration contract.
# foremast: device-boundary
def _coerce_entry(entry) -> tuple:
    """Normalize a cache entry to (AEParams, float, float, mvn | None).

    `mvn` is the seasonal-residual Gaussian state as a plain 9-tuple of
    host values — (level [F], trend [F], season [F, m], phase [F],
    resid_mu [F], cov [F, F], valid bool, hist_last_ts int, hist_len int);
    the two trailing ints are the time anchor `_mvn_fresh` checks — see
    `_judge_lstm_group`. Orbax restores NamedTuple pytrees as plain dicts
    and tuples as lists (models/cache.py load); scoring stacks entries
    with jax.tree.map, so every entry must share exact structures. Legacy
    3-tuples (pre-mvn checkpoints) coerce with mvn=None and are refit."""
    params, mu, sd = entry[0], entry[1], entry[2]
    mvn = entry[3] if len(entry) > 3 else None
    changed = not (isinstance(entry, tuple) and len(entry) == 4)
    if not isinstance(params, AEParams):
        changed = True

        def lstm(d) -> LSTMParams:
            return LSTMParams(
                w_x=jnp.asarray(d["w_x"]),
                w_h=jnp.asarray(d["w_h"]),
                b=jnp.asarray(d["b"]),
            )

        params = AEParams(
            enc=lstm(params["enc"]),
            dec=lstm(params["dec"]),
            w_out=jnp.asarray(params["w_out"]),
            b_out=jnp.asarray(params["b_out"]),
        )
    mvn_ok = mvn is None or (
        isinstance(mvn, tuple)
        and len(mvn) == 9
        and all(isinstance(a, np.ndarray) for a in mvn[:6])
        and isinstance(mvn[6], bool)
    )
    if not mvn_ok:
        if not (hasattr(mvn, "__len__") and len(mvn) == 9):
            # unknown/older layout: drop — the judge refits the MVN
            mvn = None
        else:
            mvn = (
                np.asarray(mvn[0], np.float32),
                np.asarray(mvn[1], np.float32),
                np.asarray(mvn[2], np.float32),
                np.asarray(mvn[3], np.int32),
                np.asarray(mvn[4], np.float32),
                np.asarray(mvn[5], np.float32),
                bool(np.asarray(mvn[6])),
                int(np.asarray(mvn[7])),
                int(np.asarray(mvn[8])),
            )
        changed = True
    return (params, float(mu), float(sd), mvn) if changed else entry


@dataclasses.dataclass
class _JointJob:
    tasks: list[MetricTask]
    hist_t: np.ndarray
    hist_v: np.ndarray  # [F, nh]
    cur_t: np.ndarray
    cur_v: np.ndarray  # [F, nc]


def _pack_bf16_delta_rows(values: np.ndarray, mask: np.ndarray):
    """Anchor-shifted bf16-delta pack of left-packed joint histories.

    values [..., T] f32 with a valid-prefix mask [..., T] (broadcastable)
    -> (anchor [...] f32, delta [..., T] bf16). Anchor is the first slot
    (left-packed rows put the first valid value there; all-masked rows
    anchor 0), the same shift `judge._pack_hist_bf16_host` uses, so cold
    joint fits ship 2 B/point instead of 5."""
    import ml_dtypes

    anchor = (values[..., 0] * mask[..., 0]).astype(np.float32)
    delta = (values - anchor[..., None]) * mask
    return anchor, delta.astype(ml_dtypes.bfloat16)


@jax.jit
def lstm_joint_score_from_rows(state, rows, x, mask, cut, cutoff, hi_cutoff, gaps):
    """The LSTM-AE hybrid judgment from ARENA-resident joint state —
    the joint counterpart of `scoring.score_from_arena` (ISSUE 4
    tentpole): one compiled program gathers each doc's state row on
    device (`rows` [S] into the TreeArena leaves), runs the AE
    reconstruction check and the echo-robust residual-MVN check, and
    applies the confirmation-band corroboration rule — exactly the
    `_judge_lstm_group` scoring tail, with zero per-tick state upload.

    state: TreeArena pytree — `ae` (stacked AEParams), `level`/`trend`/
    `season`/`phase` (per-metric HW terminal state, season tiled to the
    arena width), `rmu`/`cov` (residual Gaussian), `valid`.
    x [S, 1, tc, F] padded aligned current windows; mask [S, tc] real
    points; cut [S] gamma-calibrated AE error cutoffs; cutoff/hi_cutoff
    [S] chi^2 base / strong-evidence cutoffs; gaps [S] int32 hist->cur
    gap steps (phase advance — the arena state itself stays pristine).
    Returns anomaly flags [S, tc] bool."""
    ae_flags, _err = score_rows_cutoff(
        state["ae"], rows, x, mask[:, None, :], cut
    )
    st = jax.tree.map(
        lambda leaf: jnp.take(leaf, rows, axis=0),
        {k: v for k, v in state.items() if k != "ae"},
    )
    return _lstm_joint_judgment(
        ae_flags[:, 0, :], st, x, mask, cutoff, hi_cutoff, gaps
    )


@partial(jax.jit, static_argnames=("mesh",))
def lstm_joint_score_from_rows_sharded(
    state, rows, x, mask, cut, cutoff, hi_cutoff, gaps, mesh=None
):
    """`lstm_joint_score_from_rows` against a DATA-AXIS-SHARDED
    TreeArena (ISSUE 19): every leaf (the stacked AEParams included)
    block-shards its [capacity] leading axis over `mesh`'s data axis
    and `rows` [S] carries LOCAL (per-shard) indices, so the whole-tree
    gather runs as one shard_map against each device's own block —
    zero cross-chip transfer — before the identical judgment tail."""
    from foremast_tpu.parallel import mesh as meshlib

    gathered = meshlib.shard_rows_take(state, rows, mesh)
    ae_flags, _err = score_many_cutoff(
        gathered["ae"], x, mask[:, None, :], cut
    )
    st = {k: v for k, v in gathered.items() if k != "ae"}
    return _lstm_joint_judgment(
        ae_flags[:, 0, :], st, x, mask, cutoff, hi_cutoff, gaps
    )


def _lstm_joint_judgment(ae_flags, st, x, mask, cutoff, hi_cutoff, gaps):
    """Shared scoring tail of the two from-rows LSTM programs: HW gap
    advance, echo-robust residual-MVN distance, confirmation-band
    corroboration. `ae_flags` [S, tc]; `st` the gathered per-batch (not
    per-capacity) non-AE state dict."""
    s, f = x.shape[0], x.shape[-1]
    m = st["season"].shape[-1]
    gap = gaps.astype(jnp.int32)
    # phase advances by the TRUE gap (mod m); only the trend
    # extrapolation is bounded — same rule as the object path and the
    # univariate scorer's _advance_gap
    phase = ((st["phase"] + gap[:, None]) % m).astype(jnp.int32)
    level = st["level"] + st["trend"] * jnp.minimum(
        gap, scoring.GAP_TREND_CAP_STEPS
    ).astype(jnp.float32)[:, None]
    hw = Forecast(
        pred=jnp.zeros((s * f, 0), jnp.float32),
        scale=jnp.zeros((s * f,), jnp.float32),
        level=level.reshape(-1),
        trend=st["trend"].reshape(-1),
        season=st["season"].reshape(s * f, m),
        season_phase=phase.reshape(-1),
    )
    mvn = MVNState(hw=hw, mu=st["rmu"], cov=st["cov"], valid=st["valid"])
    cur_sf = jnp.swapaxes(x[:, 0], 1, 2)  # [S, F, tc]
    d2 = residual_mvn_d2_robust(mvn, cur_sf, cutoff)
    # confirmation band (see _judge_lstm_group): strong evidence flags
    # alone; borderline needs AE agreement or a BORDERLINE neighbor
    valid = st["valid"][:, None] & mask
    over = (d2 > cutoff[:, None]) & valid
    strong = (d2 > hi_cutoff[:, None]) & valid
    border = over & ~strong
    neighbor = jnp.pad(border[:, :-1], ((0, 0), (1, 0))) | jnp.pad(
        border[:, 1:], ((0, 0), (0, 1))
    )
    mvn_flags = strong | (border & (ae_flags | neighbor))
    return ae_flags | mvn_flags


class MultivariateJudge:
    """Dispatcher: routes each job to univariate/bivariate/LSTM judgment.

    Drop-in for HealthJudge at the worker level: same
    `judge(tasks) -> [MetricVerdict]` surface over the flat task list.
    """

    def __init__(
        self,
        config: BrainConfig | None = None,
        univariate: HealthJudge | None = None,
        cache: ModelCache | None = None,
    ):
        self.config = config or BrainConfig()
        uni_cfg = self.config
        if self.config.algorithm in MULTIVARIATE_ALGOS:
            fallback = (
                FALLBACK_AUTO
                if self.config.algorithm == ALGO_AUTO
                else FALLBACK_UNIVARIATE
            )
            uni_cfg = dataclasses.replace(self.config, algorithm=fallback)
        self.univariate = univariate or HealthJudge(uni_cfg)
        if self.univariate.config.algorithm in MULTIVARIATE_ALGOS:
            # an injected judge (e.g. ShardedJudge) built from the raw
            # config must not hand a multivariate algorithm name to the
            # univariate scoring program
            self.univariate.config = uni_cfg
        self.cache = cache or ModelCache(self.config.max_cache_size)
        self.lstm_steps = int(os.environ.get("FOREMAST_LSTM_STEPS", "60"))
        # Joint columnar support (ISSUE 4 tentpole): per-key warm-path
        # metadata the slow path records next to every joint fit —
        # aligned-history moments (the per-alias gauge bounds), the
        # time anchors for the MVN phase advance, and the window bucket
        # the model was fitted at. Keyed by (mode, app, aliases, the
        # per-alias fit keys), so a redeploy with new historical ranges
        # can never replay a stale-phase model.
        self.joint_meta = ModelCache(self.config.max_cache_size)
        # device arenas holding joint-model state rows (TreeArena), one
        # per (mode, feature count); monotone counter base folds retired
        # arenas like HealthJudge._counters_base
        self._joint_arenas: dict = {}
        self._joint_counters_base = {
            "hits": 0,
            "misses": 0,
            "evictions": 0,
            "shard_moves": 0,
            "fallbacks": 0,
        }
        # joint columnar batch-padding accounting (ISSUE 13) — the
        # joint-path counterpart of HealthJudge.pad_rows_total; the
        # worker's device_mesh varz sums both
        self.pad_rows_total = 0
        self.batch_rows_total = 0

    # -- public ----------------------------------------------------------

    def judge(self, tasks: list[MetricTask]) -> list[MetricVerdict]:
        if not tasks:
            return []
        by_job: dict[str, list[MetricTask]] = {}
        for t in tasks:
            by_job.setdefault(t.job_id, []).append(t)

        uni: list[MetricTask] = []
        bi: list[list[MetricTask]] = []
        lstm: list[list[MetricTask]] = []
        for job_tasks in by_job.values():
            mode = select_mode(self.config.algorithm, len(job_tasks))
            if mode == "bivariate":
                bi.append(job_tasks)
            elif mode == "lstm":
                lstm.append(job_tasks)
            else:
                uni.extend(job_tasks)

        out: list[MetricVerdict] = []
        if uni:
            out.extend(self.univariate.judge(uni))
        if bi:
            out.extend(self._judge_bivariate(bi))
        if lstm:
            out.extend(self._judge_lstm(lstm))
        return out

    # -- shared helpers --------------------------------------------------

    def _joint(self, job_tasks: list[MetricTask]) -> _JointJob:
        ht, hv = _align(job_tasks, "hist")
        ct, cv = _align(job_tasks, "cur")
        return _JointJob(job_tasks, ht, hv, ct, cv)

    # Pairwise decode stage: gathers the jitted rank-test program's
    # (p, differs) result for host emission.
    # foremast: device-boundary
    def _pairwise(
        self, joints: list[_JointJob]
    ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Per-job (p [F], differs [F]) — each alias's raw current window
        tested against its own baseline window, exactly the univariate
        canary check (`design.md:31-33`). Metrics without a baseline (the
        rollingUpdate strategy) fail every min-points gate and report
        (1.0, False)."""
        cfg = self.config
        tasks = [t for j in joints for t in j.tasks]
        if all(t.base_values is None for t in tasks):
            # baseline-less batch (rollingUpdate): provably (1.0, False)
            # everywhere — skip the packing + kernel dispatch entirely
            return [
                (np.ones(len(j.tasks)), np.zeros(len(j.tasks), bool))
                for j in joints
            ]
        tc = bucket_length(
            max(
                max(
                    len(t.cur_values),
                    0 if t.base_values is None else len(t.base_values),
                )
                for t in tasks
            )
        )
        empty = (np.zeros(0, np.int64), np.zeros(0, np.float32))
        cur = MetricWindows.from_ragged(
            [(t.cur_times, t.cur_values) for t in tasks], tc
        )
        base = MetricWindows.from_ragged(
            [
                (t.base_times, t.base_values)
                if t.base_values is not None
                else empty
                for t in tasks
            ],
            tc,
        )
        p, differs = scoring.pairwise(
            cur,
            base,
            algorithm=cfg.pairwise.algorithm,
            p_threshold=cfg.pairwise.threshold,
            min_mw=cfg.pairwise.min_mann_white_points,
            min_wilcoxon=cfg.pairwise.min_wilcoxon_points,
            min_kruskal=cfg.pairwise.min_kruskal_points,
            min_friedman=cfg.pairwise.min_friedman_points,
        )
        p, differs = np.asarray(p), np.asarray(differs)
        out, i = [], 0
        for j in joints:
            f = len(j.tasks)
            out.append((p[i : i + f], differs[i : i + f]))
            i += f
        return out

    def _unknown(
        self,
        job_tasks: list[MetricTask],
        pairwise: tuple[np.ndarray, np.ndarray] | None = None,
    ) -> list[MetricVerdict]:
        """UNKNOWN verdicts still carry real pairwise evidence when it was
        computed (parity with the univariate ScoreResult, which always
        publishes p/differs regardless of measurability)."""
        return [
            MetricVerdict(
                job_id=t.job_id,
                alias=t.alias,
                verdict=scoring.UNKNOWN,
                anomaly_pairs=[],
                upper=np.zeros(len(t.cur_values), np.float32),
                lower=np.zeros(len(t.cur_values), np.float32),
                p_value=1.0 if pairwise is None else float(pairwise[0][f]),
                dist_differs=False
                if pairwise is None
                else bool(pairwise[1][f]),
            )
            for f, t in enumerate(job_tasks)
        ]

    def _effective_thresholds(
        self,
        pw: list[tuple[np.ndarray, np.ndarray]],
        threshold: float,
    ) -> np.ndarray:
        """Per-job joint threshold: lowered by DIFF_THRESHOLD_FACTOR when
        ANY alias's distributions differ (design.md:33) — the one rule both
        joint paths share."""
        return np.asarray(
            [
                threshold * scoring.DIFF_THRESHOLD_FACTOR
                if bool(d.any())
                else threshold
                for _, d in pw
            ],
            np.float32,
        )

    def _emit(
        self,
        job: _JointJob,
        flags: np.ndarray,  # [nc] bool over the aligned current points
        threshold: float,
        pairwise: tuple[np.ndarray, np.ndarray] | None = None,  # (p[F], differs[F])
    ) -> list[MetricVerdict]:
        """Joint flags -> per-alias verdicts in the reference wire form."""
        flagged_times = job.cur_t[flags]
        verdict = scoring.UNHEALTHY if flags.any() else scoring.HEALTHY
        up, lo = _marginal_bounds(job.hist_v, threshold, max(len(job.cur_t), 1))
        out = []
        for f, t in enumerate(job.tasks):
            # pairs carry each alias's own measured value at the joint
            # anomalous timestamps
            vals = job.cur_v[f][flags]
            pairs: list[float] = []
            for ts, v in zip(flagged_times, vals):
                pairs.extend([float(ts), float(v)])
            out.append(
                MetricVerdict(
                    job_id=t.job_id,
                    alias=t.alias,
                    verdict=verdict,
                    anomaly_pairs=pairs,
                    upper=up[f],
                    lower=lo[f],
                    p_value=1.0 if pairwise is None else float(pairwise[0][f]),
                    dist_differs=False
                    if pairwise is None
                    else bool(pairwise[1][f]),
                )
            )
        return out

    # -- bivariate -------------------------------------------------------

    # Slow-path bivariate stage: fit + dispatch + gather + verdict
    # decode in one body (cold-fit latency regime; the warm path is
    # joint_columnar).
    # foremast: device-boundary
    def _judge_bivariate(self, jobs: list[list[MetricTask]]) -> list[MetricVerdict]:
        threshold = self.config.anomaly.rule_for(None).threshold
        min_pts = self.config.min_historical_points
        # pairwise evidence is computed for EVERY job — even ones that end
        # up UNKNOWN — so the wire always carries it (univariate parity)
        all_joints = [self._joint(job_tasks) for job_tasks in jobs]
        all_pw = self._pairwise(all_joints)
        joints, pw, out = [], [], []
        for j, p in zip(all_joints, all_pw):
            if len(j.hist_t) < min_pts or len(j.cur_t) == 0:
                out.extend(self._unknown(j.tasks, p))
            else:
                joints.append(j)
                pw.append(p)
        if not joints:
            return out

        th = bucket_length(max(len(j.hist_t) for j in joints))
        tc = bucket_length(max(len(j.cur_t) for j in joints))
        hx_np, hm_np = _pack_np([j.hist_v[0] for j in joints], th)
        hy_np, _ = _pack_np([j.hist_v[1] for j in joints], th)
        cx, cm = _pack([j.cur_v[0] for j in joints], tc)
        cy, _ = _pack([j.cur_v[1] for j in joints], tc)

        eff_thr = self._effective_thresholds(pw, threshold)
        if scoring.bf16_delta_enabled():
            # cold joint fits ship anchor + bf16 deltas (2 B/point) —
            # the same wire layout as the univariate cold-fit upload
            ax, dx = _pack_bf16_delta_rows(hx_np, hm_np)
            ay, dy = _pack_bf16_delta_rows(hy_np, hm_np)
            fit = fit_bivariate_bf16_delta(
                jnp.asarray(ax),
                jnp.asarray(dx),
                jnp.asarray(ay),
                jnp.asarray(dy),
                jnp.asarray(hm_np),
                min_points=min_pts,
            )
        else:
            fit = fit_bivariate(
                jnp.asarray(hx_np),
                jnp.asarray(hy_np),
                jnp.asarray(hm_np),
                min_points=min_pts,
            )
        flags = np.asarray(detect_bivariate(fit, cx, cy, cm, jnp.asarray(eff_thr)))
        valid = np.asarray(fit.valid)
        mean_np = np.asarray(fit.mean)
        cov_np = np.asarray(fit.cov)
        for i, j in enumerate(joints):
            if not valid[i]:
                out.extend(self._unknown(j.tasks, pw[i]))
            else:
                # valid fits become warm-path state: the entry is the
                # fitted Gaussian, the meta carries the warm-band inputs
                # (invalid fits cache NOTHING, so the columnar path can
                # never turn an UNKNOWN doc healthy)
                self._record_joint(
                    "bivariate", j, 0, entry=(mean_np[i], cov_np[i])
                )
                out.extend(
                    self._emit(
                        j, flags[i, : len(j.cur_t)], float(eff_thr[i]), pw[i]
                    )
                )
        return out

    # -- LSTM autoencoder ------------------------------------------------

    def _judge_lstm(self, jobs: list[list[MetricTask]]) -> list[MetricVerdict]:
        threshold = self.config.anomaly.rule_for(None).threshold
        min_pts = self.config.min_historical_points
        out: list[MetricVerdict] = []
        # one batched pairwise call for ALL jobs (gated-out ones included)
        # — same shape discipline as the bivariate path
        all_joints = [self._joint(job_tasks) for job_tasks in jobs]
        all_pw = self._pairwise(all_joints)
        # group by (feature count, per-JOB window bucket): fit_many needs
        # uniform [S, W, T, F], and using a group-wide max tc would let one
        # long-current job starve a short-history job into all-masked
        # training windows (mu=sd=0 -> everything flags)
        groups: dict[tuple[int, int], list[tuple[_JointJob, tuple]]] = {}
        for j, p in zip(all_joints, all_pw):
            f = j.hist_v.shape[0]
            tc = bucket_length(max(len(j.cur_t), 1))
            # Explicit min-history gate: the history must fill at least
            # TWO training windows of this job's own bucket (and clear
            # the configured minimum). One window is not a model: the
            # AE's mu/sd cutoff calibration comes from the training
            # reconstruction errors, and a single-window "distribution"
            # degenerates — measured, it flags clean in-band noise as
            # UNHEALTHY (the short-history regression test). Too-short
            # jobs degrade to UNKNOWN, never to a fragile fit.
            if len(j.cur_t) == 0 or len(j.hist_t) < max(min_pts, 2 * tc):
                out.extend(self._unknown(j.tasks, p))
            else:
                groups.setdefault((f, tc), []).append((j, p))

        for (f, tc), pairs in groups.items():
            out.extend(
                self._judge_lstm_group(
                    [j for j, _ in pairs], [p for _, p in pairs], f, tc, threshold
                )
            )
        return out

    # Slow-path LSTM/MVN group stage: fit + dispatch + gather + verdict
    # decode in one body (cold-fit latency regime; the warm path is
    # joint_columnar).
    # foremast: device-boundary
    def _judge_lstm_group(
        self,
        joints: list[_JointJob],
        pw: list[tuple[np.ndarray, np.ndarray]],
        f: int,
        tc: int,
        threshold: float,
    ) -> list[MetricVerdict]:
        cfg = LSTMAEConfig(features=f)
        # entry per joint job, kept locally — the bounded ModelCache may
        # evict mid-batch, so never re-read what was just trained
        entries: dict[int, tuple] = {}
        to_train: list[_JointJob] = []
        for j in joints:
            cached = self.cache.get(self._key(j, tc))
            if cached is None:
                to_train.append(j)
            else:
                entry = _coerce_entry(cached)
                if entry is not cached:  # orbax-restored form: fix once
                    self.cache.put(self._key(j, tc), entry)
                entries[id(j)] = entry

        if to_train:
            # chop each history into tc-length windows (newest-aligned);
            # every job has >= 1 real window (admission: hist >= tc), and
            # shorter histories pad with fully-masked windows. The 8-window
            # cap is justified empirically: raising it to 32 (and steps to
            # 150) left joint-detection F1 unchanged — the AE's blind spot
            # is structural (it copies in-window anomalies), which the
            # residual-Gaussian companion below covers instead.
            n_win = min(max(len(j.hist_t) // tc for j in to_train), 8)
            xs, ms = [], []
            for j in to_train:
                wins, wmask = [], []
                usable = (len(j.hist_t) // tc) * tc
                chunks = j.hist_v[:, len(j.hist_t) - usable:].reshape(f, -1, tc)
                for w in range(min(chunks.shape[1], n_win)):
                    wins.append(chunks[:, -(w + 1), :].T)  # [tc, F]
                    wmask.append(np.ones(tc, bool))
                while len(wins) < n_win:
                    wins.append(np.zeros((tc, f), np.float32))
                    wmask.append(np.zeros(tc, bool))
                xs.append(np.stack(wins))  # [n_win, tc, F]
                ms.append(np.stack(wmask))
            x = jnp.asarray(np.stack(xs))  # [S, n_win, tc, F]
            mask = jnp.asarray(np.stack(ms))
            params, mu, sd, _ = fit_many(
                jax.random.key(0), x, mask, cfg, steps=self.lstm_steps
            )
            mu_np, sd_np = np.asarray(mu), np.asarray(sd)
            for i, j in enumerate(to_train):
                leaf = jax.tree.map(lambda a, i=i: a[i], params)
                entry = (leaf, float(mu_np[i]), float(sd_np[i]), None)
                entries[id(j)] = entry

        # seasonal-residual Gaussian companion (models/residual_mvn.py):
        # fitted once per job next to the AE and cached with it — catches
        # contextual anomalies the reconstruction path copies. Unlike the
        # AE (window-normalized, roughly phase-free), the MVN's HW state is
        # TIME-ANCHORED, so a cached fit is only reused for the exact same
        # history (last timestamp + length); a later deployment of the
        # same app refits instead of replaying a phase-stale season.
        def _mvn_fresh(j: _JointJob, mvn) -> bool:
            return (
                mvn is not None
                and len(j.hist_t) == mvn[8]
                and int(j.hist_t[-1]) == mvn[7]
            )

        need_mvn = [
            j for j in joints if not _mvn_fresh(j, entries[id(j)][3])
        ]
        # Partition by the 2-cycle identifiability rule BEFORE bucketing:
        # fit_residual_mvn's season guard keys off the batch's STATIC
        # length, so a 12-hour job bucket-padded next to a 3-day job would
        # be fitted at the long batch's m and land an empty warm region
        # (valid=False). Short jobs get their own m=1 (Holt) fit instead.
        # The short partition is fitted at m=1 EXPLICITLY: its bucket can
        # still round up past 2*season (a 1.5-day job pads to 4096 > 2880),
        # which would defeat fit_residual_mvn's static-length guard.
        season = self.config.season_steps
        for need, m_part in (
            ([j for j in need_mvn if len(j.hist_t) >= 2 * season], season),
            ([j for j in need_mvn if len(j.hist_t) < 2 * season], 1),
        ):
            if need:
                self._fit_mvn_batch(need, entries, f, tc, m_part)

        # score every joint job against its (possibly cached) model
        out: list[MetricVerdict] = []
        ordered = [entries[id(j)] for j in joints]
        stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *[e[0] for e in ordered])
        mu = jnp.asarray([e[1] for e in ordered])
        sd = jnp.asarray([e[2] for e in ordered])
        cur_rows = []
        cur_masks = []
        for j in joints:
            row = np.zeros((tc, f), np.float32)
            n = min(len(j.cur_t), tc)
            row[:n] = j.cur_v[:, :n].T
            m = np.zeros(tc, bool)
            m[:n] = True
            cur_rows.append(row[None])  # [1, tc, F]
            cur_masks.append(m[None])
        cur_np = np.stack(cur_rows)  # [S, 1, tc, F]
        cur_mask = np.stack(cur_masks)[:, 0, :]  # [S, tc] real points
        xq = jnp.asarray(cur_np)
        mq = jnp.asarray(cur_mask[:, None, :])
        # canary check: a differing alias lowers the job's joint recon-error
        # threshold (design.md:33), same rule as the bivariate path; the
        # cutoff is the gamma-quantile calibration (models/lstm_ae.ae_cutoff)
        eff_thr = self._effective_thresholds(pw, threshold)
        cut = ae_cutoff(np.asarray(mu), np.asarray(sd), eff_thr)
        flags, _err = score_many_cutoff(stacked, xq, mq, jnp.asarray(cut))
        flags = np.asarray(flags)[:, 0, :]  # [S, tc]

        # hybrid judgment: reconstruction flags UNION residual-Gaussian
        # flags — the learned model covers pattern deviations, the
        # closed-form covers contextual/correlation-break anomalies it
        # can copy (see models/residual_mvn.py docstring)
        s_count = len(joints)
        mvns = [entries[id(j)][3] for j in joints]
        levels = np.stack([m[0] for m in mvns])  # [S, F]
        trends = np.stack([m[1] for m in mvns])
        # entries may mix season widths (identifiability partitions fit
        # short histories at m=1; scoring.tile_season documents exactness)
        m_len = max(m[2].shape[-1] for m in mvns)
        seasons = np.stack(
            [scoring.tile_season(m[2], m_len) for m in mvns]
        )  # [S, F, m]
        phases = np.stack([m[3] for m in mvns]).astype(np.int64)
        # advance each job's HW state across the real history->current gap
        # (from timestamps) so the seasonal phase lines up with the window
        # being scored; the fitted phase assumes cur starts one step after
        # the history's last point
        for i, j in enumerate(joints):
            step = infer_step(j.hist_t)
            # every scored joint job becomes warm-path state: entry is
            # already in the cache (trained/refit jobs were put by
            # _fit_mvn_batch); the meta records the warm-band inputs and
            # the time anchors the columnar path advances phases with
            self._record_joint("lstm", j, tc, step=step)
            k = int(round((float(j.cur_t[0]) - mvns[i][7]) / max(step, 1.0)))
            gap = max(k - 1, 0)
            # phase advances by the TRUE gap (mod m — clamping here would
            # corrupt the phase, e.g. 10*m ≡ 0); only the trend
            # extrapolation is bounded against runaway level drift (same
            # cap as the univariate scorer's _advance_gap)
            phases[i] = (phases[i] + gap) % m_len
            levels[i] = levels[i] + trends[i] * min(
                gap, scoring.GAP_TREND_CAP_STEPS
            )
        hw = Forecast(
            pred=jnp.zeros((s_count * f, 0), jnp.float32),
            scale=jnp.zeros((s_count * f,), jnp.float32),
            level=jnp.asarray(levels.reshape(-1)),
            trend=jnp.asarray(trends.reshape(-1)),
            season=jnp.asarray(seasons.reshape(s_count * f, -1)),
            season_phase=jnp.asarray(phases.reshape(-1).astype(np.int32)),
        )
        state = MVNState(
            hw=hw,
            mu=jnp.asarray(np.stack([m[4] for m in mvns])),
            cov=jnp.asarray(np.stack([m[5] for m in mvns])),
            valid=jnp.asarray(np.asarray([m[6] for m in mvns])),
        )
        # same padded buffer the AE scored, in the MVN's [S, F, tc] layout
        cur_sf = cur_np[:, 0].transpose(0, 2, 1)
        cutoffs = np.asarray(
            [chi2_quantile(float(eff_thr[i]), f) for i in range(s_count)],
            np.float32,
        )
        # Strong-evidence cutoff for the confirmation band: the chi^2
        # quantile at (threshold + MVN_CONFIRM_MARGIN) sigmas. The chi^2
        # calibration is exact only for Gaussian residuals; real HW
        # residuals are heavier-tailed, so points BETWEEN the two cutoffs
        # (borderline by construction — measured FPs land 1.1-1.6x the
        # base cutoff while true anomalies clear 2x, BENCHMARKS.md) flag
        # only with corroboration: the AE reconstruction flags the same
        # point, or a NEIGHBORING point also exceeds the base cutoff (a
        # sustained shift). Fail-fast + AutoRollback semantics
        # (design.md:43, MonitorController.go:214-229) make every false
        # point a potential rollback, so borderline single-point evidence
        # from one detector alone is not enough.
        hi_cutoffs = np.asarray(
            [
                chi2_quantile(float(eff_thr[i]) + MVN_CONFIRM_MARGIN, f)
                for i in range(s_count)
            ],
            np.float32,
        )
        d2 = np.asarray(
            residual_mvn_d2_robust(
                state, jnp.asarray(cur_sf), jnp.asarray(cutoffs)
            )
        )
        # cur_mask keeps bucket padding out of the band logic: a padded
        # zero can land a borderline d^2 and would otherwise corroborate
        # the last REAL point through the neighbor rule
        valid = np.asarray(state.valid)[:, None] & cur_mask
        over = (d2 > cutoffs[:, None]) & valid
        strong = (d2 > hi_cutoffs[:, None]) & valid
        border = over & ~strong
        # A neighboring exceedance corroborates a borderline point only if
        # it is itself BORDERLINE (a sustained moderate shift spans
        # consecutive moderate points). A STRONG neighbor must not count:
        # the causal HW state absorbs each observed point, so a strong
        # spike at t contaminates the t+1 prediction and manufactures a
        # borderline echo right next to itself — exactly the false point
        # this rule would otherwise confirm.
        neighbor = np.zeros_like(border)
        neighbor[:, 1:] |= border[:, :-1]
        neighbor[:, :-1] |= border[:, 1:]
        mvn_flags = strong | (border & (flags | neighbor))
        flags = flags | mvn_flags

        for i, j in enumerate(joints):
            out.extend(
                self._emit(j, flags[i, : len(j.cur_t)], float(eff_thr[i]), pw[i])
            )
        return out

    # Cold MVN fit stage: uploads aligned histories, runs the jitted
    # fit, gathers the state tuple to host numpy for the cache entry.
    # foremast: device-boundary
    def _fit_mvn_batch(
        self,
        need: list[_JointJob],
        entries: dict[int, tuple],
        f: int,
        tc: int,
        season: int,
    ) -> None:
        """Fit the residual MVN for one identifiability partition and fold
        the state into each job's cache entry (time-anchored)."""
        thb = bucket_length(max(len(j.hist_t) for j in need))
        hist = np.zeros((len(need), f, thb), np.float32)
        hmask = np.zeros((len(need), thb), bool)
        for i, j in enumerate(need):
            nh = j.hist_v.shape[1]
            hist[i, :, :nh] = j.hist_v
            hmask[i, :nh] = True
        if scoring.bf16_delta_enabled():
            # cold joint fits ship anchor + bf16 deltas: the [S, F, Th]
            # aligned-history upload is the H2D bound of a joint-cold
            # tick, the same regime as the univariate cold-fit upload
            anchor, delta = _pack_bf16_delta_rows(hist, hmask[:, None, :])
            st = fit_residual_mvn_bf16_delta(
                jnp.asarray(anchor),
                jnp.asarray(delta),
                jnp.asarray(hmask),
                season_length=season,
            )
        else:
            st = fit_residual_mvn(
                jnp.asarray(hist), jnp.asarray(hmask), season_length=season
            )
        n = len(need)
        lv = np.asarray(st.hw.level, np.float32).reshape(n, f)
        tr = np.asarray(st.hw.trend, np.float32).reshape(n, f)
        se = np.asarray(st.hw.season, np.float32).reshape(n, f, -1)
        ph = np.asarray(st.hw.season_phase, np.int32).reshape(n, f)
        rmu = np.asarray(st.mu, np.float32)
        cov = np.asarray(st.cov, np.float32)
        va = np.asarray(st.valid)
        for i, j in enumerate(need):
            e = entries[id(j)]
            entry = (
                e[0],
                e[1],
                e[2],
                (
                    lv[i],
                    tr[i],
                    se[i],
                    ph[i],
                    rmu[i],
                    cov[i],
                    bool(va[i]),
                    int(j.hist_t[-1]),
                    len(j.hist_t),
                ),
            )
            entries[id(j)] = entry
            self.cache.put(self._key(j, tc), entry)

    def _key(self, j: _JointJob, tc: int) -> tuple:
        # per (app, aliases, feature-count, window-bucket, season): job ids
        # differ per run, but different SERVICES with the same standard
        # alias set (the instrument starter emits identical names for every
        # app) must never share a model; season_steps keys the entry too —
        # the cached MVN season buffer's length must match the configured
        # season at score time
        return (
            "lstm",
            j.tasks[0].app,
            tuple(t.alias for t in j.tasks),
            j.hist_v.shape[0],
            tc,
            self.config.season_steps,
        )

    # -- joint columnar fast path (ISSUE 4 tentpole) ----------------------
    #
    # The slow path above records, next to every joint fit, the warm-path
    # metadata a history-free re-check needs; the worker's fast tick then
    # admits joint docs whose (entry, meta) pair is cached and scores them
    # through one arena-gathered program per model kind — no MetricTask
    # objects, no history fetch, no per-tick state upload.

    def _joint_keys(self, mode: str, j: _JointJob, tc: int):
        """(cache_key, meta_key) for a joint job, or None when any alias
        lacks a fit key (unsettled history — never warm-admissible)."""
        aliases = tuple(t.alias for t in j.tasks)
        app = j.tasks[0].app
        hkeys = tuple(t.fit_key for t in j.tasks)
        if any(k is None for k in hkeys):
            return None
        if mode == "bivariate":
            # history identity IS part of the key: two live docs for the
            # same app/aliases over different historical ranges (two
            # deployments) must never share a fitted Gaussian — the lstm
            # key predates this path and is instead anchored to its
            # history via the entry's mvn[7]/mvn[8] check in
            # columnar_joint_peek
            key = ("bivariate", app, aliases, hkeys)
        else:
            key = self._key(j, tc)
        return key, ("jmeta", mode, app, aliases, hkeys)

    def _record_joint(
        self,
        mode: str,
        j: _JointJob,
        tc: int,
        entry=None,
        step: float | None = None,
    ) -> None:
        """Fold one slow-path joint judgment into warm-path state.

        meta layout: (tc, hist_mu [F], hist_sd [F], step, last_ts,
        n_hist) — the aligned-history moments reproduce `_marginal_bounds`
        without the history, and (step, last_ts) anchor the MVN phase
        advance. The meta is only REPLACED when its anchors change, so a
        stable fleet keeps stable meta identity (the worker revalidates
        admission by identity, exactly like the univariate path)."""
        keys = self._joint_keys(mode, j, tc)
        if keys is None:
            return
        key, meta_key = keys
        if entry is not None:
            self.cache.put(key, entry)
        last_ts = int(j.hist_t[-1])
        n_hist = len(j.hist_t)
        prev = self.joint_meta.peek(meta_key)
        if (
            prev is not None
            and prev[0] == tc
            and prev[4] == last_ts
            and prev[5] == n_hist
        ):
            return
        self.joint_meta.put(
            meta_key,
            (
                tc,
                j.hist_v.mean(axis=1),
                j.hist_v.std(axis=1),
                infer_step(j.hist_t) if step is None else step,
                last_ts,
                n_hist,
            ),
        )

    def columnar_joint_peek(self, mode: str, app: str, aliases: tuple, hist_keys: tuple):
        """Warm-admission probe: (cache_key, entry, meta_key, meta) when
        this joint job can be scored columnar — both the fitted state and
        the warm metadata are cached, the history clears the same
        measurability gates the object path applies, and (lstm) the MVN
        state is anchored to exactly the history the meta describes.
        None otherwise (the doc stays on the slow path). Lock-free peeks:
        admission runs per doc per tick."""
        meta = self.joint_meta.peek(("jmeta", mode, app, aliases, hist_keys))
        if meta is None:
            return None
        tc, _mu, _sd, _step, last_ts, n_hist = meta
        min_pts = self.config.min_historical_points
        if mode == "bivariate":
            if n_hist < min_pts:
                return None
            key = ("bivariate", app, aliases, hist_keys)
            entry = self.cache.peek(key)
            if entry is None:
                return None
        else:
            # same 2-window floor as _judge_lstm's explicit min-history
            # gate — warm admission must never accept a job the slow
            # path would refuse to fit
            if n_hist < max(min_pts, 2 * tc):
                return None
            key = (
                "lstm",
                app,
                aliases,
                len(aliases),
                tc,
                self.config.season_steps,
            )
            entry = self.cache.peek(key)
            # orbax-restored entries coerce on the slow path first; a
            # stale-anchored MVN (same app redeployed over a different
            # history) must refit there too
            if (
                not isinstance(entry, tuple)
                or len(entry) != 4
                or not isinstance(entry[0], AEParams)
            ):
                return None
            mvn = entry[3]
            if mvn is None or mvn[7] != last_ts or mvn[8] != n_hist:
                return None
        return key, entry, ("jmeta", mode, app, aliases, hist_keys), meta

    def _bi_template(self):
        sd = jax.ShapeDtypeStruct
        return {
            "mean": sd((2,), jnp.float32),
            "cov": sd((2, 2), jnp.float32),
        }

    def _lstm_template(self, f: int, m: int):
        sd = jax.ShapeDtypeStruct
        h = LSTMAEConfig(features=f).hidden

        def cell():
            return LSTMParams(
                w_x=sd((f, 4 * h), jnp.float32),
                w_h=sd((h, 4 * h), jnp.float32),
                b=sd((4 * h,), jnp.float32),
            )

        return {
            "ae": AEParams(
                enc=cell(),
                dec=cell(),
                w_out=sd((h, f), jnp.float32),
                b_out=sd((f,), jnp.float32),
            ),
            "level": sd((f,), jnp.float32),
            "trend": sd((f,), jnp.float32),
            "season": sd((f, m), jnp.float32),
            "phase": sd((f,), jnp.int32),
            "rmu": sd((f,), jnp.float32),
            "cov": sd((f, f), jnp.float32),
            "valid": sd((), jnp.bool_),
        }

    def _joint_sharding(self):
        uni = self.univariate
        return uni._arena_sharding() if isinstance(uni, HealthJudge) else None

    def _joint_shards(self) -> int:
        """Row-space shard count for joint arenas — the univariate
        judge's (ISSUE 19): joint TreeArenas block-partition their row
        space over the same data axis as the batch buffers, so warm
        joint gathers are device-local like the univariate path."""
        uni = self.univariate
        return uni._arena_shards() if isinstance(uni, HealthJudge) else 1

    def _joint_multiple(self) -> int:
        """Joint batch leading-axis multiple — the univariate judge's
        (a ShardedJudge's data-axis size), so the joint from-rows
        programs partition over the same mesh (ISSUE 13)."""
        uni = self.univariate
        return uni._batch_multiple() if isinstance(uni, HealthJudge) else 1

    def _place_joint(self, *arrays):
        """Leading-axis placement for joint columnar buffers, through
        the univariate judge's `_place_cols` hook (identity on a plain
        judge; data-axis NamedSharding device_put + partition assert on
        a ShardedJudge)."""
        uni = self.univariate
        if isinstance(uni, HealthJudge):
            return uni._place_cols(*arrays)
        return arrays

    def _joint_arena_for(self, mode: str, f: int, m_need: int):
        """The (mode, f) TreeArena, season buffers at least m_need wide.
        Widening rebuilds empty (host cache entries re-scatter lazily),
        folding the dying arena's counters into the monotone base —
        the same lifecycle as HealthJudge._arena_for. None when arenas
        are disabled (FOREMAST_ARENA_BYTES=0)."""
        from foremast_tpu.engine.arena import TreeArena, _arena_bytes

        if _arena_bytes() <= 0:
            return None
        key = (mode, f)
        arena = self._joint_arenas.get(key)
        if arena is None or getattr(arena, "season_m", 0) < m_need:
            if arena is not None:
                self._retire_joint(arena)
            template = (
                self._bi_template()
                if mode == "bivariate"
                else self._lstm_template(f, m_need)
            )
            arena = TreeArena(
                template,
                sharding=self._joint_sharding(),
                shards=self._joint_shards(),
            )
            arena.season_m = m_need
            self._joint_arenas[key] = arena
        return arena

    def _retire_joint(self, arena) -> None:
        c = arena.counters()
        for k in ("hits", "misses", "evictions", "shard_moves"):
            self._joint_counters_base[k] += c.get(k, 0)

    def joint_state_counters(self) -> dict:
        """Aggregated joint-arena counters, monotone across rebuilds
        (mirrors HealthJudge.device_state_counters)."""
        agg = dict(self._joint_counters_base, rows_live=0, capacity_rows=0)
        for arena in self._joint_arenas.values():
            c = arena.counters()
            for k in (
                "hits",
                "misses",
                "evictions",
                "shard_moves",
                "rows_live",
                "capacity_rows",
            ):
                agg[k] += c.get(k, 0)
        return agg

    def _row_tree(self, mode: str, entry, m: int):
        """One arena row (host numpy pytree) from a cache entry."""
        if mode == "bivariate":
            return {"mean": entry[0], "cov": entry[1]}
        mvn = entry[3]
        return {
            "ae": jax.tree.map(np.asarray, entry[0]),
            "level": mvn[0],
            "trend": mvn[1],
            "season": scoring.tile_season(mvn[2], m),
            "phase": mvn[3].astype(np.int32),
            "rmu": mvn[4],
            "cov": mvn[5],
            "valid": np.bool_(mvn[6]),
        }

    # The warm joint gather stage: arrays in, jitted from-rows programs
    # dispatched, flags gathered to host numpy out (the joint counterpart
    # of the worker's _decode_uni).
    # foremast: device-boundary
    def joint_columnar(
        self,
        mode: str,
        keys: list,
        entries: list,
        metas: list,
        cur: np.ndarray,
        mask: np.ndarray,
        gaps: np.ndarray | None = None,
    ) -> np.ndarray:
        """Batched warm judgment of admitted joint docs — arrays in,
        anomaly flags out (the joint counterpart of `judge_columnar`).

        cur [S, F, tcb] aligned current windows (caller-packed), mask
        [S, tcb] real points, keys/entries/metas per doc from
        `columnar_joint_peek`, gaps [S] int32 hist->cur steps (lstm).
        Returns flags [S, tcb] bool (host numpy). The batch axis is
        pow2-padded (dup of row 0, mask all-False => flags all-False) so
        claim-size jitter cannot force recompiles."""
        s0, f, tcb = cur.shape
        thr = float(self.config.anomaly.rule_for(None).threshold)
        m_need = (
            1
            if mode == "bivariate"
            else max(e[3][2].shape[-1] for e in entries)
        )
        arena = self._joint_arena_for(mode, f, m_need)
        # batch target shape FIRST (pow2 bucket + data-axis rounding,
        # same rule as judge_columnar) — a sharded arena's assign must
        # see the PADDED position list, because row placement is a
        # function of position // (B / shards)
        sb = bucket_length(s0)
        mult = self._joint_multiple()
        if mult > 1 and sb % mult:
            sb += mult - sb % mult
        rows = None
        state = None
        if arena is not None:
            re_ = arena.row_entry
            force = [
                i
                for i, (k, e) in enumerate(zip(keys, entries))
                if re_.get(k) is not None and re_.get(k) is not e
            ]
            keys_a, entries_a = keys, entries
            if arena.shards > 1 and sb != s0:
                # shard-qualified pad keys (ISSUE 19): one stable pad
                # row per data-axis block (same contract as the
                # univariate "__pad__col__@N" family — a single shared
                # key would migrate between blocks as s0 jitters);
                # mask all-False keeps the pad rows' flags inert
                per = sb // arena.shards
                keys_a = list(keys) + [
                    f"__pad__joint__@{(s0 + j) // per}"
                    for j in range(sb - s0)
                ]
                entries_a = list(entries) + [entries[0]] * (sb - s0)
            with span(
                "judge.arena_assemble",
                stage="arena_assemble",
                rows=s0,
                device=True,
            ):
                assigned = arena.assign(keys_a, force, s0)
                if assigned is not None:
                    rows_idx, scat = assigned
                    if scat:
                        trees = [None] * len(entries_a)
                        for i in scat:
                            trees[i] = self._row_tree(
                                mode, entries_a[i], arena.season_m
                            )
                            re_[keys_a[i]] = entries_a[i]
                        arena.scatter(rows_idx, scat, trees)
                    state = arena.state
                    rows = rows_idx
        if rows is None:
            # arena disabled or batch over the hard byte cap: one-off
            # host stack + upload — counted, never silent (same contract
            # as the univariate fallback)
            if arena is not None:
                self._joint_counters_base["fallbacks"] += 1
                log.warning(
                    "joint arena fallback: %d %s rows exceed the hard "
                    "cap — full state restack this tick; raise "
                    "FOREMAST_ARENA_MAX_BYTES",
                    s0,
                    mode,
                )
            trees = [
                self._row_tree(mode, e, m_need) for e in entries
            ]
            state = jax.tree.map(
                lambda *ls: jnp.asarray(np.stack(ls)), *trees
            )
            rows = np.arange(s0, dtype=np.int64)
        # data-axis rounding (ISSUE 13): same rule as judge_columnar —
        # a sharded univariate judge means the joint programs partition
        # over the same mesh, so S must divide by its data axis. A
        # sharded arena assigned real pad rows above (rows is already
        # sb-long); the replicated/stacked layouts pad by duplicating
        # row 0 with an all-False mask: flags all-False, dropped on the
        # [:s0] decode.
        self.batch_rows_total += sb
        self.pad_rows_total += sb - s0
        if sb != s0:
            pad = sb - s0
            cur = np.concatenate(
                [cur, np.zeros((pad, f, tcb), np.float32)]
            )
            mask = np.concatenate([mask, np.zeros((pad, tcb), bool)])
            if len(rows) != sb:
                rows = np.concatenate(
                    [rows, np.full(pad, rows[0], rows.dtype)]
                )
            if gaps is not None:
                gaps = np.concatenate([gaps, np.zeros(pad, np.int32)])
        # sharded-arena dispatch (ISSUE 19): when the joint arena row
        # space is block-partitioned over the data axis, ship LOCAL
        # (per-shard) indices through the same placement hook as the
        # batch buffers and run the shard_map from-rows programs —
        # device-local gather, zero cross-chip transfer. The stacked
        # fallback (state is not arena.state) keeps global rows + the
        # replicated programs.
        sharded = (
            arena is not None
            and arena.shards > 1
            and state is arena.state
        )
        if sharded:
            (rows_j,) = self._place_joint(
                (rows % arena.cap_s).astype(np.int32)
            )
            rows_j = jnp.asarray(rows_j)
            mesh = self.univariate.mesh
        else:
            rows_j = jnp.asarray(rows)
        with span(
            "judge.score", stage="score", rows=sb, device=True
        ):
            if mode == "bivariate":
                bx, by, bm = self._place_joint(
                    cur[:, 0], cur[:, 1], mask
                )
                if sharded:
                    flags = detect_bivariate_from_rows_sharded(
                        state["mean"],
                        state["cov"],
                        rows_j,
                        jnp.asarray(bx),
                        jnp.asarray(by),
                        jnp.asarray(bm),
                        jnp.full((sb,), thr, jnp.float32),
                        mesh=mesh,
                    )
                else:
                    flags = detect_bivariate_from_rows(
                        state["mean"],
                        state["cov"],
                        rows_j,
                        jnp.asarray(bx),
                        jnp.asarray(by),
                        jnp.asarray(bm),
                        jnp.full((sb,), thr, jnp.float32),
                    )
            else:
                thr_arr = np.full(sb, thr, np.float32)
                cut = ae_cutoff(
                    np.asarray([e[1] for e in entries] + [1.0] * (sb - s0)),
                    np.asarray([e[2] for e in entries] + [1.0] * (sb - s0)),
                    thr_arr,
                )
                cutoff = np.full(sb, chi2_quantile(thr, f), np.float32)
                hi = np.full(
                    sb,
                    chi2_quantile(thr + MVN_CONFIRM_MARGIN, f),
                    np.float32,
                )
                xh, mh = self._place_joint(
                    np.ascontiguousarray(cur.transpose(0, 2, 1))[:, None],
                    mask,
                )
                gaps_j = jnp.asarray(
                    gaps if gaps is not None else np.zeros(sb, np.int32)
                )
                if sharded:
                    flags = lstm_joint_score_from_rows_sharded(
                        state,
                        rows_j,
                        jnp.asarray(xh),
                        jnp.asarray(mh),
                        jnp.asarray(cut),
                        jnp.asarray(cutoff),
                        jnp.asarray(hi),
                        gaps_j,
                        mesh=mesh,
                    )
                else:
                    flags = lstm_joint_score_from_rows(
                        state,
                        rows_j,
                        jnp.asarray(xh),
                        jnp.asarray(mh),
                        jnp.asarray(cut),
                        jnp.asarray(cutoff),
                        jnp.asarray(hi),
                        gaps_j,
                    )
        with span("judge.decode", stage="decode", rows=sb, device=True):
            return np.asarray(flags)[:s0]
