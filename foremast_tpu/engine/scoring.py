"""The batched health-judgment engine — the reference brain's hot loop,
re-centered as one jitted TPU program.

Reference semantics being reproduced (`foremast-brain/README.md:5-11`,
`docs/guides/design.md:31-33`):
  1. compute the historical model from the 7-day window;
  2. for canary strategies, run pairwise same-distribution tests between
     baseline and current (Mann-Whitney / Wilcoxon / Kruskal / Friedman,
     combinable via ML_PAIRWISE_ALGORITHM);
  3. if the distributions differ, *lower the threshold*;
  4. threshold-based anomaly detection of current points against the
     historical model's bounds (per-metric-type threshold/bound matrix,
     `foremast-brain.yaml:26-73`);
  5. fail fast: any anomaly -> unhealthy (`design.md:43`).

TPU-first re-design: instead of one job at a time on a CPU sliver, the
whole (service x metric) population is one `[B, T]` batch; every step above
is a masked array op, and the entire judgment is a single `jax.jit`
program. Ragged windows are validity masks; per-metric-type config rows are
gathered into dense `[B]` operand vectors host-side (config.AnomalyConfig
.gather); strategy/bound/algorithm switches are `jnp.where` selects, not
Python branches.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from foremast_tpu.config import (
    PAIRWISE_ALL,
    PAIRWISE_ANY,
    PAIRWISE_FRIEDMAN,
    PAIRWISE_KRUSKAL,
    PAIRWISE_MANN_WHITE,
    PAIRWISE_WILCOXON,
)

# Engine-internal selector (NOT a config choice): compile the judgment
# WITHOUT the pairwise rank tests. Only valid when the caller proves the
# baseline is absent — see pairwise_decision.
PAIRWISE_NONE = "NONE"
from foremast_tpu.ops import kernels
from foremast_tpu.ops.anomaly import compute_bounds, detect_anomalies
from foremast_tpu.ops.forecasters import (
    Forecast,
    double_exponential,
    ewma,
    fit_auto_univariate,
    fit_holt_winters,
    fit_phase_means,
    horizon,
    moving_average,
    moving_average_all,
)
from foremast_tpu.ops.ranks import (
    friedman_chi_square,
    kruskal_wallis,
    mann_whitney_u,
    wilcoxon_signed_rank,
)
from foremast_tpu.ops.windows import MetricWindows

# Verdict codes (map onto the ES status machine, converter.go:13-26:
# HEALTHY -> completed_health, UNHEALTHY -> completed_unhealth,
# UNKNOWN -> completed_unknown).
HEALTHY = 0
UNHEALTHY = 1
UNKNOWN = 2

# The model registry — the reference's "AI_MODEL" table lives in
# `src/models/modelclass.py` of the external brain repo
# (`foremast-brain/README.md:22`); deployed default is `moving_average_all`
# (`foremast-brain.yaml:24-25`). Each entry: (values, mask) -> Forecast.
AI_MODEL = {
    "moving_average_all": moving_average_all,
    "moving_average": moving_average,
    "ewma": ewma,
    "exponential_smoothing": ewma,
    "double_exponential_smoothing": double_exponential,
    "holtwinters": fit_holt_winters,
    "holt_winters": fit_holt_winters,
    # pooled per-phase means + linear trend: the long-season (daily)
    # workhorse — parallel reductions, representation-free cycle shape
    "phase_means": fit_phase_means,
    # structure-screened per-series selection (MA vs structured fits):
    # the recommended default where metric shapes are unknown
    "auto_univariate": fit_auto_univariate,
}


def register_model(name: str, fit_fn) -> None:
    """Extend the registry (used by models/ for seasonal + learned models)."""
    AI_MODEL[name] = fit_fn


# Registry entries that take a season/period dimension, with the keyword
# each expects — the engine threads one configured value (ML_SEASON_STEPS,
# config.BrainConfig.season_steps) through all of them.
_SEASON_KWARG = {
    "holtwinters": "season_length",
    "holt_winters": "season_length",
    "phase_means": "season_length",
    "auto_univariate": "season_length",
    "seasonal": "period",
    "prophet": "period",
}


def _fit_model(algorithm: str, values, mask, season_length: int):
    fit = AI_MODEL.get(algorithm)
    if fit is None:
        # models/ registers its detectors (seasonal/prophet/...) on import;
        # resolve lazily so the registry works without callers importing it
        import foremast_tpu.models  # noqa: F401

        fit = AI_MODEL[algorithm]
    kw = _SEASON_KWARG.get(algorithm)
    return fit(values, mask, **({kw: season_length} if kw else {}))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ScoreBatch:
    """One fixed-shape batch of scoring work.

    historical: [B, Th] 7-day model window (60 s step, ~10,080 pts max)
    current:    [B, Tc] the window under judgment
    baseline:   [B, Tc] pre-deploy window (mask all-False when absent —
                rollingUpdate strategy has no baseline, metricsquery.go:111-116)
    threshold/bound/min_lower_bound: [B] per-window config vectors
    min_points: [B] minimum historical points to measure at all
    """

    historical: MetricWindows
    current: MetricWindows
    baseline: MetricWindows
    threshold: jax.Array
    bound: jax.Array
    min_lower_bound: jax.Array
    min_points: jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ScoreResult:
    """Batched judgment output.

    verdict:  [B] int32 (0 healthy / 1 unhealthy / 2 unknown)
    anomalies:[B, Tc] bool — which current points breached bounds
    upper/lower: [B, Tc] the model band over the current window (published
                 as foremastbrain:*_{upper,lower} gauges)
    p_value:  [B] combined pairwise p (1.0 when no baseline)
    dist_differs: [B] bool — pairwise tests rejected same-distribution
    """

    verdict: jax.Array
    anomalies: jax.Array
    upper: jax.Array
    lower: jax.Array
    p_value: jax.Array
    dist_differs: jax.Array


def pairwise_decision(
    current: MetricWindows,
    baseline: MetricWindows,
    algorithm: str,
    p_threshold: float,
    min_mw: int,
    min_wilcoxon: int,
    min_kruskal: int,
    min_friedman: int = 20,
) -> tuple[jax.Array, jax.Array]:
    """Combined same-distribution decision, [B] (p_combined, differs).

    ALL = every applicable test must reject to call it different;
    ANY = one rejection suffices (`foremast-brain/README.md:34`). Tests
    whose min-points gate fails are inconclusive (p=1, not counted).

    `PAIRWISE_NONE` is the compile-time skip for callers that can PROVE
    the baseline is absent (the worker's columnar fast path compiles it
    for its baseline-LESS bucket; the canary bucket — baseline-carrying
    docs, ISSUE 14 — compiles the configured algorithm with the real
    [B, Tc] baseline buffer instead): an empty baseline gates every
    test off anyway — the result is the (p=1, differs=False) constant —
    but the rank tests' comparison matrices still execute inside the
    program. At fleet batch sizes those dominate the warm judgment's
    memory traffic, so the skip is a large win with byte-identical
    outputs. `algorithm` is static in every jit entry point, so this is
    a Python branch, not a device select.
    """
    x, xm = current.values, current.mask
    if algorithm == PAIRWISE_NONE:
        b = x.shape[0]
        return jnp.ones(b, x.dtype), jnp.zeros(b, bool)
    y, ym = baseline.values, baseline.mask
    _, p_mw, ok_mw = mann_whitney_u(x, xm, y, ym, min_points=min_mw)
    _, p_wx, ok_wx = wilcoxon_signed_rank(x, xm, y, ym, min_points=min_wilcoxon)
    _, p_kw, ok_kw = kruskal_wallis(x, xm, y, ym, min_points=min_kruskal)
    _, p_fr, ok_fr = friedman_chi_square(x, xm, y, ym, min_points=min_friedman)

    rej_mw = ok_mw & (p_mw < p_threshold)
    rej_wx = ok_wx & (p_wx < p_threshold)
    rej_kw = ok_kw & (p_kw < p_threshold)
    rej_fr = ok_fr & (p_fr < p_threshold)

    if algorithm == PAIRWISE_MANN_WHITE:
        differs, p = rej_mw, p_mw
    elif algorithm == PAIRWISE_WILCOXON:
        differs, p = rej_wx, p_wx
    elif algorithm == PAIRWISE_KRUSKAL:
        differs, p = rej_kw, p_kw
    elif algorithm == PAIRWISE_FRIEDMAN:
        differs, p = rej_fr, p_fr
    elif algorithm == PAIRWISE_ANY:
        differs = rej_mw | rej_wx | rej_kw | rej_fr
        p = jnp.minimum(
            jnp.minimum(jnp.minimum(p_mw, p_wx), p_kw), p_fr
        )
    elif algorithm == PAIRWISE_ALL:
        any_ok = ok_mw | ok_wx | ok_kw | ok_fr
        all_rej = (
            (rej_mw | ~ok_mw)
            & (rej_wx | ~ok_wx)
            & (rej_kw | ~ok_kw)
            & (rej_fr | ~ok_fr)
        )
        differs = any_ok & all_rej
        # max over *applicable* tests only: gated-out tests have p forced to
        # 1.0 and would otherwise mask a rejection in the published p
        p = jnp.maximum(
            jnp.maximum(
                jnp.where(ok_mw, p_mw, 0.0), jnp.where(ok_wx, p_wx, 0.0)
            ),
            jnp.maximum(
                jnp.where(ok_kw, p_kw, 0.0), jnp.where(ok_fr, p_fr, 0.0)
            ),
        )
        p = jnp.where(any_ok, p, 1.0)
    else:  # pragma: no cover - config validates
        raise ValueError(f"unknown pairwise algorithm {algorithm!r}")
    return p, differs


# jitted form of pairwise_decision for callers outside an enclosing jit
# (the multivariate judge runs it stand-alone per joint-job batch)
pairwise = partial(
    jax.jit,
    static_argnames=(
        "algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
        "min_friedman",
    ),
)(pairwise_decision)


# Threshold multiplier applied when baseline and current distributions
# differ ("lower the threshold", design.md:33): tighter bounds => more
# sensitive detection during a suspicious canary.
DIFF_THRESHOLD_FACTOR = 0.5


def tile_season(s: np.ndarray, m: int) -> np.ndarray:
    """Tile a host-side season buffer's last axis from length l to m.

    Exact whenever l | m: the tiled buffer satisfies tiled[i] = s[i mod l],
    which commutes with every (phase + k) mod m lookup downstream — so
    non-seasonal [..., 1] zero buffers (and m=1 Holt fits) stack next to
    full-season ones in a single batch. Shared by the univariate fit-cache
    scorer and the multivariate MVN scorer."""
    ell = s.shape[-1]
    if ell == m:
        return s
    assert m % ell == 0, f"incompatible season lengths {ell} vs {m}"
    return np.tile(s, (1,) * (s.ndim - 1) + (m // ell,))


# Trend extrapolation across a hist->cur gap is capped at one day of
# steps (60 s step): a pathologically stale fit + huge gap must not run a
# linear trend off to infinity. Deliberately independent of the season
# length — non-seasonal models carry a [B, 1] season buffer, and a cap of
# 10*m would collapse to 10 steps for exactly the trended models that
# need the advance. Shared with the residual-MVN host path.
GAP_TREND_CAP_STEPS = 1440


def _advance_gap(fc: Forecast, gap_steps: jax.Array | None) -> Forecast:
    """Advance terminal forecaster state across the real hist->cur gap.

    The fitted phase assumes the scored window starts one step after the
    history's last point; a drifted re-check tick (the fit-cache headline
    path) or a lagged fetch starts later. The seasonal phase advances by
    the TRUE gap mod m (clamping would corrupt the phase — 10*m ≡ 0);
    only the trend extrapolation is bounded against runaway level drift
    (GAP_TREND_CAP_STEPS), mirroring the residual-MVN path
    (multivariate._judge_lstm_group). Trendless, seasonless models (the
    deployed moving_average_all default) are bit-for-bit unaffected."""
    if gap_steps is None:
        return fc
    m = fc.season.shape[-1]
    gap = gap_steps.astype(jnp.int32)
    return dataclasses.replace(
        fc,
        season_phase=((fc.season_phase + gap) % m).astype(jnp.int32),
        level=fc.level
        + fc.trend
        * jnp.minimum(gap, GAP_TREND_CAP_STEPS).astype(fc.level.dtype),
    )


_STATIC = (
    "algorithm",
    "season_length",
    "pairwise_algorithm",
    "p_threshold",
    "min_mw",
    "min_wilcoxon",
    "min_kruskal",
    "min_friedman",
)


def _judgment_tail(
    batch: ScoreBatch,
    pred: jax.Array,
    scale: jax.Array,
    n_hist: jax.Array,
    pairwise_algorithm: str,
    p_threshold: float,
    min_mw: int,
    min_wilcoxon: int,
    min_kruskal: int,
    min_friedman: int = 20,
) -> ScoreResult:
    """Everything after the model fit: pairwise -> threshold lowering ->
    bounds -> flags -> measurability gate -> verdict. Shared by the XLA
    program and the context-parallel path (parallel/seqparallel.py) so the
    judgment semantics can never diverge."""
    cur = batch.current
    p, differs = pairwise_decision(
        cur,
        batch.baseline,
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
        min_friedman,
    )
    eff_threshold = jnp.where(
        differs, batch.threshold * DIFF_THRESHOLD_FACTOR, batch.threshold
    )
    upper, lower = compute_bounds(pred, scale, eff_threshold, batch.min_lower_bound)
    anomalies = detect_anomalies(cur.values, cur.mask, upper, lower, batch.bound)
    n_cur = cur.count()
    measurable = (n_hist >= batch.min_points) & (n_cur > 0)
    any_anom = jnp.any(anomalies, axis=-1)
    verdict = jnp.where(
        measurable,
        jnp.where(any_anom, UNHEALTHY, HEALTHY),
        UNKNOWN,
    ).astype(jnp.int32)
    # anomalies only count when measurable (unknown windows report none)
    anomalies = anomalies & measurable[:, None]
    return ScoreResult(
        verdict=verdict,
        anomalies=anomalies,
        upper=upper,
        lower=lower,
        p_value=p,
        dist_differs=differs,
    )


# jitted form for callers outside an enclosing jit (the context-parallel
# path); static args match the dispatcher's
judgment_tail = partial(
    jax.jit,
    static_argnames=(
        "pairwise_algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
        "min_friedman",
    ),
)(_judgment_tail)


@partial(jax.jit, static_argnames=_STATIC)
def _score_xla(
    batch: ScoreBatch,
    gap_steps: jax.Array | None = None,
    algorithm: str = "moving_average_all",
    season_length: int = 24,
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
    min_friedman: int = 20,
) -> ScoreResult:
    """The pure-XLA scoring program (partitions under GSPMD for the
    sharded path — no custom calls, so the mesh slices it freely)."""
    hist = batch.historical
    fc: Forecast = _fit_model(algorithm, hist.values, hist.mask, season_length)
    fc = _advance_gap(fc, gap_steps)
    pred = horizon(fc, batch.current.length)  # [B, Tc] forecast

    return _judgment_tail(
        batch,
        pred,
        fc.scale,
        hist.count(),
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
        min_friedman,
    )


@partial(jax.jit, static_argnames=_STATIC)
def _score_pallas(
    batch: ScoreBatch,
    gap_steps: jax.Array | None = None,
    algorithm: str = "moving_average_all",
    season_length: int = 24,
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
    min_friedman: int = 20,
) -> ScoreResult:
    """Fused-kernel path: pairwise stays XLA; the moving_average_all
    judgment runs as one pallas_call (ops/kernels.py)."""
    # dispatcher guarantees moving_average_all, whose forecast is the
    # global mean — trendless and seasonless, so the gap is a no-op too
    del algorithm, season_length, gap_steps
    cur = batch.current
    p, differs = pairwise_decision(
        cur,
        batch.baseline,
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
        min_friedman,
    )
    eff_threshold = jnp.where(
        differs, batch.threshold * DIFF_THRESHOLD_FACTOR, batch.threshold
    )
    verdict, anomalies, upper, lower = kernels.ma_judgment(
        batch.historical.values,
        batch.historical.mask,
        cur.values,
        cur.mask,
        eff_threshold,
        batch.bound,
        batch.min_lower_bound,
        batch.min_points,
    )
    return ScoreResult(
        verdict=verdict,
        anomalies=anomalies,
        upper=upper,
        lower=lower,
        p_value=p,
        dist_differs=differs,
    )


@partial(jax.jit, static_argnames=("algorithm", "season_length"))
def fit_forecast(
    values: jax.Array,
    mask: jax.Array,
    algorithm: str = "moving_average_all",
    season_length: int = 24,
) -> Forecast:
    """Fit the historical model alone (no judgment) — the program behind
    the univariate fit cache: a re-check tick whose history is unchanged
    skips this and replays the cached terminal state through
    `score_from_state`."""
    return _fit_model(algorithm, values, mask, season_length)


@partial(jax.jit, static_argnames=("algorithm", "season_length"))
def fit_forecast_bf16_delta(
    anchor: jax.Array,
    delta: jax.Array,
    lens: jax.Array,
    algorithm: str = "moving_average_all",
    season_length: int = 24,
) -> Forecast:
    """`fit_forecast` from a bf16-delta upload (any algorithm).

    Values are reconstructed IN-PROGRAM — f32(anchor + delta) over the
    valid prefix, mask from `lens` — and fed to the same fit. The
    reconstruction is transient HBM; what it buys is the 2 B/point WIRE
    upload (vs 5 B/point f32 values + bool mask), which is what bounds
    cold fleet ticks over the tunnel (BENCHMARKS.md). Deviation
    precision is bf16's ~3 significant digits relative to the window's
    own range — pinned for the seasonal fits by the quality gates in
    tests/test_engine.py."""
    t = delta.shape[1]
    mask = jnp.arange(t, dtype=jnp.int32)[None, :] < lens[:, None]
    values = (anchor[:, None] + delta.astype(jnp.float32)) * mask
    return _fit_model(algorithm, values, mask, season_length)


@partial(
    jax.jit,
    static_argnames=(
        "pairwise_algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
        "min_friedman",
    ),
)
def score_from_state(
    batch: ScoreBatch,
    level: jax.Array,
    trend: jax.Array,
    season: jax.Array,
    season_phase: jax.Array,
    scale: jax.Array,
    n_hist: jax.Array,
    gap_steps: jax.Array | None = None,
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
    min_friedman: int = 20,
) -> ScoreResult:
    """Judgment from fitted forecaster terminal state (no history scan).

    Identical semantics to `_score_xla`: the in-sample `pred` is never
    consumed by the judgment — only `horizon` extrapolation from terminal
    (level, trend, season, phase), the residual `scale`, and the history
    point count feed `_judgment_tail` — so a cached fit reproduces the
    fresh-fit verdict bit for bit (including the `gap_steps` phase/level
    advance, applied identically in both programs)."""
    fc = Forecast(
        pred=jnp.zeros((level.shape[0], 0), level.dtype),
        scale=scale,
        level=level,
        trend=trend,
        season=season,
        season_phase=season_phase,
    )
    fc = _advance_gap(fc, gap_steps)
    pred = horizon(fc, batch.current.length)
    return _judgment_tail(
        batch,
        pred,
        scale,
        n_hist,
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
        min_friedman,
    )


@partial(
    jax.jit,
    static_argnames=(
        "pairwise_algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
        "min_friedman",
    ),
)
def score_from_arena(
    batch: ScoreBatch,
    level: jax.Array,
    trend: jax.Array,
    season: jax.Array,
    season_phase: jax.Array,
    scale: jax.Array,
    n_hist: jax.Array,
    rows: jax.Array,
    gap_steps: jax.Array | None = None,
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
    min_friedman: int = 20,
) -> ScoreResult:
    """Judgment from ARENA-resident terminal state (engine.arena).

    The batch's fitted state is assembled on device — `rows` [B] indexes
    into the arena's [capacity] state vectors / [capacity, m] season
    buffer — so a warm re-check tick ships only current windows and a
    [B] int32 index array; the gather fuses into the same program as the
    judgment tail. Semantics are exactly `score_from_state` of the
    gathered rows."""
    take = lambda a: jnp.take(a, rows, axis=0)  # noqa: E731
    return score_from_state(
        batch,
        take(level),
        take(trend),
        take(season),
        take(season_phase),
        take(scale),
        take(n_hist),
        gap_steps=gap_steps,
        pairwise_algorithm=pairwise_algorithm,
        p_threshold=p_threshold,
        min_mw=min_mw,
        min_wilcoxon=min_wilcoxon,
        min_kruskal=min_kruskal,
        min_friedman=min_friedman,
    )


@partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "pairwise_algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
        "min_friedman",
    ),
)
def score_from_arena_sharded(
    batch: ScoreBatch,
    level: jax.Array,
    trend: jax.Array,
    season: jax.Array,
    season_phase: jax.Array,
    scale: jax.Array,
    n_hist: jax.Array,
    rows: jax.Array,
    mesh=None,
    gap_steps: jax.Array | None = None,
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
    min_friedman: int = 20,
) -> ScoreResult:
    """`score_from_arena` against a DATA-AXIS-SHARDED arena (ISSUE 19).

    The arena's [capacity] leading axis is block-sharded over `mesh`'s
    data axis and the judge's block placement rule guarantees every
    batch position's row lives on the device holding that position, so
    `rows` [B] carries LOCAL (per-shard) indices and the gather runs as
    a shard_map — device-local by construction, zero cross-chip
    transfer on a warm tick (the replicated variant achieved the same
    by paying capacity_bytes of HBM on every device). Semantics are
    exactly `score_from_state` of the gathered rows."""
    from foremast_tpu.parallel import mesh as meshlib

    gathered = meshlib.shard_rows_take(
        (level, trend, season, season_phase, scale, n_hist), rows, mesh
    )
    return score_from_state(
        batch,
        *gathered,
        gap_steps=gap_steps,
        pairwise_algorithm=pairwise_algorithm,
        p_threshold=p_threshold,
        min_mw=min_mw,
        min_wilcoxon=min_wilcoxon,
        min_kruskal=min_kruskal,
        min_friedman=min_friedman,
    )


# -- anchor-shifted bf16-delta history storage (FOREMAST_BF16_DELTA) ---------
#
# The headline kernel is HBM-bound on the [B, 10080] f32 history read
# (BENCHMARKS.md roofline). Raw bf16 storage was measured and refused in
# round 3: XLA materialized the fp32 upcast AND bf16's 8-bit mantissa
# quantizes low-CV series (100 +- 0.1 has ulp 0.5). This is the principled
# variant flagged there: store each window as (f32 anchor, bf16 DELTAS
# from the anchor). Deviations keep ~3 significant digits relative to the
# window's own range (what the band width is made of), and the
# moving-average moments never reconstruct values at all —
# E[v] = anchor + E[d], Var[v] = Var[d] — so the program reads half the
# bytes with f32 accumulation. Only meaningful where the history RESIDES
# in bf16 across reads (steady-state scoring); the shipped warm worker
# path reads no history at all.


# Explicit override beats the env: pod-mode followers adopt the
# leader's broadcast value via set_bf16_delta() — a per-host skew here
# dispatches differently-shaped SPMD programs, and mutating os.environ
# after threads start is a cross-thread race.
_BF16_DELTA_OVERRIDE: bool | None = None


def set_bf16_delta(enabled: bool | None) -> None:
    """Pin the bf16-delta gate for this process (None clears the
    override back to the env default)."""
    global _BF16_DELTA_OVERRIDE
    _BF16_DELTA_OVERRIDE = enabled if enabled is None else bool(enabled)


def bf16_delta_enabled() -> bool:
    """FOREMAST_BF16_DELTA gate (default ON): anchor-shifted bf16-delta
    history handling for the moving-average family — the steady-state
    headline storage AND the worker's cold-fit upload (judge.
    _score_with_fit_cache), where history H2D is the cold-tick bound.
    Set FOREMAST_BF16_DELTA=0 for full-f32 behavior."""
    import os

    if _BF16_DELTA_OVERRIDE is not None:
        return _BF16_DELTA_OVERRIDE
    return os.environ.get("FOREMAST_BF16_DELTA", "1") == "1"


@jax.jit
def fit_ma_from_bf16_delta(anchor: jax.Array, delta: jax.Array, lens: jax.Array):
    """moving_average_all terminal state from bf16-delta history upload.

    `delta` [B, T] bf16 (anchor-shifted, left-packed: padding slots are
    exact zeros), `anchor` [B] f32, `lens` [B] int32 valid counts — the
    mask is reconstructed on device from lengths, so the upload is
    2 B/point instead of 5 B/point (f32 values + bool mask). Matches
    ops.forecasters.moving_average_all's moments up to bf16 rounding of
    the deviations (same pinned tolerance as score_bf16_delta)."""
    n = lens.astype(jnp.float32)
    s1 = jnp.sum(delta, axis=1, dtype=jnp.float32)
    d32 = delta.astype(jnp.float32)
    s2 = jnp.sum(d32 * d32, axis=1)
    nn = jnp.maximum(n, 1.0)
    mean_d = s1 / nn
    mean = jnp.where(n > 0, anchor + mean_d, 0.0)
    var = jnp.where(n > 0, jnp.maximum(s2 / nn - mean_d * mean_d, 0.0), 0.0)
    return mean, jnp.sqrt(var), lens


@jax.jit
def pack_hist_bf16_delta(values: jax.Array, mask: jax.Array):
    """[B, T] f32 history -> (anchor [B] f32, delta [B, T] bf16).

    anchor = first masked value per row (a member of the sample, so
    deltas are bounded by the window range — same conditioning argument
    as windows.masked_moments); masked slots pack as exact 0."""
    first_idx = jnp.argmax(mask, axis=-1)
    c = jnp.take_along_axis(values, first_idx[..., None], axis=-1)[..., 0]
    c = jnp.where(mask.any(axis=-1), c, 0.0)
    d = ((values - c[..., None]) * mask).astype(jnp.bfloat16)
    return c, d


def make_bf16_delta_batch(batch: ScoreBatch):
    """(slim_batch, anchor, delta) for `score_bf16_delta`.

    Pins the structural contract in one place: the slim batch carries a
    [B, 0] values buffer (no f32 history resides on device) but keeps
    the FULL [B, T] mask, which score_bf16_delta reads for the valid
    counts. Used by bench.py, the multichip dry run, and the tests."""
    import dataclasses

    anchor, delta = pack_hist_bf16_delta(
        batch.historical.values, batch.historical.mask
    )
    b = batch.historical.values.shape[0]
    slim = dataclasses.replace(
        batch,
        historical=MetricWindows(
            values=jnp.zeros((b, 0), jnp.float32),
            mask=batch.historical.mask,
            times=None,
        ),
    )
    return slim, anchor, delta


@partial(
    jax.jit,
    static_argnames=(
        "pairwise_algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
        "min_friedman",
    ),
)
def score_bf16_delta(
    batch: ScoreBatch,
    anchor: jax.Array,
    delta: jax.Array,
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
    min_friedman: int = 20,
) -> ScoreResult:
    """moving_average_all judgment from bf16-delta history storage.

    `batch.historical` carries only the mask (values may be [B, 0]); the
    moments come from the bf16 deltas with f32 accumulation. Semantics
    match `_score_xla(algorithm="moving_average_all")` up to bf16
    rounding of the deviations (pinned by test + quality gate)."""
    mask = batch.historical.mask
    m = mask.astype(jnp.float32)
    n = jnp.sum(m, axis=-1)
    # deltas were packed masked (exact zeros in masked slots), so plain
    # sums ARE the masked sums; accumulate in f32 off the bf16 reads
    s1 = jnp.sum(delta, axis=-1, dtype=jnp.float32)
    d32 = delta.astype(jnp.float32)
    s2 = jnp.sum(d32 * d32, axis=-1)
    nn = jnp.maximum(n, 1.0)
    mean_d = s1 / nn
    mean = jnp.where(n > 0, anchor + mean_d, 0.0)
    var = jnp.where(n > 0, jnp.maximum(s2 / nn - mean_d * mean_d, 0.0), 0.0)
    b = mean.shape[0]
    fc = Forecast(
        pred=jnp.zeros((b, 0), jnp.float32),
        scale=jnp.sqrt(var),
        level=mean,
        trend=jnp.zeros_like(mean),
        season=jnp.zeros((b, 1), jnp.float32),
        season_phase=jnp.zeros((b,), jnp.int32),
    )
    pred = horizon(fc, batch.current.length)
    return _judgment_tail(
        batch,
        pred,
        fc.scale,
        n.astype(jnp.int32),
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
        min_friedman,
    )


def _is_multi_device(batch: ScoreBatch) -> bool:
    """True when the batch is placed across >1 device (GSPMD path)."""
    sharding = getattr(batch.current.values, "sharding", None)
    if sharding is None:
        return False
    try:
        return len(sharding.device_set) > 1
    except Exception:  # tracers / abstract values: assume the safe path
        return True


def score(batch: ScoreBatch, **kwargs) -> ScoreResult:
    """Judge a whole batch in one compiled program (call stack 3.2 of
    SURVEY.md collapsed into array ops).

    Un-jitted dispatcher over two jitted programs so (a) the
    FOREMAST_PALLAS gate is honored at *call* time, not frozen into a
    trace cache, and (b) multi-device batches always take the XLA
    program, which GSPMD partitions freely (a pallas_call has no
    partitioning rule and would force a gather).
    """
    algorithm = kwargs.get("algorithm", "moving_average_all")
    if (
        algorithm == "moving_average_all"
        and kernels.use_pallas()
        and not _is_multi_device(batch)
    ):
        return _score_pallas(batch, **kwargs)
    return _score_xla(batch, **kwargs)
