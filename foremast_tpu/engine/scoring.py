"""The batched health-judgment engine — the reference brain's hot loop,
re-centered as one jitted TPU program.

Reference semantics being reproduced (`foremast-brain/README.md:5-11`,
`docs/guides/design.md:31-33`):
  1. compute the historical model from the 7-day window;
  2. for canary strategies, run pairwise same-distribution tests between
     baseline and current (Mann-Whitney / Wilcoxon / Kruskal, combinable
     via ML_PAIRWISE_ALGORITHM);
  3. if the distributions differ, *lower the threshold*;
  4. threshold-based anomaly detection of current points against the
     historical model's bounds (per-metric-type threshold/bound matrix,
     `foremast-brain.yaml:26-73`);
  5. fail fast: any anomaly -> unhealthy (`design.md:43`).

TPU-first re-design: instead of one job at a time on a CPU sliver, the
whole (service x metric) population is one `[B, T]` batch; every step above
is a masked array op, and the entire judgment is a single `jax.jit`
program. Ragged windows are validity masks; per-metric-type config rows are
gathered into dense `[B]` operand vectors host-side (config.AnomalyConfig
.gather); strategy/bound/algorithm switches are `jnp.where` selects, not
Python branches.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from foremast_tpu.config import (
    PAIRWISE_ALL,
    PAIRWISE_ANY,
    PAIRWISE_KRUSKAL,
    PAIRWISE_MANN_WHITE,
    PAIRWISE_WILCOXON,
)
from foremast_tpu.ops import kernels
from foremast_tpu.ops.anomaly import compute_bounds, detect_anomalies
from foremast_tpu.ops.forecasters import (
    Forecast,
    double_exponential,
    ewma,
    fit_auto_univariate,
    fit_holt_winters,
    horizon,
    moving_average,
    moving_average_all,
)
from foremast_tpu.ops.ranks import kruskal_wallis, mann_whitney_u, wilcoxon_signed_rank
from foremast_tpu.ops.windows import MetricWindows

# Verdict codes (map onto the ES status machine, converter.go:13-26:
# HEALTHY -> completed_health, UNHEALTHY -> completed_unhealth,
# UNKNOWN -> completed_unknown).
HEALTHY = 0
UNHEALTHY = 1
UNKNOWN = 2

# The model registry — the reference's "AI_MODEL" table lives in
# `src/models/modelclass.py` of the external brain repo
# (`foremast-brain/README.md:22`); deployed default is `moving_average_all`
# (`foremast-brain.yaml:24-25`). Each entry: (values, mask) -> Forecast.
AI_MODEL = {
    "moving_average_all": moving_average_all,
    "moving_average": moving_average,
    "ewma": ewma,
    "exponential_smoothing": ewma,
    "double_exponential_smoothing": double_exponential,
    "holtwinters": fit_holt_winters,
    "holt_winters": fit_holt_winters,
    # structure-screened per-series selection (MA vs fitted Holt-Winters):
    # the recommended default where metric shapes are unknown
    "auto_univariate": fit_auto_univariate,
}


def register_model(name: str, fit_fn) -> None:
    """Extend the registry (used by models/ for seasonal + learned models)."""
    AI_MODEL[name] = fit_fn


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ScoreBatch:
    """One fixed-shape batch of scoring work.

    historical: [B, Th] 7-day model window (60 s step, ~10,080 pts max)
    current:    [B, Tc] the window under judgment
    baseline:   [B, Tc] pre-deploy window (mask all-False when absent —
                rollingUpdate strategy has no baseline, metricsquery.go:111-116)
    threshold/bound/min_lower_bound: [B] per-window config vectors
    min_points: [B] minimum historical points to measure at all
    """

    historical: MetricWindows
    current: MetricWindows
    baseline: MetricWindows
    threshold: jax.Array
    bound: jax.Array
    min_lower_bound: jax.Array
    min_points: jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ScoreResult:
    """Batched judgment output.

    verdict:  [B] int32 (0 healthy / 1 unhealthy / 2 unknown)
    anomalies:[B, Tc] bool — which current points breached bounds
    upper/lower: [B, Tc] the model band over the current window (published
                 as foremastbrain:*_{upper,lower} gauges)
    p_value:  [B] combined pairwise p (1.0 when no baseline)
    dist_differs: [B] bool — pairwise tests rejected same-distribution
    """

    verdict: jax.Array
    anomalies: jax.Array
    upper: jax.Array
    lower: jax.Array
    p_value: jax.Array
    dist_differs: jax.Array


def pairwise_decision(
    current: MetricWindows,
    baseline: MetricWindows,
    algorithm: str,
    p_threshold: float,
    min_mw: int,
    min_wilcoxon: int,
    min_kruskal: int,
) -> tuple[jax.Array, jax.Array]:
    """Combined same-distribution decision, [B] (p_combined, differs).

    ALL = every applicable test must reject to call it different;
    ANY = one rejection suffices (`foremast-brain/README.md:34`). Tests
    whose min-points gate fails are inconclusive (p=1, not counted).
    """
    x, xm = current.values, current.mask
    y, ym = baseline.values, baseline.mask
    _, p_mw, ok_mw = mann_whitney_u(x, xm, y, ym, min_points=min_mw)
    _, p_wx, ok_wx = wilcoxon_signed_rank(x, xm, y, ym, min_points=min_wilcoxon)
    _, p_kw, ok_kw = kruskal_wallis(x, xm, y, ym, min_points=min_kruskal)

    rej_mw = ok_mw & (p_mw < p_threshold)
    rej_wx = ok_wx & (p_wx < p_threshold)
    rej_kw = ok_kw & (p_kw < p_threshold)

    if algorithm == PAIRWISE_MANN_WHITE:
        differs, p = rej_mw, p_mw
    elif algorithm == PAIRWISE_WILCOXON:
        differs, p = rej_wx, p_wx
    elif algorithm == PAIRWISE_KRUSKAL:
        differs, p = rej_kw, p_kw
    elif algorithm == PAIRWISE_ANY:
        differs = rej_mw | rej_wx | rej_kw
        p = jnp.minimum(jnp.minimum(p_mw, p_wx), p_kw)
    elif algorithm == PAIRWISE_ALL:
        any_ok = ok_mw | ok_wx | ok_kw
        all_rej = (
            (rej_mw | ~ok_mw) & (rej_wx | ~ok_wx) & (rej_kw | ~ok_kw)
        )
        differs = any_ok & all_rej
        # max over *applicable* tests only: gated-out tests have p forced to
        # 1.0 and would otherwise mask a rejection in the published p
        p = jnp.maximum(
            jnp.maximum(
                jnp.where(ok_mw, p_mw, 0.0), jnp.where(ok_wx, p_wx, 0.0)
            ),
            jnp.where(ok_kw, p_kw, 0.0),
        )
        p = jnp.where(any_ok, p, 1.0)
    else:  # pragma: no cover - config validates
        raise ValueError(f"unknown pairwise algorithm {algorithm!r}")
    return p, differs


# jitted form of pairwise_decision for callers outside an enclosing jit
# (the multivariate judge runs it stand-alone per joint-job batch)
pairwise = partial(
    jax.jit,
    static_argnames=(
        "algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
    ),
)(pairwise_decision)


# Threshold multiplier applied when baseline and current distributions
# differ ("lower the threshold", design.md:33): tighter bounds => more
# sensitive detection during a suspicious canary.
DIFF_THRESHOLD_FACTOR = 0.5


_STATIC = (
    "algorithm",
    "pairwise_algorithm",
    "p_threshold",
    "min_mw",
    "min_wilcoxon",
    "min_kruskal",
)


def _judgment_tail(
    batch: ScoreBatch,
    pred: jax.Array,
    scale: jax.Array,
    n_hist: jax.Array,
    pairwise_algorithm: str,
    p_threshold: float,
    min_mw: int,
    min_wilcoxon: int,
    min_kruskal: int,
) -> ScoreResult:
    """Everything after the model fit: pairwise -> threshold lowering ->
    bounds -> flags -> measurability gate -> verdict. Shared by the XLA
    program and the context-parallel path (parallel/seqparallel.py) so the
    judgment semantics can never diverge."""
    cur = batch.current
    p, differs = pairwise_decision(
        cur,
        batch.baseline,
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
    )
    eff_threshold = jnp.where(
        differs, batch.threshold * DIFF_THRESHOLD_FACTOR, batch.threshold
    )
    upper, lower = compute_bounds(pred, scale, eff_threshold, batch.min_lower_bound)
    anomalies = detect_anomalies(cur.values, cur.mask, upper, lower, batch.bound)
    n_cur = cur.count()
    measurable = (n_hist >= batch.min_points) & (n_cur > 0)
    any_anom = jnp.any(anomalies, axis=-1)
    verdict = jnp.where(
        measurable,
        jnp.where(any_anom, UNHEALTHY, HEALTHY),
        UNKNOWN,
    ).astype(jnp.int32)
    # anomalies only count when measurable (unknown windows report none)
    anomalies = anomalies & measurable[:, None]
    return ScoreResult(
        verdict=verdict,
        anomalies=anomalies,
        upper=upper,
        lower=lower,
        p_value=p,
        dist_differs=differs,
    )


# jitted form for callers outside an enclosing jit (the context-parallel
# path); static args match the dispatcher's
judgment_tail = partial(
    jax.jit,
    static_argnames=(
        "pairwise_algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
    ),
)(_judgment_tail)


@partial(jax.jit, static_argnames=_STATIC)
def _score_xla(
    batch: ScoreBatch,
    algorithm: str = "moving_average_all",
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
) -> ScoreResult:
    """The pure-XLA scoring program (partitions under GSPMD for the
    sharded path — no custom calls, so the mesh slices it freely)."""
    hist = batch.historical

    fit = AI_MODEL.get(algorithm)
    if fit is None:
        # models/ registers its detectors (seasonal/prophet/...) on import;
        # resolve lazily so the registry works without callers importing it
        import foremast_tpu.models  # noqa: F401

        fit = AI_MODEL[algorithm]
    fc: Forecast = fit(hist.values, hist.mask)
    pred = horizon(fc, batch.current.length)  # [B, Tc] forecast

    return _judgment_tail(
        batch,
        pred,
        fc.scale,
        hist.count(),
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
    )


@partial(jax.jit, static_argnames=_STATIC)
def _score_pallas(
    batch: ScoreBatch,
    algorithm: str = "moving_average_all",
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
) -> ScoreResult:
    """Fused-kernel path: pairwise stays XLA; the moving_average_all
    judgment runs as one pallas_call (ops/kernels.py)."""
    del algorithm  # dispatcher guarantees moving_average_all
    cur = batch.current
    p, differs = pairwise_decision(
        cur,
        batch.baseline,
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
    )
    eff_threshold = jnp.where(
        differs, batch.threshold * DIFF_THRESHOLD_FACTOR, batch.threshold
    )
    verdict, anomalies, upper, lower = kernels.ma_judgment(
        batch.historical.values,
        batch.historical.mask,
        cur.values,
        cur.mask,
        eff_threshold,
        batch.bound,
        batch.min_lower_bound,
        batch.min_points,
    )
    return ScoreResult(
        verdict=verdict,
        anomalies=anomalies,
        upper=upper,
        lower=lower,
        p_value=p,
        dist_differs=differs,
    )


@partial(jax.jit, static_argnames=("algorithm",))
def fit_forecast(
    values: jax.Array, mask: jax.Array, algorithm: str = "moving_average_all"
) -> Forecast:
    """Fit the historical model alone (no judgment) — the program behind
    the univariate fit cache: a re-check tick whose history is unchanged
    skips this and replays the cached terminal state through
    `score_from_state`."""
    fit = AI_MODEL.get(algorithm)
    if fit is None:
        import foremast_tpu.models  # noqa: F401

        fit = AI_MODEL[algorithm]
    return fit(values, mask)


@partial(
    jax.jit,
    static_argnames=(
        "pairwise_algorithm",
        "p_threshold",
        "min_mw",
        "min_wilcoxon",
        "min_kruskal",
    ),
)
def score_from_state(
    batch: ScoreBatch,
    level: jax.Array,
    trend: jax.Array,
    season: jax.Array,
    season_phase: jax.Array,
    scale: jax.Array,
    n_hist: jax.Array,
    pairwise_algorithm: str = PAIRWISE_ALL,
    p_threshold: float = 0.05,
    min_mw: int = 20,
    min_wilcoxon: int = 20,
    min_kruskal: int = 5,
) -> ScoreResult:
    """Judgment from fitted forecaster terminal state (no history scan).

    Identical semantics to `_score_xla`: the in-sample `pred` is never
    consumed by the judgment — only `horizon` extrapolation from terminal
    (level, trend, season, phase), the residual `scale`, and the history
    point count feed `_judgment_tail` — so a cached fit reproduces the
    fresh-fit verdict bit for bit."""
    fc = Forecast(
        pred=jnp.zeros((level.shape[0], 0), level.dtype),
        scale=scale,
        level=level,
        trend=trend,
        season=season,
        season_phase=season_phase,
    )
    pred = horizon(fc, batch.current.length)
    return _judgment_tail(
        batch,
        pred,
        scale,
        n_hist,
        pairwise_algorithm,
        p_threshold,
        min_mw,
        min_wilcoxon,
        min_kruskal,
    )


def _is_multi_device(batch: ScoreBatch) -> bool:
    """True when the batch is placed across >1 device (GSPMD path)."""
    sharding = getattr(batch.current.values, "sharding", None)
    if sharding is None:
        return False
    try:
        return len(sharding.device_set) > 1
    except Exception:  # tracers / abstract values: assume the safe path
        return True


def score(batch: ScoreBatch, **kwargs) -> ScoreResult:
    """Judge a whole batch in one compiled program (call stack 3.2 of
    SURVEY.md collapsed into array ops).

    Un-jitted dispatcher over two jitted programs so (a) the
    FOREMAST_PALLAS gate is honored at *call* time, not frozen into a
    trace cache, and (b) multi-device batches always take the XLA
    program, which GSPMD partitions freely (a pallas_call has no
    partitioning rule and would force a gather).
    """
    algorithm = kwargs.get("algorithm", "moving_average_all")
    if (
        algorithm == "moving_average_all"
        and kernels.use_pallas()
        and not _is_multi_device(batch)
    ):
        return _score_pallas(batch, **kwargs)
    return _score_xla(batch, **kwargs)
