"""Host-side wrapper: ragged jobs in, reference-wire verdicts out.

Bridges the untyped job plane (ES documents with per-alias ragged series)
and the fixed-shape jitted scorer (`engine.scoring.score`). Responsibilities
(SURVEY.md section 7.4): pack pending metric windows into fixed-shape
batches (bucketing by window length to bound recompiles), gather the
per-metric-type config table into dense operand vectors, run the compiled
program, and decode results into the reference's wire format — anomalies as
flat `[t1, v1, t2, v2, ...]` pairs (decoded by the Go side at
`foremast-barrelman/pkg/controller/Barrelman.go:593-620`).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import scoring
from foremast_tpu.ops.windows import MetricWindows

# Bucket window lengths to powers of two >= 8 so XLA compiles a handful of
# shapes total, not one per ragged job (SURVEY.md "hard parts" (b)).
_MIN_BUCKET = 8


def bucket_length(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True)
class MetricTask:
    """One metric of one job, host-side ragged form.

    times/values arrays for historical, current and (optionally) baseline
    windows; metric_type selects the threshold row (error5xx/latency/...).
    """

    job_id: str
    alias: str
    metric_type: str | None
    hist_times: np.ndarray
    hist_values: np.ndarray
    cur_times: np.ndarray
    cur_values: np.ndarray
    base_times: np.ndarray | None = None
    base_values: np.ndarray | None = None
    # stable service identity (job ids change per run); keys the
    # per-service model cache in the multivariate judge
    app: str = ""

    def __post_init__(self):
        if (self.base_times is None) != (self.base_values is None):
            raise ValueError("base_times and base_values must be set together")


@dataclasses.dataclass(frozen=True)
class MetricVerdict:
    """Judgment for one metric, in wire-friendly form."""

    job_id: str
    alias: str
    verdict: int  # scoring.HEALTHY / UNHEALTHY / UNKNOWN
    anomaly_pairs: list[float]  # flat [t1, v1, t2, v2, ...]
    upper: np.ndarray  # [Tc] model band (gauge export)
    lower: np.ndarray
    p_value: float
    dist_differs: bool


class HealthJudge:
    """Batched scorer with reference-parity config semantics."""

    def __init__(self, config: BrainConfig | None = None):
        self.config = config or BrainConfig()

    def judge(self, tasks: Sequence[MetricTask]) -> list[MetricVerdict]:
        """Score a set of metric tasks, batching same-shaped buckets."""
        if not tasks:
            return []
        # Bucket by (hist_len_bucket, cur_len_bucket) to bound recompiles.
        buckets: dict[tuple[int, int], list[int]] = {}
        for i, t in enumerate(tasks):
            key = (
                bucket_length(len(t.hist_values)),
                bucket_length(
                    max(
                        len(t.cur_values),
                        0 if t.base_values is None else len(t.base_values),
                    )
                ),
            )
            buckets.setdefault(key, []).append(i)

        out: list[MetricVerdict | None] = [None] * len(tasks)
        for (th, tc), idxs in buckets.items():
            chunk = [tasks[i] for i in idxs]
            for v, i in zip(self._judge_bucket(chunk, th, tc), idxs):
                out[i] = v
        return [v for v in out if v is not None]

    def _place(self, batch: scoring.ScoreBatch) -> scoring.ScoreBatch:
        """Device-placement hook — identity here (default device);
        parallel.ShardedJudge overrides it to shard over the mesh."""
        return batch

    def _judge_bucket(
        self, tasks: list[MetricTask], th: int, tc: int
    ) -> list[MetricVerdict]:
        cfg = self.config
        hist = MetricWindows.from_ragged(
            [(t.hist_times, t.hist_values) for t in tasks], th
        )
        cur = MetricWindows.from_ragged(
            [(t.cur_times, t.cur_values) for t in tasks], tc
        )
        empty = (np.zeros(0, np.int64), np.zeros(0, np.float32))
        base = MetricWindows.from_ragged(
            [
                (t.base_times, t.base_values)
                if t.base_values is not None
                else empty
                for t in tasks
            ],
            tc,
        )
        thr, bound, mlb = cfg.anomaly.gather([t.metric_type for t in tasks])
        batch = scoring.ScoreBatch(
            historical=hist,
            current=cur,
            baseline=base,
            threshold=jnp.asarray(thr),
            bound=jnp.asarray(bound),
            min_lower_bound=jnp.asarray(mlb),
            min_points=jnp.full((len(tasks),), cfg.min_historical_points, jnp.int32),
        )
        batch = self._place(batch)
        res = scoring.score(
            batch,
            algorithm=cfg.algorithm,
            pairwise_algorithm=cfg.pairwise.algorithm,
            p_threshold=cfg.pairwise.threshold,
            min_mw=cfg.pairwise.min_mann_white_points,
            min_wilcoxon=cfg.pairwise.min_wilcoxon_points,
            min_kruskal=cfg.pairwise.min_kruskal_points,
        )
        verdicts = np.asarray(res.verdict)
        anoms = np.asarray(res.anomalies)
        uppers = np.asarray(res.upper)
        lowers = np.asarray(res.lower)
        ps = np.asarray(res.p_value)
        differs = np.asarray(res.dist_differs)

        from foremast_tpu import native

        use_native = native.available()
        out = []
        for i, t in enumerate(tasks):
            n = len(t.cur_values)
            # flat [t, v, ...] pairs — barrelman's convertToAnomaly format
            # (Barrelman.go:605-615)
            if use_native:
                pairs = native.anomaly_pairs(
                    anoms[i, :n], np.asarray(t.cur_times), np.asarray(t.cur_values)
                )
            else:
                idx = np.nonzero(anoms[i, :n])[0]
                flat = np.empty(2 * len(idx), dtype=np.float64)
                flat[0::2] = np.asarray(t.cur_times)[idx]
                flat[1::2] = np.asarray(t.cur_values)[idx]
                pairs = flat.tolist()
            out.append(
                MetricVerdict(
                    job_id=t.job_id,
                    alias=t.alias,
                    verdict=int(verdicts[i]),
                    anomaly_pairs=pairs,
                    upper=uppers[i, :n].copy(),
                    lower=lowers[i, :n].copy(),
                    p_value=float(ps[i]),
                    dist_differs=bool(differs[i]),
                )
            )
        return out


def combine_verdicts(verdicts: Sequence[MetricVerdict]) -> int:
    """Job-level verdict: fail-fast — any unhealthy metric makes the job
    unhealthy (`design.md:43`); all-unknown stays unknown."""
    if not verdicts:
        return scoring.UNKNOWN
    vs = [v.verdict for v in verdicts]
    if any(v == scoring.UNHEALTHY for v in vs):
        return scoring.UNHEALTHY
    if all(v == scoring.UNKNOWN for v in vs):
        return scoring.UNKNOWN
    return scoring.HEALTHY
