"""Host-side wrapper: ragged jobs in, reference-wire verdicts out.

Bridges the untyped job plane (ES documents with per-alias ragged series)
and the fixed-shape jitted scorer (`engine.scoring.score`). Responsibilities
(SURVEY.md section 7.4): pack pending metric windows into fixed-shape
batches (bucketing by window length to bound recompiles), gather the
per-metric-type config table into dense operand vectors, run the compiled
program, and decode results into the reference's wire format — anomalies as
flat `[t1, v1, t2, v2, ...]` pairs (decoded by the Go side at
`foremast-barrelman/pkg/controller/Barrelman.go:593-620`).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import scoring
from foremast_tpu.observe.spans import span
from foremast_tpu.ops.windows import MetricWindows

log = logging.getLogger("foremast_tpu.judge")

# Bucket window lengths to powers of two >= 8 so XLA compiles a handful of
# shapes total, not one per ragged job (SURVEY.md "hard parts" (b)).
_MIN_BUCKET = 8

# Max rows per fit sub-batch (see _score_with_fit_cache): bounds peak
# packing/upload memory on fleet-cold ticks at the 7-day history length
# (4096 x 10,080 x 5 B ~= 200 MB per chunk).
_FIT_CHUNK = 4096


def bucket_length(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass
class MetricTask:
    """One metric of one job, host-side ragged form.

    times/values arrays for historical, current and (optionally) baseline
    windows; metric_type selects the threshold row (error5xx/latency/...).

    A plain (non-frozen) dataclass on purpose: a fleet tick constructs
    one of these per (job x alias) — 40k+ per tick — and frozen's
    `object.__setattr__`-per-field init measurably taxes the worker's
    host budget (the end-to-end loop runs on one CPU core per chip).
    """

    job_id: str
    alias: str
    metric_type: str | None
    hist_times: np.ndarray
    hist_values: np.ndarray
    cur_times: np.ndarray
    cur_values: np.ndarray
    base_times: np.ndarray | None = None
    base_values: np.ndarray | None = None
    # stable service identity (job ids change per run); keys the
    # per-service model cache in the multivariate judge
    app: str = ""
    # set by the worker ONLY when the historical range is provably
    # immutable (its end safely in the past): keys the fitted-forecast
    # cache so re-check ticks skip the history scan (SURVEY hard part (d))
    fit_key: str | None = None
    # warm-tick fast path: a task whose fit is already cached may carry
    # EMPTY hist arrays (the worker skips the historical fetch entirely —
    # no Prometheus round trip, no 10k-pt parse) plus the history's
    # inferred step and last timestamp so the seasonal gap advance
    # (_gap_steps) still has its anchors
    hist_step: float | None = None
    hist_last_t: float | None = None
    # the cached fit state itself, attached by the worker at fetch time.
    # Carrying the ENTRY (not just the key) makes the skip-fetch decision
    # race-free: a colder bucket's fits in the same tick may LRU-evict
    # this key from the fit cache before this bucket is judged, and a
    # key-only task would then be "refit" on its empty history — caching
    # garbage under the real key. A referenced entry cannot be evicted
    # out from under the task.
    fit_entry: tuple | None = None

    def __post_init__(self):
        if (self.base_times is None) != (self.base_values is None):
            raise ValueError("base_times and base_values must be set together")


@dataclasses.dataclass
class MetricVerdict:
    """Judgment for one metric, in wire-friendly form."""

    job_id: str
    alias: str
    verdict: int  # scoring.HEALTHY / UNHEALTHY / UNKNOWN
    anomaly_pairs: list[float]  # flat [t1, v1, t2, v2, ...]
    upper: np.ndarray  # [Tc] model band (gauge export)
    lower: np.ndarray
    p_value: float
    dist_differs: bool


# Every algorithm caches its terminal state when a fit_key is present —
# including the plain moving averages. Round 3 exempted them ("cheaper
# than the cache round trip"), which was true of the fit FLOPs but
# ignored what the cache actually saves on the shipped path: packing and
# re-uploading the [B, 10080] history every re-check tick. Measured over
# the TPU tunnel the history upload dominates the warm tick by orders of
# magnitude (H2D degrades to tens of MB/s mid-stream — BENCHMARKS.md
# worker-tick notes), so a cached MA fit turns a ~200 MB/tick upload
# into a [B] index gather.


# Fits whose horizon depends on trend or seasonal phase: only these need
# the hist->cur gap advance (scoring._advance_gap). The gap is a provable
# no-op for level-only models (moving averages, EWMA), so the judge skips
# computing it there — the deployed default stays zero-overhead.
GAP_SENSITIVE_FITS = frozenset(
    {
        "double_exponential_smoothing",
        "holtwinters",
        "holt_winters",
        "phase_means",
        "auto_univariate",
        "seasonal",
        "prophet",
        "seasonal_hourly",
    }
)


def infer_step(times: np.ndarray) -> float:
    """Sampling step of a window — median of (subsampled) spacings.

    Median, not endpoint spacing: PromQL query_range omits empty steps,
    so a scrape outage mid-window inflates (end-start)/(n-1) by the
    missing fraction and would mis-advance the seasonal phase. A FULL
    median-of-diffs per task measured ~20% of a warm 8k-window tick, so
    long windows median 64 evenly spaced consecutive spacings instead:
    same robustness class (correct whenever under half the sampled
    positions border an omission), O(1) in the window length, and no
    endpoint-equality shortcut an adversarial omission pattern can game.
    Shared by the univariate gap advance and the multivariate MVN scorer
    so the two paths cannot diverge. Falls back to the reference's 60 s
    step (`metricsquery.go:43`) for single-point or all-duplicate
    windows."""
    n = len(times)
    if n < 2:
        return 60.0
    t = np.asarray(times)
    if n > 65:
        idx = np.linspace(0, n - 2, 64).astype(np.int64)
        gaps = t[idx + 1] - t[idx]
    else:
        gaps = np.diff(t)
    step = float(np.median(gaps))
    return step if step > 0 else 60.0


def _gap_steps(tasks: Sequence[MetricTask]) -> np.ndarray:
    """Per-task hist->cur gap in whole steps, [B] int32.

    The fitted forecaster's phase assumes the current window starts ONE
    step after the history's last point; re-check ticks drift later.
    Tasks without both windows gap 0. Only computed for gap-sensitive
    algorithms (GAP_SENSITIVE_FITS) — the gap is a provable no-op for
    level-only models, so the deployed default skips even the O(1)
    subsampled step inference."""
    out = np.zeros(len(tasks), np.int32)
    for i, t in enumerate(tasks):
        ht = t.hist_times
        ct = t.cur_times
        if len(ct) == 0:
            continue
        if len(ht) == 0:
            # warm fast path: the worker skipped the hist fetch but
            # carried the step/last-time anchors (MetricTask.hist_step)
            if t.hist_step is None or t.hist_last_t is None:
                continue
            step, last = t.hist_step, t.hist_last_t
        else:
            step, last = infer_step(np.asarray(ht)), float(ht[-1])
        k = int(round((float(ct[0]) - last) / max(step, 1.0)))
        out[i] = max(k - 1, 0)
    return out


# Empty padding row for batch-axis bucketing: zero windows everywhere
# (verdict UNKNOWN, dropped on decode); the constant fit key means the
# empty-history "fit" caches once, so padded warm ticks stay fit-free.
@jax.jit
def _compact_min(verdict, anoms):
    """Minimal result for hook-less columnar ticks: verdicts + bit-packed
    anomaly flags only — nothing else leaves the device."""
    return verdict.astype(jnp.int8), jnp.packbits(anoms, axis=1)


@jax.jit
def _compact_full_nopair(verdict, anoms, upper, lower):
    """Columnar result with FULL [B, Tc] bands (band_mode="full"): only
    the verdict/anomaly compaction is applied; hooks that consume the
    band shape get the same band the object path's "full" mode carries
    (ADVICE r4: the fast path must not silently truncate bands once fits
    warm up)."""
    return verdict.astype(jnp.int8), jnp.packbits(anoms, axis=1), upper, lower


@jax.jit
def _compact_min_pair(verdict, anoms, p, differs):
    """`_compact_min` plus the pairwise outputs — the canary columnar
    bucket's hook-less decode (baseline-carrying docs compute a REAL
    (p, differs) on device; the host must not fabricate the constants
    the baseline-less program is entitled to)."""
    return (
        verdict.astype(jnp.int8),
        jnp.packbits(anoms, axis=1),
        p,
        differs,
    )


@jax.jit
def _compact_full_pair(verdict, anoms, upper, lower, p, differs):
    """`_compact_full_nopair` plus the pairwise outputs (canary columnar
    bucket, band_mode="full")."""
    return (
        verdict.astype(jnp.int8),
        jnp.packbits(anoms, axis=1),
        upper,
        lower,
        p,
        differs,
    )


@jax.jit
def _compact_result_nopair(verdict, anoms, upper, lower, nidx):
    """_compact_result without the pairwise outputs — the columnar warm
    path serves baseline-less re-checks, where (p=1.0, differs=False)
    are compile-time constants the host fills itself."""
    b = verdict.shape[0]
    ar = jnp.arange(b)
    return (
        verdict.astype(jnp.int8),
        jnp.packbits(anoms, axis=1),
        upper[ar, nidx],
        lower[ar, nidx],
    )


@jax.jit
def _compact_result(verdict, anoms, upper, lower, p, differs, nidx):
    """Shrink a ScoreResult for the device->host hop (band_mode="last").

    The worker's only band consumer is the gauge exporter, which
    publishes the band's LAST point per metric (observe/gauges.py hook:
    `v.upper[-1]`); fetching the full [B, Tc] f32 bands plus the [B, Tc]
    bool anomaly map was the single largest warm-tick cost over the
    tunnel (~60% of wall-clock at fleet batch). This trivial postlude
    returns int8 verdicts, bit-packed anomaly flags, and the per-row
    last-valid band values — ~15x fewer D2H bytes, one device_get.
    """
    b = verdict.shape[0]
    ar = jnp.arange(b)
    return (
        verdict.astype(jnp.int8),
        jnp.packbits(anoms, axis=1),
        upper[ar, nidx],
        lower[ar, nidx],
        p,
        differs,
    )


def _pack_hist_bf16_host(series, length: int):
    """Host-side anchor-shifted bf16-delta packing of ragged histories.

    Returns (anchor f32 [B], delta bf16 [B, length], lens int32 [B]).
    Rows are left-packed (valid prefix), so the device reconstructs the
    mask from `lens` and the upload is 2 B/point — the cold-tick H2D is
    the worker's dominant cost over the degraded tunnel (BENCHMARKS.md),
    and this path ships ~2.5x fewer bytes than f32 values + bool mask.
    Anchor = first valid value (the same shift masked_moments uses), so
    deltas are bounded by the window range and bf16 keeps ~3 significant
    digits of the deviations."""
    import ml_dtypes

    from foremast_tpu import native

    b = len(series)
    packed = native.pack_windows(list(series), length) if b else None
    if packed is not None:
        values, _, mask = packed
        lens = mask.sum(axis=1).astype(np.int32)
    else:
        values = np.zeros((b, length), np.float32)
        lens = np.zeros(b, np.int32)
        for i, (_, v) in enumerate(series):
            n = min(len(v), length)
            values[i, :n] = np.asarray(v, np.float32)[:n]
            lens[i] = n
    anchor = values[:, 0].copy() if length else np.zeros(b, np.float32)
    anchor[lens == 0] = 0.0
    delta = values - anchor[:, None]
    delta[np.arange(length)[None, :] >= lens[:, None]] = 0.0
    return anchor, delta.astype(ml_dtypes.bfloat16), lens


# Columnar-path padding: a zero terminal-state entry (n_hist=0 =>
# UNKNOWN, dropped on decode) under one shared arena key.
_PAD_ENTRY = (0.0, 0.0, np.zeros(1, np.float32), 0, 0.0, 0)
_PAD_COL_KEY = "__pad__col__"

_PAD_TASK = MetricTask(
    job_id="__pad__",
    alias="__pad__",
    metric_type=None,
    hist_times=np.zeros(0, np.int64),
    hist_values=np.zeros(0, np.float32),
    cur_times=np.zeros(0, np.int64),
    cur_values=np.zeros(0, np.float32),
    fit_key="__pad__",
)


class HealthJudge:
    """Batched scorer with reference-parity config semantics.

    `fit_cache` (a models.cache.ModelCache, set by the worker — the
    reference brain's MAX_CACHE_SIZE model cache, `foremast-brain/
    README.md:30`) memoizes fitted forecaster terminal state per
    (algorithm, task.fit_key). A re-check tick whose history is unchanged
    re-runs only the judgment tail on the new current window."""

    def __init__(self, config: BrainConfig | None = None):
        self.config = config or BrainConfig()
        self.fit_cache = None
        # "full": MetricVerdict.upper/lower carry the whole band over the
        # current window (direct API users, tests, UI shaping).
        # "last": only the final band point crosses the tunnel (as a
        # length-1 array, so `v.upper[-1]` consumers work unchanged) and
        # anomaly flags cross bit-packed — the worker's fleet-tick mode.
        self.band_mode = "full"
        # Device-resident state arenas (engine.arena.StateArena), one per
        # (algorithm, season) the judge has scored: warm rows are
        # gathered ON DEVICE by row index, so re-check ticks ship zero
        # state bytes and a churned claim set re-uploads only its changed
        # rows (round 3's whole-claim-set restack keyed on the ordered
        # fit-key tuple paid ~25 MB/tick on ANY churn).
        self._arenas: dict = {}
        # Counters of arenas retired by clear_device_state / widen
        # rebuilds: device_state_counters() stays MONOTONE across arena
        # lifetimes so the gauge exporter never needs a re-baseline
        # heuristic (ADVICE r4: the heuristic dropped or double-counted
        # events around rebuilds).
        self._counters_base = {
            "hits": 0,
            "misses": 0,
            "evictions": 0,
            "fallbacks": 0,
            "shard_moves": 0,
        }
        # Columnar batch-padding accounting (ISSUE 13): rows dispatched
        # vs rows that were padding (bucket rounding + data-axis
        # rounding). Exposed through the worker's device_mesh varz /
        # metrics so the <2% padded-row overhead bar is observable, not
        # assumed. Plain HealthJudge counts too (pow2 bucketing pads
        # even without a mesh) — the fraction is a property of the
        # dispatch shape, not of sharding.
        self.pad_rows_total = 0
        self.batch_rows_total = 0

    def judge(self, tasks: Sequence[MetricTask]) -> list[MetricVerdict]:
        """Score a set of metric tasks, batching same-shaped buckets."""
        if not tasks:
            return []
        # Bucket by (hist_len_bucket, cur_len_bucket) to bound recompiles.
        buckets: dict[tuple[int, int], list[int]] = {}
        for i, t in enumerate(tasks):
            key = (
                bucket_length(len(t.hist_values)),
                bucket_length(
                    max(
                        len(t.cur_values),
                        0 if t.base_values is None else len(t.base_values),
                    )
                ),
            )
            buckets.setdefault(key, []).append(i)

        out: list[MetricVerdict | None] = [None] * len(tasks)
        for (th, tc), idxs in buckets.items():
            # The BATCH axis is bucketed too: XLA compiles one program per
            # (B, Th, Tc) triple, and production claim sizes vary tick to
            # tick — without padding, a 255-doc claim after a 256-doc one
            # would eat a fresh 20-40 s TPU compile. Pad rows are empty
            # (verdict UNKNOWN) and dropped below; their constant
            # "__pad__" fit key keeps warm ticks fit-free.
            chunk = [tasks[i] for i in idxs]
            rows = bucket_length(len(chunk))
            if rows != len(chunk):
                chunk = chunk + [_PAD_TASK] * (rows - len(chunk))
            for v, i in zip(self._judge_bucket(chunk, th, tc), idxs):
                out[i] = v
        return [v for v in out if v is not None]

    def _place(self, batch: scoring.ScoreBatch) -> scoring.ScoreBatch:
        """Device-placement hook — identity here (default device);
        parallel.ShardedJudge overrides it to shard over the mesh."""
        return batch

    def _place_cols(self, *arrays):
        """Placement hook for bare leading-axis-[B] columnar operands
        (the joint from-rows paths' cur/mask/x buffers, which never ride
        a ScoreBatch) — identity here; parallel.ShardedJudge device_puts
        each with its leading axis over the mesh's data axis."""
        return arrays

    def _batch_multiple(self) -> int:
        """Every dispatched batch's leading axis must be a multiple of
        this (1 here; ShardedJudge returns its data-axis size so XLA
        partitions rows evenly with fully-masked pad rows)."""
        return 1

    def _arena_for(self, m_need: int):
        """The (algorithm, season) arena, grown to season width m_need.

        Widening (a later batch carrying a longer season buffer than any
        before) rebuilds the arena empty; host fit-cache entries persist,
        so the next assign simply re-scatters what it needs. Returns None
        when arenas are disabled (FOREMAST_ARENA_BYTES=0)."""
        from foremast_tpu.engine.arena import StateArena, _arena_bytes

        if _arena_bytes() <= 0:
            return None
        key = (self.config.algorithm, self.config.season_steps)
        arena = self._arenas.get(key)
        if arena is None or arena.m < m_need:
            if arena is not None:
                self._retire_counters(arena)
            arena = StateArena(
                m_need,
                sharding=self._arena_sharding(),
                shards=self._arena_shards(),
            )
            self._arenas[key] = arena
        return arena

    def _arena_sharding(self):
        """Placement for arena device buffers — None (default device)
        here; ShardedJudge places over its mesh: data-axis block-sharded
        by default (ISSUE 19 — capacity scales with the mesh), or fully
        replicated when FOREMAST_ARENA_SHARDED is off / in pod mode."""
        return None

    def _arena_shards(self) -> int:
        """Number of data-axis blocks the arena row space splits into —
        1 here (single device: the whole arena is one block);
        ShardedJudge returns its data-axis size so each device hosts
        exactly its batch block's rows and the warm gather is
        device-local by construction (ISSUE 19)."""
        return 1

    def _fetch(self, tree):
        """Device->host fetch for result decode — one overlapped
        device_get; ShardedJudge under multi-controller overrides this
        with a process_allgather (sharded outputs are not fully
        addressable from any single process)."""
        return jax.device_get(tree)

    def _retire_counters(self, arena) -> None:
        """Fold a dying arena's event counters into the monotone base so
        device_state_counters() never moves backwards across rebuilds."""
        c = arena.counters()
        for k in ("hits", "misses", "evictions", "shard_moves"):
            self._counters_base[k] += c.get(k, 0)

    def clear_device_state(self) -> None:
        """Release every arena's device buffers (e.g. after warmup: the
        synthetic rows must not occupy HBM). The host fit cache is
        untouched — rows repopulate lazily on the next tick. Event
        counters are folded into the monotone base first."""
        for arena in self._arenas.values():
            self._retire_counters(arena)
            arena.clear()
        self._arenas.clear()

    def device_state_counters(self) -> dict:
        """Aggregated arena hit/miss/eviction/fallback counters (worker
        self-telemetry; VERDICT r3 asked for the churn cost to be
        observable rather than silent). MONOTONE across arena rebuilds:
        retired arenas' events are kept in a base accumulator, so the
        gauge exporter can export plain deltas (ADVICE r4)."""
        agg = dict(self._counters_base, rows_live=0)
        for arena in self._arenas.values():
            c = arena.counters()
            for k in ("hits", "misses", "evictions", "rows_live",
                      "shard_moves"):
                agg[k] += c.get(k, 0)
        return agg

    def _score_with_fit_cache(
        self, batch: scoring.ScoreBatch, tasks: list[MetricTask], th: int
    ) -> scoring.ScoreResult:
        """Score reusing cached fits; fit only the cache-miss rows.

        Cache entries hold the forecaster's terminal state as host numpy
        (level, trend, season, season_phase, scale, n_hist) — everything
        `score_from_state` needs; the 7-day history scan runs once per
        (algorithm, fit_key), not once per re-check tick. Only miss rows'
        histories are packed and uploaded, as one sub-batch padded to a
        power-of-two row count so the fit program compiles for a handful
        of shapes.
        """
        cfg = self.config
        # season_steps keys the cache too: season buffers of different
        # lengths must never stack into one batch (and a reconfigured
        # season invalidates every fitted seasonal state)
        keys = [
            (cfg.algorithm, cfg.season_steps, t.fit_key) if t.fit_key else None
            for t in tasks
        ]
        # tasks that carry their entry (worker warm path) skip the lookup;
        # everything else goes through ONE batched cache get
        entries = [t.fit_entry for t in tasks]
        need = [i for i, e in enumerate(entries) if e is None]
        if need:
            fetched = self.fit_cache.get_many([keys[i] for i in need])
            for i, e in zip(need, fetched):
                entries[i] = e
        miss = [i for i, e in enumerate(entries) if e is None]
        # fit stage spans the whole miss-refit loop; near-zero samples on
        # warm ticks are the signal that the fit cache is doing its job
        with span(
            "judge.fit",
            stage="fit",
            rows=len(tasks),
            misses=len(miss),
            device=True,
        ):
            self._fit_miss_rows(miss, tasks, keys, entries, th)
        gap = (
            jnp.asarray(_gap_steps(tasks))
            if cfg.algorithm in GAP_SENSITIVE_FITS
            else None
        )
        pw = dict(
            pairwise_algorithm=cfg.pairwise.algorithm,
            p_threshold=cfg.pairwise.threshold,
            min_mw=cfg.pairwise.min_mann_white_points,
            min_wilcoxon=cfg.pairwise.min_wilcoxon_points,
            min_kruskal=cfg.pairwise.min_kruskal_points,
            min_friedman=cfg.pairwise.min_friedman_points,
        )
        return self._arena_score(batch, keys, entries, miss, gap, pw)

    def _fit_miss_rows(self, miss, tasks, keys, entries, th) -> None:
        """Fit the cache-miss rows in bounded chunks, filling `entries`
        in place and populating the fit cache.

        A fleet-cold tick can miss 40k+ rows at the 10,080-pt history,
        and one bucket-padded fit batch would materialize gigabytes of
        host+device buffers; fixed-size chunks reuse one compiled fit
        shape and bound peak memory. Cold fits ship anchor + bf16
        deltas + lengths (2 B/point vs 5 B/point f32+mask): the cold
        tick is H2D-bound over the tunnel. The deployed default's fit
        needs only moments, which come from the deltas exactly; every
        other algorithm reconstructs f32 values in-program
        (fit_forecast_bf16_delta — the reconstruction is transient HBM,
        the saving is the wire). Quality pinned with the headline
        storage's tests; FOREMAST_BF16_DELTA=0 opts out."""
        cfg = self.config
        bf16_fit = scoring.bf16_delta_enabled()
        ma_fit = cfg.algorithm == "moving_average_all"
        _zero_season = np.zeros(1, np.float32)
        for c0 in range(0, len(miss), _FIT_CHUNK):
            chunk = miss[c0 : c0 + _FIT_CHUNK]
            rows = bucket_length(len(chunk))
            pad = [chunk[0]] * (rows - len(chunk))  # repeat a real row:
            ragged = [  # bounded compile shapes
                (tasks[i].hist_times, tasks[i].hist_values)
                for i in chunk + pad
            ]
            if bf16_fit and ma_fit:
                anchor, delta, lens = _pack_hist_bf16_host(ragged, th)
                level, scale, nh = self._fetch(
                    scoring.fit_ma_from_bf16_delta(
                        jnp.asarray(anchor),
                        jnp.asarray(delta),
                        jnp.asarray(lens),
                    )
                )
                puts = []
                for j, i in enumerate(chunk):
                    entry = (
                        float(level[j]),
                        0.0,
                        _zero_season,
                        0,
                        float(scale[j]),
                        int(nh[j]),
                    )
                    entries[i] = entry
                    if keys[i] is not None:
                        puts.append((keys[i], entry))
                if puts:
                    self.fit_cache.put_many(puts)
                continue
            if bf16_fit:
                anchor, delta, lens = _pack_hist_bf16_host(ragged, th)
                fc = scoring.fit_forecast_bf16_delta(
                    jnp.asarray(anchor),
                    jnp.asarray(delta),
                    jnp.asarray(lens),
                    algorithm=cfg.algorithm,
                    season_length=cfg.season_steps,
                )
                n_hist = jnp.asarray(lens)
            else:
                hist = MetricWindows.from_ragged(
                    ragged, th, device_times=False
                )
                fc = scoring.fit_forecast(
                    hist.values,
                    hist.mask,
                    algorithm=cfg.algorithm,
                    season_length=cfg.season_steps,
                )
                n_hist = hist.count().astype(jnp.int32)
            # one overlapped D2H (same rationale as the result decode)
            level, trend, season, phase, scale, nh = self._fetch(
                (fc.level, fc.trend, fc.season, fc.season_phase, fc.scale, n_hist)
            )
            puts = []
            for j, i in enumerate(chunk):
                entry = (
                    float(level[j]),
                    float(trend[j]),
                    season[j].copy(),
                    int(phase[j]),
                    float(scale[j]),
                    int(nh[j]),
                )
                entries[i] = entry
                if keys[i] is not None:
                    puts.append((keys[i], entry))
            if puts:
                self.fit_cache.put_many(puts)

    def _arena_score(
        self, batch, keys, entries, force, gap, pw, n_real=None
    ):
        """Arena-gathered judgment shared by the object and columnar
        paths: assign rows, widen-rebuild if a scattered row carries a
        longer season buffer than the arena was built for, scatter the
        changed rows, and score via on-device gather. Falls back to a
        one-off host stack when arenas are disabled or the batch exceeds
        the byte budget.

        Season buffers may mix lengths within one batch: auto fits on a
        history shorter than two cycles return the mean model's [1]
        zero buffer (scoring.tile_season documents why tiling is exact);
        the arena is sized for the widest and tiles the rest. The
        max-width scan is O(B) host work, so on warm ticks it runs only
        over rows actually being scattered (usually none)."""
        cfg = self.config
        arena = self._arenas.get((cfg.algorithm, cfg.season_steps))
        if arena is None:
            arena = self._arena_for(max(len(e[2]) for e in entries))
        if arena is not None:
            with span(
                "judge.arena_assemble",
                stage="arena_assemble",
                rows=len(keys),
                device=True,
            ):
                assigned = arena.assign(keys, force, n_real)
                if assigned is not None and assigned[1]:
                    m_scat = max(len(entries[i][2]) for i in assigned[1])
                    if m_scat > arena.m:
                        # wider season than the arena was built for:
                        # rebuild (empty) at the new width and re-assign
                        # everything
                        arena = self._arena_for(m_scat)
                        assigned = arena.assign(keys, force, n_real)
                    if assigned is not None and assigned[1]:
                        arena.scatter(assigned[0], assigned[1], entries)
            if assigned is not None:
                with span(
                    "judge.score", stage="score", rows=len(keys), device=True
                ):
                    if arena.shards > 1:
                        # data-axis-sharded arena (ISSUE 19): hand the
                        # program LOCAL row indices, placed over the
                        # mesh like every other [B] operand, and gather
                        # via the device-local shard_map program
                        (rows_dev,) = self._place_cols(
                            (np.asarray(assigned[0]) % arena.cap_s)
                            .astype(np.int32)
                        )
                        return scoring.score_from_arena_sharded(
                            batch,
                            *arena.state,
                            rows_dev,
                            mesh=arena.sharding.mesh,
                            gap_steps=gap,
                            **pw,
                        )
                    return scoring.score_from_arena(
                        batch,
                        *arena.state,
                        jnp.asarray(assigned[0]),
                        gap_steps=gap,
                        **pw,
                    )
        # fallback (arena disabled, or batch exceeds even the hard byte
        # cap): one-off host stack + upload, no cross-tick device reuse.
        # COUNTED and logged — a fleet living on this path re-pays its
        # whole state upload every tick, which must never be silent
        # (VERDICT r4: the daily-season cliff).
        if arena is not None:
            self._counters_base["fallbacks"] += 1
            log.warning(
                "arena fallback: batch of %d rows exceeds the hard cap "
                "(%d rows at season_len=%d) — full state restack this "
                "tick; raise FOREMAST_ARENA_MAX_BYTES",
                len(keys),
                arena.hard_rows,
                arena.m,
            )
        with span("judge.score", stage="score", rows=len(keys), device=True):
            return self._stacked_score(batch, entries, gap, pw)

    def _stacked_score(self, batch, entries, gap, pw):
        """One-off host stack + upload of terminal state (the no-arena
        path: FOREMAST_ARENA_BYTES=0 or a batch over the byte budget)."""
        m = max(len(e[2]) for e in entries)
        stacked = (
            jnp.asarray([e[0] for e in entries], jnp.float32),
            jnp.asarray([e[1] for e in entries], jnp.float32),
            jnp.asarray(
                np.stack([scoring.tile_season(e[2], m) for e in entries])
            ),
            jnp.asarray([e[3] for e in entries], jnp.int32),
            jnp.asarray([e[4] for e in entries], jnp.float32),
            jnp.asarray([e[5] for e in entries], jnp.int32),
        )
        return scoring.score_from_state(batch, *stacked, gap_steps=gap, **pw)

    def judge_columnar(
        self,
        values: np.ndarray,
        mask: np.ndarray,
        keys: list,
        entries: list,
        nidx: np.ndarray,
        thr: np.ndarray,
        bound: np.ndarray,
        mlb: np.ndarray,
        gap_steps: np.ndarray | None = None,
        with_bands: bool = True,
        base_values: np.ndarray | None = None,
        base_mask: np.ndarray | None = None,
    ):
        """Columnar warm-tick scoring: arrays in, compact arrays out.
        Dispatch + blocking gather in one call — `judge_columnar_async`
        + `ColumnarPending.wait()` split the two halves so a pipelined
        caller can overlap the device's execution with host work
        (ISSUE 15); this wrapper IS that split, so the monolithic and
        pipelined paths cannot diverge.

        The worker's fleet fast path (jobs/worker.py _fast_tick) calls
        this for re-check ticks where EVERY row already carries a cached
        fit entry: no MetricTask/MetricVerdict objects, no ragged
        packing, no per-task key tuples — per-window host cost is one
        buffer write and one dict lookup, which is what lets the shipped
        loop approach the engine's throughput (BASELINE.md's 100k
        windows/s is a SYSTEM number).

        values/mask: [B, tc] current windows (host numpy, caller-packed);
        keys/entries: per-row fit-cache key + terminal-state entry (pad
        rows use the shared _PAD constants); nidx: per-row last-valid
        index for the band-last gather; thr/bound/mlb: per-row anomaly
        operands. base_values/base_mask (ISSUE 14): an optional SECOND
        [B, tc] buffer pair carrying baseline windows — the canary
        bucket. When present the program compiles with the configured
        pairwise rank tests active (Mann-Whitney/Wilcoxon/Kruskal/
        Friedman with their min-points gates, batched over [B, tc]) and
        the decode also fetches (p [B], differs [B]); rows whose
        baseline mask is all-False get the same hardwired (p=1, False)
        the object path's gates produce. When absent the baseline-less
        PAIRWISE_NONE program runs, exactly as before.

        Returns (verdict int8 [B], anomaly flags bool [B, tc],
        upper_last [B], lower_last [B], p [B] | None, differs [B] |
        None); with_bands=False skips the band fetch entirely
        (upper/lower come back as None) for callers with no gauge hook;
        p/differs are None on the baseline-less variant (the host fills
        the (1.0, False) constants itself).
        """
        return self.judge_columnar_async(
            values,
            mask,
            keys,
            entries,
            nidx,
            thr,
            bound,
            mlb,
            gap_steps=gap_steps,
            with_bands=with_bands,
            base_values=base_values,
            base_mask=base_mask,
        ).wait()

    def judge_columnar_async(
        self,
        values: np.ndarray,
        mask: np.ndarray,
        keys: list,
        entries: list,
        nidx: np.ndarray,
        thr: np.ndarray,
        bound: np.ndarray,
        mlb: np.ndarray,
        gap_steps: np.ndarray | None = None,
        with_bands: bool = True,
        base_values: np.ndarray | None = None,
        base_mask: np.ndarray | None = None,
    ) -> "ColumnarPending":
        """The dispatch half of `judge_columnar` (ISSUE 15): pad, place
        (one H2D off the caller's HOST numpy — the handoff contract that
        keeps a sharded judge's placement a single copy), run the arena
        gather + score + compact programs, and return WITHOUT blocking.
        JAX async dispatch means the device is now executing while the
        caller packs the next slice or decodes the previous one; the
        only blocking point is `ColumnarPending.wait()`'s gather.

        Arena mutation (assign/scatter) happens HERE, so dispatch calls
        must stay on one thread in slice order — the same contract the
        slow pipeline pins for its judge stage. wait() touches no arena
        state and may run on a writer thread."""
        cfg = self.config
        b0, tc = values.shape
        pairwise = base_values is not None
        rows_b = bucket_length(b0)
        # data-axis rounding on top of the pow2 bucket (ISSUE 13): a
        # sharded judge needs B divisible by the mesh's data axis so
        # every device holds an identical-shape shard. For power-of-two
        # axes this is already true past 8 rows; the general form keeps
        # non-pow2 meshes (a 6-chip host) compiling a bounded shape set
        # (pow2 buckets x one constant multiple).
        mult = self._batch_multiple()
        if mult > 1 and rows_b % mult:
            rows_b += mult - rows_b % mult
        self.batch_rows_total += rows_b
        self.pad_rows_total += rows_b - b0
        if rows_b != b0:
            pad = rows_b - b0
            values = np.concatenate(
                [values, np.zeros((pad, tc), np.float32)]
            )
            mask = np.concatenate([mask, np.zeros((pad, tc), bool)])
            nidx = np.concatenate([nidx, np.zeros(pad, np.int32)])
            thr = np.concatenate([thr, np.ones(pad, np.float32)])
            bound = np.concatenate([bound, np.ones(pad, np.int32)])
            mlb = np.concatenate([mlb, np.zeros(pad, np.float32)])
            shards = self._arena_shards()
            if shards > 1:
                # shard-qualified pad keys (ISSUE 19): pad positions land
                # in whatever data-axis block the tail falls in, which
                # varies with b0 — one stable pad row PER SHARD keeps the
                # warm path scatter-free where a single shared key would
                # migrate between blocks every tick
                per = rows_b // shards
                keys = list(keys) + [
                    _PAD_COL_KEY + "@" + str((b0 + j) // per)
                    for j in range(pad)
                ]
            else:
                keys = list(keys) + [_PAD_COL_KEY] * pad
            entries = list(entries) + [_PAD_ENTRY] * pad
            if gap_steps is not None:
                gap_steps = np.concatenate(
                    [gap_steps, np.zeros(pad, np.int32)]
                )
            if pairwise:
                # pad baseline rows all-masked: every rank-test gate
                # fails, (p=1, differs=False) — inert like the rest of
                # the pad row
                base_values = np.concatenate(
                    [base_values, np.zeros((pad, tc), np.float32)]
                )
                base_mask = np.concatenate(
                    [base_mask, np.zeros((pad, tc), bool)]
                )
        # HOST buffers all the way into _place: committing them with
        # jnp.asarray first would make a sharded judge's device_put a
        # second full-batch copy (default device -> mesh reshard) on
        # every warm tick — the placement hook must see numpy so the
        # one H2D lands directly in the sharded layout. The identity
        # judge is unchanged: the jit call commits uncommitted numpy
        # operands exactly as jnp.asarray did (same weak-type casts).
        batch = scoring.ScoreBatch(
            historical=MetricWindows(
                values=np.zeros((rows_b, 0), np.float32),
                mask=np.zeros((rows_b, 0), bool),
                times=None,
            ),
            current=MetricWindows(values=values, mask=mask, times=None),
            baseline=MetricWindows(
                values=(
                    base_values
                    if pairwise
                    else np.zeros((rows_b, tc), np.float32)
                ),
                mask=(
                    base_mask
                    if pairwise
                    else np.zeros((rows_b, tc), bool)
                ),
                times=None,
            ),
            threshold=thr,
            bound=bound,
            min_lower_bound=mlb,
            min_points=np.full((rows_b,), cfg.min_historical_points, np.int32),
        )
        batch = self._place(batch)
        # The warm program splits into TWO compiled variants (ISSUE 14):
        # the baseline-less bucket proves no baselines exist, and an
        # empty baseline gates every rank test off — (p=1,
        # differs=False) is the hardwired outcome — so PAIRWISE_NONE
        # compiles the judgment without the tests at all
        # (byte-identical verdicts; at fleet batch sizes their ranking
        # compare-matrices dominate the warm program's memory traffic —
        # the cost that capped co-hosted mesh workers in
        # benchmarks/scaleout_bench.py). The CANARY bucket carries a
        # real [B, tc] baseline buffer, so it compiles the configured
        # pairwise algorithm — rank transforms batched over the buffer,
        # threshold lowering fused into the same program.
        pw = dict(
            pairwise_algorithm=(
                cfg.pairwise.algorithm if pairwise else scoring.PAIRWISE_NONE
            ),
            p_threshold=cfg.pairwise.threshold,
            min_mw=cfg.pairwise.min_mann_white_points,
            min_wilcoxon=cfg.pairwise.min_wilcoxon_points,
            min_kruskal=cfg.pairwise.min_kruskal_points,
            min_friedman=cfg.pairwise.min_friedman_points,
        )
        gap = None if gap_steps is None else jnp.asarray(gap_steps)
        res = self._arena_score(batch, keys, entries, (), gap, pw, b0)
        # dispatch the compact program too (still async): the pending
        # handle holds only the small result-shaped device arrays, so a
        # pipelined caller queues O(depth) compact outputs, never whole
        # score batches
        full = with_bands and self.band_mode == "full"
        if full:
            # full [B, tc] bands for custom hooks (parity with the
            # object path's "full" mode — same band shape on warm
            # and cold ticks)
            if pairwise:
                dev = _compact_full_pair(
                    res.verdict, res.anomalies, res.upper,
                    res.lower, res.p_value, res.dist_differs,
                )
            else:
                dev = _compact_full_nopair(
                    res.verdict, res.anomalies, res.upper, res.lower
                )
        elif with_bands:
            if pairwise:
                dev = _compact_result(
                    res.verdict,
                    res.anomalies,
                    res.upper,
                    res.lower,
                    res.p_value,
                    res.dist_differs,
                    jnp.asarray(nidx),
                )
            else:
                dev = _compact_result_nopair(
                    res.verdict,
                    res.anomalies,
                    res.upper,
                    res.lower,
                    jnp.asarray(nidx),
                )
        else:
            if pairwise:
                dev = _compact_min_pair(
                    res.verdict, res.anomalies,
                    res.p_value, res.dist_differs,
                )
            else:
                dev = _compact_min(res.verdict, res.anomalies)
        return ColumnarPending(
            self, dev, b0, tc, rows_b, with_bands, pairwise
        )

    def _columnar_wait(self, pending: "ColumnarPending"):
        """The gather half: ONE overlapped device->host fetch of the
        compact result arrays, then the host-side unpack. No judge
        state is touched — safe off the tick thread."""
        b0, tc = pending.b0, pending.tc
        with span(
            "judge.decode", stage="decode", rows=pending.rows, device=True
        ):
            ps = differs = None
            if pending.with_bands and pending.pairwise:
                v8, packed, ub, lb, ps, differs = self._fetch(pending.dev)
                ub, lb = ub[:b0], lb[:b0]
            elif pending.with_bands:
                v8, packed, ub, lb = self._fetch(pending.dev)
                ub, lb = ub[:b0], lb[:b0]
            elif pending.pairwise:
                v8, packed, ps, differs = self._fetch(pending.dev)
                ub = lb = None
            else:
                v8, packed = self._fetch(pending.dev)
                ub = lb = None
            anoms = np.unpackbits(packed, axis=1, count=tc)
        if ps is not None:
            ps, differs = ps[:b0], differs[:b0]
        return v8[:b0], anoms[:b0], ub, lb, ps, differs

    def _judge_bucket(
        self, tasks: list[MetricTask], th: int, tc: int
    ) -> list[MetricVerdict]:
        cfg = self.config
        use_cache = self.fit_cache is not None
        cur = MetricWindows.from_ragged(
            [(t.cur_times, t.cur_values) for t in tasks], tc, device_times=False
        )
        if all(t.base_values is None for t in tasks):
            # baseline-less bucket (the rollingUpdate strategy): an
            # all-masked baseline fails every pairwise min-points gate,
            # so skip the 40k-tuple ragged list + pack and ship zeros at
            # the SAME [B, tc] compiled shape (no extra specialization)
            b = len(tasks)
            base = MetricWindows(
                values=jnp.zeros((b, tc), jnp.float32),
                mask=jnp.zeros((b, tc), bool),
                times=None,
            )
        else:
            empty = (np.zeros(0, np.int64), np.zeros(0, np.float32))
            base = MetricWindows.from_ragged(
                [
                    (t.base_times, t.base_values)
                    if t.base_values is not None
                    else empty
                    for t in tasks
                ],
                tc,
                device_times=False,
            )
        if use_cache:
            # the cached path packs/uploads histories only for cache-miss
            # rows; a fully-warm re-check tick ships zero history bytes
            b = len(tasks)
            hist = MetricWindows(
                values=jnp.zeros((b, 0), jnp.float32),
                mask=jnp.zeros((b, 0), bool),
                times=jnp.zeros((b, 0), jnp.int32),
            )
        else:
            hist = MetricWindows.from_ragged(
                [(t.hist_times, t.hist_values) for t in tasks],
                th,
                device_times=False,
            )
        thr, bound, mlb = cfg.anomaly.gather([t.metric_type for t in tasks])
        batch = scoring.ScoreBatch(
            historical=hist,
            current=cur,
            baseline=base,
            threshold=jnp.asarray(thr),
            bound=jnp.asarray(bound),
            min_lower_bound=jnp.asarray(mlb),
            min_points=jnp.full((len(tasks),), cfg.min_historical_points, jnp.int32),
        )
        batch = self._place(batch)
        if use_cache:
            res = self._score_with_fit_cache(batch, tasks, th)
        else:
            with span(
                "judge.score", stage="score", rows=len(tasks), device=True
            ):
                res = scoring.score(
                    batch,
                    gap_steps=(
                        jnp.asarray(_gap_steps(tasks))
                        if cfg.algorithm in GAP_SENSITIVE_FITS
                        else None
                    ),
                    algorithm=cfg.algorithm,
                    season_length=cfg.season_steps,
                    pairwise_algorithm=cfg.pairwise.algorithm,
                    p_threshold=cfg.pairwise.threshold,
                    min_mw=cfg.pairwise.min_mann_white_points,
                    min_wilcoxon=cfg.pairwise.min_wilcoxon_points,
                    min_kruskal=cfg.pairwise.min_kruskal_points,
                    min_friedman=cfg.pairwise.min_friedman_points,
                )
        # decode waits on the device (score spans measure async dispatch
        # only), so XLA execution time lands here on the stage histogram
        with span(
            "judge.decode", stage="decode", rows=len(tasks), device=True
        ):
            return self._decode_bucket(tasks, res, tc)

    # The object path's designated gather stage: one overlapped
    # device_get of the whole result tuple, then pure-host verdict
    # construction.
    # foremast: device-boundary
    def _decode_bucket(
        self, tasks: list[MetricTask], res, tc: int
    ) -> list[MetricVerdict]:
        # ONE overlapped device->host fetch for all result arrays: a bare
        # np.asarray per jax.Array issues a synchronous round trip PER
        # ARRAY, and over the TPU tunnel each such round trip carries a
        # fixed latency in the hundreds of ms (measured: sequential
        # fetches of 6 small result arrays cost 20-60x more wall-clock
        # than jax.device_get of the tuple, which starts every
        # copy_to_host_async before the first blocking read).
        compact = self.band_mode == "last"
        if compact:
            nidx = np.fromiter(
                (max(min(len(t.cur_values), tc) - 1, 0) for t in tasks),
                np.int32,
                count=len(tasks),
            )
            verdicts, packed, ub, lb, ps, differs = self._fetch(
                _compact_result(
                    res.verdict,
                    res.anomalies,
                    res.upper,
                    res.lower,
                    res.p_value,
                    res.dist_differs,
                    jnp.asarray(nidx),
                )
            )
            anoms = np.unpackbits(packed, axis=1, count=tc)
            uppers = lowers = None
        else:
            verdicts, anoms, uppers, lowers, ps, differs = self._fetch(
                (
                    res.verdict,
                    res.anomalies,
                    res.upper,
                    res.lower,
                    res.p_value,
                    res.dist_differs,
                )
            )

        # Decode anomaly positions for the WHOLE batch in one pass (flags
        # are sparse and already mask-gated, so padding never fires); a
        # per-row loop of nonzero/ctypes calls costs ~30-90 us/row and
        # caps the worker at ~10k windows/s regardless of device speed.
        nz_r, nz_c = np.nonzero(anoms)
        row_start = np.searchsorted(nz_r, np.arange(len(tasks)))
        row_end = np.searchsorted(nz_r, np.arange(len(tasks)), side="right")

        empty_band = np.zeros(0, np.float32)
        out = []
        for i, t in enumerate(tasks):
            n = len(t.cur_values)
            # flat [t, v, ...] pairs — barrelman's convertToAnomaly format
            # (Barrelman.go:605-615)
            cols = nz_c[row_start[i] : row_end[i]]
            if len(cols):
                flat = np.empty(2 * len(cols), dtype=np.float64)
                flat[0::2] = np.asarray(t.cur_times)[cols]
                flat[1::2] = np.asarray(t.cur_values)[cols]
                pairs = flat.tolist()
            else:
                pairs = []
            if compact:
                # length-1 band (the last point) so `upper[-1]` consumers
                # (the gauge exporter) work unchanged; len-0 for empty
                # windows so the hook's measurability gate still fires
                up = ub[i : i + 1] if n else empty_band
                lo = lb[i : i + 1] if n else empty_band
            else:
                # views into the tick's result buffer (fresh per tick, so
                # no aliasing hazard): a per-row .copy() here costs ~2 us
                # x 40k tasks on the fleet tick's one host core
                up = uppers[i, :n]
                lo = lowers[i, :n]
            out.append(
                MetricVerdict(
                    job_id=t.job_id,
                    alias=t.alias,
                    verdict=int(verdicts[i]),
                    anomaly_pairs=pairs,
                    upper=up,
                    lower=lo,
                    p_value=float(ps[i]),
                    dist_differs=bool(differs[i]),
                )
            )
        return out


class ColumnarPending:
    """A dispatched-but-ungathered columnar judgment (ISSUE 15).

    Holds the compact result arrays still resident on the device plus
    the decode shape. The device may still be executing; `wait()` is
    the one blocking point (`HealthJudge._columnar_wait` — a sharded
    judge's `_fetch` override rides along, so mesh-partitioned slices
    gather exactly as the monolithic call did). Thread contract: the
    producing `judge_columnar_async` call ran on the dispatch (tick)
    thread; `wait()` may run on any single consumer thread."""

    __slots__ = ("judge", "dev", "b0", "tc", "rows", "with_bands", "pairwise")

    def __init__(self, judge, dev, b0, tc, rows, with_bands, pairwise):
        self.judge = judge
        self.dev = dev
        self.b0 = b0
        self.tc = tc
        self.rows = rows
        self.with_bands = with_bands
        self.pairwise = pairwise

    def wait(self):
        return self.judge._columnar_wait(self)


def combine_verdicts(verdicts: Sequence[MetricVerdict]) -> int:
    """Job-level verdict: fail-fast — any unhealthy metric makes the job
    unhealthy (`design.md:43`); all-unknown stays unknown."""
    if not verdicts:
        return scoring.UNKNOWN
    vs = [v.verdict for v in verdicts]
    if any(v == scoring.UNHEALTHY for v in vs):
        return scoring.UNHEALTHY
    if all(v == scoring.UNKNOWN for v in vs):
        return scoring.UNKNOWN
    return scoring.HEALTHY
