"""`python -m foremast_tpu` — the foremast CLI."""

from foremast_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
