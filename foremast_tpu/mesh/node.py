"""`MeshNode` — one worker's seat in the mesh, wired into the tick.

Composes the mesh planes (membership heartbeat, ownership router,
optional local ring shard, optional planned-handoff manager) behind the
tiny surface the worker loop consumes:

  * ``claim_filter(doc)`` — the predicate `JobStore.claim` applies
    BEFORE flipping a doc in-progress, so a worker only ever claims
    its partition (claim-CAS stays the double-judgment safety net for
    stale views);
  * ``on_tick()`` — lease renew (rate-limited) + ring refresh; drives
    the handoff plane (stream to joiners, activate a fenced join); on
    a membership change, series this worker neither serves now nor is
    about to own are evicted from its ring shard so the freed budget
    serves the partition it actually holds;
  * ``drain()`` — the planned scale-down: flip to ``draining``, stream
    owned ring series + fits to the post-drain owners, then leave
    (docs/operations.md "Elastic scaling");
  * ``debug_state()`` — the worker `/debug/state` ``mesh`` section;
  * ``close()`` — leave the mesh (peers drop this worker immediately
    instead of waiting out the lease).

`MeshCollector` exports the same counters as `foremast_mesh_*` /
`foremast_handoff_*` families (docs/observability.md), materialized at
scrape time like the ingest plane's collector — nothing on the tick
path touches prometheus_client.
"""

from __future__ import annotations

import logging
import threading
import time

from foremast_tpu.mesh.membership import (
    MEMBER_STATES,
    STATE_ACTIVE,
    STATE_DRAINING,
    STATE_JOINING,
    Membership,
)
from foremast_tpu.mesh.routing import MeshRouter

log = logging.getLogger("foremast_tpu.mesh")


class MeshNode:
    def __init__(
        self,
        membership: Membership,
        router: MeshRouter,
        ring_store=None,  # ingest.shards.RingStore (optional)
        clock=time.time,
        handoff: "HandoffManager | None" = None,
        join_fenced: bool | None = None,
    ):
        """`handoff` mounts the planned-handoff plane; `join_fenced`
        (default: handoff wired) makes `start()` register as a fenced
        ``joining`` member when active peers exist, so the current
        owners stream this worker its partition before it claims."""
        self.membership = membership
        self.router = router
        self.ring_store = ring_store
        self._clock = clock
        self.handoff = handoff
        self.join_fenced = (
            (handoff is not None) if join_fenced is None else bool(join_fenced)
        )
        # claim-filter traffic: owned vs skipped docs seen by claims
        self.claim_counts = {"owned": 0, "skipped": 0}
        self._started = False
        self._drain_out: dict | None = None  # stream_drain ran (result)
        self._serve_thread: threading.Thread | None = None

    @property
    def worker_id(self) -> str:
        return self.membership.worker_id

    @property
    def state(self) -> str:
        return self.membership.state

    @property
    def draining(self) -> bool:
        return self.membership.state == STATE_DRAINING

    @property
    def joining(self) -> bool:
        return self.membership.state == STATE_JOINING

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self.join_fenced and self.handoff is not None:
            # fence only when there is someone to hand off FROM: a solo
            # first member (or a fleet of simultaneous bootstrappers)
            # must come up claiming, not waiting on a deadline. A
            # worker RE-TAKING a still-live seat (the PR-7 SIGKILL
            # restart: persisted identity, lease not yet expired, ring
            # never moved) must not fence either — joining would evict
            # it from the claim ring and hand its partition to peers
            # COLD, exactly the refit wall the warm restart exists to
            # avoid.
            records = self.membership.live_members()
            self_alive = any(
                m.worker_id == self.worker_id for m in records
            )
            peers = [
                m
                for m in records
                if m.state == STATE_ACTIVE
                and m.worker_id != self.worker_id
            ]
            if peers and not self_alive:
                self.membership.state = STATE_JOINING
                self.handoff.begin_join({m.worker_id for m in peers})
                log.info(
                    "mesh join (fenced): %s waits for handoff from %s "
                    "(deadline %.1fs)",
                    self.worker_id,
                    sorted(m.worker_id for m in peers),
                    self.handoff.deadline_seconds,
                )
        self.membership.join()
        self.router.refresh(force=True)
        self._started = True

    def close(self) -> None:
        if self._started:
            self.membership.leave()
            self._started = False

    def stream_drain(self) -> dict:
        """The streaming half of a planned scale-down (ISSUE 11):
        publish ``draining`` (peers hint pushers at the post-drain
        owners and protect transferred state from eviction) and stream
        every owned ring series + fit to its new owner — WITHOUT
        leaving, so the caller can keep ticking while the transfer is
        in flight: a draining member stays on the claim ring, claiming
        and judging its partition to the end, and no verdict is lost
        or delayed behind a slow target (cli runs this on a side
        thread under the loop). Idempotent — a second call returns the
        first call's outcomes without re-streaming. A failed transfer
        degrades to the PR-6 cold-refit rebalance (counted), never a
        wedge. Returns per-target send outcomes."""
        if not self._started:
            return {}
        if self._drain_out is not None:
            return self._drain_out
        self.membership.set_state(STATE_DRAINING)
        self.router.refresh(force=True)
        out: dict = {"targets": {}, "state": "drained"}
        if self.handoff is not None:
            # joiners are targets too: the target ring may hand part of
            # this partition straight to a still-fenced joiner, and a
            # draining member's tick no longer serves joiners — skipping
            # them here would silently drop that slice to a cold refit
            # exactly when scale-down and scale-up overlap
            targets = [
                m
                for m in self.router.members()
                if m.state in (STATE_ACTIVE, STATE_JOINING)
                and m.worker_id != self.worker_id
                and m.ingest_address
            ]
            sent = self.handoff.send_all(
                targets, self.router, self.worker_id
            )
            out["targets"] = {
                tid: "ok" if ok else "failed" for tid, ok in sent.items()
            }
        self._drain_out = out
        return out

    def drain(self) -> dict:
        """Planned scale-down: `stream_drain()` (skipped if the caller
        already ran it under the tick loop), then leave. Returns the
        per-target send outcomes."""
        if not self._started:
            return self._drain_out or {}
        out = self.stream_drain()
        self.membership.leave()
        self._started = False
        log.info("mesh drain complete: %s (%s)", self.worker_id, out)
        return out

    # -- tick hooks -----------------------------------------------------

    def on_tick(self) -> None:
        """Called at the top of every worker tick (idle ones too — the
        lease must outlive quiet fleets). Takes no simulated `now`:
        lease and refresh timing run on the membership's/router's OWN
        injectable clocks, so a test driving worker.tick(now=t) injects
        clocks there instead of threading t through here (a parameter
        that was accepted but ignored would make simulated-time tests
        lie).

        A TRANSIENT store failure during renew/refresh degrades, never
        raises (ISSUE 9): the lease holds until expiry and retries next
        tick, the ring keeps its last view — a stale ring only
        mis-scopes claims, and claim-CAS already nets double judgment.
        A store down long enough to expire the lease costs this worker
        its seat, exactly the price a genuinely dead worker pays."""
        if not self._started:
            self.start()
            return
        from foremast_tpu.chaos.degrade import is_transient_error

        try:
            self.membership.renew()
            changed = self.router.refresh()
        except Exception as e:
            if not is_transient_error(e):
                raise
            log.warning(
                "mesh renew/refresh degraded (transient store error: "
                "%s); keeping the last ring view", e,
            )
            return
        if self.handoff is not None:
            self._drive_handoff()
        if changed and self.ring_store is not None:
            dropped = self.ring_store.evict_unowned(self._retains)
            if dropped:
                log.info(
                    "mesh rebalance: evicted %d series no longer owned "
                    "by %s", dropped, self.worker_id,
                )

    def _retains(self, key: str) -> bool:
        """The eviction-retention predicate: keep a series owned on the
        claim ring, on the target ring (a planned change is about to
        hand it to us), or just transferred here (`evict_unowned` must
        never race a shard mid-flight — the transfer may land before
        this router has even SEEN the planned state that justifies it)."""
        if self.handoff is None:
            return self.router.owns_series(key)
        return self.router.retains_series(key) or self.handoff.is_protected(
            key
        )

    def _drive_handoff(self) -> None:
        """Per-tick handoff plane work: active members stream state to
        newly-visible joiners; a fenced joiner activates once every
        live active member's `done` marker arrived (or the deadline
        passed — degradation to cold refit, never a deadlock)."""
        handoff = self.handoff
        members = self.router.members()
        handoff.note_members(members)
        handoff.purge_protected()
        if self.membership.state == STATE_ACTIVE:
            t = self._serve_thread
            if t is not None and not t.is_alive():
                self._serve_thread = None
                t = None
            if t is None:
                pending = handoff.pending_joiners(members, self.worker_id)
                if pending:
                    # served even on failure: the joiner's deadline owns
                    # the degradation, a resend against a blackholed
                    # receiver would wedge behind the timeout. One
                    # send_all for every joiner visible this tick (the
                    # moving state is enumerated once, not per joiner),
                    # on a SIDE THREAD: the stream — full-partition
                    # enumeration plus batched POSTs with retries —
                    # must not stall this member's claiming/judging,
                    # symmetric with the cli drain path. At most one
                    # stream in flight; joiners appearing meanwhile
                    # wait for the next tick.
                    for rec in pending:
                        handoff.mark_served(rec.worker_id)
                    t = threading.Thread(
                        target=handoff.send_all,
                        args=(pending, self.router, self.worker_id),
                        name="handoff-serve",
                        daemon=True,
                    )
                    self._serve_thread = t
                    t.start()
        elif self.membership.state == STATE_JOINING:
            live_active = {
                m.worker_id for m in members if m.state == STATE_ACTIVE
            }
            if handoff.join_ready(live_active):
                self.membership.set_state(STATE_ACTIVE)
                self.router.refresh(force=True)
                log.info(
                    "mesh join complete: %s active after %.2fs handoff "
                    "wait", self.worker_id,
                    handoff.join_wait_seconds or 0.0,
                )

    def wait_handoff_streams(self, timeout: float | None = None) -> bool:
        """Block until the in-flight joiner stream (if any) finished —
        a test/bench synchronization hook; the production tick never
        waits on it. Returns whether the stream is done."""
        t = self._serve_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def claim_filter(self, doc) -> bool:
        owned = self.router.owns_doc(doc)
        self.claim_counts["owned" if owned else "skipped"] += 1
        return owned

    # -- observability --------------------------------------------------

    def debug_state(self) -> dict:
        members = self.router.members()
        return {
            "worker_id": self.worker_id,
            "state": self.membership.state,
            "live_members": len(members),
            "members": [
                {
                    "worker_id": m.worker_id,
                    "state": m.state,
                    "ingest_address": m.ingest_address,
                    "observe_port": m.observe_port,
                    "capacity": m.capacity,
                    "lease_seconds": m.lease_seconds,
                    "lease_age_seconds": round(
                        max(0.0, self._clock() - m.renewed_at), 2
                    ),
                }
                for m in members
            ],
            "route_label": self.router.route_label,
            "replicas": self.router.replicas,
            "rebalances": self.router.counters["rebalances"],
            "redirect_hints": self.router.counters["redirect_hints"],
            "foreign_series": self.router.counters["foreign_series"],
            "claim_docs": dict(self.claim_counts),
            "handoff": (
                self.handoff.debug_state()
                if self.handoff is not None
                else None
            ),
        }


class MeshCollector:
    """prometheus_client custom collector over a `MeshNode`."""

    def __init__(self, node: MeshNode):
        self._node = node

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        node = self._node
        members = GaugeMetricFamily(
            "foremast_mesh_members",
            "live mesh members (fresh leases, including this worker), "
            "by lifecycle state (active=claiming, draining=planned "
            "scale-down streaming its state out, joining=fenced until "
            "handoff completes)",
            labels=["state"],
        )
        by_state = dict.fromkeys(MEMBER_STATES, 0)
        for m in node.router.members():
            by_state[m.state] = by_state.get(m.state, 0) + 1
        for state in MEMBER_STATES:
            members.add_metric([state], by_state[state])
        yield members
        yield CounterMetricFamily(
            "foremast_mesh_rebalances",
            "hash-ring swaps after membership changes",
            value=node.router.counters["rebalances"],
        )
        yield CounterMetricFamily(
            "foremast_mesh_redirect_hints",
            "receiver responses carrying an owning-member address for a "
            "series this worker does not own",
            value=node.router.counters["redirect_hints"],
        )
        claims = CounterMetricFamily(
            "foremast_mesh_claim_docs",
            "documents seen by the partition claim filter, by outcome "
            "(owned=claimed here, skipped=another member's partition)",
            labels=["result"],
        )
        for result, n in node.claim_counts.items():
            claims.add_metric([result], n)
        yield claims

        # planned-handoff plane (zeros when no handoff manager is
        # wired — a stable exposition so dashboards need no existence
        # checks)
        from foremast_tpu.mesh.handoff import RECEIVE_RESULTS, SEND_RESULTS

        counters = (
            node.handoff.counters_snapshot()
            if node.handoff is not None
            else None
        )
        state = CounterMetricFamily(
            "foremast_handoff_state",
            "ring series and fit-cache entries moved by planned "
            "handoff, by payload kind and direction",
            labels=["kind", "direction"],
        )
        for kind in ("series", "fits"):
            for direction in ("sent", "received"):
                state.add_metric(
                    [kind, direction],
                    counters[f"{kind}_{direction}"] if counters else 0,
                )
        yield state
        transfers = CounterMetricFamily(
            "foremast_handoff_transfers",
            "planned-handoff transfer outcomes by role (send=this "
            "member streaming out, receive=transfer batches applied "
            "here); failed/torn/rejected transfers degrade the moved "
            "state to a cold refit, never a wedge",
            labels=["role", "result"],
        )
        for result in SEND_RESULTS:
            transfers.add_metric(
                ["send", result],
                counters["send"][result] if counters else 0,
            )
        for result in RECEIVE_RESULTS:
            transfers.add_metric(
                ["receive", result],
                counters["receive"][result] if counters else 0,
            )
        yield transfers
