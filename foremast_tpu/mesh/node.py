"""`MeshNode` — one worker's seat in the mesh, wired into the tick.

Composes the three mesh planes (membership heartbeat, ownership
router, optional local ring shard) behind the tiny surface the worker
loop consumes:

  * ``claim_filter(doc)`` — the predicate `JobStore.claim` applies
    BEFORE flipping a doc in-progress, so a worker only ever claims
    its partition (claim-CAS stays the double-judgment safety net for
    stale views);
  * ``on_tick(now)`` — lease renew (rate-limited) + ring refresh; on a
    membership change, series this worker no longer owns are evicted
    from its ring shard so the freed budget serves the partition it
    actually holds (newly-owned cold series backfill through the
    existing fallback path — rebalance needs no data transfer);
  * ``debug_state()`` — the worker `/debug/state` ``mesh`` section;
  * ``close()`` — leave the mesh (peers drop this worker immediately
    instead of waiting out the lease).

`MeshCollector` exports the same counters as `foremast_mesh_*`
families (docs/observability.md), materialized at scrape time like the
ingest plane's collector — nothing on the tick path touches
prometheus_client.
"""

from __future__ import annotations

import logging
import time

from foremast_tpu.mesh.membership import Membership
from foremast_tpu.mesh.routing import MeshRouter

log = logging.getLogger("foremast_tpu.mesh")


class MeshNode:
    def __init__(
        self,
        membership: Membership,
        router: MeshRouter,
        ring_store=None,  # ingest.shards.RingStore (optional)
        clock=time.time,
    ):
        self.membership = membership
        self.router = router
        self.ring_store = ring_store
        self._clock = clock
        # claim-filter traffic: owned vs skipped docs seen by claims
        self.claim_counts = {"owned": 0, "skipped": 0}
        self._started = False

    @property
    def worker_id(self) -> str:
        return self.membership.worker_id

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self.membership.join()
        self.router.refresh(force=True)
        self._started = True

    def close(self) -> None:
        if self._started:
            self.membership.leave()
            self._started = False

    # -- tick hooks -----------------------------------------------------

    def on_tick(self) -> None:
        """Called at the top of every worker tick (idle ones too — the
        lease must outlive quiet fleets). Takes no simulated `now`:
        lease and refresh timing run on the membership's/router's OWN
        injectable clocks, so a test driving worker.tick(now=t) injects
        clocks there instead of threading t through here (a parameter
        that was accepted but ignored would make simulated-time tests
        lie).

        A TRANSIENT store failure during renew/refresh degrades, never
        raises (ISSUE 9): the lease holds until expiry and retries next
        tick, the ring keeps its last view — a stale ring only
        mis-scopes claims, and claim-CAS already nets double judgment.
        A store down long enough to expire the lease costs this worker
        its seat, exactly the price a genuinely dead worker pays."""
        if not self._started:
            self.start()
            return
        from foremast_tpu.chaos.degrade import is_transient_error

        try:
            self.membership.renew()
            changed = self.router.refresh()
        except Exception as e:
            if not is_transient_error(e):
                raise
            log.warning(
                "mesh renew/refresh degraded (transient store error: "
                "%s); keeping the last ring view", e,
            )
            return
        if changed and self.ring_store is not None:
            dropped = self.ring_store.evict_unowned(self.router.owns_series)
            if dropped:
                log.info(
                    "mesh rebalance: evicted %d series no longer owned "
                    "by %s", dropped, self.worker_id,
                )

    def claim_filter(self, doc) -> bool:
        owned = self.router.owns_doc(doc)
        self.claim_counts["owned" if owned else "skipped"] += 1
        return owned

    # -- observability --------------------------------------------------

    def debug_state(self) -> dict:
        members = self.router.members()
        return {
            "worker_id": self.worker_id,
            "live_members": len(members),
            "members": [
                {
                    "worker_id": m.worker_id,
                    "ingest_address": m.ingest_address,
                    "observe_port": m.observe_port,
                    "capacity": m.capacity,
                    "lease_seconds": m.lease_seconds,
                    "lease_age_seconds": round(
                        max(0.0, self._clock() - m.renewed_at), 2
                    ),
                }
                for m in members
            ],
            "route_label": self.router.route_label,
            "replicas": self.router.replicas,
            "rebalances": self.router.counters["rebalances"],
            "redirect_hints": self.router.counters["redirect_hints"],
            "foreign_series": self.router.counters["foreign_series"],
            "claim_docs": dict(self.claim_counts),
        }


class MeshCollector:
    """prometheus_client custom collector over a `MeshNode`."""

    def __init__(self, node: MeshNode):
        self._node = node

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        node = self._node
        yield GaugeMetricFamily(
            "foremast_mesh_members",
            "live mesh members (fresh leases, including this worker)",
            value=len(node.router.members()),
        )
        yield CounterMetricFamily(
            "foremast_mesh_rebalances",
            "hash-ring swaps after membership changes",
            value=node.router.counters["rebalances"],
        )
        yield CounterMetricFamily(
            "foremast_mesh_redirect_hints",
            "receiver responses carrying an owning-member address for a "
            "series this worker does not own",
            value=node.router.counters["redirect_hints"],
        )
        claims = CounterMetricFamily(
            "foremast_mesh_claim_docs",
            "documents seen by the partition claim filter, by outcome "
            "(owned=claimed here, skipped=another member's partition)",
            labels=["result"],
        )
        for result, n in node.claim_counts.items():
            claims.add_metric([result], n)
        yield claims
