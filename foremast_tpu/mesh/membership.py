"""Mesh membership: heartbeat documents in the job store.

Workers register themselves as documents in the SAME store the fleet's
jobs live in (id ``mesh::<worker_id>``, app ``__foremast_mesh__``) —
the store is the one piece of shared infrastructure every worker
already reaches, so membership needs no extra system (no etcd, no
gossip). The record's status, ``mesh_member``, is outside every
claimable/terminal set in jobs/models.py, so member docs are invisible
to the claim query; discovery is a `list_app` on the mesh app name.

Liveness is lease-based: a member stamps ``renewed_at`` (its own
clock) into the record payload every ``lease_seconds / 3`` and peers
treat a record whose stamp is older than ``lease_seconds`` (by the
READER's clock) as dead. Clocks therefore need only coarse agreement —
a skew much smaller than the lease, the same assumption the store's
MAX_STUCK_IN_SECONDS takeover already makes about ``modified_at``.

The tolerance is pinned (`CLOCK_SKEW_TOLERANCE_FRACTION`, test:
tests/test_mesh.py clock-skew cases): a renewing member's record is at
most ``lease/3`` stale (the renewal cadence) plus store write latency,
so a reader whose clock runs FAST by strictly less than ``2/3 ×
lease_seconds`` can never see a healthy renewing peer as expired.
Deployments should keep worst-case clock skew at or below ``lease/2``
(7.5 s at the 15 s default) — comfortably inside the bound with margin
for write latency. A reader running SLOW only delays dead-peer
detection; it never falsely kills anyone.

Dead-peer handling is deliberately lazy: an expired record simply
stops counting toward `live_members`, the hash ring heals around it
(mesh/partition.py minimal movement), and the dead worker's in-flight
claims age out through the existing stuck-claim CAS takeover — the
mesh adds no second fencing mechanism, claim-CAS remains the one
safety net against double judgment.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time

from foremast_tpu.jobs.models import Document
from foremast_tpu.jobs.store import JobStore

log = logging.getLogger("foremast_tpu.mesh")

# app_name shared by every member record — the `list_app` discovery key
MESH_APP = "__foremast_mesh__"
# outside CLAIMABLE/TERMINAL/INPROGRESS: never claimed, never counted
# as a finished judgment
STATUS_MESH_MEMBER = "mesh_member"
# a clean leave: the record stays (stores here have no delete) but is
# filtered out of membership regardless of lease freshness
STATUS_MESH_LEFT = "mesh_left"

# -- member lifecycle states (ISSUE 11: planned elasticity) -------------
#
# `active`   the steady state: the member claims its partition and is a
#            handoff target for planned moves.
# `draining` a planned scale-down in flight: the member still CLAIMS and
#            judges its partition (nothing un-judged is abandoned), but
#            ownership-to-be excludes it — receivers hint pushers at the
#            post-drain owners and the member streams its ring shards +
#            fit entries to them before flipping to `mesh_left`.
# `joining`  a planned scale-up in flight: the member is visible (its
#            lease counts, its record advertises the transfer endpoint)
#            but FENCED from claims until the current owners finish
#            streaming it the partition it is about to take — the fence
#            is what makes a partition move a warm state TRANSFER
#            instead of a cold refit race.
#
# A record from a build that predates states (or a state this build
# does not know) reads as `active`: old readers keep claiming/routing
# to new members exactly as before, which degrades planned handoff to
# the PR-6 cold-refit rebalance, never to wrong ownership.
STATE_ACTIVE = "active"
STATE_DRAINING = "draining"
STATE_JOINING = "joining"
MEMBER_STATES = (STATE_ACTIVE, STATE_DRAINING, STATE_JOINING)
# which states sit in which ring (mesh/routing.py two-ring ownership):
# the CLAIM ring answers "who judges this doc RIGHT NOW" (a draining
# member keeps judging until it leaves; a joining member is fenced),
# the TARGET ring answers "who owns this key once the planned change
# completes" (hints, handoff destinations, eviction retention).
CLAIM_STATES = frozenset({STATE_ACTIVE, STATE_DRAINING})
TARGET_STATES = frozenset({STATE_ACTIVE, STATE_JOINING})

DEFAULT_LEASE_SECONDS = 15.0

# A fast reader tolerates skew < lease × (1 - 1/3 renewal cadence);
# ops guidance is half the lease (see module docstring — test-pinned).
CLOCK_SKEW_TOLERANCE_FRACTION = 2.0 / 3.0


def member_doc_id(worker_id: str) -> str:
    return f"mesh::{worker_id}"


@dataclasses.dataclass(frozen=True)
class MemberRecord:
    """One worker's advertisement: identity, addresses, share weight."""

    worker_id: str
    ingest_address: str = ""  # "host:port" of the push receiver ("" = none)
    observe_port: int = 0  # the worker's actual /debug/state port
    capacity: int = 1  # hash-ring share weight
    lease_seconds: float = DEFAULT_LEASE_SECONDS
    renewed_at: float = 0.0  # member's clock, unix seconds
    state: str = STATE_ACTIVE  # lifecycle state (see MEMBER_STATES)

    def expired(self, now: float) -> bool:
        return now - self.renewed_at > self.lease_seconds

    def to_payload(self) -> str:
        return json.dumps(
            {
                "workerId": self.worker_id,
                "ingestAddress": self.ingest_address,
                "observePort": self.observe_port,
                "capacity": self.capacity,
                "leaseSeconds": self.lease_seconds,
                "renewedAt": self.renewed_at,
                "state": self.state,
            }
        )

    @staticmethod
    def from_payload(raw: str) -> "MemberRecord | None":
        try:
            d = json.loads(raw)
            state = str(d.get("state", STATE_ACTIVE))
            if state not in MEMBER_STATES:
                # forward compatibility: an unknown lifecycle state from
                # a newer build reads as plain membership — old readers
                # keep claiming/routing to it (see the states note above)
                state = STATE_ACTIVE
            return MemberRecord(
                worker_id=str(d["workerId"]),
                ingest_address=str(d.get("ingestAddress", "")),
                observe_port=int(d.get("observePort", 0)),
                capacity=max(1, int(d.get("capacity", 1))),
                lease_seconds=float(
                    d.get("leaseSeconds", DEFAULT_LEASE_SECONDS)
                ),
                renewed_at=float(d.get("renewedAt", 0.0)),
                state=state,
            )
        except (ValueError, TypeError, KeyError):
            return None  # a corrupt record is a dead record, not a crash


def live_members(
    store: JobStore, now: float | None = None
) -> list[MemberRecord]:
    """Every member whose lease is fresh at `now` (reader's clock when
    None), sorted by worker id. Standalone so store-side claim filters
    (benchmarks) and the router share one definition of 'alive'."""
    now = time.time() if now is None else now
    out = []
    for doc in store.list_app(MESH_APP):
        if doc.status != STATUS_MESH_MEMBER:
            continue
        rec = MemberRecord.from_payload(doc.current_config)
        if rec is not None and not rec.expired(now):
            out.append(rec)
    out.sort(key=lambda r: r.worker_id)
    return out


class Membership:
    """This worker's own seat at the table: join / renew / leave.

    `clock` is injectable for tests; renewals are rate-limited to
    lease/3 so the per-tick `renew()` call is almost always a no-op
    integer compare, not a store write."""

    def __init__(
        self,
        store: JobStore,
        worker_id: str,
        lease_seconds: float = DEFAULT_LEASE_SECONDS,
        ingest_address: str = "",
        observe_port: int = 0,
        capacity: int = 1,
        clock=time.time,
        state: str = STATE_ACTIVE,
    ):
        self.store = store
        self.worker_id = worker_id
        self.lease_seconds = float(lease_seconds)
        self.ingest_address = ingest_address
        self.observe_port = int(observe_port)
        self.capacity = max(1, int(capacity))
        self._clock = clock
        self.state = state
        self._doc: Document | None = None
        self._last_renew = 0.0

    def _record(self, now: float) -> MemberRecord:
        return MemberRecord(
            worker_id=self.worker_id,
            ingest_address=self.ingest_address,
            observe_port=self.observe_port,
            capacity=self.capacity,
            lease_seconds=self.lease_seconds,
            renewed_at=now,
            state=self.state,
        )

    def set_state(self, state: str) -> None:
        """Flip this member's lifecycle state and publish it at once (a
        forced renew): peers must see `draining`/`joining` promptly —
        the fence and the hint routing both hang off it."""
        if state not in MEMBER_STATES:
            raise ValueError(f"unknown member state {state!r}")
        if state == self.state:
            return
        self.state = state
        self.renew(force=True)
        log.info("mesh state: %s -> %s", self.worker_id, state)

    def join(self) -> MemberRecord:
        now = self._clock()
        rec = self._record(now)
        doc = Document(
            id=member_doc_id(self.worker_id),
            app_name=MESH_APP,
            status=STATUS_MESH_MEMBER,
            processing_content=self.worker_id,
            current_config=rec.to_payload(),
        )
        # idempotent create then unconditional update: a restart reusing
        # a worker id simply re-takes its old seat with a fresh lease
        self._doc, _ = self.store.create(doc)
        self._doc.status = STATUS_MESH_MEMBER
        self._doc.current_config = rec.to_payload()
        self._doc = self.store.update(self._doc)
        self._last_renew = now
        log.info("mesh join: %s (lease %.1fs)", self.worker_id, self.lease_seconds)
        return rec

    def renew(self, force: bool = False) -> bool:
        """Refresh the lease when a third of it has elapsed (or
        `force`); returns whether a store write happened."""
        if self._doc is None:
            self.join()
            return True
        now = self._clock()
        if not force and now - self._last_renew < self.lease_seconds / 3.0:
            return False
        self._doc.current_config = self._record(now).to_payload()
        self._doc = self.store.update(self._doc)
        self._last_renew = now
        return True

    def leave(self) -> None:
        """Clean departure: the record flips to `mesh_left` so peers
        drop this member immediately instead of waiting out the lease."""
        if self._doc is None:
            return
        self._doc.status = STATUS_MESH_LEFT
        try:
            self.store.update(self._doc)
        except Exception as e:  # noqa: BLE001 — leaving must never crash shutdown
            log.warning("mesh leave failed for %s: %s", self.worker_id, e)
        self._doc = None
        log.info("mesh leave: %s", self.worker_id)

    def live_members(self, now: float | None = None) -> list[MemberRecord]:
        return live_members(
            self.store, self._clock() if now is None else now
        )
