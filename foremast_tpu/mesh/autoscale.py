"""Autoscaling driver: join/leave decisions from exported signals.

The mesh can now scale up and down without cold refits (mesh/handoff);
this module decides WHEN. It is deliberately a pure decision component
— it spawns nothing and kills nothing. The operator (or the harness:
benchmarks/elastic_bench.py) feeds it the three saturation signals the
observability plane already exports and acts on its verdicts:

  * **tick occupancy** — busy seconds per wall second of the worker
    loop (`foremast_worker_tick_seconds` over the poll cadence): the
    direct "is this worker keeping up" signal;
  * **write-queue peak** — `foremast_worker_pipeline_write_queue_peak`:
    a store write path that cannot drain as fast as the judge produces;
  * **ring budget pressure** — `foremast_ingest_bytes_resident` over
    `FOREMAST_INGEST_BUDGET_BYTES`: eviction pressure that turns warm
    fetches back into fallback fetches.

Decisions are hysteretic: a signal must breach its threshold for
`breach_ticks` CONSECUTIVE observations before a verdict fires, and a
`cooldown_seconds` window after every verdict absorbs the rebalance
transient (a scale-up's own handoff work briefly inflates occupancy —
reacting to it would oscillate). Scale-down requires EVERY signal low
(removing a worker on one quiet signal while another is saturated is
how autoscalers melt fleets), and never drops below `min_workers`.
"""

from __future__ import annotations

import dataclasses
import os
import time

DECISION_UP = "scale_up"
DECISION_DOWN = "scale_down"
DECISION_HOLD = "hold"


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Thresholds + hysteresis (FOREMAST_AUTOSCALE_* knobs)."""

    high_occupancy: float = 0.80
    low_occupancy: float = 0.30
    high_ring_pressure: float = 0.85
    high_write_queue: int = 8
    breach_ticks: int = 3
    cooldown_seconds: float = 120.0
    min_workers: int = 1
    max_workers: int = 64

    @staticmethod
    def from_env(env=None) -> "AutoscaleConfig":
        e = os.environ if env is None else env

        def f(name, default):
            return float(e.get(name, "") or default)

        return AutoscaleConfig(
            high_occupancy=f("FOREMAST_AUTOSCALE_HIGH_OCCUPANCY", 0.80),
            low_occupancy=f("FOREMAST_AUTOSCALE_LOW_OCCUPANCY", 0.30),
            high_ring_pressure=f(
                "FOREMAST_AUTOSCALE_HIGH_RING_PRESSURE", 0.85
            ),
            high_write_queue=int(
                f("FOREMAST_AUTOSCALE_HIGH_WRITE_QUEUE", 8)
            ),
            breach_ticks=int(f("FOREMAST_AUTOSCALE_BREACH_TICKS", 3)),
            cooldown_seconds=f("FOREMAST_AUTOSCALE_COOLDOWN_SECONDS", 120.0),
            min_workers=int(f("FOREMAST_AUTOSCALE_MIN_WORKERS", 1)),
            max_workers=int(f("FOREMAST_AUTOSCALE_MAX_WORKERS", 64)),
        )


class AutoscaleDriver:
    """Consecutive-breach + cooldown state machine over the signals."""

    def __init__(
        self,
        config: AutoscaleConfig | None = None,
        clock=time.monotonic,
    ):
        self.config = config or AutoscaleConfig()
        self._clock = clock
        self._high_streak = 0
        self._low_streak = 0
        self._last_decision_at: float | None = None
        self.decisions = {DECISION_UP: 0, DECISION_DOWN: 0}
        self.last_signals: dict | None = None

    def _cooling(self, now: float) -> bool:
        return (
            self._last_decision_at is not None
            and now - self._last_decision_at < self.config.cooldown_seconds
        )

    def observe(
        self,
        occupancy: float,
        members: int,
        write_queue_peak: int = 0,
        ring_pressure: float = 0.0,
    ) -> str:
        """Feed one observation window; returns the verdict. `members`
        is the current live worker count (bounds both directions)."""
        cfg = self.config
        now = self._clock()
        self.last_signals = {
            "occupancy": round(float(occupancy), 4),
            "write_queue_peak": int(write_queue_peak),
            "ring_pressure": round(float(ring_pressure), 4),
            "members": int(members),
        }
        high = (
            occupancy >= cfg.high_occupancy
            or ring_pressure >= cfg.high_ring_pressure
            or write_queue_peak >= cfg.high_write_queue
        )
        low = (
            occupancy <= cfg.low_occupancy
            and ring_pressure < cfg.high_ring_pressure
            and write_queue_peak < cfg.high_write_queue
        )
        if self._cooling(now):
            # observations inside the cooldown must not bank toward the
            # next verdict: the window exists to absorb the rebalance
            # transient a verdict itself causes, and a streak built
            # from that transient would fire the moment the window
            # expires — the oscillation this hysteresis prevents. A
            # genuine sustained breach re-earns its breach_ticks after.
            self._high_streak = 0
            self._low_streak = 0
            return DECISION_HOLD
        self._high_streak = self._high_streak + 1 if high else 0
        self._low_streak = self._low_streak + 1 if low else 0
        if (
            self._high_streak >= cfg.breach_ticks
            and members < cfg.max_workers
        ):
            self._high_streak = 0
            self._last_decision_at = now
            self.decisions[DECISION_UP] += 1
            return DECISION_UP
        if (
            self._low_streak >= cfg.breach_ticks
            and members > cfg.min_workers
        ):
            self._low_streak = 0
            self._last_decision_at = now
            self.decisions[DECISION_DOWN] += 1
            return DECISION_DOWN
        return DECISION_HOLD

    def debug_state(self) -> dict:
        return {
            "config": dataclasses.asdict(self.config),
            "high_streak": self._high_streak,
            "low_streak": self._low_streak,
            "cooling": self._cooling(self._clock()),
            "decisions": dict(self.decisions),
            "last_signals": self.last_signals,
        }
