"""Route keys + the router: documents and pushed series → one owner.

The partition unit is the APPLICATION, not the document or the series:
``doc_route_key`` is the document's app name (every doc of a service
lands on one worker — its fit cache, arena rows and ring series stay
together), and ``series_route_key`` extracts the same identity from a
pushed series' canonical selector via the routing label (default
``app``, `FOREMAST_MESH_ROUTE_LABEL`). A series that carries the label
therefore hashes to the SAME member as the documents that query it —
that is what makes the receiver's redirect hint converge pushers onto
the worker whose ring actually feeds those documents' fetches.

Series without the routing label (opaque expressions, alias-form
pushes) fall back to hashing the whole canonical key: still a single
well-defined home every worker agrees on, just not guaranteed to be
co-resident with a document — such fetches degrade to the existing
cold-miss fallback path, never to wrong answers.

`MeshRouter` owns the member→ring cache: `refresh()` re-lists
membership at most every `refresh_seconds` (or on demand) and swaps in
a new `HashRing` only when the live-member set actually changed, so
the per-claim `owns_doc` filter is a dict peek + one blake2b hash.
"""

from __future__ import annotations

import logging
import re
import threading
import time

from foremast_tpu.ingest.wire import canonical_series
from foremast_tpu.mesh.membership import (
    CLAIM_STATES,
    TARGET_STATES,
    MemberRecord,
    Membership,
)
from foremast_tpu.mesh.partition import HashRing

log = logging.getLogger("foremast_tpu.mesh")

DEFAULT_ROUTE_LABEL = "app"
DEFAULT_REPLICAS = 64
DEFAULT_REFRESH_SECONDS = 2.0

# label extraction from a CANONICAL selector (label values are escaped
# and sorted by wire.canonical_series / series_key, so a plain scan for
# `label="value"` is exact, not heuristic)
_LABEL_RE_CACHE: dict[str, re.Pattern] = {}


def _label_re(label: str) -> re.Pattern:
    pat = _LABEL_RE_CACHE.get(label)
    if pat is None:
        pat = re.compile(
            r'[{,]\s*%s="((?:[^"\\]|\\.)*)"' % re.escape(label)
        )
        _LABEL_RE_CACHE[label] = pat
    return pat


def doc_route_key(doc) -> str:
    """A document's partition identity: its app (all of a service's
    docs co-locate), falling back to the id for app-less docs."""
    return doc.app_name or doc.id


def series_route_key(key: str, route_label: str = DEFAULT_ROUTE_LABEL) -> str:
    """A series' partition identity: the routing label's value when the
    canonical selector carries it, else the whole key."""
    canon = canonical_series(key)
    m = _label_re(route_label).search(canon)
    if m:
        return m.group(1)
    return canon


class MeshRouter:
    """Membership-backed ownership oracle. Thread-safe: the receiver's
    handler threads and the worker's tick thread both consult it.

    Two rings since ISSUE 11 (planned elasticity):

      * the CLAIM ring (states active + draining) answers "who judges
        this document RIGHT NOW" — a draining member keeps judging its
        partition until it leaves, a joining member is fenced out;
      * the TARGET ring (states active + joining) answers "who owns
        this key once the in-flight planned change completes" — it
        routes redirect hints (pushers converge onto the new owner
        DURING the transfer window) and picks handoff destinations.

    A fleet with no planned change in flight has identical rings, and
    every pre-states code path keeps its exact behavior."""

    def __init__(
        self,
        membership: Membership,
        replicas: int = DEFAULT_REPLICAS,
        route_label: str = DEFAULT_ROUTE_LABEL,
        refresh_seconds: float = DEFAULT_REFRESH_SECONDS,
        clock=time.time,
    ):
        self.membership = membership
        self.replicas = int(replicas)
        self.route_label = route_label
        self.refresh_seconds = float(refresh_seconds)
        self._clock = clock
        self._lock = threading.Lock()
        self._ring = HashRing((), replicas=self.replicas)
        self._target_ring = HashRing((), replicas=self.replicas)
        self._members: dict[str, MemberRecord] = {}
        self._last_refresh = 0.0
        # rebalances = ring swaps after the first build; redirect_hints /
        # foreign_series are receiver traffic (mesh/node.py exports them)
        self.counters = {
            "rebalances": 0,
            "redirect_hints": 0,
            "foreign_series": 0,
        }

    @property
    def self_id(self) -> str:
        return self.membership.worker_id

    def refresh(self, force: bool = False) -> bool:
        """Re-list membership (rate-limited) and swap the rings when the
        live set (or any member's state/capacity) changed. Returns True
        on a membership change."""
        now = self._clock()
        with self._lock:
            if not force and now - self._last_refresh < self.refresh_seconds:
                return False
            self._last_refresh = now
            first = not self._members
        members = {m.worker_id: m for m in self.membership.live_members(now)}
        with self._lock:
            if set(members) == set(self._members) and all(
                members[k].capacity == self._members[k].capacity
                and members[k].state == self._members[k].state
                for k in members
            ):
                self._members = members  # refreshed addresses/leases
                return False
            old = set(self._members)
            self._members = members
            self._ring = HashRing(
                {
                    m.worker_id: m.capacity
                    for m in members.values()
                    if m.state in CLAIM_STATES
                },
                replicas=self.replicas,
            )
            self._target_ring = HashRing(
                {
                    m.worker_id: m.capacity
                    for m in members.values()
                    if m.state in TARGET_STATES
                },
                replicas=self.replicas,
            )
        if not first:
            # counters are monotonic telemetry, deliberately unguarded
            # (single-writer per counter key in practice; drift under a
            # race is bounded and harmless). Keep it consistent: the
            # thread-escape rule treats an attribute as lock-guarded the
            # moment ONE mutation site takes a lock — if you ever guard
            # one of these bumps, guard all of them or `make check`
            # fails the stragglers.
            self.counters["rebalances"] += 1
            log.info(
                "mesh rebalance: members %s -> %s",
                sorted(old), sorted(members),
            )
        return True

    def members(self) -> list[MemberRecord]:
        with self._lock:
            return sorted(
                self._members.values(), key=lambda m: m.worker_id
            )

    def member(self, worker_id: str) -> MemberRecord | None:
        with self._lock:
            return self._members.get(worker_id)

    # -- ownership ------------------------------------------------------

    def owner_of_doc(self, doc) -> str | None:
        with self._lock:
            return self._ring.owner(doc_route_key(doc))

    def owns_doc(self, doc) -> bool:
        # a worker alone on the ring (or with membership unreadable)
        # owns everything — a degraded mesh must degrade to the
        # single-worker behavior, never to an unclaimable fleet
        with self._lock:
            ring = self._ring
        if len(ring) == 0:
            return True
        return ring.owns(doc_route_key(doc), self.self_id)

    def owner_of_series(self, key: str) -> str | None:
        with self._lock:
            return self._ring.owner(
                series_route_key(key, self.route_label)
            )

    def owns_series(self, key: str) -> bool:
        with self._lock:
            ring = self._ring
        if len(ring) == 0:
            return True
        return ring.owns(
            series_route_key(key, self.route_label), self.self_id
        )

    def retains_series(self, key: str) -> bool:
        """Whether this member's ring shard should KEEP a resident
        series: owned under the claim ring (serving it now) OR under
        the target ring (about to own it — a just-transferred series
        must survive the eviction pass that runs while the planned
        change is still in flight). With no change in flight the rings
        agree and this is exactly `owns_series`."""
        rk = series_route_key(key, self.route_label)
        with self._lock:
            claim, target = self._ring, self._target_ring
        if len(claim) == 0 and len(target) == 0:
            return True
        return (len(claim) == 0 or claim.owns(rk, self.self_id)) or (
            len(target) > 0 and target.owns(rk, self.self_id)
        )

    def target_owner_of_route(self, route_key: str) -> str | None:
        """The TARGET-ring owner of a route key (an app, or a whole
        canonical series key for label-less series)."""
        with self._lock:
            return self._target_ring.owner(route_key)

    def transfer_target(self, route_key: str) -> str | None:
        """Where a planned change moves this route key: the target-ring
        owner, IFF this member owns the key on the claim ring right now
        and the target ring hands it to someone else. None = the key is
        not this member's to move (or is not moving)."""
        with self._lock:
            claim, target = self._ring, self._target_ring
        if len(claim) == 0 or len(target) == 0:
            return None
        if not claim.owns(route_key, self.self_id):
            return None
        owner = target.owner(route_key)
        return None if owner in (None, self.self_id) else owner

    def redirect_hint(self, key: str) -> str | None:
        """The owning member's advertised ingest address for a series
        this worker does NOT own (None when owned, owner unknown, or
        the owner advertises no receiver). Ownership here is the
        TARGET ring: during a planned join/drain the pushers should
        converge onto the post-change owner while the transfer is
        still in flight, so the new owner's ring is fresh the moment
        it starts claiming. Counts receiver traffic."""
        with self._lock:
            ring = self._target_ring
            if len(ring) == 0:
                ring = self._ring  # degenerate: every member draining
        if len(ring) == 0:
            return None
        owner = ring.owner(series_route_key(key, self.route_label))
        if owner is None or owner == self.self_id:
            return None
        self.counters["foreign_series"] += 1
        rec = self.member(owner)
        if rec is None or not rec.ingest_address:
            return None
        self.counters["redirect_hints"] += 1
        return rec.ingest_address


DEFAULT_PUSH_RETRIES = 2
DEFAULT_PUSH_BACKOFF_SECONDS = 0.2
DEFAULT_PUSH_BUFFER_BYTES = 4 * 1024 * 1024


class _PushRejected(Exception):
    """The receiver ANSWERED with an error status (400 malformed, 413
    over the body cap): a permanent verdict on this batch, never a
    retry/buffer candidate (see RoutingPusher._post_with_retry)."""

    def __init__(self, code: int):
        super().__init__(f"push rejected with HTTP {code}")
        self.code = code


class RoutingPusher:
    """A mesh-aware push client (tests, benchmarks, sidecar pushers).

    Pushes every series to its cached route (any seed address until a
    hint arrives) and learns from the `redirects` map in each receiver
    response — by the next cycle every series lands directly on its
    owner, the 'converge within one push cycle' contract the receiver's
    accept-and-hint behavior is designed for.

    Receiver-restart degradation (ISSUE 7 satellite, the client half of
    the receiver contract in docs/operations.md "Ingest plane"): a
    failed POST retries with jittered exponential backoff (`retries`
    attempts past the first — a worker's restart window is seconds, a
    blind drop would cost exactly the samples the snapshot plane exists
    to keep); past the retry budget the batch is BUFFERED and re-sent
    at the front of the next cycle, up to `buffer_bytes` — beyond it
    the OLDEST buffered series drop, counted on
    ``counters["dropped_series"]``, because an unbounded buffer against
    a receiver that never comes back is just a slower OOM.

    Learned routes survive ONE failed cycle per address (ISSUE 11
    satellite): a single transient failure at a freshly-hinted receiver
    — exactly what a just-joined member under a pusher thundering herd
    looks like — must not throw the hint away and bounce the series
    back through a seed. Only `FORGET_AFTER_FAILURES` consecutive
    failed cycles on the same address mark it dead: routes still
    pointing at it are forgotten (address-scoped — a route re-learned
    onto another member meanwhile is never clobbered) and, when the
    dead address was the current fallback seed, the fallback ROTATES to
    the next seed — after a planned scale-down the departed member's
    address may BE a seed, and pinning the fallback to ``addresses[0]``
    forever would blackhole re-convergence.
    """

    # consecutive failed cycles on one address before its routes are
    # forgotten and the fallback seed rotates past it
    FORGET_AFTER_FAILURES = 2

    def __init__(
        self,
        addresses: list[str],
        timeout: float = 10.0,
        retries: int = DEFAULT_PUSH_RETRIES,
        backoff_seconds: float = DEFAULT_PUSH_BACKOFF_SECONDS,
        buffer_bytes: int = DEFAULT_PUSH_BUFFER_BYTES,
        sleep=time.sleep,
        rng=None,
        chaos=None,
    ):
        if not addresses:
            raise ValueError("RoutingPusher needs at least one address")
        self.addresses = list(addresses)
        self.timeout = timeout
        # chaos.EdgeChaos seam (ISSUE 9) at the POST choke point;
        # injected faults are OSErrors, so they exercise exactly the
        # retry-then-buffer degradation a real receiver outage would
        self.chaos = chaos
        self.retries = max(0, int(retries))
        self.backoff_seconds = float(backoff_seconds)
        self.buffer_bytes = int(buffer_bytes)
        self._sleep = sleep
        import random

        self._rng = rng or random.Random()
        self._route: dict[str, str] = {}  # series key -> "host:port"
        # routeless series fall back to addresses[_seed_idx % n]; the
        # index rotates past seeds observed dead (see class docstring)
        self._seed_idx = 0
        # address -> consecutive failed cycles (reset on any success)
        self._addr_fails: dict[str, int] = {}
        # (approx bytes, key, entry) pending re-send, oldest first
        self._buffer: list[tuple[int, str, dict]] = []
        self._buffer_nbytes = 0
        self.counters = {
            "retries": 0,
            "buffered_series": 0,
            "resent_series": 0,
            "dropped_series": 0,
            "rejected_series": 0,
        }

    def _post(self, address: str, entries: list[dict]) -> dict:
        import json as _json
        import urllib.request

        from foremast_tpu.ingest.receiver import WRITE_PATH

        if self.chaos is not None:
            self.chaos.perturb(address)
        req = urllib.request.Request(
            f"http://{address}{WRITE_PATH}",
            data=_json.dumps({"timeseries": entries}).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return _json.loads(resp.read())

    def _post_with_retry(self, address: str, entries: list[dict]) -> dict | None:
        """POST with jittered exponential backoff; None past the retry
        budget (the caller buffers). Jitter keeps a fleet of pushers
        retrying a restarted receiver from re-arriving in lockstep.

        TRANSPORT failures (connection refused, reset, timeout — the
        restart window) and TRANSIENT statuses (429, 5xx — a proxy
        answering for a pod that is down, an overloaded receiver; the
        same classification PrometheusSource retries) retry and then
        buffer. A hard 4xx is the receiver's permanent VERDICT on this
        batch (400 malformed, 413 over the cap — HTTPError is an
        OSError subclass, so it must be separated explicitly):
        retrying it would burn the backoff budget, and buffering it
        would merge the poisoned batch into every later cycle's POST
        until the byte cap silently dropped healthy series along with
        it. Rejected batches are dropped and counted on
        ``counters["rejected_series"]``."""
        import urllib.error

        for attempt in range(self.retries + 1):
            try:
                return self._post(address, entries)
            except urllib.error.HTTPError as e:
                code = e.code
                e.close()
                if code < 500 and code != 429:
                    self.counters["rejected_series"] += len(entries)
                    raise _PushRejected(code) from None
            except OSError:
                pass
            if attempt == self.retries:
                return None
            self.counters["retries"] += 1
            delay = self.backoff_seconds * (2.0**attempt)
            self._sleep(delay * (0.5 + self._rng.random()))
        return None

    def _buffer_failed(self, keyed: list[tuple[str, dict]]) -> None:
        """Keep a failed batch for the next cycle, newest-wins under
        the byte cap: drop-OLDEST past it (the staleness cutoff would
        reject ancient samples anyway; recent ones are the warm-fetch
        window the restart recovery needs)."""
        import json as _json

        for key, entry in keyed:
            nbytes = len(_json.dumps(entry))
            self._buffer.append((nbytes, key, entry))
            self._buffer_nbytes += nbytes
            self.counters["buffered_series"] += 1
        while self._buffer and self._buffer_nbytes > self.buffer_bytes:
            old_bytes, _, _ = self._buffer.pop(0)
            self._buffer_nbytes -= old_bytes
            self.counters["dropped_series"] += 1

    @property
    def buffered(self) -> int:
        return len(self._buffer)

    def push_cycle(
        self, series: list[tuple[str, list, list, float | None]]
    ) -> dict:
        """One cycle: re-send any buffered backlog first, group by
        learned route, POST (with retry), learn hints. `series` entries
        are (key, times, values, start|None); returns {"accepted",
        "redirects", "errors", "buffered", "dropped", "by_address"}."""
        by_addr: dict[str, list[tuple[str, dict]]] = {}
        fallback = self.addresses[self._seed_idx % len(self.addresses)]
        backlog, self._buffer, self._buffer_nbytes = self._buffer, [], 0
        self.counters["resent_series"] += len(backlog)
        for _, key, entry in backlog:
            addr = self._route.get(key, fallback)
            by_addr.setdefault(addr, []).append((key, entry))
        for key, ts, vs, start in series:
            entry = {
                "alias": key,
                "times": list(ts),
                "values": [float(v) for v in vs],
            }
            if start is not None:
                entry["start"] = float(start)
            addr = self._route.get(key, fallback)
            by_addr.setdefault(addr, []).append((key, entry))
        accepted = 0
        redirected = 0
        errors = 0
        rejected = 0
        for addr, keyed in by_addr.items():
            try:
                body = self._post_with_retry(addr, [e for _, e in keyed])
            except _PushRejected:
                # the receiver answered and said no (malformed batch,
                # body over the cap): dropping is the only non-poisoning
                # option — buffering would re-merge the rejected batch
                # into every later cycle
                errors += 1
                rejected += len(keyed)
                continue
            if body is None:
                errors += 1
                strikes = self._addr_fails.get(addr, 0) + 1
                if len(self._addr_fails) > 256:
                    self._addr_fails.clear()  # crude bound; repopulates
                self._addr_fails[addr] = strikes
                if strikes >= self.FORGET_AFTER_FAILURES:
                    # persistently dead (not a one-cycle restart and
                    # not a just-joined member shedding one burst):
                    # forget routes STILL pointing at it — a route a
                    # new member's hint re-learned meanwhile must not
                    # be clobbered on its way out the door
                    for key, _ in keyed:
                        if self._route.get(key) == addr:
                            self._route.pop(key, None)
                    if addr == fallback:
                        # a dead fallback seed (a drained member) must
                        # not absorb re-convergence traffic forever
                        self._seed_idx += 1
                self._buffer_failed(keyed)
                continue
            self._addr_fails.pop(addr, None)
            accepted += int(body.get("accepted_samples", 0))
            for key, owner_addr in (body.get("redirects") or {}).items():
                self._route[key] = owner_addr
                redirected += 1
        return {
            "accepted": accepted,
            "redirects": redirected,
            "errors": errors,
            "buffered": len(self._buffer),
            "rejected": rejected,
            "dropped": self.counters["dropped_series"],
            "by_address": {a: len(e) for a, e in by_addr.items()},
        }
