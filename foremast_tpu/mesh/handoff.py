"""Planned state handoff: rebalance as a TRANSFER, not a refit.

The PR-6 mesh survives unplanned death — stuck-claim takeover plus a
cold refit of every inherited partition. ISSUE 11 makes the PLANNED
membership changes (scale-up, drain, rolling restarts an operator
announces) move state instead of reconstructing it: the current owner
of every route key a change is about to move streams the affected ring
series and fit-cache entries directly to the new owner, over the same
crc-framed record format the PR-7 snapshot plane uses, applied through
the receiver's production push path — so budget accounting, coverage
semantics, last-write-wins merge and (when mounted) the durability
journal all hold for transferred state exactly as for pushed state.

Protocol (the lifecycle states live in mesh/membership.py, the two
rings in mesh/routing.py):

  * **scale-up** — the joiner registers with state ``joining``: its
    lease counts and its record advertises the transfer endpoint, but
    it is FENCED from the claim ring. Every active member's next tick
    notices it, streams it the keys the target ring moves to it, and
    finishes with a ``done`` marker. When the joiner has a ``done``
    from every active member (or `deadline_seconds` passes — a torn or
    blackholed transfer must degrade to the PR-6 cold-refit path,
    never park the joiner forever), it flips ``active``; the claim
    ring now includes it and its first claims judge from transferred
    state: zero fallback fetches, zero cold refits.
  * **drain** — the leaver flips to ``draining``: it KEEPS claiming
    and judging its partition (no verdict is lost or delayed), while
    receivers hint pushers at the post-drain owners and the drainer
    streams its ring series + fits to them; then it leaves. Survivors
    take over a partition whose state is already resident.

Degradation (chaos edge ``transfer``): every POST runs through the
chaos seam + a per-edge circuit breaker; transport failures retry with
jittered backoff, a hard 4xx (version mismatch) is a permanent verdict
on the transfer, and a transfer given up on is COUNTED and abandoned —
the receiving side simply cold-refits whatever never arrived, through
exactly the rebalance path that existed before this module. Torn
streams keep their healthy prefix per record (PR-7 semantics); every
record kind is idempotent (ring pushes merge last-write-wins, fit puts
overwrite equal state, ``done`` markers are a set), so a duplicated
delivery replays clean.
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import threading
import time

import numpy as np

from foremast_tpu.ingest.receiver import TRANSFER_PATH
from foremast_tpu.ingest.snapshot import append_record, read_record_stream
from foremast_tpu.mesh.membership import (
    STATE_ACTIVE,
    STATE_JOINING,
    MemberRecord,
)
from foremast_tpu.mesh.routing import series_route_key

log = logging.getLogger("foremast_tpu.mesh")

HANDOFF_VERSION = 1

DEFAULT_DEADLINE_SECONDS = 30.0
DEFAULT_BATCH_BYTES = 1 << 20  # well under the receiver's 8 MiB body cap
DEFAULT_RETRIES = 2
DEFAULT_BACKOFF_SECONDS = 0.2
DEFAULT_TIMEOUT_SECONDS = 10.0

# transfer outcome label values (foremast_handoff_transfers{role,result})
SEND_RESULTS = ("ok", "failed", "rejected")
RECEIVE_RESULTS = ("ok", "rejected", "torn", "duplicate")


def fit_route_key(name: str, key, value) -> str | None:
    """The mesh route key (app) a fit-cache entry belongs to, per cache
    (the key shapes are the worker's: jobs/worker.py + engine). None =
    no recognizable partition identity; the entry stays put and the new
    owner cold-refits it — a degradation, never a wrong answer."""
    try:
        if name == "fits":  # (algo, season, "app|alias|url")
            return key[2].split("|", 1)[0] or None
        if name == "gaps":  # "app|alias|url"
            return key.split("|", 1)[0] or None
        if name == "joint":  # (mode, app, ...)
            return key[1] or None
        if name == "jmeta":  # ("jmeta", mode, app, ...)
            return key[2] or None
        if name == "refine":
            # ("uni", (algo, season, "app|alias|url")) | ("joint", doc)
            if key[0] == "uni":
                return key[1][2].split("|", 1)[0] or None
            return (value or {}).get("app") or None
    except (TypeError, IndexError, KeyError, AttributeError):
        return None
    return None


class HandoffManager:
    """One worker's handoff plane: sender, receiver, and the joining /
    draining bookkeeping. Thread-safe — the receiver's handler threads
    apply inbound transfers while the tick thread streams outbound
    ones."""

    def __init__(
        self,
        ring_store=None,  # ingest.shards.RingStore (optional)
        route_label: str = "app",
        deadline_seconds: float | None = None,
        batch_bytes: int = DEFAULT_BATCH_BYTES,
        retries: int = DEFAULT_RETRIES,
        backoff_seconds: float = DEFAULT_BACKOFF_SECONDS,
        timeout: float = DEFAULT_TIMEOUT_SECONDS,
        chaos=None,  # chaos.EdgeChaos for the "transfer" edge
        breaker=None,  # chaos.CircuitBreaker for the "transfer" edge
        clock=time.time,
        sleep=time.sleep,
        rng=None,
    ):
        if deadline_seconds is None:
            deadline_seconds = float(
                os.environ.get("FOREMAST_HANDOFF_DEADLINE_SECONDS", "")
                or DEFAULT_DEADLINE_SECONDS
            )
        self.ring_store = ring_store
        self.route_label = route_label
        self.deadline_seconds = float(deadline_seconds)
        self.batch_bytes = int(batch_bytes)
        self.retries = max(0, int(retries))
        self.backoff_seconds = float(backoff_seconds)
        self.timeout = float(timeout)
        self.chaos = chaos
        self.breaker = breaker
        self._clock = clock
        self._sleep = sleep
        import random

        self._rng = rng or random.Random()
        # registered fit caches (name -> ModelCache/RefineBook); the
        # worker attaches its own set (BrainWorker.attach_handoff)
        self.fit_caches: dict[str, object] = {}
        self._lock = threading.Lock()
        self.counters = {
            "series_sent": 0,
            "series_received": 0,
            "fits_sent": 0,
            "fits_received": 0,
            "send": dict.fromkeys(SEND_RESULTS, 0),
            "receive": dict.fromkeys(RECEIVE_RESULTS, 0),
        }
        # receiver side: series keys applied by a transfer, protected
        # from evict_unowned until the claim ring catches up with the
        # target ring (TTL-bounded so an abandoned change cannot pin
        # foreign state forever)
        self._protected: dict[str, float] = {}
        # joiner side: sender ids whose `done` marker arrived, and the
        # member set we are waiting on
        self._done_from: set[str] = set()
        self._join_expected: set[str] | None = None
        self._join_deadline: float | None = None
        self._join_started: float | None = None
        self.join_wait_seconds: float | None = None
        # sender side: joiner ids this member already streamed to (a
        # failed send still marks served — the joiner's deadline owns
        # the degradation, a per-tick retry against a blackholed
        # receiver would wedge every tick behind the transfer timeout)
        self._served: set[str] = set()
        # membership fingerprint at the last note_members: when the set
        # MOVES under an in-flight join (a second joiner appearing
        # reshapes the first one's target share), served joiners are
        # re-streamed — duplicate delivery is idempotent, a silently
        # missing delta is a cold refit
        self._members_fp: tuple | None = None

    # -- cache registration ---------------------------------------------

    def register_caches(self, caches: dict) -> None:
        """Attach the fit caches the sender enumerates and the receiver
        applies into. Duck-typed: `persistable_snapshot()` to read,
        `put_many(items)` (or `restore_lazy(items)`) to write."""
        self.fit_caches = dict(caches)

    # -- counters ---------------------------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    def _count_result(self, role: str, result: str, n: int = 1) -> None:
        with self._lock:
            self.counters[role][result] += n

    # -- eviction protection ----------------------------------------------

    def protect(self, keys) -> None:
        deadline = self._clock() + 2.0 * self.deadline_seconds
        with self._lock:
            for k in keys:
                self._protected[k] = deadline

    def is_protected(self, key: str) -> bool:
        with self._lock:
            dl = self._protected.get(key)
            if dl is None:
                return False
            if self._clock() > dl:
                del self._protected[key]
                return False
            return True

    def purge_protected(self, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            dead = [k for k, dl in self._protected.items() if now > dl]
            for k in dead:
                del self._protected[k]

    # -- join fencing ------------------------------------------------------

    def begin_join(self, expected_senders: set[str]) -> None:
        """Arm the joiner: activation waits for a `done` from every
        member in `expected_senders` (the active set at join time), or
        for the deadline — whichever comes first."""
        now = self._clock()
        with self._lock:
            self._join_expected = set(expected_senders)
            self._join_started = now
            self._join_deadline = now + self.deadline_seconds

    def join_ready(self, live_active_ids: set[str]) -> bool:
        """Whether the fenced joiner may activate: every EXPECTED sender
        that is still alive and active has sent its `done` (a sender
        that died or left mid-join is discounted — waiting on a ghost
        would turn its crash into our deadlock), or the deadline passed
        (torn/blackholed transfers degrade to cold refits)."""
        now = self._clock()
        with self._lock:
            if self._join_expected is None:
                return True
            pending = (self._join_expected & live_active_ids) - self._done_from
            if not pending:
                self.join_wait_seconds = now - (self._join_started or now)
                self._join_expected = None
                return True
            if self._join_deadline is not None and now >= self._join_deadline:
                log.warning(
                    "handoff join deadline (%.1fs) passed with %d "
                    "sender(s) pending (%s); activating anyway — "
                    "missing state cold-refits through the normal "
                    "rebalance path",
                    self.deadline_seconds, len(pending), sorted(pending),
                )
                self.join_wait_seconds = now - (self._join_started or now)
                self._join_expected = None
                return True
            return False

    def join_pending(self) -> bool:
        with self._lock:
            return self._join_expected is not None

    # -- sender side -------------------------------------------------------

    def note_members(self, members: list[MemberRecord]) -> None:
        """Prune sender/receiver bookkeeping against the live view: a
        joiner that activated (or vanished) can be served again if it
        ever re-joins — and when the member SET moves while a join is
        still in flight (a second joiner appearing reshapes the first
        one's target-ring share), already-served joiners are re-queued
        for a fresh full stream: every record kind is idempotent, so a
        duplicated delivery replays clean while a missing delta would
        cold-refit."""
        joining = {
            m.worker_id for m in members if m.state == STATE_JOINING
        }
        fingerprint = tuple(
            sorted((m.worker_id, m.state) for m in members)
        )
        with self._lock:
            self._served &= joining
            if fingerprint != self._members_fp:
                self._members_fp = fingerprint
                self._served.clear()

    def pending_joiners(
        self, members: list[MemberRecord], self_id: str
    ) -> list[MemberRecord]:
        with self._lock:
            served = set(self._served)
        return [
            m
            for m in members
            if m.state == STATE_JOINING
            and m.worker_id != self_id
            and m.worker_id not in served
            and m.ingest_address
        ]

    def mark_served(self, worker_id: str) -> None:
        with self._lock:
            self._served.add(worker_id)

    def _moving_records(self, router, target_ids: set):
        """Yield ``(target_id, record)`` for every transfer record this
        member should stream to any target in `target_ids`: resident
        ring series first (consistent column copies via the snapshot
        read path), then fit-cache entries. One pass regardless of how
        many targets — a drain with N survivors must not copy the full
        resident state N times. Ownership: claim-owned here,
        target-owned there."""
        ring = self.ring_store
        if ring is not None:
            for i in range(ring.shard_count):
                for key, t, v, cf, ct, older in ring.shard_state(i):
                    rk = series_route_key(key, self.route_label)
                    tid = router.transfer_target(rk)
                    if tid not in target_ids:
                        continue
                    spans = [list(iv) for iv in older]
                    if cf is not None or ct is not None:
                        spans.append([cf, ct])
                    yield tid, ("series", key, t, v, spans)
        for name, cache in self.fit_caches.items():
            snap = getattr(cache, "persistable_snapshot", None)
            if snap is None:
                continue
            for key, value in snap().items():
                rk = fit_route_key(name, key, value)
                if rk is None:
                    continue
                tid = router.transfer_target(rk)
                if tid not in target_ids:
                    continue
                yield tid, ("fit", name, key, value)

    def _post(self, address: str, body: bytes) -> None:
        """One framed batch over the wire — the single choke point the
        chaos plane and the breaker guard (edge ``transfer``)."""
        import urllib.request

        if self.breaker is not None:
            self.breaker.allow()  # raises BreakerOpen — fail fast
        try:
            if self.chaos is not None:
                self.chaos.perturb(address)
            req = urllib.request.Request(
                f"http://{address}{TRANSFER_PATH}",
                data=body,
                method="POST",
                headers={"Content-Type": "application/octet-stream"},
            )
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
        except Exception:
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if self.breaker is not None:
            self.breaker.record_success()

    def _post_with_retry(self, address: str, body: bytes) -> str:
        """POST with jittered exponential backoff on transient failures
        (transport errors, 429/5xx); a hard 4xx is the receiver's
        permanent verdict (version mismatch) — no retry. Returns the
        transfer outcome: ``"ok"`` (landed), ``"rejected"`` (counted
        HERE — the caller must not count it again as failed), or
        ``"failed"`` (retries exhausted; the caller counts it)."""
        import urllib.error

        from foremast_tpu.chaos.degrade import is_transient_error

        for attempt in range(self.retries + 1):
            try:
                self._post(address, body)
                return "ok"
            except urllib.error.HTTPError as e:
                code = e.code
                e.close()
                if code < 500 and code != 429:
                    self._count_result("send", "rejected")
                    log.warning(
                        "handoff transfer to %s rejected (HTTP %d); "
                        "abandoning — the receiver cold-refits",
                        address, code,
                    )
                    return "rejected"
            except Exception as e:  # noqa: BLE001 — classified below
                if not is_transient_error(e):
                    raise
            if attempt == self.retries:
                return "failed"
            delay = self.backoff_seconds * (2.0**attempt)
            self._sleep(delay * (0.5 + self._rng.random()))
        return "failed"

    def send_to(self, record: MemberRecord, router, self_id: str) -> bool:
        """Stream everything the planned change moves from this member
        to `record`'s transfer endpoint, in bounded batches, ending
        with a ``done`` marker. Returns True when every batch landed;
        False degrades to the receiver cold-refitting (counted)."""
        return self.send_all([record], router, self_id)[record.worker_id]

    def send_all(
        self, records: list[MemberRecord], router, self_id: str
    ) -> dict[str, bool]:
        """Stream everything the planned change moves from this member
        to EVERY target in `records`, enumerating the resident ring +
        fit caches ONCE (a drain with N survivors must not take N full
        consistent copies of the shard state on the shutdown path) and
        bucketing records by their target-ring owner. Each target gets
        bounded batches ending with its own ``done`` marker; a target
        whose batch fails stops receiving (its outcome is final) while
        the others keep streaming. Returns per-target landed flags —
        False degrades to that receiver cold-refitting (counted)."""

        def frame(buf, rec) -> None:
            append_record(
                buf, pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            )

        streams = {}
        for record in records:
            buf = io.BytesIO()
            frame(buf, ("hello", HANDOFF_VERSION, self_id))
            streams[record.worker_id] = {
                "record": record, "buf": buf,
                "series": 0, "fits": 0, "outcome": None,
            }

        def flush(s) -> None:
            body = s["buf"].getvalue()
            s["buf"] = io.BytesIO()
            frame(s["buf"], ("hello", HANDOFF_VERSION, self_id))
            result = self._post_with_retry(
                s["record"].ingest_address, body
            )
            if result != "ok":
                s["outcome"] = result

        for tid, rec in self._moving_records(router, set(streams)):
            s = streams[tid]
            if s["outcome"] is not None:  # this target already failed
                continue
            s["series" if rec[0] == "series" else "fits"] += 1
            frame(s["buf"], rec)
            if s["buf"].tell() >= self.batch_bytes:
                flush(s)
        for tid, s in streams.items():
            if s["outcome"] is None:
                frame(
                    s["buf"], ("done", self_id, s["series"], s["fits"])
                )
                flush(s)
            if s["outcome"] is None:
                s["outcome"] = "ok"
                self._count("series_sent", s["series"])
                self._count("fits_sent", s["fits"])
                self._count_result("send", "ok")
                log.info(
                    "handoff: streamed %d series / %d fit(s) to %s (%s)",
                    s["series"], s["fits"], tid,
                    s["record"].ingest_address,
                )
            elif s["outcome"] == "failed":
                # a rejected batch was counted + logged at the POST —
                # only the retries-exhausted path is counted here
                self._count_result("send", "failed")
                log.warning(
                    "handoff transfer to %s (%s) failed after retries; "
                    "abandoned — %s cold-refits the moved partition "
                    "through the PR-6 rebalance path",
                    tid, s["record"].ingest_address, tid,
                )
        return {tid: s["outcome"] == "ok" for tid, s in streams.items()}

    # -- receiver side -----------------------------------------------------

    def apply_transfer(self, raw: bytes) -> tuple[int, dict]:
        """Apply one framed transfer batch (the receiver's
        ``/api/v1/transfer`` body). Returns (http_status, body).
        Damage degrades PER RECORD: a torn tail keeps the applied
        prefix (samples merge last-write-wins, fits overwrite — a
        duplicated delivery replays clean), a version-mismatched hello
        rejects the whole batch (400 — the sender's build must not
        guess at our format), and whatever never applies cold-refits."""
        stream = read_record_stream(io.BytesIO(raw))
        n_series = 0
        n_fits = 0
        torn = False
        sender = None
        done = False
        fit_batches: dict[str, list] = {}
        protected: list[str] = []
        first = True
        for payload, reason in stream:
            if reason is not None:
                torn = True
                break
            try:
                rec = pickle.loads(payload)
                kind = rec[0]
                if first:
                    if kind != "hello" or int(rec[1]) != HANDOFF_VERSION:
                        self._count_result("receive", "rejected")
                        log.warning(
                            "handoff transfer rejected: %s",
                            "missing hello frame"
                            if kind != "hello"
                            else f"version {rec[1]} (want {HANDOFF_VERSION})",
                        )
                        return 400, {
                            "reason": "handoff version mismatch",
                            "want": HANDOFF_VERSION,
                        }
                    sender = str(rec[2])
                    first = False
                    continue
                if kind == "series":
                    _, key, t, v, spans = rec
                    self._apply_series(key, t, v, spans)
                    protected.append(key)
                    n_series += 1
                elif kind == "fit":
                    _, name, fkey, value = rec
                    fit_batches.setdefault(name, []).append((fkey, value))
                    n_fits += 1
                elif kind == "done":
                    sender = str(rec[1])
                    done = True
                elif kind == "hello":
                    pass  # a retried batch re-announcing itself
            except Exception as e:  # noqa: BLE001 — one bad record
                torn = True
                log.warning(
                    "handoff transfer: undecodable record (%s); keeping "
                    "the applied prefix", e,
                )
                break
        if first:
            # no intact hello frame decoded — empty body, unframed
            # garbage, or torn inside the very first record: nothing in
            # the batch was trusted, so the sender gets the permanent
            # 400 verdict (no retry burn) instead of a torn-prefix 200
            self._count_result("receive", "rejected")
            return 400, {"reason": "missing hello frame"}
        for name, items in fit_batches.items():
            self._apply_fits(name, items)
        if protected:
            self.protect(protected)
        duplicate = False
        if done and sender is not None:
            with self._lock:
                duplicate = sender in self._done_from
                self._done_from.add(sender)
        self._count("series_received", n_series)
        self._count("fits_received", n_fits)
        self._count_result(
            "receive",
            "torn" if torn else ("duplicate" if duplicate else "ok"),
        )
        if torn:
            log.warning(
                "handoff transfer torn mid-stream: applied %d series / "
                "%d fit(s), the rest cold-refits", n_series, n_fits,
            )
        return 200, {
            "applied_series": n_series,
            "applied_fits": n_fits,
            "torn": torn,
            "done": done,
        }

    def _apply_series(self, key: str, t, v, spans) -> None:
        """Replay one transferred series through the production push
        path — older authoritative spans first as empty backfills, then
        the columns under the head span (mirrors snapshot restore, so a
        restored and a transferred ring are bit-for-bit the same
        machinery)."""
        ring = self.ring_store
        if ring is None:
            return
        t = np.asarray(t, np.int64)
        v = np.asarray(v, np.float32)
        spans = list(spans or ())
        head = spans[-1] if spans else (None, None)
        for iv in spans[:-1]:
            try:
                f0, f1 = float(iv[0]), float(iv[1])
            except (TypeError, ValueError, IndexError):
                continue
            ring.push(key, (), (), start=f0, end=f1, record_lag=False)
        cf = None if head[0] is None else float(head[0])
        ct = None if head[1] is None else float(head[1])
        ring.push(key, t, v, start=cf, end=ct, record_lag=False)

    def _apply_fits(self, name: str, items: list) -> None:
        cache = self.fit_caches.get(name)
        if cache is None:
            return
        put_many = getattr(cache, "put_many", None)
        if put_many is not None:
            put_many(items)
            return
        restore = getattr(cache, "restore_lazy", None)
        if restore is not None:
            restore(dict(items))

    # -- observability -----------------------------------------------------

    def counters_snapshot(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["send"] = dict(self.counters["send"])
            out["receive"] = dict(self.counters["receive"])
            return out

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "series_sent": self.counters["series_sent"],
                "series_received": self.counters["series_received"],
                "fits_sent": self.counters["fits_sent"],
                "fits_received": self.counters["fits_received"],
                "send": dict(self.counters["send"]),
                "receive": dict(self.counters["receive"]),
                "join_pending": self._join_expected is not None,
                "join_wait_seconds": (
                    round(self.join_wait_seconds, 3)
                    if self.join_wait_seconds is not None
                    else None
                ),
                "done_from": sorted(self._done_from),
                "protected_series": len(self._protected),
                "deadline_seconds": self.deadline_seconds,
            }
