"""Worker mesh: consistent-hash fleet partitioning across N workers.

Four planes (docs/operations.md "Worker mesh"):

  * membership — heartbeat documents in the job store
    (`mesh/membership.py`): join/renew/leave, dead peers detected by
    lease expiry;
  * partitioning — a consistent-hash ring over live members
    (`mesh/partition.py`) assigns every document's route key one
    owner; the worker's claim loop claims only its partition
    (claim-CAS stays the double-judgment safety net);
  * routed ingest — each worker runs its own receiver + ring shard;
    pushes for series a worker does not own are accepted AND answered
    with the owner's advertised address (`mesh/routing.py`), so
    pushers converge within one push cycle;
  * rebalance — UNPLANNED: a dead member's lease expires, the ring
    heals with minimal movement, orphaned claims age out through the
    existing stuck-claim CAS takeover, and newly-owned cold series
    backfill through the fallback path. PLANNED (`mesh/handoff.py`):
    a joining or draining member's state is STREAMED to the new
    owners — lifecycle states `joining`/`draining` fence claims while
    the transfer is in flight, so a scale event costs zero fallback
    fetches and zero cold refits instead of a fleet-wide refit wall;
  * autoscaling — `mesh/autoscale.py` turns the exported saturation
    signals (tick occupancy, write-queue peak, ring budget pressure)
    into hysteretic join/leave decisions.
"""

from foremast_tpu.mesh.autoscale import AutoscaleConfig, AutoscaleDriver
from foremast_tpu.mesh.handoff import HandoffManager
from foremast_tpu.mesh.membership import (
    CLAIM_STATES,
    MEMBER_STATES,
    MESH_APP,
    STATE_ACTIVE,
    STATE_DRAINING,
    STATE_JOINING,
    STATUS_MESH_LEFT,
    STATUS_MESH_MEMBER,
    TARGET_STATES,
    MemberRecord,
    Membership,
    live_members,
    member_doc_id,
)
from foremast_tpu.mesh.node import MeshCollector, MeshNode
from foremast_tpu.mesh.partition import HashRing
from foremast_tpu.mesh.routing import (
    MeshRouter,
    RoutingPusher,
    doc_route_key,
    series_route_key,
)

__all__ = [
    "CLAIM_STATES",
    "MEMBER_STATES",
    "MESH_APP",
    "STATE_ACTIVE",
    "STATE_DRAINING",
    "STATE_JOINING",
    "STATUS_MESH_LEFT",
    "STATUS_MESH_MEMBER",
    "TARGET_STATES",
    "AutoscaleConfig",
    "AutoscaleDriver",
    "HandoffManager",
    "HashRing",
    "MemberRecord",
    "Membership",
    "MeshCollector",
    "MeshNode",
    "MeshRouter",
    "RoutingPusher",
    "doc_route_key",
    "live_members",
    "member_doc_id",
    "series_route_key",
]
