"""Consistent-hash partitioning: which member owns which route key.

The mesh shards the fleet by hashing *route keys* (mesh/routing.py —
app names for documents, the routing label for series) onto a ring of
virtual nodes, `replicas` per member (the `DataParallelPartitioner` /
named-sharding shape from SNIPPETS.md [2]/[3], applied to documents
instead of array rows). Properties the rest of the mesh stands on:

  * deterministic across processes — the hash is blake2b, never
    Python's randomized `hash()`, so every worker (and the store-side
    claim filter in the scale-out bench) computes the SAME owner for
    the same (members, key) pair;
  * minimal movement — when a member dies, only the keys it owned move
    (to their next clockwise survivor); everyone else's partition is
    untouched, so a rebalance re-fits only the orphaned documents;
  * weightable — a member's `capacity` multiplies its replica count,
    so a half-sized worker owns roughly half a share.

No locking here: a `HashRing` is immutable after construction; the
router swaps whole rings on membership change.
"""

from __future__ import annotations

import bisect
import hashlib


def _point(data: str) -> int:
    """64-bit ring coordinate; blake2b so placement is identical in
    every process regardless of PYTHONHASHSEED."""
    return int.from_bytes(
        hashlib.blake2b(data.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Immutable consistent-hash ring over member ids."""

    def __init__(
        self,
        members: dict[str, int] | list[str] | tuple[str, ...],
        replicas: int = 64,
    ):
        """`members` is either a list of ids (capacity 1 each) or an
        id -> capacity map; `replicas` virtual nodes per unit capacity."""
        if not isinstance(members, dict):
            members = {m: 1 for m in members}
        self.replicas = max(1, int(replicas))
        self.members = tuple(sorted(members))
        points: list[tuple[int, str]] = []
        for member, capacity in members.items():
            n = self.replicas * max(1, int(capacity))
            for i in range(n):
                points.append((_point(f"{member}#{i}"), member))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [m for _, m in points]

    def __len__(self) -> int:
        return len(self.members)

    def owner(self, key: str) -> str | None:
        """The member owning `key` (first virtual node clockwise), or
        None on an empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, _point(key))
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def owns(self, key: str, member: str) -> bool:
        return self.owner(key) == member
