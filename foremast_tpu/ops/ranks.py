"""Masked, batched rank statistics on TPU.

The reference brain's pairwise baseline-vs-current same-distribution tests:
Mann-Whitney U, Wilcoxon signed-rank, Kruskal-Wallis, and the two-group
Friedman chi-square special case (all four named in reference
`docs/guides/design.md:90-93`), selectable/combinable via
`ML_PAIRWISE_ALGORITHM` = ALL | ANY | MANN_WHITE | WILCOXON | KRUSKAL |
FRIEDMAN (`foremast-brain/README.md:34`), each gated on a minimum number
of points (`deploy/foremast/3_brain/foremast-brain.yaml:74-79`).

TPU-first design (SURVEY.md section 7 "hard parts" (a)): ranking under masks
without host round-trips. Pairwise windows are short (the 10-minute analysis
window at 60 s step is ~10-40 points), so tie-averaged ranks are computed
from O(N^2) comparison matrices — pure VPU-friendly broadcasting, fully
batched over [B], no sorting, no gather/scatter:

    rank_i = (# valid j with x_j < x_i) + (1 + # valid j with x_j == x_i) / 2

Tie corrections come for free: sum over elements of (t_i^2 - 1), where t_i is
the size of element i's tie group, equals sum over groups of (t^3 - t).

Each test returns (stat, p, ok): `ok` is False where the min-points gate
fails; callers must treat gated-out tests as inconclusive (p forced to 1.0,
i.e. "no evidence of distribution change"), matching the reference's
behavior of skipping tests below their data-point minimums.

p-values use the normal / chi-squared asymptotic approximations (golden-
tested against scipy's `method="asymptotic"` paths).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import erfc, gammaincc

_BIG = jnp.float32(3.0e38)


def _normal_sf(z):
    return 0.5 * erfc(z / jnp.sqrt(jnp.asarray(2.0, z.dtype)))


def _chi2_sf(x, df):
    """Survival function of chi^2 with `df` dof via the regularized upper
    incomplete gamma function Q(df/2, x/2)."""
    return gammaincc(df / 2.0, x / 2.0)


def masked_ranks(values: jax.Array, mask: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Tie-averaged ranks among valid entries.

    values: [B, N]; mask: [B, N].
    Returns (ranks [B, N] — 0.0 at invalid positions, ranks 1..n at valid
    ones; tie_term [B] — sum over tie groups of (t^3 - t), for variance
    corrections).
    """
    x = jnp.where(mask, values, _BIG)  # park invalid entries far away
    xi = x[..., :, None]  # [B, N, 1]
    xj = x[..., None, :]  # [B, 1, N]
    validj = mask[..., None, :]
    less = ((xj < xi) & validj).astype(values.dtype)
    equal = ((xj == xi) & validj).astype(values.dtype)
    cnt_less = jnp.sum(less, axis=-1)  # [B, N]
    cnt_eq = jnp.sum(equal, axis=-1)  # includes self
    ranks = jnp.where(mask, cnt_less + (cnt_eq + 1.0) * 0.5, 0.0)
    # sum_i (t_i^2 - 1) over valid i == sum_groups (t^3 - t)
    tie_term = jnp.sum(jnp.where(mask, cnt_eq * cnt_eq - 1.0, 0.0), axis=-1)
    return ranks, tie_term


def _two_sample_rank_stats(
    x: jax.Array, x_mask: jax.Array, y: jax.Array, y_mask: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Union-rank ingredients of the two-sample tests without ranking
    the union: (r1 [B], tie [B], nx [B], ny [B]).

    r1 is the tie-averaged rank sum of x among concat(x, y); `tie` the
    union's sum over tie groups of (t^3 - t). Computing them through
    `masked_ranks` on the concatenation builds [B, Nx+Ny, Nx+Ny]
    comparison blocks; this helper exploits the two-sample structure —
    rank_x(i) = #(x_j < x_i) + #(y_j < x_i) + (ties + 1)/2 — so
    only [B, Nx, Ny]-shaped blocks materialize: ~40% fewer compares,
    and the narrower blocks fuse far better (measured 5x on the fleet
    warm program, CPU host; still pure VPU-friendly broadcasting — no
    sort, no gather — per this module's TPU-first design). BIT-IDENTICAL
    to the concat path: every count is an exact small integer, and the
    rank/tie sums are multiples of 0.5 whose partial sums stay far
    below 2^23, so f32 addition is exact in any order (pinned by the
    golden tests and tests/test_ranks_property.py).
    """
    dt = x.dtype
    xs = jnp.where(x_mask, x, _BIG)  # park invalid entries far away
    ys = jnp.where(y_mask, y, _BIG)
    xi = xs[..., :, None]  # [B, Nx, 1]
    yj = ys[..., None, :]  # [B, 1, Ny]
    vy = y_mask[..., None, :]
    xy_less = (yj < xi) & vy  # [B, Nx, Ny]
    xy_eq = (yj == xi) & vy  # parked x_i never equals a valid y_j
    xj = xs[..., None, :]
    vx = x_mask[..., None, :]
    xx_less = (xj < xi) & vx  # [B, Nx, Nx]
    xx_eq = (xj == xi) & vx  # includes self
    yy_eq = (ys[..., None, :] == ys[..., :, None]) & y_mask[..., None, :]
    lxy = jnp.sum(xy_less, axis=-1, dtype=jnp.int32).astype(dt)
    exy = jnp.sum(xy_eq, axis=-1, dtype=jnp.int32).astype(dt)
    # the SAME xy_eq block read down its other axis: x's equal to y_j
    eyx = jnp.sum(xy_eq, axis=-2, dtype=jnp.int32).astype(dt)
    lxx = jnp.sum(xx_less, axis=-1, dtype=jnp.int32).astype(dt)
    exx = jnp.sum(xx_eq, axis=-1, dtype=jnp.int32).astype(dt)
    eyy = jnp.sum(yy_eq, axis=-1, dtype=jnp.int32).astype(dt)
    rank_x = lxx + lxy + (exx + exy + 1.0) * 0.5
    r1 = jnp.sum(jnp.where(x_mask, rank_x, 0.0), axis=-1)
    # union tie term: sum over valid union elements of (cnt_eq^2 - 1)
    tie = jnp.sum(
        jnp.where(x_mask, (exx + exy) ** 2 - 1.0, 0.0), axis=-1
    ) + jnp.sum(jnp.where(y_mask, (eyy + eyx) ** 2 - 1.0, 0.0), axis=-1)
    nx = jnp.sum(x_mask, axis=-1).astype(dt)
    ny = jnp.sum(y_mask, axis=-1).astype(dt)
    return r1, tie, nx, ny


def mann_whitney_u(
    x: jax.Array,
    x_mask: jax.Array,
    y: jax.Array,
    y_mask: jax.Array,
    min_points: int = 20,
    use_continuity: bool = True,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched two-sided Mann-Whitney U (normal approximation, tie-corrected).

    x: [B, Nx] current window, y: [B, Ny] baseline window, with masks.
    Returns (U1 [B], p [B], ok [B]). Parity target:
    scipy.stats.mannwhitneyu(method="asymptotic", use_continuity=True).
    Gate: both samples need >= min_points valid points
    (`MIN_MANN_WHITE_DATA_POINTS=20`, `foremast-brain.yaml:74-75`).
    """
    dtype = x.dtype
    r1, tie, nx, ny = _two_sample_rank_stats(x, x_mask, y, y_mask)
    n = nx + ny
    u1 = r1 - nx * (nx + 1.0) / 2.0
    mean = nx * ny / 2.0
    tie_frac = tie / jnp.maximum(n * (n - 1.0), 1.0)
    var = nx * ny / 12.0 * ((n + 1.0) - tie_frac)
    sd = jnp.sqrt(jnp.maximum(var, 0.0))
    cc = jnp.asarray(0.5 if use_continuity else 0.0, dtype)
    z = (jnp.abs(u1 - mean) - cc) / jnp.maximum(sd, 1e-30)
    z = jnp.maximum(z, 0.0)
    p = jnp.clip(2.0 * _normal_sf(z), 0.0, 1.0)
    ok = (nx >= min_points) & (ny >= min_points) & (sd > 0)
    p = jnp.where(ok, p, 1.0)
    return u1, p, ok


def wilcoxon_signed_rank(
    x: jax.Array,
    x_mask: jax.Array,
    y: jax.Array,
    y_mask: jax.Array,
    min_points: int = 20,
    correction: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched two-sided Wilcoxon signed-rank test (paired; normal approx).

    Pairs position-wise (both masks valid); zero differences are dropped
    (scipy zero_method="wilcox"). Returns (W+ [B], p [B], ok [B]). Parity:
    scipy.stats.wilcoxon(zero_method="wilcox", correction=False,
    mode="approx"). Gate: `MIN_WILCOXON_DATA_POINTS=20`
    (`foremast-brain.yaml:76-77`).
    """
    dtype = x.dtype
    d = x - y
    pair_mask = x_mask & y_mask
    nz_mask = pair_mask & (d != 0.0)
    ranks, tie = masked_ranks(jnp.abs(d), nz_mask)
    n = jnp.sum(nz_mask, axis=-1).astype(dtype)
    w_plus = jnp.sum(jnp.where(nz_mask & (d > 0), ranks, 0.0), axis=-1)
    mean = n * (n + 1.0) / 4.0
    var = n * (n + 1.0) * (2.0 * n + 1.0) / 24.0 - tie / 48.0
    sd = jnp.sqrt(jnp.maximum(var, 0.0))
    diff = w_plus - mean
    cc = jnp.asarray(0.5 if correction else 0.0, dtype)
    z = (jnp.abs(diff) - cc) / jnp.maximum(sd, 1e-30)
    p = jnp.clip(2.0 * _normal_sf(z), 0.0, 1.0)
    ok = (jnp.sum(pair_mask, axis=-1) >= min_points) & (n > 0) & (sd > 0)
    p = jnp.where(ok, p, 1.0)
    return w_plus, p, ok


def friedman_chi_square(
    x: jax.Array,
    x_mask: jax.Array,
    y: jax.Array,
    y_mask: jax.Array,
    min_points: int = 20,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched two-group paired Friedman chi-square — the reference's
    "Fried manchi square (special case)" (`docs/guides/design.md:90-93`):
    the fourth and last named pairwise algorithm.

    Blocks are position-wise (baseline, current) pairs; ranks within each
    block are 1/2 (1.5/1.5 on a within-pair tie); the column rank sums
    feed the standard Friedman statistic with k=2 treatments:

        chi2_F = [12 / (n k (k+1))] (R1^2 + R2^2) - 3 n (k+1),  k = 2,

    divided by the tie correction C = 1 - sum(t^3 - t) / [n k (k^2-1)]
    = 1 - ties/n (each tied block contributes t=2 -> 6), then referred to
    chi^2 with k-1 = 1 dof. With no within-pair ties this reduces
    algebraically to the sign-test form (n_plus - n_minus)^2 / n. scipy's
    public `friedmanchisquare` refuses k < 3, so the golden test
    replicates its exact formula (per-block `rankdata` + chi2.sf) at k=2.

    Pairs position-wise like Wilcoxon, but uses only the SIGN of each
    difference — insensitive to magnitude outliers a single spike injects.
    Returns (chi2 [B], p [B], ok [B]). Gate: `MIN_FRIEDMAN_DATA_POINTS`
    valid pairs, and at least one untied pair (C > 0).
    """
    dtype = x.dtype
    pair = x_mask & y_mask
    n = jnp.sum(pair, axis=-1).astype(dtype)
    n_plus = jnp.sum(pair & (x > y), axis=-1).astype(dtype)
    n_minus = jnp.sum(pair & (x < y), axis=-1).astype(dtype)
    ties = jnp.sum(pair & (x == y), axis=-1).astype(dtype)
    # column rank sums: winner ranks 2, loser 1, tie 1.5 each
    r1 = 2.0 * n_plus + n_minus + 1.5 * ties  # x column
    r2 = 2.0 * n_minus + n_plus + 1.5 * ties
    n_safe = jnp.maximum(n, 1.0)
    stat = 2.0 / n_safe * (r1 * r1 + r2 * r2) - 9.0 * n
    c = 1.0 - ties / n_safe
    stat = jnp.maximum(stat / jnp.maximum(c, 1e-30), 0.0)
    p = jnp.clip(_chi2_sf(stat, jnp.asarray(1.0, dtype)), 0.0, 1.0)
    ok = (n >= min_points) & (c > 0)
    p = jnp.where(ok, p, 1.0)
    return stat, p, ok


def kruskal_wallis(
    x: jax.Array,
    x_mask: jax.Array,
    y: jax.Array,
    y_mask: jax.Array,
    min_points: int = 5,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched Kruskal-Wallis H test for two groups (chi^2 approximation).

    Returns (H [B], p [B], ok [B]). Parity: scipy.stats.kruskal.
    Gate: `MIN_KRUSKAL_DATA_POINTS=5` (`foremast-brain.yaml:78-79`).

    Shares `_two_sample_rank_stats` with Mann-Whitney (one set of
    comparison blocks serves both tests inside a fused program); y's
    rank sum comes from the exact identity r1 + r2 = n(n+1)/2 — the
    tie-averaged ranks of the union always sum to that constant, and
    both sides are multiples of 0.5 far below f32's exact-integer
    range, so the subtraction is bit-identical to summing y's ranks.
    """
    dtype = x.dtype
    r1, tie, nx, ny = _two_sample_rank_stats(x, x_mask, y, y_mask)
    n = nx + ny
    r2 = n * (n + 1.0) * 0.5 - r1
    h = 12.0 / jnp.maximum(n * (n + 1.0), 1.0) * (
        r1 * r1 / jnp.maximum(nx, 1.0) + r2 * r2 / jnp.maximum(ny, 1.0)
    ) - 3.0 * (n + 1.0)
    tie_corr = 1.0 - tie / jnp.maximum(n * n * n - n, 1.0)
    # float32 rounding can leave H at a tiny negative for identical samples;
    # gammaincc(df/2, h/2) NaNs on negative input, so clamp at 0 (p=1)
    h = jnp.maximum(h / jnp.maximum(tie_corr, 1e-30), 0.0)
    p = jnp.clip(_chi2_sf(h, jnp.asarray(1.0, dtype)), 0.0, 1.0)
    ok = (nx >= min_points) & (ny >= min_points) & (tie_corr > 0)
    p = jnp.where(ok, p, 1.0)
    return h, p, ok
