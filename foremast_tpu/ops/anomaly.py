"""Bound computation and anomaly flagging.

Mirrors the reference brain's threshold semantics: a global
`threshold=2.0` / `min_lower_bound=0` / `bound=1` plus a per-metric-type
override matrix (error5xx t=2 b=1, error4xx t=3 b=1, latency t=10 b=3,
cpu t=5 b=1, memory t=5 b=1) — reference
`deploy/foremast/3_brain/foremast-brain.yaml:26-73`. The bound selector
chooses which side(s) of the forecast band flag anomalies
(`ML_BOUND` upper/lower/both, `foremast-brain/README.md:24`).

All functions are batched: thresholds/bounds/min_lower_bounds may be
scalars or per-window [B] arrays (the per-metric-type table turns into a
gathered [B] vector — config table lookups happen host-side once, outside
jit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

BOUND_UPPER = 1
BOUND_LOWER = 2
BOUND_BOTH = 3


def compute_bounds(
    pred: jax.Array,
    scale: jax.Array,
    threshold: jax.Array | float,
    min_lower_bound: jax.Array | float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Upper/lower anomaly bounds around predictions.

    pred: [B, T] predicted values; scale: [B] residual std;
    threshold: scalar or [B] multiplier. Lower bound is floored at
    min_lower_bound (reference `min_lower_bound=0`,
    `foremast-brain.yaml:28-29` — metric rates cannot go negative).
    Returns (upper [B, T], lower [B, T]).
    """
    threshold = jnp.asarray(threshold, pred.dtype)
    mlb = jnp.asarray(min_lower_bound, pred.dtype)
    if threshold.ndim == 1:
        threshold = threshold[:, None]
    if mlb.ndim == 1:
        mlb = mlb[:, None]
    band = threshold * jnp.expand_dims(scale, -1)
    upper = pred + band
    lower = jnp.maximum(pred - band, mlb)
    return upper, lower


def detect_anomalies(
    current: jax.Array,
    cur_mask: jax.Array,
    upper: jax.Array,
    lower: jax.Array,
    bound: jax.Array | int = BOUND_UPPER,
) -> jax.Array:
    """Flag current points outside the band per the bound selector.

    current/cur_mask/upper/lower: [B, T]; bound: scalar or [B] int
    (1=upper, 2=lower, 3=both). Returns bool [B, T].
    """
    bound = jnp.asarray(bound, jnp.int32)
    if bound.ndim == 1:
        bound = bound[:, None]
    over = current > upper
    under = current < lower
    use_upper = (bound == BOUND_UPPER) | (bound == BOUND_BOTH)
    use_lower = (bound == BOUND_LOWER) | (bound == BOUND_BOTH)
    return cur_mask & ((over & use_upper) | (under & use_lower))
