"""Pure-JAX batched ops over masked metric windows.

Everything here is jit-friendly: fixed shapes, validity masks instead of
ragged windows, `lax.scan`/`lax.associative_scan` instead of Python loops.
"""

from foremast_tpu.ops.windows import MetricWindows, masked_mean, masked_std, masked_var
from foremast_tpu.ops.forecasters import (
    Forecast,
    moving_average_all,
    moving_average,
    ewma,
    double_exponential,
    holt_winters,
    fit_auto_univariate,
    fit_holt_winters,
    fit_phase_means,
)
from foremast_tpu.ops.ranks import (
    masked_ranks,
    mann_whitney_u,
    wilcoxon_signed_rank,
    kruskal_wallis,
    friedman_chi_square,
)
from foremast_tpu.ops.anomaly import (
    BOUND_UPPER,
    BOUND_LOWER,
    BOUND_BOTH,
    compute_bounds,
    detect_anomalies,
)

__all__ = [
    "MetricWindows",
    "masked_mean",
    "masked_moments",
    "masked_std",
    "masked_var",
    "Forecast",
    "moving_average_all",
    "moving_average",
    "ewma",
    "double_exponential",
    "holt_winters",
    "fit_auto_univariate",
    "fit_holt_winters",
    "fit_phase_means",
    "masked_ranks",
    "mann_whitney_u",
    "wilcoxon_signed_rank",
    "kruskal_wallis",
    "friedman_chi_square",
    "BOUND_UPPER",
    "BOUND_LOWER",
    "BOUND_BOTH",
    "compute_bounds",
    "detect_anomalies",
]
