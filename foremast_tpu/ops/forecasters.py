"""Batched masked time-series forecasters.

The reference brain's model zoo (reference `docs/guides/design.md:57-93`):
moving average, exponential smoothing (EWMA), double exponential smoothing,
Holt-Winters (+ Prophet, approximated separately in models/seasonal.py).
Deployed default algorithm is `moving_average_all`
(`deploy/foremast/3_brain/foremast-brain.yaml:24-25`).

TPU-first design notes:
  * every forecaster is batched over a leading [B] axis and jit-friendly;
  * ragged history is handled by validity masks, never by dynamic shapes;
  * EWMA is a linear recurrence, so it runs as `lax.associative_scan`
    (log-depth on the VPU, and shardable along time for sequence
    parallelism — see parallel/seqparallel.py);
  * Holt / Holt-Winters run as `lax.scan` with the whole batch inside the
    carry, so XLA emits one fused loop over time for all series at once;
  * smoothing parameters are *fit* by a vectorized grid search (vmap over
    the grid), not per-series Python loops.

All forecasters return a `Forecast` carrying in-sample one-step-ahead
predictions (for residual scale), a residual scale, and terminal state
(level/trend/season) from which `horizon` extrapolates future bounds.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from foremast_tpu.ops.windows import masked_mean, masked_moments, masked_std


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Forecast:
    """Fitted forecaster state for a batch of series.

    pred:   [B, T] one-step-ahead in-sample predictions
    scale:  [B]    residual standard deviation (deviation unit for bounds)
    level:  [B]    terminal level
    trend:  [B]    terminal per-step trend (0 for trendless models)
    season: [B, m] terminal seasonal offsets (m=1 zeros when non-seasonal)
    season_phase: [B] int32 — season index of the *next* (first forecast) step
    """

    pred: jax.Array
    scale: jax.Array
    level: jax.Array
    trend: jax.Array
    season: jax.Array
    season_phase: jax.Array


def _finalize(
    pred, values, mask, level, trend, season=None, season_phase=None, scale=None
):
    if scale is None:
        resid = values - pred
        scale = masked_std(resid, mask, ddof=0)
    b = values.shape[0]
    if season is None:
        season = jnp.zeros((b, 1), dtype=values.dtype)
        season_phase = jnp.zeros((b,), dtype=jnp.int32)
    return Forecast(
        pred=pred,
        scale=scale,
        level=level,
        trend=trend,
        season=season,
        season_phase=season_phase,
    )


def horizon(fc: Forecast, h: int) -> jax.Array:
    """Extrapolate h future points from terminal state -> [B, h]."""
    steps = jnp.arange(1, h + 1, dtype=fc.level.dtype)  # [h]
    base = fc.level[:, None] + fc.trend[:, None] * steps[None, :]
    m = fc.season.shape[-1]
    idx = (fc.season_phase[:, None] + jnp.arange(h)[None, :]) % m  # [B,h]
    seas = jnp.take_along_axis(fc.season, idx, axis=-1)
    return base + seas


# ---------------------------------------------------------------------------
# Moving averages
# ---------------------------------------------------------------------------


def moving_average_all(values: jax.Array, mask: jax.Array) -> Forecast:
    """Global-mean model over the whole masked history.

    This is the reference's deployed default `moving_average_all`
    (`foremast-brain.yaml:24-25`): the "model" is the historical mean, the
    deviation unit is the historical std, and bounds are
    mean +/- threshold * std.

    Uses `masked_moments` — mean and variance in ONE fused reduction over
    the [B, 10k] history (the two-pass mean-then-centered-squares form
    reads the 7-day window twice, and this model is pure HBM bandwidth;
    headline note in BENCHMARKS.md).
    """
    b, t_len = values.shape
    if t_len == 0:  # empty-history batch: unmeasurable, not a crash
        zeros = jnp.zeros((b,), values.dtype)
        return _finalize(values, values, mask, level=zeros, trend=zeros, scale=zeros)
    _, mu, var = masked_moments(values, mask)
    scale = jnp.sqrt(var)
    pred = jnp.broadcast_to(mu[:, None], values.shape)
    zeros = jnp.zeros_like(mu)
    return _finalize(pred, values, mask, level=mu, trend=zeros, scale=scale)


def moving_average(values: jax.Array, mask: jax.Array, window: int = 10) -> Forecast:
    """Causal rolling mean of the previous `window` time steps.

    pred[t] = mean of valid points in [t-window, t); falls back to the
    running global mean until enough history accumulates.
    """
    v = values * mask
    m = mask.astype(values.dtype)
    # prefix sums shifted so position t sums strictly-previous samples
    csum_v = jnp.cumsum(v, axis=-1)
    csum_m = jnp.cumsum(m, axis=-1)
    pad = jnp.zeros_like(csum_v[..., :1])
    prev_v = jnp.concatenate([pad, csum_v[..., :-1]], axis=-1)
    prev_m = jnp.concatenate([pad, csum_m[..., :-1]], axis=-1)
    lo_v = jnp.roll(prev_v, window, axis=-1).at[..., :window].set(0.0)
    lo_m = jnp.roll(prev_m, window, axis=-1).at[..., :window].set(0.0)
    win_v = prev_v - lo_v
    win_m = prev_m - lo_m
    run_mean = prev_v / jnp.maximum(prev_m, 1.0)
    pred = jnp.where(win_m > 0, win_v / jnp.maximum(win_m, 1.0), run_mean)
    # first point has no history at all: predict itself (zero residual)
    pred = jnp.where((prev_m == 0), values, pred)
    # terminal level: mean of the last `window` valid points
    last_mask = mask & (csum_m > jnp.maximum(csum_m[..., -1:] - window, 0))
    level = masked_mean(values, last_mask)
    zeros = jnp.zeros_like(level)
    return _finalize(pred, values, mask, level=level, trend=zeros)


# ---------------------------------------------------------------------------
# Exponential smoothing (associative-scan form)
# ---------------------------------------------------------------------------


def _linrec_assoc(elem_a, elem_b):
    """Compose linear recurrence elements l_t = a*l_{t-1} + b."""
    a1, b1 = elem_a
    a2, b2 = elem_b
    return a1 * a2, a2 * b1 + b2


def ewma_levels(values: jax.Array, mask: jax.Array, alpha) -> jax.Array:
    """Exponentially weighted level after each step, [B, T].

    Implemented as `lax.associative_scan` over the linear recurrence
    l_t = (1-a_t) l_{t-1} + a_t x_t — log-depth, and the same composition
    law the sequence-parallel path uses across devices.
    `alpha` may be scalar or [B] (per-series), broadcast over time.
    """
    alpha = jnp.asarray(alpha, dtype=values.dtype)
    if alpha.ndim == 1:
        alpha = alpha[:, None]
    is_first = mask & (jnp.cumsum(mask, axis=-1) == 1)
    a_eff = jnp.where(mask, alpha, 0.0)
    a_eff = jnp.where(is_first, 1.0, a_eff)
    a = 1.0 - a_eff
    b = a_eff * values
    comp_a, comp_b = jax.lax.associative_scan(_linrec_assoc, (a, b), axis=-1)
    return comp_b  # composed-from-start b is the level (l_0 treated as 0)


def ewma(values: jax.Array, mask: jax.Array, alpha: float = 0.3) -> Forecast:
    """EWMA forecaster: pred[t] is the EW level of points before t."""
    levels = ewma_levels(values, mask, alpha)
    # one-step-ahead: prediction at t is the level after t-1; before any
    # history exists, predict the point itself (zero residual)
    shifted = jnp.concatenate([levels[..., :1] * 0, levels[..., :-1]], axis=-1)
    inited_before = (jnp.cumsum(mask, axis=-1) - mask) > 0
    pred = jnp.where(inited_before, shifted, values)
    level = levels[..., -1]
    zeros = jnp.zeros_like(level)
    return _finalize(pred, values, mask, level=level, trend=zeros)


# ---------------------------------------------------------------------------
# Double exponential smoothing (Holt's linear trend)
# ---------------------------------------------------------------------------


def double_exponential(
    values: jax.Array, mask: jax.Array, alpha: float = 0.3, beta: float = 0.1
) -> Forecast:
    """Holt's linear method, batched inside a single `lax.scan` over time.

    Masked steps carry (level, trend) through unchanged. Initialization:
    level <- first valid point, trend <- 0 (updated from data thereafter).
    """
    alpha = jnp.asarray(alpha, dtype=values.dtype)
    beta = jnp.asarray(beta, dtype=values.dtype)
    b = values.shape[0]

    def step(carry, xs):
        level, trend, inited = carry
        x, m = xs
        pred = level + trend
        new_level = alpha * x + (1.0 - alpha) * (level + trend)
        new_trend = beta * (new_level - level) + (1.0 - beta) * trend
        # first valid point: initialize level=x, trend=0
        first = m & ~inited
        upd = m & inited
        level_out = jnp.where(first, x, jnp.where(upd, new_level, level))
        trend_out = jnp.where(first, 0.0, jnp.where(upd, new_trend, trend))
        pred_out = jnp.where(inited, pred, x)  # zero residual pre-init
        return (level_out, trend_out, inited | m), pred_out

    init = (
        jnp.zeros((b,), values.dtype),
        jnp.zeros((b,), values.dtype),
        jnp.zeros((b,), bool),
    )
    (level, trend, _), preds = jax.lax.scan(
        step, init, (values.T, mask.T)
    )  # scan over time with batch inside
    pred = preds.T
    return _finalize(pred, values, mask, level=level, trend=trend)


# ---------------------------------------------------------------------------
# Holt-Winters (additive seasonal)
# ---------------------------------------------------------------------------


# Season lengths up to this are run with all m phase updates unrolled in
# the scan body (fastest small-m shape, measured below); longer seasons
# (daily m=1440 at the reference's 60 s step) take the rolled path whose
# compiled program is O(1) in m — unrolling 1440 phases emits O(T) HLO
# and explodes compile time.
_HW_UNROLL_MAX = 64


def _hw_season_blocked(values, mask, m_len, alpha, beta, gamma, init_level, init_season):
    """Small-m Holt-Winters body: scan over whole seasons, phases unrolled.

    TPU shape choice: the scan iterates over T/m seasons with the m phase
    updates unrolled inside the body, and the seasonal state carried as a
    tuple of m per-phase [B] vectors. Each phase's slot is then a *static*
    index — no one-hot scatter and no [B, m] buffer rewrite per time step,
    which cuts the sequential loop to T/m steps and the per-step memory
    traffic by ~m x versus the naive time-step scan (measured 34.6k ->
    ~80k windows/s on a v5e chip at B=1024, T=2016, m=24, 8-point grid).
    The math per time step is identical to the textbook recurrence.
    (Also measured and rejected on the same config: a matrix-form
    parallelization over phases via precomputed A-powers — chain T/m
    matmul steps — lands at 44-62k; scan unroll=2 at 68-79k; decimated
    grid selection + full-res final per-series pass at 29-55k. The fused
    season body wins because fit time tracks the sequential substep chain
    almost exclusively.)
    """
    b, t_len = values.shape
    # pad the series to whole seasons; padded steps are masked, so state
    # carries through them unchanged and their preds are sliced away
    n_seasons = -(-t_len // m_len)
    t_pad = n_seasons * m_len - t_len
    v = jnp.pad(values, ((0, 0), (0, t_pad))) if t_pad else values
    mk = jnp.pad(mask, ((0, 0), (0, t_pad))) if t_pad else mask
    xs = v.T.reshape(n_seasons, m_len, b)
    ms = mk.T.reshape(n_seasons, m_len, b)

    def season_step(carry, chunk):
        level, trend, season, inited = carry  # season: tuple of m [B] rows
        x_c, m_c = chunk  # [m, B] each
        season = list(season)
        preds = []
        for p in range(m_len):  # unrolled; p is this step's phase
            x, msk = x_c[p], m_c[p]
            s_t = season[p]
            pred = level + trend + s_t
            new_level = alpha * (x - s_t) + (1.0 - alpha) * (level + trend)
            new_trend = beta * (new_level - level) + (1.0 - beta) * trend
            new_s = gamma * (x - new_level) + (1.0 - gamma) * s_t
            upd = msk & inited
            season[p] = jnp.where(upd, new_s, s_t)
            level = jnp.where(upd, new_level, level)
            trend = jnp.where(upd, new_trend, trend)
            preds.append(jnp.where(inited, pred, x))
            inited = inited | msk
        return (level, trend, tuple(season), inited), jnp.stack(preds)

    init = (
        init_level,
        jnp.zeros((b,), values.dtype),
        tuple(init_season[:, p] for p in range(m_len)),
        jnp.zeros((b,), bool),
    )
    (level, trend, season_t, _), preds = jax.lax.scan(season_step, init, (xs, ms))
    pred = preds.reshape(n_seasons * m_len, -1).T[..., :t_len]
    pred = pred.reshape(values.shape)
    season = jnp.stack(season_t, axis=-1)  # [B, m]
    return pred, level, trend, season


def _hw_rolled(values, mask, m_len, alpha, beta, gamma, init_level, init_season):
    """Long-season Holt-Winters body: one scan step per time step with the
    seasonal state as a [m, B] carry indexed by a *dynamic* phase.

    The phase p = t mod m is shared by the whole batch (season indexing is
    by absolute time-step index), so the per-step seasonal access is a
    single dynamic row slice + in-place row write — O(B) traffic per step
    and O(1) HLO in m, which is what makes daily cycles (m=1440,
    `metricsquery.go:43` 60 s step over the 7-day window) compile at all.
    The recurrence is bit-identical to the season-blocked body.
    """
    b, t_len = values.shape
    phases = jnp.arange(t_len, dtype=jnp.int32) % m_len

    def step(carry, xs):
        level, trend, season, inited = carry  # season: [m, B]
        x, msk, p = xs
        s_t = jax.lax.dynamic_slice_in_dim(season, p, 1, axis=0)[0]  # [B]
        pred = level + trend + s_t
        new_level = alpha * (x - s_t) + (1.0 - alpha) * (level + trend)
        new_trend = beta * (new_level - level) + (1.0 - beta) * trend
        new_s = gamma * (x - new_level) + (1.0 - gamma) * s_t
        upd = msk & inited
        row = jnp.where(upd, new_s, s_t)
        season = jax.lax.dynamic_update_slice_in_dim(season, row[None], p, axis=0)
        level = jnp.where(upd, new_level, level)
        trend = jnp.where(upd, new_trend, trend)
        pred = jnp.where(inited, pred, x)
        return (level, trend, season, inited | msk), pred

    init = (
        init_level,
        jnp.zeros((b,), values.dtype),
        init_season.T,  # [m, B]
        jnp.zeros((b,), bool),
    )
    (level, trend, season, _), preds = jax.lax.scan(
        step, init, (values.T, mask.T, phases)
    )
    return preds.T, level, trend, season.T


def holt_winters(
    values: jax.Array,
    mask: jax.Array,
    season_length: int = 24,
    alpha: float = 0.3,
    beta: float = 0.05,
    gamma: float = 0.1,
) -> Forecast:
    """Additive Holt-Winters, batched.

    Season indexing uses the absolute time-step index modulo m (windows are
    regularly sampled — 60 s PromQL step in the reference,
    `metricsquery.go:43` — so gaps keep their phase).

    Two compile shapes for one recurrence: season lengths up to
    `_HW_UNROLL_MAX` scan over whole seasons with the m phase updates
    unrolled (`_hw_season_blocked`); longer seasons — the reference's
    canonical *daily* cycle is m=1440 at the 60 s step — take the rolled
    per-step scan (`_hw_rolled`), whose program size is independent of m.

    `alpha`/`beta`/`gamma` may be scalars or per-series [B] arrays.

    Initialization: level <- mean of the first season's valid points,
    seasonal offsets <- first-season residuals vs that mean.
    """
    m_len = int(season_length)
    b, t_len = values.shape
    dtype = values.dtype
    alpha = jnp.asarray(alpha, dtype)
    beta = jnp.asarray(beta, dtype)
    gamma = jnp.asarray(gamma, dtype)

    first_season_mask = mask & (jnp.arange(t_len)[None, :] < m_len)
    init_level = masked_mean(values, first_season_mask)  # [B]
    # seasonal init: first-season residuals (0 where that slot was invalid)
    pad = m_len - min(m_len, t_len)
    fs_vals = values[:, :m_len]
    fs_mask = first_season_mask[:, :m_len]
    if pad:
        fs_vals = jnp.pad(fs_vals, ((0, 0), (0, pad)))
        fs_mask = jnp.pad(fs_mask, ((0, 0), (0, pad)))
    init_season = jnp.where(fs_mask, fs_vals - init_level[:, None], 0.0)

    body = _hw_season_blocked if m_len <= _HW_UNROLL_MAX else _hw_rolled
    pred, level, trend, season = body(
        values, mask, m_len, alpha, beta, gamma, init_level, init_season
    )
    # horizon continues right after each series' LAST VALID point: phase
    # from the last valid absolute index (consistent with the in-fit
    # "gaps keep their phase" indexing), not the bucket-padded array
    # length — a [B, 288]-valid history packed into a [B, 512] bucket must
    # not shift the seasonal forecast by 512 % m
    last_valid = jnp.max(
        jnp.where(mask, jnp.arange(t_len)[None, :], -1), axis=-1
    )
    phase_next = ((last_valid + 1) % m_len).astype(jnp.int32)
    return _finalize(
        pred, values, mask, level=level, trend=trend, season=season, season_phase=phase_next
    )


def _guard_unidentifiable(fc: Forecast, values, mask, m_len: int) -> Forecast:
    """Per-series 2-cycle identifiability select.

    The static guards in the fit entries key off the (bucket-padded)
    batch length; a series with fewer than two cycles of REAL points can
    ride a long bucket past them and get a memorized noise season. This
    select keeps the global-mean model for exactly those series — the
    dynamic companion to the static early-outs."""
    enough = jnp.sum(mask, axis=-1) >= 2 * m_len  # [B]
    ma = moving_average_all(values, mask)
    ma = Forecast(
        pred=ma.pred,
        scale=ma.scale,
        level=ma.level,
        trend=ma.trend,
        season=jnp.zeros_like(fc.season),
        season_phase=fc.season_phase,
    )

    def sel(a_leaf, b_leaf):
        keep = enough.reshape((-1,) + (1,) * (a_leaf.ndim - 1))
        return jnp.where(keep, a_leaf, b_leaf)

    return jax.tree_util.tree_map(sel, fc, ma)


# auto_univariate: a series must beat the global-mean model's in-sample
# SSE by at least this factor for the structured (Holt-Winters) fit to be
# selected — in-sample SSE alone always favors the flexible model, so the
# margin screens for REAL seasonality/trend instead of soaked-up noise.
AUTO_SSE_RATIO = 0.5


@partial(jax.jit, static_argnames=("season_length",))
def fit_auto_univariate(
    values: jax.Array, mask: jax.Array, season_length: int = 24
) -> Forecast:
    """Structure-screened model selection, per series.

    The deployed default `moving_average_all` is blind to seasonality and
    trend (its band must widen to cover the cycle), while a flexible fit
    on a genuinely flat series merely soaks up noise. This fit runs three
    candidates — the global mean, an ADAPTIVE structured fit, and the
    changepoint-trend+Fourier seasonal model (models/seasonal.py,
    period=m) — and picks per series: a structured model wins only where
    it explains at least half the mean model's variance (AUTO_SSE_RATIO);
    between the two structured fits the lower SSE wins.

    The adaptive candidate depends on the season length: small m (<=
    _HW_UNROLL_MAX) uses the fitted Holt-Winters; LONG cycles (m=1440
    daily at the 60 s step) use the pooled phase-means fit
    (fit_phase_means) — Holt-Winters there would burn a T-step
    sequential scan for (T/m)-sample-noisy per-phase state, while the
    pooled fit is one parallel reduction and carries arbitrary cycle
    shapes. Long seasons also add a phase-SIGNIFICANCE routing gate
    (Bonferroni-corrected z on the pooled phase means): sparse cycle
    features — a cron-style burst 10 sigmas high but <1% of samples —
    cannot move the SSE ratio, yet a phase-blind band false-flags every
    burst occurrence.

    The screen is scored on the *warm* region only (absolute index >= m):
    Holt-Winters' first season has near-zero residuals by construction
    (seasonal state is initialized from those very residuals), which would
    bias an all-points SSE toward HW by a full season's share.

    Histories shorter than two full cycles keep the mean model outright:
    seasonal structure is unidentifiable from <2 periods, and a "fitted"
    cycle there would be pure noise soak-up. One jitted program.
    """
    m_len = int(season_length)
    t_len = values.shape[1]
    ma = moving_average_all(values, mask)
    if t_len < 2 * m_len:  # also the guard inside both structured fits
        return ma
    # import at call time: models.seasonal imports this module at top level
    from foremast_tpu.models.seasonal import fit_seasonal

    # Long seasons swap the adaptive candidate: Holt-Winters needs a
    # T-step sequential scan and its per-phase state is ~(T/m)-sample
    # noisy, while the pooled phase-means fit is one parallel reduction
    # and representation-free (sharp cron-style cycle features included)
    # — see fit_phase_means. Its in-sample SSE is ~(1-m/T) optimistic
    # (each phase mean includes the scored point), comfortably inside
    # the AUTO_SSE_RATIO=0.5 margin that keeps flat series on the mean
    # model.
    if m_len <= _HW_UNROLL_MAX:
        hw = fit_holt_winters(values, mask, m_len)
    else:
        hw = fit_phase_means(values, mask, m_len)
    se = fit_seasonal(values, mask, period=m_len)
    warm = (mask & (jnp.arange(t_len)[None, :] >= m_len)).astype(values.dtype)

    def sse(fc):
        r = (values - fc.pred) * warm
        return jnp.sum(r * r, axis=-1)  # [B]

    sse_ma, sse_hw, sse_se = sse(ma), sse(hw), sse(se)
    use_struct = jnp.minimum(sse_hw, sse_se) < AUTO_SSE_RATIO * sse_ma  # [B]
    prefer_se = sse_se <= sse_hw  # [B]
    if m_len > _HW_UNROLL_MAX:
        # The SSE-ratio gate is blind to SPARSE cycle features: a
        # cron-style burst 10 sigmas high but 10/1440 of the cycle wide
        # moves total SSE by <1%, yet a phase-blind band false-flags
        # every burst occurrence. Under "no structure" a pooled phase
        # mean is ~N(0, sigma^2/k), so a phase whose |mean| * sqrt(k) /
        # sigma clears a Bonferroni-corrected normal quantile (alpha =
        # 1e-3 over m phases; ~4.9 sigmas at m=1440, comfortably above
        # the ~3.8 max-of-1440 null expectation) is real structure —
        # route those series to the phase-means fit regardless of SSE.
        from scipy import stats as _stats  # host-side, static per m

        z_thr = float(_stats.norm.ppf(1.0 - 1e-3 / m_len))
        kcnt = _phase_counts(mask, m_len, values.dtype)  # [B, m]
        z = jnp.abs(hw.season) * jnp.sqrt(jnp.maximum(kcnt, 1.0)) / jnp.maximum(
            hw.scale[:, None], 1e-30
        )
        z_gate = jnp.max(z, axis=-1) > z_thr
        use_struct = use_struct | z_gate
        # A z-gated series carries a sharp phase feature only the
        # phase-means fit can represent — force that candidate even when
        # a level shift hands the Fourier/changepoint fit the lower SSE
        # (min-SSE there would re-create the burst false-flags this gate
        # exists to prevent).
        prefer_se = prefer_se & ~z_gate

    def sel(flag, a_leaf, b_leaf):
        return jnp.where(
            flag.reshape((-1,) + (1,) * (a_leaf.ndim - 1)), a_leaf, b_leaf
        )

    # ma's seasonal buffer is [B, 1] zeros; expand to the structured [B, m]
    # so all three Forecasts share one structure (se/hw phases are both
    # (last_valid + 1) mod m, so the select is phase-consistent)
    ma = Forecast(
        pred=ma.pred,
        scale=ma.scale,
        level=ma.level,
        trend=ma.trend,
        season=jnp.zeros_like(hw.season),
        season_phase=hw.season_phase,
    )
    structured = jax.tree_util.tree_map(partial(sel, prefer_se), se, hw)
    return jax.tree_util.tree_map(partial(sel, use_struct), structured, ma)


def _phase_counts(mask: jax.Array, m_len: int, dtype) -> jax.Array:
    """Valid observations per phase, [B, m] — the k the phase-means fit
    pools over AND the z-gate in the auto screen tests against (one
    definition so the two can never desynchronize)."""
    b, t_len = mask.shape
    n_seasons = -(-t_len // m_len)
    pad = n_seasons * m_len - t_len
    mm = mask.astype(dtype)
    return jnp.sum(
        jnp.pad(mm, ((0, 0), (0, pad))).reshape(b, n_seasons, m_len), axis=1
    )


@partial(jax.jit, static_argnames=("season_length",))
def fit_phase_means(
    values: jax.Array, mask: jax.Array, season_length: int = 1440
) -> Forecast:
    """Pooled per-phase means + linear trend — the long-season workhorse.

    For daily cycles (m=1440 at the 60 s step) the 7-day window holds
    only ~7 observations per phase; Holt-Winters burns a 10,080-step
    sequential scan to produce 7-sample-noisy per-phase state, and a
    low-order Fourier basis cannot represent SHARP cycle features (a
    cron job's minute-wide daily spike). This model is the TPU-native
    answer: detrend with a masked linear fit, then pool each phase's
    residuals across seasons — season[p] = mean of detrended values at
    absolute index ≡ p (mod m). Everything is a parallel reduction
    (reshape to [B, seasons, m], masked mean over the seasons axis) —
    no sequential chain at all — and the cycle shape is unconstrained.

    The residual scale uses leave-one-out corrected residuals: with k
    observations per phase, the in-sample residual against a mean that
    INCLUDES the point shrinks by (k-1)/k, so r_loo = r * k/(k-1) —
    at k=7 an uncorrected band would be ~8% too tight. Points at phases
    observed exactly ONCE carry an identically-zero residual (the phase
    mean IS the point) and are EXCLUDED from the scale reduction — on
    gappy histories they would deflate the band below the true noise.

    Same identifiability rule as every seasonal fit: under two full
    cycles (static batch length or per-series valid count) the series
    keeps the global-mean model.
    """
    m_len = int(season_length)
    b, t_len = values.shape
    dtype = values.dtype
    if t_len < 2 * m_len:
        return moving_average_all(values, mask)

    # Backfit the masked linear trend and the pooled phase means jointly.
    # Time is NOT orthogonal to the phase dummies (the mean time of phase
    # p's occurrences grows linearly in p), so a single detrend-then-pool
    # pass leaves cycle leakage in the slope — on a pure 20-amplitude
    # daily sine the one-shot slope drifts the level by ~2.7 and inflates
    # the band ~2x (round-4 regression find). Alternating the two LS fits
    # contracts that leakage by ~(m/T)^2 per iteration (1/49 at 7 daily
    # cycles), so 3 iterations are exact to float precision; everything
    # stays a parallel reduction. Normalized time keeps the Gram terms
    # TPU bf16-matmul-safe.
    tn = (jnp.arange(t_len, dtype=dtype) / t_len)[None, :]  # [1, T]
    mm = mask.astype(dtype)
    n = jnp.maximum(jnp.sum(mm, axis=-1), 1.0)
    st = jnp.sum(tn * mm, axis=-1)
    stt = jnp.sum(tn * tn * mm, axis=-1)
    denom = stt - st * st / n
    n_seasons = -(-t_len // m_len)
    pad = n_seasons * m_len - t_len
    k = _phase_counts(mask, m_len, dtype)  # [B, m] observations per phase
    phase_idx = jnp.arange(t_len) % m_len
    season = jnp.zeros((b, m_len), dtype)
    for _ in range(3):
        y = values - jnp.take(season, phase_idx, axis=1)
        sx = jnp.sum(y * mm, axis=-1)
        stx = jnp.sum(tn * y * mm, axis=-1)
        slope_n = jnp.where(
            denom > 1e-12, (stx - st * sx / n) / jnp.maximum(denom, 1e-12), 0.0
        )
        intercept = sx / n - slope_n * st / n
        detrended = values - (intercept[:, None] + slope_n[:, None] * tn)
        dv = jnp.pad(detrended * mm, ((0, 0), (0, pad))).reshape(
            b, n_seasons, m_len
        )
        season = jnp.where(k > 0, jnp.sum(dv, axis=1) / jnp.maximum(k, 1.0), 0.0)

    pred = (
        intercept[:, None]
        + slope_n[:, None] * tn
        + jnp.take(season, phase_idx, axis=1)
    )
    # leave-one-out residuals: k/(k-1) per the point's own phase count;
    # k=1 points are zero-information (their residual is exactly 0) and
    # drop out of the scale estimate entirely
    k_at = jnp.take(k, phase_idx, axis=1)  # [B, T]
    loo = k_at / jnp.maximum(k_at - 1.0, 1.0)
    resid = (values - pred) * loo
    scale_mask = mask & (k_at > 1.5)
    scale = masked_std(resid, scale_mask, ddof=0)
    # pathological gap patterns can leave NO multiply-observed phase;
    # an empty scale estimate (0) would mean a zero-width band — fall
    # back to the plain residual std rather than flag everything
    scale = jnp.where(
        jnp.sum(scale_mask, axis=-1) > 0,
        scale,
        masked_std(values - pred, mask, ddof=0),
    )

    last_valid = jnp.max(jnp.where(mask, jnp.arange(t_len)[None, :], -1), axis=-1)
    lv = last_valid.astype(dtype)
    fc = Forecast(
        pred=pred,
        scale=scale,
        level=intercept + slope_n * lv / t_len,
        trend=slope_n / t_len,
        season=season,
        season_phase=((last_valid + 1) % m_len).astype(jnp.int32),
    )
    return _guard_unidentifiable(fc, values, mask, m_len)


def hw_continue(
    fc: Forecast,
    values: jax.Array,
    mask: jax.Array,
    season_length: int = 24,
    alpha: float = 0.3,
    beta: float = 0.05,
    gamma: float = 0.1,
) -> tuple[jax.Array, Forecast]:
    """Continue a fitted Holt-Winters recurrence over new points, causally.

    pred[:, t] is the one-step-ahead forecast made from state updated
    through values[:, :t] — the prediction never sees the point it scores,
    so residuals are contamination-free anomaly evidence (unlike
    autoencoder reconstruction, which can copy an in-window anomaly).
    Starts from `fc`'s terminal (level, trend, season, phase); masked
    steps carry state through but still advance the phase (gaps keep
    their place in the cycle). Returns (pred [B, T], updated Forecast).

    T here is a current window (tens of points), so a plain per-step scan
    is cheap; the heavy 7-day fit stays in `fit_holt_winters`/`holt_winters`.
    """
    m_len = int(season_length)
    b, t_len = values.shape
    dtype = values.dtype
    alpha = jnp.asarray(alpha, dtype)
    beta = jnp.asarray(beta, dtype)
    gamma = jnp.asarray(gamma, dtype)
    season = fc.season
    if season.shape[-1] != m_len:  # non-seasonal fit: zero offsets
        season = jnp.zeros((b, m_len), dtype)

    rows = jnp.arange(b)

    def step(carry, xs):
        level, trend, season, phase = carry
        x, m = xs
        # per-series dynamic phase: one gathered element + one scattered
        # write per step (O(B), not an O(B*m) one-hot — the seasonal
        # buffer is [B, 1440] for daily cycles)
        s_t = jnp.take_along_axis(season, phase[:, None], axis=1)[:, 0]  # [B]
        pred = level + trend + s_t
        new_level = alpha * (x - s_t) + (1.0 - alpha) * (level + trend)
        new_trend = beta * (new_level - level) + (1.0 - beta) * trend
        new_s = gamma * (x - new_level) + (1.0 - gamma) * s_t
        season_out = season.at[rows, phase].set(jnp.where(m, new_s, s_t))
        level_out = jnp.where(m, new_level, level)
        trend_out = jnp.where(m, new_trend, trend)
        return (level_out, trend_out, season_out, (phase + 1) % m_len), pred

    init = (fc.level, fc.trend, season, fc.season_phase)
    (level, trend, season, phase), preds = jax.lax.scan(
        step, init, (values.T, mask.T)
    )
    out = Forecast(
        pred=preds.T,
        scale=fc.scale,
        level=level,
        trend=trend,
        season=season,
        season_phase=phase,
    )
    return preds.T, out


_HW_GRID = (
    (0.1, 0.01, 0.05),
    (0.1, 0.05, 0.1),
    (0.3, 0.05, 0.1),
    (0.3, 0.1, 0.2),
    (0.5, 0.1, 0.1),
    (0.5, 0.05, 0.3),
    (0.7, 0.1, 0.1),
    (0.8, 0.2, 0.2),
)


@partial(jax.jit, static_argnames=("season_length",))
def fit_holt_winters(
    values: jax.Array, mask: jax.Array, season_length: int = 24
) -> Forecast:
    """Per-series fitted Holt-Winters: vectorized grid search over smoothing
    parameters (SURVEY.md section 7 "hard parts" (c)) — the whole grid runs as
    one vmapped program; each series independently picks its SSE-minimizing
    (alpha, beta, gamma).

    Histories shorter than two full seasons are seasonally unidentifiable:
    every grid point memorizes the single partial cycle (the seasonal
    state is initialized from those very residuals, so in-sample SSE ~ 0
    and the fitted band degenerates to ~zero width), while the unfilled
    seasonal slots zero out the horizon. Such SERIES get the global-mean
    model instead — a static early-out when the whole batch is short,
    plus a per-series select (`_guard_unidentifiable`) because bucket
    padding can carry a short real history inside a long batch.
    """
    if values.shape[1] < 2 * int(season_length):
        return moving_average_all(values, mask)
    grid = jnp.asarray(_HW_GRID, dtype=values.dtype)  # [G,3]

    def run(params):
        a, bta, g = params[0], params[1], params[2]
        fc = holt_winters(values, mask, season_length, a, bta, g)
        resid = (values - fc.pred) * mask
        sse = jnp.sum(resid * resid, axis=-1)  # [B]
        return fc, sse

    fcs, sses = jax.vmap(run)(grid)  # Forecast with leading [G], sse [G,B]
    best = jnp.argmin(sses, axis=0)  # [B]

    def pick(leaf):
        # leaf: [G, B, ...] -> [B, ...] selecting per-series best grid point
        moved = jnp.moveaxis(leaf, 0, 1)  # [B, G, ...]
        idx = best.reshape((-1,) + (1,) * (moved.ndim - 1))
        return jnp.take_along_axis(moved, idx, axis=1).squeeze(1)

    fc = jax.tree_util.tree_map(pick, fcs)
    return _guard_unidentifiable(fc, values, mask, int(season_length))
