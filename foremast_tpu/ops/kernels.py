"""Pallas TPU kernels for the scoring hot path.

The engine's hot loop (SURVEY.md §3.2) under the deployed default
algorithm `moving_average_all` (`foremast-brain.yaml:24-25`) is:
one pass over the [B, Th] 7-day history for masked mean/std, then a tiny
[B, Tc] band comparison. The XLA path (`engine/scoring.py`) expresses this
as several fused elementwise/reduce ops; the kernels here collapse the
entire judgment into ONE `pallas_call` so each history block is read from
HBM exactly once and everything downstream (bounds, flags, verdict)
happens on VMEM-resident data — the "native layer" of this framework
(the reference has no native code to port; SURVEY.md §2 maps its role to
XLA/Pallas kernels).

Kernels:
  * `masked_stats`  — count/mean/std of a masked [B, T] batch, one pass
    (sum, sum-of-squares, count accumulated together).
  * `ma_judgment`   — the full moving_average_all judgment: stats ->
    band (threshold * sigma, lower floored at min_lower_bound) ->
    bound-selector flags (1=upper/2=lower/3=both) -> measurability gate
    (min_points) -> verdict codes. Exact-output parity with the XLA path
    is pinned by tests/test_kernels.py.

All wrappers pad B to the sublane tile and T to the 128-lane tile with
masked-out slots (masking is already the framework's ragged-window
idiom), and run in interpreter mode automatically off-TPU so tests and
CPU meshes execute the same code path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Verdict codes — must match engine/scoring.py (HEALTHY/UNHEALTHY/UNKNOWN).
_HEALTHY, _UNHEALTHY, _UNKNOWN = 0, 1, 2

TILE_B = 32  # sublane-aligned batch tile (f32 min 8; 32 amortizes grid)
LANE = 128


def use_pallas() -> bool:
    """Kernel dispatch gate: FOREMAST_PALLAS=1 opts in.

    Default OFF: measured on a v5e chip at the bench.py shapes, XLA's own
    fusion of the scoring program beats this kernel at every batch size
    (B=4096: 379k vs 363k windows/s; B=32768: 1.89M vs 1.26M) — the rank
    tests dominate and the MA-stats pass is memory-bound either way. The
    kernel remains the building block for shapes/fusions XLA handles
    poorly (e.g. much longer histories that blow VMEM-friendly fusion, or
    future multi-stat one-pass variants)."""
    return os.environ.get("FOREMAST_PALLAS", "") == "1"


def _interpret(interpret: bool | None) -> bool:
    return jax.default_backend() != "tpu" if interpret is None else interpret


def _pad_axis(x: jax.Array, mult: int, axis: int, fill) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


def _pad_bt(values: jax.Array, mask: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Pad [B, T] to (TILE_B, LANE) multiples; padding is masked out."""
    v = _pad_axis(_pad_axis(values, LANE, 1, 0.0), TILE_B, 0, 0.0)
    m = _pad_axis(_pad_axis(mask, LANE, 1, False), TILE_B, 0, False)
    return v, m.astype(values.dtype)


def _col(x, b_padded, dtype):
    """[B] (or scalar) parameter -> padded [Bp, 1] column."""
    x = jnp.asarray(x, dtype)
    if x.ndim == 0:
        x = jnp.full((b_padded,), x, dtype)
    else:
        x = _pad_axis(x, TILE_B, 0, 0)
    return x[:, None]


# ---------------------------------------------------------------------------
# masked_stats
# ---------------------------------------------------------------------------


def _stats_kernel(v_ref, m_ref, cnt_ref, mean_ref, std_ref):
    v = v_ref[:]
    m = m_ref[:]
    cnt = jnp.sum(m, axis=-1, keepdims=True)  # [TB, 1]
    c = jnp.maximum(cnt, 1.0)
    mu = jnp.sum(v * m, axis=-1, keepdims=True) / c
    # two-pass variance on the VMEM-resident block: same numerics as
    # windows.masked_std (E[x^2]-E[x]^2 cancels catastrophically when
    # mu >> sigma), and the second pass costs no extra HBM traffic
    d = (v - mu) * m
    var = jnp.sum(d * d, axis=-1, keepdims=True) / c
    cnt_ref[:] = cnt
    mean_ref[:] = mu
    std_ref[:] = jnp.sqrt(var)


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_stats(
    values: jax.Array, mask: jax.Array, interpret: bool | None = None
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One-pass masked (count, mean, std[ddof=0]) over the time axis.

    values [B, T] float32, mask [B, T] bool -> three [B] float32 arrays.
    """
    b = values.shape[0]
    v, m = _pad_bt(values.astype(jnp.float32), mask)
    bp, tp = v.shape
    grid = (bp // TILE_B,)
    row_spec = pl.BlockSpec((TILE_B, tp), lambda i: (i, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((TILE_B, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out = jax.ShapeDtypeStruct((bp, 1), jnp.float32)
    cnt, mean, std = pl.pallas_call(
        _stats_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec],
        out_specs=(col_spec, col_spec, col_spec),
        out_shape=(out, out, out),
        interpret=_interpret(interpret),
    )(v, m)
    return cnt[:b, 0], mean[:b, 0], std[:b, 0]


# ---------------------------------------------------------------------------
# ma_judgment — the fused default-algorithm scoring kernel
# ---------------------------------------------------------------------------


def _judgment_kernel(
    hv_ref, hm_ref, cv_ref, cm_ref, thr_ref, bnd_ref, mlb_ref, mnp_ref,
    verdict_ref, anom_ref, upper_ref, lower_ref,
):
    hv = hv_ref[:]
    hm = hm_ref[:]
    cnt = jnp.sum(hm, axis=-1, keepdims=True)  # [TB, 1]
    c = jnp.maximum(cnt, 1.0)
    mu = jnp.sum(hv * hm, axis=-1, keepdims=True) / c
    d = (hv - mu) * hm  # two-pass variance, see _stats_kernel
    sigma = jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) / c)

    band = thr_ref[:] * sigma  # [TB, 1]
    up = mu + band
    lo = jnp.maximum(mu - band, mlb_ref[:])

    cur = cv_ref[:]
    curm = cm_ref[:] > 0.0
    bnd = bnd_ref[:].astype(jnp.int32)
    use_up = (bnd == 1) | (bnd == 3)
    use_lo = (bnd == 2) | (bnd == 3)
    flags = curm & (((cur > up) & use_up) | ((cur < lo) & use_lo))

    ncur = jnp.sum(cm_ref[:], axis=-1, keepdims=True)
    measurable = (cnt >= mnp_ref[:]) & (ncur > 0.0)
    flags = flags & measurable
    any_anom = jnp.any(flags, axis=-1, keepdims=True)
    verdict_ref[:] = jnp.where(
        measurable,
        jnp.where(any_anom, _UNHEALTHY, _HEALTHY),
        _UNKNOWN,
    ).astype(jnp.int32)
    anom_ref[:] = flags.astype(jnp.float32)
    upper_ref[:] = jnp.broadcast_to(up, cur.shape)
    lower_ref[:] = jnp.broadcast_to(lo, cur.shape)


def _judgment_bf16_kernel(
    anchor_ref, delta_ref, lens_ref, cv_ref, cm_ref, thr_ref, bnd_ref,
    mlb_ref, mnp_ref, verdict_ref, anom_ref, upper_ref, lower_ref,
):
    # moments straight off the bf16 deltas with f32 accumulation —
    # E[v] = anchor + E[d], Var[v] = Var[d]; left-packed deltas are
    # exact zeros past `lens`, so plain sums ARE the masked sums
    # (identical algebra to scoring.score_bf16_delta)
    d = delta_ref[:].astype(jnp.float32)
    n = lens_ref[:]  # [TB, 1] f32 valid counts
    c = jnp.maximum(n, 1.0)
    s1 = jnp.sum(d, axis=-1, keepdims=True)
    s2 = jnp.sum(d * d, axis=-1, keepdims=True)
    mean_d = s1 / c
    mean = jnp.where(n > 0, anchor_ref[:] + mean_d, 0.0)
    var = jnp.where(
        n > 0, jnp.maximum(s2 / c - mean_d * mean_d, 0.0), 0.0
    )
    sigma = jnp.sqrt(var)

    band = thr_ref[:] * sigma
    up = mean + band
    lo = jnp.maximum(mean - band, mlb_ref[:])

    cur = cv_ref[:]
    curm = cm_ref[:] > 0.0
    bnd = bnd_ref[:].astype(jnp.int32)
    use_up = (bnd == 1) | (bnd == 3)
    use_lo = (bnd == 2) | (bnd == 3)
    flags = curm & (((cur > up) & use_up) | ((cur < lo) & use_lo))

    ncur = jnp.sum(cm_ref[:], axis=-1, keepdims=True)
    measurable = (n >= mnp_ref[:]) & (ncur > 0.0)
    flags = flags & measurable
    any_anom = jnp.any(flags, axis=-1, keepdims=True)
    verdict_ref[:] = jnp.where(
        measurable,
        jnp.where(any_anom, _UNHEALTHY, _HEALTHY),
        _UNKNOWN,
    ).astype(jnp.int32)
    anom_ref[:] = flags.astype(jnp.float32)
    upper_ref[:] = jnp.broadcast_to(up, cur.shape)
    lower_ref[:] = jnp.broadcast_to(lo, cur.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ma_judgment_bf16_delta(
    anchor: jax.Array,
    delta: jax.Array,
    lens: jax.Array,
    cur_values: jax.Array,
    cur_mask: jax.Array,
    threshold: jax.Array,
    bound: jax.Array,
    min_lower_bound: jax.Array,
    min_points: jax.Array,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """`ma_judgment` on the bf16-delta history layout (VERDICT r5 #5).

    anchor [B] f32, delta [B, Th] bf16 (anchor-shifted, LEFT-PACKED:
    exact zeros past `lens`), lens [B] int32 — the same wire layout as
    `scoring.score_bf16_delta`/`fit_ma_from_bf16_delta`, so the kernel
    reads 2 B/point like the shipped XLA program instead of the f32
    kernel's 5 B/point. Same outputs/semantics as `ma_judgment` up to
    bf16 rounding of the deviations (parity pinned by tests)."""
    b, tc = cur_values.shape
    dv = _pad_axis(_pad_axis(delta, LANE, 1, 0), TILE_B, 0, 0)
    cv, cm = _pad_bt(cur_values.astype(jnp.float32), cur_mask)
    bp = dv.shape[0]
    thp = dv.shape[1]
    tcp = cv.shape[1]
    f32 = jnp.float32
    anc = _col(anchor, bp, f32)
    nvl = _col(lens, bp, f32)
    thr = _col(threshold, bp, f32)
    bnd = _col(bound, bp, jnp.int32)
    mlb = _col(min_lower_bound, bp, f32)
    mnp = _col(min_points, bp, f32)

    grid = (bp // TILE_B,)
    hist_spec = pl.BlockSpec((TILE_B, thp), lambda i: (i, 0), memory_space=pltpu.VMEM)
    cur_spec = pl.BlockSpec((TILE_B, tcp), lambda i: (i, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((TILE_B, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)

    verdict, anom, upper, lower = pl.pallas_call(
        _judgment_bf16_kernel,
        grid=grid,
        in_specs=[col_spec, hist_spec, col_spec, cur_spec, cur_spec,
                  col_spec, col_spec, col_spec, col_spec],
        out_specs=(col_spec, cur_spec, cur_spec, cur_spec),
        out_shape=(
            jax.ShapeDtypeStruct((bp, 1), jnp.int32),
            jax.ShapeDtypeStruct((bp, tcp), f32),
            jax.ShapeDtypeStruct((bp, tcp), f32),
            jax.ShapeDtypeStruct((bp, tcp), f32),
        ),
        interpret=_interpret(interpret),
    )(anc, dv, nvl, cv, cm, thr, bnd, mlb, mnp)
    return (
        verdict[:b, 0],
        anom[:b, :tc] > 0.0,
        upper[:b, :tc],
        lower[:b, :tc],
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def ma_judgment(
    hist_values: jax.Array,
    hist_mask: jax.Array,
    cur_values: jax.Array,
    cur_mask: jax.Array,
    threshold: jax.Array,
    bound: jax.Array,
    min_lower_bound: jax.Array,
    min_points: jax.Array,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused moving_average_all judgment (see module docstring).

    hist [B, Th], cur [B, Tc]; threshold/bound/min_lower_bound/min_points
    scalar or [B]. Returns (verdict [B] int32, anomalies [B, Tc] bool,
    upper [B, Tc], lower [B, Tc]) — matches the XLA path in
    engine/scoring.py for algorithm="moving_average_all" (fp32 tolerance;
    parity pinned by tests).
    """
    b, tc = cur_values.shape
    hv, hm = _pad_bt(hist_values.astype(jnp.float32), hist_mask)
    cv, cm = _pad_bt(cur_values.astype(jnp.float32), cur_mask)
    bp, thp = hv.shape
    tcp = cv.shape[1]
    f32 = jnp.float32
    thr = _col(threshold, bp, f32)
    bnd = _col(bound, bp, jnp.int32)
    mlb = _col(min_lower_bound, bp, f32)
    mnp = _col(min_points, bp, f32)

    grid = (bp // TILE_B,)
    hist_spec = pl.BlockSpec((TILE_B, thp), lambda i: (i, 0), memory_space=pltpu.VMEM)
    cur_spec = pl.BlockSpec((TILE_B, tcp), lambda i: (i, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((TILE_B, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)

    verdict, anom, upper, lower = pl.pallas_call(
        _judgment_kernel,
        grid=grid,
        in_specs=[hist_spec, hist_spec, cur_spec, cur_spec,
                  col_spec, col_spec, col_spec, col_spec],
        out_specs=(col_spec, cur_spec, cur_spec, cur_spec),
        out_shape=(
            jax.ShapeDtypeStruct((bp, 1), jnp.int32),
            jax.ShapeDtypeStruct((bp, tcp), f32),
            jax.ShapeDtypeStruct((bp, tcp), f32),
            jax.ShapeDtypeStruct((bp, tcp), f32),
        ),
        interpret=_interpret(interpret),
    )(hv, hm, cv, cm, thr, bnd, mlb, mnp)
    return (
        verdict[:b, 0],
        anom[:b, :tc] > 0.0,
        upper[:b, :tc],
        lower[:b, :tc],
    )
