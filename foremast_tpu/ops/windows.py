"""Fixed-shape masked metric-window container.

The reference brain processes one ragged time series per job (ES document ->
N `query_range` URLs -> lists of points). On TPU, ragged data kills tiling,
so the core container is a dense `[batch, T]` array plus a validity mask —
ragged windows become masks (SURVEY.md section 7.1). All downstream ops
(forecasters, rank tests, bounds) accept and respect the mask.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MetricWindows:
    """A batch of fixed-length metric windows.

    values: [..., T] float32 — metric samples (padding arbitrary where invalid)
    mask:   [..., T] bool    — True where the sample is real
    times:  [..., T] int32   — unix seconds per sample (0 where invalid);
            int32 because float32 ulp at current epochs is 128 s, which
            would collapse adjacent 60 s samples. Carried for anomaly
            reporting (the reference returns flat [t1,v1,t2,v2,...] pairs —
            foremast-barrelman `pkg/controller/Barrelman.go:593-620`).
            May be None (`from_ragged(..., device_times=False)`): no
            compiled program consumes times, and the shipped judge skips
            the upload entirely.
    """

    values: jax.Array
    mask: jax.Array
    times: jax.Array | None

    @property
    def batch_shape(self):
        return self.values.shape[:-1]

    @property
    def length(self) -> int:
        return self.values.shape[-1]

    def count(self) -> jax.Array:
        """Number of valid points per window, [...]."""
        return jnp.sum(self.mask, axis=-1)

    @staticmethod
    def from_ragged(
        series: Sequence[tuple[np.ndarray, np.ndarray]],
        length: int | None = None,
        device_times: bool = True,
    ) -> "MetricWindows":
        """Pack a list of (times, values) ragged series into one padded batch.

        Host-side helper (numpy): used by the dispatcher when packing pending
        jobs into fixed-shape batches (bucketing bounds recompiles).

        `device_times=False` skips uploading the packed times (times=None):
        no compiled scoring program reads them — anomaly timestamps are
        decoded on the host from each task's own ragged times — and the
        [B, T] int32 upload is pure tunnel bandwidth on the fleet tick.
        None is a valid empty pytree, so jit/sharding treewalks skip it.
        """
        if length is None:
            length = max((len(v) for _, v in series), default=1)
            length = max(length, 1)
        b = len(series)

        from foremast_tpu import native

        packed = native.pack_windows(list(series), length) if b else None
        if packed is not None:
            values, times, mask = packed
        else:
            values = np.zeros((b, length), dtype=np.float32)
            times = np.zeros((b, length), dtype=np.int32)
            mask = np.zeros((b, length), dtype=bool)
            for i, (t, v) in enumerate(series):
                n = min(len(v), length)
                values[i, :n] = np.asarray(v, dtype=np.float32)[:n]
                times[i, :n] = np.asarray(t, dtype=np.int64)[:n].astype(np.int32)
                mask[i, :n] = True
        return MetricWindows(
            values=jnp.asarray(values),
            mask=jnp.asarray(mask),
            times=jnp.asarray(times) if device_times else None,
        )


def masked_moments(
    values: jax.Array, mask: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(n, mean, var) over the last axis in ONE fused pass.

    Uses shifted moments — d = x - x[first valid index] — so the
    E[d^2] - E[d]^2 form stays well-conditioned (the shift point is a
    member of the sample, so deviations are bounded by the sample range)
    and arbitrary padding values in masked slots can never poison the
    result. This is the bandwidth-optimal form for the 7-day histories
    the deployed-default model reduces (BENCHMARKS.md headline note); the
    two-pass `masked_mean`/`masked_var` pair remains for callers that
    need an axis argument or ddof.
    """
    m = mask.astype(values.dtype)
    first_idx = jnp.argmax(mask, axis=-1)  # 0 for all-invalid rows (gated)
    c = jnp.take_along_axis(values, first_idx[..., None], axis=-1)
    d = (values - c) * m
    n = jnp.sum(m, axis=-1)
    s1 = jnp.sum(d, axis=-1)
    s2 = jnp.sum(d * d, axis=-1)
    nn = jnp.maximum(n, 1.0)
    mean_d = s1 / nn
    mean = jnp.where(n > 0, c[..., 0] + mean_d, 0.0)
    var = jnp.where(n > 0, jnp.maximum(s2 / nn - mean_d * mean_d, 0.0), 0.0)
    return n, mean, var


def masked_mean(values: jax.Array, mask: jax.Array, axis: int = -1) -> jax.Array:
    """Mean over valid points; 0.0 where a window has no valid points."""
    m = mask.astype(values.dtype)
    n = jnp.sum(m, axis=axis)
    s = jnp.sum(values * m, axis=axis)
    return jnp.where(n > 0, s / jnp.maximum(n, 1), 0.0)


def masked_var(values: jax.Array, mask: jax.Array, axis: int = -1, ddof: int = 0) -> jax.Array:
    """Variance over valid points (ddof degrees of freedom); 0.0 if too few."""
    m = mask.astype(values.dtype)
    n = jnp.sum(m, axis=axis)
    mu = masked_mean(values, mask, axis=axis)
    d = (values - jnp.expand_dims(mu, axis)) * m
    ss = jnp.sum(d * d, axis=axis)
    denom = n - ddof
    return jnp.where(denom > 0, ss / jnp.maximum(denom, 1), 0.0)


def masked_std(values: jax.Array, mask: jax.Array, axis: int = -1, ddof: int = 0) -> jax.Array:
    return jnp.sqrt(masked_var(values, mask, axis=axis, ddof=ddof))
