"""ctypes bindings for the native runtime library (native/windowpack.cpp).

Loading policy: `load()` only loads an existing
`native/build/libforemast_native.so` — it never compiles, so the scoring
hot path can't stall behind a surprise 2-minute build. Long-lived entry
points (worker/serve CLI) call `ensure_built()` once at startup, which
runs `make -C native` when a toolchain is available (the serve CLI does not
score windows, so it never needs the library). Without it everything
falls back to the pure-Python paths — the framework never
*requires* native code (SURVEY.md: the reference has none, so this layer
has no parity obligation; it serves the 100k windows/sec target).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("foremast_tpu.native")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libforemast_native.so")
ABI_VERSION = 4

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
_mapped = False  # a .so was actually dlopen'd (even if ABI-stale)


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception as e:  # noqa: BLE001 - any failure means "no native lib"
        # warning, not debug: this is a one-shot startup event and the
        # operator needs to know the fast path is off and why
        log.warning("native build failed (pure-Python fallback active): %s", e)
        return False


def ensure_built() -> bool:
    """Build the library if missing (startup-time hook; see module doc).

    Never rebuilds a library this process may already have mapped —
    rewriting a dlopen'd .so in place corrupts the mapping."""
    global _tried
    if os.environ.get("FOREMAST_NATIVE", "") == "0":
        return False
    with _lock:
        if _lib is not None:
            return True
        if _mapped:
            # a .so is dlopen'd in this process (it was ABI-stale) —
            # rebuilding its inode now is exactly the hazard we avoid
            return False
        # Best-effort make BEFORE anything is mapped: a current build is a
        # timestamp no-op, a source-newer-than-.so build refreshes, and a
        # toolchain-less image fails harmlessly — a prebuilt .so on disk
        # still loads below. One-shot STARTUP path; the lock must span
        # the build so a racing load() cannot dlopen a half-written .so.
        _build()  # foremast: ignore[blocking-under-lock]
        if not os.path.exists(_LIB_PATH):
            return False
        _tried = False  # allow a fresh load even if one ran before the build
    return load() is not None


def load() -> ctypes.CDLL | None:
    """The already-built library, or None (no compile happens here).

    Disable entirely with FOREMAST_NATIVE=0."""
    global _lib, _tried, _mapped
    if os.environ.get("FOREMAST_NATIVE", "") == "0":
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.warning("could not load %s: %s", _LIB_PATH, e)
            return None
        _mapped = True
        lib.fp_abi_version.restype = ctypes.c_int32
        if lib.fp_abi_version() != ABI_VERSION:
            # Do NOT rebuild here: the stale object is mapped into this
            # process, and rewriting its inode risks handing back the old
            # mapping (glibc dev/inode caching) or a SIGBUS. Fall back to
            # Python; `make -C native` in a fresh process fixes it.
            log.warning(
                "stale native library (abi %s != %s); run `make -C native` "
                "and restart — falling back to pure Python",
                lib.fp_abi_version(),
                ABI_VERSION,
            )
            return None
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        lib.fp_pack_windows.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p), i64p,
            ctypes.c_int64, ctypes.c_int64,
            f32p, i32p, u8p,
        ]
        lib.fp_pack_windows.restype = None
        lib.fp_anomaly_pairs.argtypes = [u8p, i64p, f64p, ctypes.c_int64, f64p]
        lib.fp_anomaly_pairs.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def pack_windows(
    series: list[tuple[np.ndarray, np.ndarray]], length: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Native ragged->[B, T] packing; None when the library is unavailable.

    Returns (values f32 [B,T], times i32 [B,T], mask bool [B,T]) with the
    exact semantics of MetricWindows.from_ragged (truncate to T, zero pad).
    """
    lib = load()
    if lib is None:
        return None
    b = len(series)
    # keep the normalized buffers alive for the call; no staging copy —
    # the library reads straight from each numpy buffer via pointer arrays
    vals = [np.ascontiguousarray(v, dtype=np.float32) for _, v in series]
    times = [np.ascontiguousarray(t, dtype=np.int64) for t, _ in series]
    for i, (t, v) in enumerate(zip(times, vals)):
        if len(t) != len(v):  # the C code indexes times by len(values)
            raise ValueError(
                f"series {i}: {len(t)} timestamps for {len(v)} values"
            )
    lens = np.fromiter((len(v) for v in vals), np.int64, count=b)
    vptrs = (ctypes.c_void_p * b)(*(v.ctypes.data for v in vals))
    tptrs = (ctypes.c_void_p * b)(*(t.ctypes.data for t in times))
    # np.zeros: the library writes only each row's valid prefix, so the
    # padding stays on copy-on-write zero pages and is never faulted in
    out_values = np.zeros((b, length), np.float32)
    out_times = np.zeros((b, length), np.int32)
    out_mask = np.zeros((b, length), np.uint8)
    lib.fp_pack_windows(
        vptrs, tptrs, lens, b, length, out_values, out_times, out_mask
    )
    return out_values, out_times, out_mask.view(bool)


def anomaly_pairs(
    flags: np.ndarray, times: np.ndarray, values: np.ndarray
) -> list[float] | None:
    """Native flat [t1, v1, ...] pair encoding; None when unavailable.

    Not on the engine's hot path anymore: the judge decodes a whole batch
    with one `np.nonzero` pass (judge.py), which beats a per-row ctypes
    call (~30 us fixed overhead each) at fleet batch sizes. Kept for
    single-series callers on very long windows."""
    lib = load()
    if lib is None:
        return None
    flags = np.ascontiguousarray(flags, dtype=np.uint8)
    times = np.ascontiguousarray(times, dtype=np.int64)
    # float64 so the wire pairs match the Python fallback bit-for-bit
    values = np.ascontiguousarray(values, dtype=np.float64)
    n = len(flags)
    if len(times) != n or len(values) != n:
        raise ValueError(
            f"length mismatch: {n} flags, {len(times)} times, {len(values)} values"
        )
    out = np.empty(2 * n, np.float64)
    k = int(lib.fp_anomaly_pairs(flags, times, values, n, out))
    return out[: 2 * k].tolist()
