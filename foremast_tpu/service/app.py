"""REST job gateway — the foremast-service equivalent (aiohttp).

Route parity with `foremast-service/cmd/manager/main.go:262-276`:

    POST /v1/healthcheck/create      -> RegisterEntry
    GET  /v1/healthcheck/id/{id}     -> SearchByID
    GET  /api/v1/{queryproxy}        -> CORS Prometheus proxy (UI)

plus the observability surface this framework adds on top of the
reference (which exposed nothing but gin's access log):

    GET /healthz       liveness + store depth + version
    GET /metrics       Prometheus exposition (request counters + any
                       other family on the process registry)
    GET /debug/state   JSON varz: queue depth, config identity, tracer
                       state — the service side of the worker's
                       /debug/state (observe.start_observe_server)

The gateway validates + converts requests (`request_to_document`),
creates jobs idempotently in the store, and serves external-status
views; scoring happens in the BrainWorker against the same store. Every
create mints a trace ID (observe/spans.py) stored on the document, so
worker ticks and controller polls can be correlated back to the
originating request.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

import aiohttp
from aiohttp import web

from foremast_tpu import __version__
from foremast_tpu.jobs.convert import InvalidRequest, request_to_document
from foremast_tpu.jobs.models import AnalyzeRequest, document_response, status_to_external
from foremast_tpu.jobs.store import InMemoryStore, JobStore
from foremast_tpu.observe.logs import ctx_log
from foremast_tpu.observe.spans import counter, current_span, new_trace_id

log = logging.getLogger("foremast_tpu.service")

STORE_KEY = web.AppKey("store", JobStore)
SESSION_KEY = web.AppKey("session", aiohttp.ClientSession)

CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
    "Access-Control-Allow-Headers": "Content-Type",
}


def _route_label(request: web.Request) -> str:
    """Route PATTERN for the request counter ({id} stays a template —
    raw paths would be an unbounded label cardinality leak)."""
    try:
        resource = request.match_info.route.resource
        if resource is not None:
            return resource.canonical
    except Exception:  # noqa: BLE001 - labeling must never fail a request
        pass
    return "unmatched"


def make_app(
    store: JobStore | None = None,
    query_endpoint: str | None = None,
    tracer=None,
    registry=None,
) -> web.Application:
    """query_endpoint: upstream Prometheus base (QUERY_SERVICE_ENDPOINT env
    in the reference, `main.go:236-243`). `tracer` (observe.spans.Tracer)
    opens one span per request; `registry` scopes the service's metric
    families (default: the process registry)."""
    store = store if store is not None else InMemoryStore()
    query_endpoint = query_endpoint or os.environ.get("QUERY_SERVICE_ENDPOINT", "")
    started = time.time()
    requests_total = counter(
        "foremast_service_requests_total",
        "gateway requests by route pattern and status code",
        ("route", "code"),
        registry,
    )

    @web.middleware
    async def observe_mw(request: web.Request, handler):
        route = _route_label(request)
        cm = None
        if tracer is not None:
            cm = tracer.span(
                f"service.{request.method} {route}", method=request.method
            )
            cm.__enter__()
        # code stays None for anything that is not an HTTP response the
        # server produced — a CancelledError from a client disconnect
        # must neither crash the finally nor count as a 500
        code = None
        try:
            resp = await handler(request)
            code = resp.status
            return resp
        except web.HTTPException as e:
            code = e.status
            raise
        except Exception:
            code = 500
            raise
        finally:
            if code is not None:
                requests_total.labels(route=route, code=str(code)).inc()
            if cm is not None:
                cm.__exit__(None, None, None)

    async def create(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                {"status": "error", "reason": "invalid JSON"}, status=400
            )
        try:
            req = AnalyzeRequest.from_json(body)
            doc = request_to_document(req)
        except (InvalidRequest, ValueError) as e:
            # ValueError covers e.g. an unsupported dataSourceType from
            # build_url — client input, not a server fault
            return web.json_response(
                {"status": "error", "reason": str(e)}, status=400
            )
        # correlation ID: the request span's trace ID (or a fresh one
        # when tracing is off) rides on the document through the store,
        # so every later tick/poll log can join back to this create
        sp = current_span()
        doc.trace_id = sp.trace_id if sp is not None else new_trace_id()
        # the store may be backed by blocking HTTP (Elasticsearch); keep
        # it off the event loop
        stored, created = await asyncio.to_thread(store.create, doc)
        ctx_log(
            log,
            logging.INFO,
            "job created" if created else "job exists",
            job_id=stored.id,
            app=stored.app_name,
            job_trace_id=stored.trace_id,
        )
        # ApplicationHealthAnalyzeResponse shape (models.go:63-80)
        return web.json_response(
            {
                "jobId": stored.id,
                "statusCode": 201 if created else 208,
                "status": status_to_external(stored.status),
                "reason": "",
            },
            status=200,
        )

    async def by_id(request: web.Request) -> web.Response:
        doc = await asyncio.to_thread(store.get, request.match_info["id"])
        if doc is None:
            return web.json_response(
                {"status": "error", "reason": "not found"}, status=404
            )
        return web.json_response(document_response(doc))

    async def query_proxy(request: web.Request) -> web.Response:
        """GET /api/v1/{queryproxy} — forwards to the query service with
        CORS for the browser UI (`main.go:214-233`)."""
        if not query_endpoint:
            return web.json_response(
                {"status": "error", "reason": "no QUERY_SERVICE_ENDPOINT"},
                status=502,
                headers=CORS_HEADERS,
            )
        target = (
            query_endpoint.rstrip("/")
            + "/api/v1/"
            + request.match_info["queryproxy"]
        )
        session = request.app[SESSION_KEY]
        async with session.get(target, params=request.rel_url.query) as r:
            body = await r.read()
            return web.Response(
                body=body,
                status=r.status,
                content_type=r.content_type,
                headers=CORS_HEADERS,
            )

    async def _store_depth() -> int | None:
        """Open (non-terminal) job count; None when the store is
        unreachable or slow — health must report degradation in bounded
        time, not raise or hang a liveness probe. The bound must stay
        well under kubelet's default probe timeoutSeconds=1: a slow (not
        down) store must degrade the body, never fail the probe."""
        try:
            return await asyncio.wait_for(
                asyncio.to_thread(store.count_open), timeout=0.5
            )
        except Exception:  # noqa: BLE001
            return None

    async def healthz(request: web.Request) -> web.Response:
        depth = await _store_depth()
        return web.json_response(
            {
                "ok": True,
                "version": __version__,
                "store_depth": depth,
                "store_ok": depth is not None,
            }
        )

    async def metrics(request: web.Request) -> web.Response:
        from prometheus_client import (
            CONTENT_TYPE_LATEST,
            REGISTRY,
            generate_latest,
        )

        payload = generate_latest(registry if registry is not None else REGISTRY)
        return web.Response(body=payload, content_type=CONTENT_TYPE_LATEST.split(";")[0])

    async def debug_state(request: web.Request) -> web.Response:
        state = {
            "component": "service",
            "version": __version__,
            "uptime_seconds": round(time.time() - started, 1),
            "queue_depth": await _store_depth(),
            "store": type(store).__name__,
            "query_endpoint": bool(query_endpoint),
        }
        if tracer is not None:
            state["trace"] = tracer.debug_state()
        return web.json_response(state)

    async def _client_session(app: web.Application):
        app[SESSION_KEY] = aiohttp.ClientSession()
        yield
        await app[SESSION_KEY].close()

    app = web.Application(middlewares=[observe_mw])
    app.cleanup_ctx.append(_client_session)
    app.router.add_post("/v1/healthcheck/create", create)
    app.router.add_get("/v1/healthcheck/id/{id}", by_id)
    app.router.add_get("/api/v1/{queryproxy}", query_proxy)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/state", debug_state)
    app[STORE_KEY] = store
    return app


def serve(host: str = "0.0.0.0", port: int = 8099, **kwargs) -> None:
    """Blocking server on :8099 (the reference service's port)."""
    web.run_app(make_app(**kwargs), host=host, port=port)
