"""REST job gateway — the foremast-service equivalent (aiohttp).

Route parity with `foremast-service/cmd/manager/main.go:262-276`:

    POST /v1/healthcheck/create      -> RegisterEntry
    GET  /v1/healthcheck/id/{id}     -> SearchByID
    GET  /api/v1/{queryproxy}        -> CORS Prometheus proxy (UI)

plus GET /healthz. The gateway validates + converts requests
(`request_to_document`), creates jobs idempotently in the store, and
serves external-status views; scoring happens in the BrainWorker against
the same store.
"""

from __future__ import annotations

import asyncio
import logging
import os

import aiohttp
from aiohttp import web

from foremast_tpu.jobs.convert import InvalidRequest, request_to_document
from foremast_tpu.jobs.models import AnalyzeRequest, document_response, status_to_external
from foremast_tpu.jobs.store import InMemoryStore, JobStore

log = logging.getLogger("foremast_tpu.service")

STORE_KEY = web.AppKey("store", JobStore)
SESSION_KEY = web.AppKey("session", aiohttp.ClientSession)

CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
    "Access-Control-Allow-Headers": "Content-Type",
}


def make_app(
    store: JobStore | None = None,
    query_endpoint: str | None = None,
) -> web.Application:
    """query_endpoint: upstream Prometheus base (QUERY_SERVICE_ENDPOINT env
    in the reference, `main.go:236-243`)."""
    store = store if store is not None else InMemoryStore()
    query_endpoint = query_endpoint or os.environ.get("QUERY_SERVICE_ENDPOINT", "")

    async def create(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                {"status": "error", "reason": "invalid JSON"}, status=400
            )
        try:
            req = AnalyzeRequest.from_json(body)
            doc = request_to_document(req)
        except (InvalidRequest, ValueError) as e:
            # ValueError covers e.g. an unsupported dataSourceType from
            # build_url — client input, not a server fault
            return web.json_response(
                {"status": "error", "reason": str(e)}, status=400
            )
        # the store may be backed by blocking HTTP (Elasticsearch); keep
        # it off the event loop
        stored, created = await asyncio.to_thread(store.create, doc)
        # ApplicationHealthAnalyzeResponse shape (models.go:63-80)
        return web.json_response(
            {
                "jobId": stored.id,
                "statusCode": 201 if created else 208,
                "status": status_to_external(stored.status),
                "reason": "",
            },
            status=200,
        )

    async def by_id(request: web.Request) -> web.Response:
        doc = await asyncio.to_thread(store.get, request.match_info["id"])
        if doc is None:
            return web.json_response(
                {"status": "error", "reason": "not found"}, status=404
            )
        return web.json_response(document_response(doc))

    async def query_proxy(request: web.Request) -> web.Response:
        """GET /api/v1/{queryproxy} — forwards to the query service with
        CORS for the browser UI (`main.go:214-233`)."""
        if not query_endpoint:
            return web.json_response(
                {"status": "error", "reason": "no QUERY_SERVICE_ENDPOINT"},
                status=502,
                headers=CORS_HEADERS,
            )
        target = (
            query_endpoint.rstrip("/")
            + "/api/v1/"
            + request.match_info["queryproxy"]
        )
        session = request.app[SESSION_KEY]
        async with session.get(target, params=request.rel_url.query) as r:
            body = await r.read()
            return web.Response(
                body=body,
                status=r.status,
                content_type=r.content_type,
                headers=CORS_HEADERS,
            )

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    async def _client_session(app: web.Application):
        app[SESSION_KEY] = aiohttp.ClientSession()
        yield
        await app[SESSION_KEY].close()

    app = web.Application()
    app.cleanup_ctx.append(_client_session)
    app.router.add_post("/v1/healthcheck/create", create)
    app.router.add_get("/v1/healthcheck/id/{id}", by_id)
    app.router.add_get("/api/v1/{queryproxy}", query_proxy)
    app.router.add_get("/healthz", healthz)
    app[STORE_KEY] = store
    return app


def serve(host: str = "0.0.0.0", port: int = 8099, **kwargs) -> None:
    """Blocking server on :8099 (the reference service's port)."""
    web.run_app(make_app(**kwargs), host=host, port=port)
