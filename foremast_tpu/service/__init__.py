"""REST job gateway (foremast-service equivalent)."""

from foremast_tpu.service.app import make_app, serve

__all__ = ["make_app", "serve"]
