"""PromQL query construction + datasource URL builders + config codec.

Three reference contracts reproduced exactly:

1. The metrics-query builder (barrelman
   `pkg/client/metrics/metricsquery.go:14-127`): three query sets per job —
   current / baseline / historical — over the recording-rule series
   `namespace_pod:<metric>` and `namespace_app_per_pod:<metric>`, fixed
   step=60 s, +1 min Prometheus-latency offset on current, 7-day
   historical window.
2. The query_range URL builder (service
   `pkg/prometheus/prometheushelper.go:12-27`) and the wavefront stub
   (`pkg/wavefront/wavefronthelper.go:20-29`).
3. The config-string codec (service `cmd/manager/main.go:28-74`): each
   window's alias->URL map flattens to `alias== <url> ||alias2== <url2>`
   with separators `" ||"` and `"== "` — the strings the brain reads back
   from the ES document.
"""

from __future__ import annotations

import urllib.parse
from typing import Mapping

from foremast_tpu.jobs.models import MetricQuery, MetricsInfo

STEP_SECONDS = 60  # metricsquery.go:43
PROMETHEUS_LATENCY_OFFSET = 60  # +1 min compensation, metricsquery.go:53-55
HISTORICAL_WINDOW = 7 * 24 * 3600  # 7 days, metricsquery.go:75-77

STRATEGY_ROLLING_UPDATE = "rollingUpdate"
STRATEGY_CANARY = "canary"
STRATEGY_CONTINUOUS = "continuous"

CONFIG_ENTRY_SEP = " ||"  # main.go:28-31
CONFIG_KV_SEP = "== "


# ---------------------------------------------------------------------------
# PromQL query text (metricsquery.go:45-78)
# ---------------------------------------------------------------------------


def pods_query(metric: str, namespace: str, pods: list[str]) -> str:
    """`namespace_pod:<metric>{namespace="ns",pod=~"p1|p2"}` — the
    pod-pinned form for canary/rolling current+baseline windows."""
    pod_re = "|".join(pods)
    return f'namespace_pod:{metric}{{namespace="{namespace}",pod=~"{pod_re}"}}'


def app_query(metric: str, namespace: str, app: str) -> str:
    """`namespace_app_per_pod:<metric>{namespace="ns",app="app"}` — the
    app-aggregated form for historical + continuous windows."""
    return f'namespace_app_per_pod:{metric}{{namespace="{namespace}",app="{app}"}}'


def create_metrics_info(
    strategy: str,
    metric_names: Mapping[str, str],
    namespace: str,
    app: str,
    start: int,
    end: int,
    endpoint: str,
    new_pods: list[str] | None = None,
    old_pods: list[str] | None = None,
) -> MetricsInfo:
    """CreateMetricsInfo parity (metricsquery.go:91-127).

    metric_names: alias -> PromQL metric (the DeploymentMetadata monitoring
    list, types.go). Windows: current = [start+offset, end+offset] on new
    pods (or app-wide for continuous); baseline = [start-window, start] on
    old pods, only for canary/continuous with two pod groups; historical =
    app-wide last 7 days.
    """
    window = end - start
    info = MetricsInfo()
    for alias, metric in metric_names.items():
        if strategy == STRATEGY_CONTINUOUS or not new_pods:
            cur_q = app_query(metric, namespace, app)
        else:
            cur_q = pods_query(metric, namespace, new_pods)
        info.current[alias] = MetricQuery(
            "prometheus",
            {
                "endpoint": endpoint,
                "query": cur_q,
                "start": start + PROMETHEUS_LATENCY_OFFSET,
                "end": end + PROMETHEUS_LATENCY_OFFSET,
                "step": STEP_SECONDS,
            },
        )
        if strategy in (STRATEGY_CANARY, STRATEGY_CONTINUOUS) and old_pods:
            info.baseline[alias] = MetricQuery(
                "prometheus",
                {
                    "endpoint": endpoint,
                    "query": pods_query(metric, namespace, old_pods),
                    "start": start - window,
                    "end": start,
                    "step": STEP_SECONDS,
                },
            )
        info.historical[alias] = MetricQuery(
            "prometheus",
            {
                "endpoint": endpoint,
                "query": app_query(metric, namespace, app),
                "start": start - HISTORICAL_WINDOW,
                "end": start,
                "step": STEP_SECONDS,
            },
        )
    return info


# ---------------------------------------------------------------------------
# Datasource URL builders
# ---------------------------------------------------------------------------


def prometheus_url(params: Mapping[str, object]) -> str:
    """`<endpoint>query_range?query=<urlencoded>&start=&end=&step=`
    (prometheushelper.go:12-27)."""
    endpoint = str(params.get("endpoint", ""))
    q = urllib.parse.quote(str(params.get("query", "")), safe="")
    return (
        f"{endpoint}query_range?query={q}"
        f"&start={params.get('start', '')}"
        f"&end={params.get('end', '')}"
        f"&step={params.get('step', '')}"
    )


def wavefront_url(params: Mapping[str, object]) -> str:
    """`<query>&&<start>&&<step-unit>&&<end>` (wavefronthelper.go:20-29);
    step granularity mapped to wavefront units m/s/h/d."""
    step = int(params.get("step", 60) or 60)
    unit = {60: "m", 1: "s", 3600: "h", 86400: "d"}.get(step, "m")
    return (
        f"{params.get('query', '')}&&{params.get('start', '')}"
        f"&&{unit}&&{params.get('end', '')}"
    )


_URL_BUILDERS = {"prometheus": prometheus_url, "wavefront": wavefront_url}


def build_url(mq: MetricQuery) -> str:
    builder = _URL_BUILDERS.get(mq.data_source_type)
    if builder is None:
        raise ValueError(f"unsupported dataSourceType {mq.data_source_type!r}")
    return builder(mq.parameters)


# ---------------------------------------------------------------------------
# Config-string codec (main.go:28-74)
# ---------------------------------------------------------------------------


def encode_config(queries: Mapping[str, MetricQuery]) -> tuple[str, str]:
    """alias->MetricQuery map -> (config_string, source_string):
    `alias== <url> ||alias2== <url2>` and the parallel datasource list."""
    parts = []
    sources = []
    for alias in sorted(queries):
        mq = queries[alias]
        parts.append(f"{alias}{CONFIG_KV_SEP}{build_url(mq)}")
        sources.append(f"{alias}{CONFIG_KV_SEP}{mq.data_source_type}")
    return CONFIG_ENTRY_SEP.join(parts), CONFIG_ENTRY_SEP.join(sources)


def decode_config(config: str) -> dict[str, str]:
    """config string -> alias -> URL (what the brain fetches)."""
    out: dict[str, str] = {}
    if not config:
        return out
    for entry in config.split(CONFIG_ENTRY_SEP):
        entry = entry.strip()
        if not entry:
            continue
        alias, sep, url = entry.partition(CONFIG_KV_SEP)
        if sep:
            out[alias.strip()] = url.strip()
    return out
