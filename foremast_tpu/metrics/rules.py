"""Metric-aggregation layer: Prometheus recording rules, generated.

The reference ships a hand-written PrometheusRule manifest
(`deploy/foremast/2_barrelman/metrics-rules-default.yaml:15-39,45-56`) that
pre-aggregates raw app/kubelet series into the three naming families the
query builder consumes (`metricsquery.go:53-78`):

    namespace_pod:<metric>          sum by (namespace, pod)
    namespace_app:<metric>          sum by (namespace, app)
    namespace_app_per_pod:<metric>  namespace_app:<metric> / namespace_app:pod_count

Rather than maintaining a YAML blob, this module *generates* the rule set
from a compact spec: HTTP request-rate families are one template over a
status-class regex; the per-pod family is a pure quotient of the per-app
family. `prometheus_rule_manifest()` renders the PrometheusRule custom
resource used by deploy/, and `rule_expr()` lets tests and the replay
metric store resolve what a recorded series means.
"""

from __future__ import annotations

import dataclasses
import functools
import json

from foremast_tpu.observe.gauges import _san
from collections.abc import Iterable

# Rate window for request-class rules (reference uses [1m] throughout the
# spring.boot.metrics.rules group; resource rules use [5m]).
REQUEST_RATE_WINDOW = "1m"
CPU_RATE_WINDOW = "5m"

# status-class regex per derived request metric (reference
# metrics-rules-default.yaml spring.boot group). `None` means no status
# selector (total request count).
REQUEST_CLASSES: dict[str, str | None] = {
    "http_server_requests_2xx": "2[0-9]+",
    "http_server_requests_error_4xx": "4[0-9]+",
    "http_server_requests_error_5xx": "5[0-9]+",
    "http_server_requests_errors": "[4-5][0-9]+",
    "http_server_requests_count": None,
}

# Resource metrics from kubelet/cAdvisor, aggregated the same three ways.
RESOURCE_METRICS = ("cpu_usage_seconds_total", "memory_usage_bytes")

LATENCY_METRIC = "http_server_requests_latency"

#: Every derived metric name the aggregation layer records (the vocabulary
#: the DeploymentMetadata `monitoring:` lists draw from).
ALL_METRICS: tuple[str, ...] = (
    *REQUEST_CLASSES,
    LATENCY_METRIC,
    *RESOURCE_METRICS,
)


@dataclasses.dataclass(frozen=True)
class RecordingRule:
    record: str
    expr: str

    def to_dict(self) -> dict[str, str]:
        return {"record": self.record, "expr": self.expr}


def _requests_rate(status_re: str | None, by: str) -> str:
    sel = f'{{status=~"{status_re}"}}' if status_re else ""
    return (
        f"sum(rate(http_server_requests_seconds_count{sel}"
        f"[{REQUEST_RATE_WINDOW}])) by (namespace, {by})"
    )


def _latency(by: str) -> str:
    return (
        'sum(rate(http_server_requests_seconds_sum{status="200"}'
        f"[{REQUEST_RATE_WINDOW}])"
        '/rate(http_server_requests_seconds_count{status="200"}'
        f"[{REQUEST_RATE_WINDOW}])) by (namespace, {by})"
    )


# join raw cAdvisor pod_name series onto the `app` pod label via
# kube-state-metrics, the reference's label_replace dance
_APP_JOIN = (
    " * on (namespace, pod_name) group_left(app) label_replace(label_replace("
    'kube_pod_labels{job="kube-state-metrics"}, "pod_name", "$1", "pod", '
    '"(.*)"), "app", "$1", "label_app", "(.*)")'
)


def _resource_expr(metric: str, by: str) -> str:
    if metric == "cpu_usage_seconds_total":
        inner = (
            "sum(rate(container_cpu_usage_seconds_total"
            f'{{job="kubelet", image!="", container_name!=""}}[{CPU_RATE_WINDOW}]))'
            " by (namespace, pod_name)"
        )
    else:
        inner = (
            "sum(container_memory_usage_bytes"
            '{job="kubelet", image!="", container_name!=""}) by (namespace, pod_name)'
        )
    if by == "pod":
        return (
            f'sum by (namespace, pod) (label_replace({inner}, "pod", "$1", '
            '"pod_name", "(.*)"))'
        )
    return f"sum by (namespace, app) ({inner}{_APP_JOIN})"


def core_rules() -> list[RecordingRule]:
    """Resource aggregation + the pod_count denominator."""
    rules = []
    for metric in RESOURCE_METRICS:
        rules.append(
            RecordingRule(f"namespace_pod:{metric}", _resource_expr(metric, "pod"))
        )
        rules.append(
            RecordingRule(f"namespace_app:{metric}", _resource_expr(metric, "app"))
        )
    rules.append(
        RecordingRule(
            "namespace_app:pod_count",
            'count(label_replace(kube_pod_labels{job="kube-state-metrics"}, '
            '"app", "$1", "label_app", "(.*)")) by (namespace, app)',
        )
    )
    rules.extend(_per_pod_rules(RESOURCE_METRICS))
    return rules


def request_rules() -> list[RecordingRule]:
    """HTTP request-class + latency aggregation (app-instrumented series)."""
    rules = []
    for by in ("pod", "app"):
        prefix = "namespace_pod" if by == "pod" else "namespace_app"
        for metric, status_re in REQUEST_CLASSES.items():
            rules.append(
                RecordingRule(f"{prefix}:{metric}", _requests_rate(status_re, by))
            )
        rules.append(RecordingRule(f"{prefix}:{LATENCY_METRIC}", _latency(by)))
    rules.extend(_per_pod_rules((*REQUEST_CLASSES, LATENCY_METRIC)))
    return rules


def _per_pod_rules(metrics: Iterable[str]) -> list[RecordingRule]:
    return [
        RecordingRule(
            f"namespace_app_per_pod:{m}",
            f"namespace_app:{m} / namespace_app:pod_count",
        )
        for m in metrics
    ]


#: Gauge suffixes the brain publishes per metric
#: (`foremast-brain.yaml:109-122`).
BRAIN_GAUGE_SUFFIXES = ("upper", "lower", "anomaly")


#: The recorded family the engine's gauges are named after: historical
#: queries always use the per-app form (`metricsquery.go:73-78`), and the
#: reference browser charts exactly these (`metrics.js:15-23`).
def brain_gauge_series(metric: str) -> str:
    return f"namespace_app_per_pod:{metric}"


def brain_rules() -> list[RecordingRule]:
    """Restore the reference's `foremastbrain:` colon spelling.

    The scoring worker names its gauges after the job's base series and
    exposes them with '_' for ':' on :8000/metrics (prometheus_client
    forbids ':' — it is reserved for recording rules):
    `foremastbrain_namespace_app_per_pod_<metric>_{upper,lower,anomaly}`.
    The reference contract, which its dashboards and alert rules are
    written against, is the colon form
    `foremastbrain:namespace_app_per_pod:<metric>_{upper,lower,anomaly}`
    (`deploy/foremast/3_brain/foremast-brain.yaml:109-122`,
    `foremast-browser/src/config/metrics.js:15-23`). One recording rule
    per (metric, bound) republishes each exported series under the exact
    reference name, for every metric in the standard vocabulary."""
    return [
        RecordingRule(
            f"foremastbrain:{brain_gauge_series(m)}_{suffix}",
            f"foremastbrain_{_san(brain_gauge_series(m))}_{suffix}",
        )
        for m in ALL_METRICS
        for suffix in BRAIN_GAUGE_SUFFIXES
    ]


def alert_rules() -> list[dict]:
    """Alerting rules over the brain's gauge families.

    The reference declares the intent without shipping rules: "We will
    send foremast internal metrics so that we can define AlertRules in
    prometheus to generate Alerts" (`types.go:190-191`). These close that
    loop, written against the colon-spelled series `brain_rules` records:

      * ForemastAnomaly_<metric>  — the sticky anomaly gauge changed
        value or newly appeared in the last 5 m (an anomaly EVENT; the
        gauge holds the last anomalous value forever, so changes() +
        appearance isolate events — same semantics as the dashboard
        join, ui/join.py);
      * Foremast{Upper,Lower}Breach_<metric> — the measured per-app
        series breaches the model band for 2 m, direction-aware: error/
        latency/resource metrics page above the UPPER band, success/
        traffic metrics (2xx, request count) page below the LOWER band
        (label_replace aligns the gauge's exported_namespace with the
        recorded series' namespace);
      * ForemastEngineDown        — no scoring engine is exporting
        self-telemetry at all.
    """
    rules: list[dict] = []
    for m in ALL_METRICS:
        gauge = brain_gauge_series(m)  # the series the engine publishes
        anom = f"foremastbrain:{gauge}_anomaly"
        rules.append(
            {
                "alert": f"ForemastAnomaly_{m}",
                # the sticky gauge yields an event when its value CHANGES
                # or when the series APPEARS (first-ever anomaly for this
                # app — changes() alone is 0 on a newly-born series). A
                # repeat anomaly at the exact same value inside one series
                # lifetime is indistinguishable from stickiness — a
                # limitation of the reference's gauge contract itself.
                "expr": (
                    f"changes({anom}[5m]) > 0 or ({anom} unless {anom} offset 5m)"
                ),
                "labels": {"severity": "warning"},
                "annotations": {
                    "summary": (
                        "Foremast flagged an anomaly on "
                        + m
                        + " for {{ $labels.app }} in "
                        + "{{ $labels.exported_namespace }}"
                    )
                },
            }
        )
        # direction-aware band breach: error/latency/resource metrics page
        # when ABOVE the upper band; success/traffic metrics page when
        # BELOW the lower band (a 2xx/request-rate collapse is the outage
        # signal for those; healthy-high traffic above the band is not).
        low_is_bad = m in (
            "http_server_requests_2xx",
            "http_server_requests_count",
        )
        band = "lower" if low_is_bad else "upper"
        cmp_op = "<" if low_is_bad else ">"
        agg = "min" if low_is_bad else "max"
        rules.append(
            {
                "alert": (
                    f"Foremast{'Lower' if low_is_bad else 'Upper'}Breach_{m}"
                ),
                # min/max by(...) dedupes scrape-label variants of the
                # gauge (engine restart keeps the old pod's series alive
                # for the staleness window; group_left needs a unique
                # right side)
                "expr": (
                    f"{gauge} {cmp_op} on(namespace, app) group_left() "
                    f"{agg} by (namespace, app) (label_replace("
                    f'foremastbrain:{gauge}_{band}, "namespace", "$1", '
                    '"exported_namespace", "(.*)"))'
                ),
                "for": "2m",
                "labels": {"severity": "warning"},
                "annotations": {
                    "summary": (
                        m
                        + f" {'below' if low_is_bad else 'above'} the model's "
                        + f"{band} band for "
                        + "{{ $labels.app }} in {{ $labels.namespace }}"
                    )
                },
            }
        )
    rules.append(
        {
            "alert": "ForemastEngineDown",
            "expr": "absent(foremast_worker_tick_seconds_count)",
            "for": "5m",
            "labels": {"severity": "critical"},
            "annotations": {
                "summary": "no foremast scoring engine is exporting telemetry"
            },
        }
    )
    return rules


def all_rules() -> list[RecordingRule]:
    return core_rules() + request_rules() + brain_rules()


@functools.lru_cache(maxsize=1)
def _by_record() -> dict[str, str]:
    return {r.record: r.expr for r in all_rules()}


def rule_expr(record: str) -> str | None:
    """Resolve a recorded series name to its PromQL definition."""
    return _by_record().get(record)


def prometheus_rule_manifest(
    name: str = "foremast-metrics-rules", namespace: str = "monitoring"
) -> dict:
    """The PrometheusRule custom resource (monitoring.coreos.com/v1)."""
    return {
        "apiVersion": "monitoring.coreos.com/v1",
        "kind": "PrometheusRule",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"prometheus": "k8s", "role": "alert-rules"},
        },
        "spec": {
            "groups": [
                {
                    "name": "core.metrics.aggregation.rules",
                    "rules": [r.to_dict() for r in core_rules()],
                },
                {
                    "name": "request.metrics.aggregation.rules",
                    "rules": [r.to_dict() for r in request_rules()],
                },
                {
                    "name": "foremastbrain.gauge.spelling.rules",
                    "rules": [r.to_dict() for r in brain_rules()],
                },
                {
                    "name": "foremast.alert.rules",
                    "rules": alert_rules(),
                },
            ]
        },
    }


def _yaml_scalar(s: str) -> str:
    """Quote a scalar for YAML output (JSON strings are valid YAML)."""
    return json.dumps(s)


def to_yaml(manifest: dict | None = None) -> str:
    """Render the manifest as YAML without a yaml dependency (the image has
    PyYAML, but keeping the renderer dependency-free makes the deploy
    artifacts reproducible from a bare interpreter)."""
    m = manifest if manifest is not None else prometheus_rule_manifest()
    lines: list[str] = []

    def emit(obj, indent: int, in_list: bool = False) -> None:
        pad = "  " * indent
        if isinstance(obj, dict):
            first = True
            for k, v in obj.items():
                lead = pad[:-2] + "- " if in_list and first else pad
                first = False
                if isinstance(v, (dict, list)) and v:
                    lines.append(f"{lead}{k}:")
                    emit(v, indent + 1)
                else:
                    val = _yaml_scalar(v) if isinstance(v, str) else json.dumps(v)
                    lines.append(f"{lead}{k}: {val}")
        elif isinstance(obj, list):
            for item in obj:
                if isinstance(item, dict):
                    emit(item, indent + 1, in_list=True)
                else:
                    val = (
                        _yaml_scalar(item)
                        if isinstance(item, str)
                        else json.dumps(item)
                    )
                    lines.append(f"{pad}- {val}")

    emit(m, 0)
    return "\n".join(lines) + "\n"
