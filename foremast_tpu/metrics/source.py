"""Metric sources: where the brain fetches its windows from.

The reference brain HTTP-GETs each `query_range` URL stored in the ES
document's config strings (SURVEY.md section 3.2). Sources here:

  * `PrometheusSource` — real HTTP fetch (requests), parsing the
    query_range JSON matrix response;
  * `ReplaySource` — serves deterministic CSV traces keyed by substring
    match on the URL/query, the TPU-build analog of the reference demo's
    `FileErrorGenerator` replay (`error/FileErrorGenerator.java:27-37`) —
    drives golden end-to-end tests without a live Prometheus;
  * `StaticSource` — direct alias->series map for unit tests.

All return (times: int64[N], values: float32[N]) numpy arrays.
"""

from __future__ import annotations

import csv
import functools
import math
import os
import random
import threading
import time
from datetime import datetime, timezone
from typing import Callable, Mapping

import numpy as np

Series = tuple[np.ndarray, np.ndarray]


def _empty() -> Series:
    return np.zeros(0, np.int64), np.zeros(0, np.float32)


class MetricSource:
    # True => fetches block on I/O and the worker may fan a claimed
    # batch's fetches through a thread pool; in-memory sources say False
    # so the (single-core) worker skips pure-GIL thread overhead
    concurrent_fetch = True

    def fetch(self, url: str) -> Series:  # pragma: no cover - interface
        raise NotImplementedError


# HTTP statuses worth retrying: throttling and transient server-side
# failures; 4xx configuration errors (bad query) fail immediately
RETRY_STATUSES = frozenset({429, 500, 502, 503, 504})


@functools.lru_cache(maxsize=1)
def _transient_exceptions() -> tuple:
    """The retryable exception types, computed once per process:
    builtin ConnectionError/TimeoutError cover injected test sessions;
    the requests types (which do NOT subclass them) are added when
    requests is importable."""
    excs: tuple = (ConnectionError, TimeoutError)
    try:
        import requests

        excs += (
            requests.exceptions.ConnectionError,
            requests.exceptions.Timeout,
        )
    except ImportError:
        pass
    return excs


class PrometheusSource(MetricSource):
    """Fetches query_range URLs; merges a multi-series result by summing
    values per timestamp (recording rules normally return one series).

    Thread-safe: the worker fetches a claimed batch from a thread pool,
    and requests.Session is not safe for concurrent use (cookie jar /
    redirect state), so each thread gets its own Session. An explicitly
    injected `session` (tests) is used as-is.

    Transient failures (HTTP 429/5xx, connection/timeout errors) are
    retried up to `FOREMAST_FETCH_RETRIES` times (default 2) with
    exponential jittered backoff — a single flaky round trip must not
    fail the whole document's preprocess stage. Non-transient errors
    (4xx, parse errors) still raise on the first attempt.

    Chaos/degradation seams (ISSUE 9, both default None = pass-through):
    `chaos` (chaos.EdgeChaos) perturbs every attempt at this — the one
    — request choke point; `breaker` (chaos.CircuitBreaker) is checked
    once per fetch and records the fetch's final outcome, so a dead
    Prometheus fails further fetches in microseconds (BreakerOpen is a
    ConnectionError — existing fetch-failure isolation applies) instead
    of a full timeout-times-retries stall per document.
    """

    def __init__(
        self,
        session=None,
        timeout: float = 10.0,
        retries: int | None = None,
        backoff_seconds: float = 0.25,
        chaos=None,
        breaker=None,
    ):
        self._injected = session
        self._local = threading.local()
        self.timeout = timeout
        if retries is None:
            retries = int(os.environ.get("FOREMAST_FETCH_RETRIES", "") or 2)
        self.retries = max(0, retries)
        self.backoff_seconds = backoff_seconds
        self.chaos = chaos
        self.breaker = breaker

    @property
    def _session(self):
        if self._injected is not None:
            return self._injected
        sess = getattr(self._local, "session", None)
        if sess is None:
            import requests

            sess = self._local.session = requests.Session()
        return sess

    def _get_with_retries(self, url: str):
        transient = _transient_exceptions()
        breaker = self.breaker
        if breaker is not None:
            breaker.allow()  # BreakerOpen (a ConnectionError) fails fast
        chaos = self.chaos
        for attempt in range(self.retries + 1):
            last = attempt == self.retries
            try:
                if chaos is not None:
                    chaos.perturb(url)  # injected faults are transient
                resp = self._session.get(url, timeout=self.timeout)
            except transient:
                if last:
                    if breaker is not None:
                        breaker.record_failure()
                    raise
            else:
                if resp.status_code not in RETRY_STATUSES:
                    if breaker is not None:
                        breaker.record_success()
                    return resp
                if last:
                    if breaker is not None:
                        breaker.record_failure()
                    return resp
            # bounded jittered exponential backoff: 0.5-1x of
            # base * 2^attempt, so a thundering herd of claim fetches
            # doesn't re-synchronize on the throttling server
            time.sleep(
                self.backoff_seconds
                * (2**attempt)
                * (0.5 + 0.5 * random.random())
            )
        raise AssertionError("unreachable")  # pragma: no cover

    def fetch(self, url: str) -> Series:
        resp = self._get_with_retries(url)
        resp.raise_for_status()
        body = resp.json()
        if body.get("status") != "success":
            raise RuntimeError(f"prometheus error response: {body.get('error')}")
        result = body.get("data", {}).get("result", [])
        acc: dict[int, float] = {}
        for series in result:
            for t, v in series.get("values", []):
                try:
                    fv = float(v)
                    ft = int(float(t))
                except (TypeError, ValueError):
                    continue
                if not math.isfinite(fv):
                    # Prometheus emits "NaN"/"+Inf" strings (e.g. 0/0 in a
                    # recording rule); float() parses them fine, so they
                    # must be dropped explicitly or they poison the window
                    continue
                acc[ft] = acc.get(ft, 0.0) + fv
        if not acc:
            return _empty()
        ts = np.asarray(sorted(acc), np.int64)
        vs = np.asarray([acc[t] for t in ts], np.float32)
        return ts, vs


def load_csv_trace(path: str, t0: int | None = None, step: int = 60) -> Series:
    """Load a `timestamp,value` or `value`-per-line CSV trace (the demo's
    data1/data2 format: `YYYY-MM-DD HH:MM:SS,value`).

    Tolerant of real-world exports: an empty file yields the empty
    series (the brain then judges UNKNOWN, not a crash), and
    timestamped rows are STABLY sorted — an unsorted export would
    otherwise produce an out-of-order window that breaks every
    step-inference and gap-anchoring consumer downstream. Duplicate
    timestamps are kept (stable: file order within a timestamp run):
    the demo's replay traces record several observations per coarse
    5-min stamp, and collapsing them would starve the min-points gates.
    Synthetic timelines (`t0` given, or value-only rows) are generated
    in order and skip the sort."""
    ts: list[int] = []
    vs: list[float] = []
    with open(path) as f:
        for row in csv.reader(f):
            if not row:
                continue
            if len(row) == 1:
                vs.append(float(row[0]))
                ts.append(0)
            else:
                raw = row[0].strip()
                try:
                    t = int(float(raw))
                except ValueError:
                    t = int(
                        datetime.strptime(raw, "%Y-%m-%d %H:%M:%S")
                        .replace(tzinfo=timezone.utc)
                        .timestamp()
                    )
                ts.append(t)
                vs.append(float(row[1]))
    times = np.asarray(ts, np.int64)
    values = np.asarray(vs, np.float32)
    if t0 is not None or (len(times) and (times == 0).all()):
        base = 0 if t0 is None else t0
        return base + step * np.arange(len(vs), dtype=np.int64), values
    if len(times) > 1 and not (np.diff(times) >= 0).all():
        order = np.argsort(times, kind="stable")
        times = times[order]
        values = values[order]
    return times, values


class ReplaySource(MetricSource):
    """Serves canned traces by substring match against the fetched URL.

    Register patterns most-specific first; an unmatched URL returns an
    empty series (the brain then yields UNKNOWN, not a crash).
    """

    concurrent_fetch = False

    def __init__(self):
        self._routes: list[tuple[str, Callable[[], Series]]] = []

    def register(self, pattern: str, series: Series | Callable[[], Series]):
        fn = series if callable(series) else (lambda s=series: s)
        self._routes.append((pattern, fn))
        return self

    def register_csv(self, pattern: str, path: str, t0: int | None = None):
        return self.register(pattern, lambda: load_csv_trace(path, t0=t0))

    def fetch(self, url: str) -> Series:
        from urllib.parse import unquote

        target = unquote(url)
        for pattern, fn in self._routes:
            if pattern in target:
                return fn()
        return _empty()


class StaticSource(MetricSource):
    """alias-keyed direct map (unit tests)."""

    concurrent_fetch = False

    def __init__(self, data: Mapping[str, Series]):
        self.data = dict(data)

    def fetch(self, url: str) -> Series:
        for key, series in self.data.items():
            if key in url:
                return series
        return _empty()
