"""foremast-tpu: a TPU-native application-health and canary-analysis framework.

A ground-up JAX/XLA re-design of the capabilities of Intuit Foremast
(reference: pzou1974/foremast-1). Where the reference runs a shared-nothing
CPU worker pool scoring one job at a time (reference
`docs/guides/design.md:35-43`), this framework treats
(service x metric x window) as array dimensions of one jit-compiled batched
scoring program, sharded across TPU chips over ICI via `jax.sharding`.

Layers (mirrors SURVEY.md section 7 build plan):
  ops/       pure-JAX masked window math: forecasters, rank tests, bounds
  models/    learned detectors (LSTM-autoencoder, bivariate normal, seasonal)
  parallel/  mesh construction, shard_map scoring, sequence parallelism
  engine/    HealthScorer + worker loop (the "brain" equivalent)
  jobs/      idempotent job store + status state machine (the "service" data plane)
  service/   REST facade (healthcheck create/status, query proxy)
  metrics/   metric sources (Prometheus/replay), PromQL builder, gauge exporter
  watcher/   deployment watch + remediation (the "barrelman" equivalent)
"""

__version__ = "0.1.0"
