"""Deploy manifests, generated — the `deploy/` tree as code.

The reference ships a hand-maintained manifest tree whose numbered dirs
encode install order (`deploy/foremast/{00namespace,1_crds,2_barrelman,
3_brain}`, SURVEY.md §2.7). This module *generates* the equivalent tree
for the TPU framework so the CRD schemas, env-var matrix, ports, and
RBAC verbs are derived from the same Python definitions the runtime uses
(`watch/crds.py`, `config.BrainConfig`, `metrics/rules.py`) and can never
drift from them. `python -m foremast_tpu.deploy deploy/` re-renders the
checked-in tree; a test asserts it is current.

Manifest parity map (reference -> here):
  deploy/foremast/00namespace.yaml            -> 00namespace.yaml
  deploy/foremast/1_crds/*.yaml               -> 1_crds/*.yaml (same group/
      kind/plural so reference CRs apply unchanged)
  deploy/foremast/2_barrelman/*               -> 2_watch/* (watch-plane RBAC,
      controller Deployment, default DeploymentMetadata, recording rules)
  deploy/foremast/3_brain/{es,foremast-service,foremast-brain}.yaml
      -> 3_engine/{es,foremast-service,foremast-engine}.yaml; the engine
      carries the full brain env matrix (`foremast-brain.yaml:21-81`) plus
      the gauge ServiceMonitor on :8000 (`foremast-brain.yaml:87-122`)
  deploy/prometheus-operator/0additional-scrape-configs.yaml
      -> prometheus/additional-scrape-configs.yaml (pod-annotation scrape)
  deploy/minikube.sh, deploy/export/*.sh      -> same names
"""

from __future__ import annotations

from foremast_tpu.config import BrainConfig, MetricTypeRule, _DEFAULT_RULES
from foremast_tpu.metrics.rules import prometheus_rule_manifest
from foremast_tpu.watch.crds import API_VERSION, GROUP, VERSION

NAMESPACE = "foremast"
IMAGE = "foremast/foremast-tpu:0.1.0"

# ---------------------------------------------------------------------------
# CRDs — openAPIV3 schemas derived from the watch/crds.py dataclasses.
# ---------------------------------------------------------------------------

_STR = {"type": "string"}
_BOOL = {"type": "boolean"}
_INT = {"type": "integer"}
_OBJ = {"type": "object"}
_STR_MAP = {"type": "object", "additionalProperties": _STR}


def _crd(kind: str, plural: str, spec_schema: dict, status_schema: dict | None) -> dict:
    props = {"spec": spec_schema}
    if status_schema is not None:
        props["status"] = status_schema
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": kind.lower(),
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}} if status_schema else {},
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": props,
                        }
                    },
                }
            ],
        },
    }


def deployment_metadata_crd() -> dict:
    """DeploymentMetadata: per-app config CR (types.go:14-156 parity)."""
    spec = {
        "type": "object",
        "properties": {
            "analyst": {
                "type": "object",
                "properties": {"endpoint": _STR},
            },
            "metrics": {
                "type": "object",
                "properties": {
                    "source": _STR,
                    "endpoint": _STR,
                    "monitoring": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {
                                "metricName": _STR,
                                "metricType": _STR,
                                "metricAlias": _STR,
                            },
                            "required": ["metricName"],
                        },
                    },
                },
            },
            "logs": _OBJ,
            "descriptor": _OBJ,
        },
    }
    return _crd("DeploymentMetadata", "deploymentmetadatas", spec, None)


def deployment_monitor_crd() -> dict:
    """DeploymentMonitor: per-deployment runtime CR (types.go:175-295)."""
    spec = {
        "type": "object",
        "properties": {
            "selector": _STR_MAP,
            "analyst": {"type": "object", "properties": {"endpoint": _STR}},
            "startTime": _STR,
            "waitUntil": _STR,
            "metrics": _OBJ,
            "continuous": _BOOL,
            "remediation": {
                "type": "object",
                "properties": {
                    "option": {
                        "type": "string",
                        "enum": ["None", "AutoRollback", "AutoPause", "Auto"],
                    },
                    "parameters": _OBJ,
                },
            },
            "rollbackRevision": _INT,
        },
    }
    status = {
        "type": "object",
        "properties": {
            "jobId": _STR,
            "phase": {
                "type": "string",
                "enum": [
                    "",
                    "Healthy",
                    "Running",
                    "Failed",
                    "Unhealthy",
                    "Warning",
                    "Expired",
                    "Abort",
                ],
            },
            "remediationTaken": _BOOL,
            "anomaly": _OBJ,
            "timestamp": _STR,
            "expired": _BOOL,
        },
    }
    return _crd("DeploymentMonitor", "deploymentmonitors", spec, status)


# ---------------------------------------------------------------------------
# Namespace / RBAC / watch plane
# ---------------------------------------------------------------------------


def namespace() -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": NAMESPACE},
    }


def watch_rbac() -> list[dict]:
    """ClusterRole covering what the watch plane touches: Deployments
    (watch/diff/rollback/pause), ReplicaSets+Pods (pod discovery), Events,
    and both CRDs (reference RBAC: foremast-barrelman-rbac.yaml)."""
    name = "foremast-watch"
    return [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": name, "namespace": NAMESPACE},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": name},
            "rules": [
                {
                    "apiGroups": ["apps", "extensions"],
                    "resources": [
                        "deployments",
                        "deployments/rollback",
                        "replicasets",
                    ],
                    "verbs": ["get", "list", "watch", "update", "patch", "create"],
                },
                {
                    "apiGroups": [""],
                    "resources": ["pods", "namespaces"],
                    "verbs": ["get", "list", "watch"],
                },
                {
                    "apiGroups": [""],
                    "resources": ["events"],
                    "verbs": ["create", "patch"],
                },
                {
                    "apiGroups": [GROUP],
                    "resources": ["deploymentmetadatas", "deploymentmonitors"],
                    "verbs": [
                        "get",
                        "list",
                        "watch",
                        "create",
                        "update",
                        "patch",
                        "delete",
                    ],
                },
                {
                    "apiGroups": [GROUP],
                    "resources": ["deploymentmonitors/status"],
                    "verbs": ["get", "update", "patch"],
                },
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": name},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": name,
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": name,
                    "namespace": NAMESPACE,
                }
            ],
        },
    ]


def _container(name: str, args: list[str], env: list[dict], ports: list[dict],
               cpu: str = "100m", memory: str = "128Mi",
               probe_path: str | None = None, probe_port: int | None = None) -> dict:
    c = {
        "name": name,
        "image": IMAGE,
        "imagePullPolicy": "IfNotPresent",
        "command": ["foremast"],
        "args": args,
        "env": env,
        "ports": ports,
        "resources": {
            "requests": {"cpu": cpu, "memory": memory},
            "limits": {"cpu": cpu, "memory": memory},
        },
    }
    if probe_path and probe_port:
        probe = {
            "httpGet": {"path": probe_path, "port": probe_port},
            "initialDelaySeconds": 5,
            "periodSeconds": 10,
        }
        c["readinessProbe"] = probe
        c["livenessProbe"] = {**probe, "initialDelaySeconds": 30}
    return c


def _deployment(name: str, container: dict, sa: str | None = None,
                replicas: int = 1, namespace: str | None = None,
                scrape: bool = True) -> dict:
    meta: dict = {"labels": {"app": name}}
    if scrape:
        meta["annotations"] = {"prometheus.io/scrape": "true"}
    spec: dict = {
        "replicas": replicas,
        "selector": {"matchLabels": {"app": name}},
        "template": {
            "metadata": meta,
            "spec": {"containers": [container]},
        },
    }
    if sa:
        spec["template"]["spec"]["serviceAccountName"] = sa
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "namespace": namespace or NAMESPACE,
            "labels": {"app": name},
        },
        "spec": spec,
    }


def watch_deployment() -> dict:
    """The watch-plane controller (`foremast watch-plane`): informer-style
    Deployment watcher + status poller + remediation (reference:
    foremast-barrelman.yaml, 100m/30Mi budget)."""
    env = [
        {
            "name": "NAMESPACE",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.namespace"}},
        },
        {
            "name": "ANALYST_ENDPOINT",
            "value": f"http://foremast-service.{NAMESPACE}.svc:8099/v1/healthcheck/",
        },
        {
            "name": "METRICS_ENDPOINT",
            "value": "http://prometheus-k8s.monitoring.svc:9090/",
        },
    ]
    c = _container("foremast-watch", ["watch-plane"], env, [], cpu="100m", memory="64Mi")
    return _deployment("foremast-watch", c, sa="foremast-watch")


def default_metadata_cr() -> dict:
    """Cluster default DeploymentMetadata (`deployment-metadata-default.yaml`
    role): the appType fallback record the watcher resolves when an app has
    no metadata of its own (Barrelman.go:139-174 semantics)."""
    monitored = [
        {
            "metricName": "namespace_app_per_pod:http_server_requests_latency",
            "metricType": "latency",
            "metricAlias": "latency",
        },
        {
            "metricName": "namespace_app_per_pod:http_server_requests_error_5xx",
            "metricType": "error5xx",
            "metricAlias": "error5xx",
        },
        {
            "metricName": "namespace_app_per_pod:http_server_requests_error_4xx",
            "metricType": "error4xx",
            "metricAlias": "error4xx",
        },
        {
            "metricName": "namespace_app_per_pod:http_server_requests_count",
            "metricType": "tps",
            "metricAlias": "tps",
        },
    ]
    return {
        "apiVersion": API_VERSION,
        "kind": "DeploymentMetadata",
        "metadata": {"name": "default", "namespace": NAMESPACE},
        "spec": {
            "analyst": {
                "endpoint": f"http://foremast-service.{NAMESPACE}.svc:8099/v1/healthcheck/"
            },
            "metrics": {
                "source": "prometheus",
                "endpoint": "http://prometheus-k8s.monitoring.svc:9090/",
                "monitoring": monitored,
            },
        },
    }


# ---------------------------------------------------------------------------
# Engine plane: ES, REST service, TPU scoring engine
# ---------------------------------------------------------------------------


def elasticsearch() -> list[dict]:
    """Single-node ES for the durable job store (reference es.yaml role)."""
    name = "elasticsearch"
    return [
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": NAMESPACE},
            "spec": {
                "selector": {"app": name},
                "ports": [{"name": "http", "port": 9200, "targetPort": 9200}],
            },
        },
        {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {"name": name, "namespace": NAMESPACE},
            "spec": {
                "serviceName": name,
                "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": {
                        "containers": [
                            {
                                "name": name,
                                "image": "docker.elastic.co/elasticsearch/elasticsearch-oss:6.8.23",
                                "env": [
                                    {"name": "discovery.type", "value": "single-node"},
                                    {"name": "ES_JAVA_OPTS", "value": "-Xms512m -Xmx512m"},
                                ],
                                "ports": [{"containerPort": 9200}],
                                "resources": {
                                    "requests": {"cpu": "500m", "memory": "1Gi"},
                                    "limits": {"cpu": "1", "memory": "1536Mi"},
                                },
                                "volumeMounts": [
                                    {"name": "data", "mountPath": "/usr/share/elasticsearch/data"}
                                ],
                            }
                        ],
                        "volumes": [{"name": "data", "emptyDir": {}}],
                    },
                },
            },
        },
    ]


def service_deployment() -> list[dict]:
    """REST job gateway on :8099 (`foremast serve`; reference
    foremast-service.yaml, routes main.go:262-276)."""
    env = [
        {"name": "ELASTIC_URL", "value": f"http://elasticsearch.{NAMESPACE}.svc:9200"},
        {
            "name": "QUERY_SERVICE_ENDPOINT",
            "value": "http://prometheus-k8s.monitoring.svc:9090/",
        },
    ]
    c = _container(
        "foremast-service",
        ["serve", "--port", "8099"],
        env,
        [{"containerPort": 8099, "name": "http"}],
        cpu="100m",
        memory="64Mi",
        probe_path="/healthz",
        probe_port=8099,
    )
    return [
        _deployment("foremast-service", c),
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": "foremast-service", "namespace": NAMESPACE},
            "spec": {
                "selector": {"app": "foremast-service"},
                "ports": [{"name": "http", "port": 8099, "targetPort": 8099}],
            },
        },
    ]


def _rule_env(rules: tuple[MetricTypeRule, ...]) -> list[dict]:
    """The reference's indexed env-var family for the per-metric-type
    threshold matrix (`foremast-brain.yaml:32-73`)."""
    out: list[dict] = [
        {"name": "metric_type_threshold_count", "value": str(len(rules))}
    ]
    for i, r in enumerate(rules):
        out += [
            {"name": f"metric_type{i}", "value": r.metric_type},
            {"name": f"threshold{i}", "value": _num(r.threshold)},
            {"name": f"bound{i}", "value": str(r.bound)},
            {"name": f"min_lower_bound{i}", "value": _num(r.min_lower_bound)},
        ]
    return out


def _num(x: float) -> str:
    return str(int(x)) if float(x) == int(x) else str(x)


def engine_deployment(cfg: BrainConfig | None = None) -> list[dict]:
    """The TPU scoring engine (`foremast worker`) — reference
    foremast-brain.yaml role, but one jitted batch program per TPU host
    instead of N CPU slivers. Env matrix mirrors BrainConfig.from_env.
    Publishes foremastbrain:* gauges on :8000, scraped by a ServiceMonitor
    (foremast-brain.yaml:87-122)."""
    cfg = cfg or BrainConfig()
    name = "foremast-engine"
    env = [
        {"name": "ES_ENDPOINT", "value": f"http://elasticsearch.{NAMESPACE}.svc:9200"},
        {"name": "ML_ALGORITHM", "value": cfg.algorithm},
        {"name": "threshold", "value": _num(cfg.anomaly.threshold)},
        {"name": "min_lower_bound", "value": _num(cfg.anomaly.min_lower_bound)},
        {"name": "bound", "value": str(cfg.anomaly.bound)},
        *_rule_env(_DEFAULT_RULES),
        {"name": "MIN_MANN_WHITE_DATA_POINTS", "value": str(cfg.pairwise.min_mann_white_points)},
        {"name": "MIN_WILCOXON_DATA_POINTS", "value": str(cfg.pairwise.min_wilcoxon_points)},
        {"name": "MIN_KRUSKAL_DATA_POINTS", "value": str(cfg.pairwise.min_kruskal_points)},
        {"name": "ML_PAIRWISE_ALGORITHM", "value": cfg.pairwise.algorithm},
        {"name": "MAX_STUCK_IN_SECONDS", "value": _num(cfg.max_stuck_seconds)},
        {"name": "MAX_CACHE_SIZE", "value": str(cfg.max_cache_size)},
    ]
    c = _container(
        name,
        ["worker", "--gauge-port", "8000", "--sharded"],
        env,
        [{"containerPort": 8000, "name": "gauges"}],
        cpu="4",
        memory="8Gi",
        # the gauge exposition doubles as the health surface
        probe_path="/metrics",
        probe_port=8000,
    )
    # TPU scheduling: one worker per TPU host; the engine shards its batch
    # over the host's chips via jax.sharding (parallel/mesh.py).
    c["resources"]["limits"]["google.com/tpu"] = 8
    c["resources"]["requests"]["google.com/tpu"] = 8
    dep = _deployment(name, c)
    dep["spec"]["template"]["spec"]["nodeSelector"] = {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4",
    }
    return [
        dep,
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": name,
                "namespace": NAMESPACE,
                "labels": {"app": name},
            },
            "spec": {
                "selector": {"app": name},
                "ports": [{"name": "gauges", "port": 8000, "targetPort": 8000}],
            },
        },
        {
            "apiVersion": "monitoring.coreos.com/v1",
            "kind": "ServiceMonitor",
            "metadata": {
                "name": name,
                "namespace": NAMESPACE,
                "labels": {"app": name},
            },
            "spec": {
                "selector": {"matchLabels": {"app": name}},
                "endpoints": [{"port": "gauges", "interval": "15s"}],
                "namespaceSelector": {"matchNames": [NAMESPACE]},
            },
        },
    ]


def ui_deployment() -> list[dict]:
    """The dashboard (`foremast ui`) — reference foremast-browser role."""
    # NOTE: the endpoint is fetched by the *viewer's browser*, not the UI
    # pod, so it must be browser-reachable. Default matches the
    # export-service.sh port-forward; point it at your ingress in prod.
    env = [
        {
            "name": "FOREMAST_SERVICE_ENDPOINT",
            "value": "http://localhost:8099",
        }
    ]
    c = _container(
        "foremast-ui",
        ["ui", "--port", "8080"],
        env,
        [{"containerPort": 8080, "name": "http"}],
        cpu="100m",
        memory="64Mi",
        probe_path="/healthz",
        probe_port=8080,
    )
    return [
        _deployment("foremast-ui", c),
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": "foremast-ui", "namespace": NAMESPACE},
            "spec": {
                "selector": {"app": "foremast-ui"},
                "ports": [{"name": "http", "port": 8080, "targetPort": 8080}],
            },
        },
    ]


def scrape_config_secret() -> dict:
    """Pod-annotation scrape job for Prometheus (role of the reference's
    base64 `0additional-scrape-configs.yaml`): scrape any pod annotated
    prometheus.io/scrape=true, relabeling namespace/pod."""
    job = """\
- job_name: kubernetes-pods-scrape
  kubernetes_sd_configs:
    - role: pod
  relabel_configs:
    - source_labels: [__meta_kubernetes_pod_annotation_prometheus_io_scrape]
      action: keep
      regex: "true"
    - source_labels: [__meta_kubernetes_pod_annotation_prometheus_io_path]
      action: replace
      target_label: __metrics_path__
      regex: (.+)
    - source_labels: [__address__, __meta_kubernetes_pod_annotation_prometheus_io_port]
      action: replace
      regex: ([^:]+)(?::\\d+)?;(\\d+)
      replacement: $1:$2
      target_label: __address__
    - source_labels: [__meta_kubernetes_namespace]
      action: replace
      target_label: namespace
    - source_labels: [__meta_kubernetes_pod_name]
      action: replace
      target_label: pod
    - source_labels: [__meta_kubernetes_pod_label_app]
      action: replace
      target_label: app
"""
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {
            "name": "additional-scrape-configs",
            "namespace": "monitoring",
        },
        "stringData": {"prometheus-additional.yaml": job},
    }


# ---------------------------------------------------------------------------
# Standalone monitoring stack (role of the reference's
# deploy/prometheus-operator/ kube-prometheus bundle)
# ---------------------------------------------------------------------------
#
# The reference vendors the full kube-prometheus manifests; this tree
# instead GENERATES a minimal self-contained stack — Prometheus with the
# pod-annotation scrape job and the recording rules as native rule files,
# kube-state-metrics (the rules' kube_pod_labels join needs it), and
# Grafana pre-provisioned with the Prometheus datasource — so
# docs/quickstart.md works on an empty cluster with nothing but
# `kubectl apply`. Operator users can skip this dir and use
# `additional-scrape-configs.yaml` + the PrometheusRule CR instead.

MONITORING_NAMESPACE = "monitoring"


def monitoring_namespace() -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": MONITORING_NAMESPACE},
    }


def _scrape_job_yaml() -> str:
    """The pod-annotation scrape job (shared with the operator secret)."""
    return scrape_config_secret()["stringData"]["prometheus-additional.yaml"]


def prometheus_rbac() -> list[dict]:
    return [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": "prometheus", "namespace": MONITORING_NAMESPACE},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "foremast-prometheus"},
            "rules": [
                {
                    "apiGroups": [""],
                    "resources": [
                        "nodes",
                        "nodes/metrics",
                        "services",
                        "endpoints",
                        "pods",
                    ],
                    "verbs": ["get", "list", "watch"],
                },
                {"nonResourceURLs": ["/metrics"], "verbs": ["get"]},
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "foremast-prometheus"},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "foremast-prometheus",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": "prometheus",
                    "namespace": MONITORING_NAMESPACE,
                }
            ],
        },
    ]


def prometheus_config() -> dict:
    """prometheus.yml + the recording rules as a native rule file (no
    operator needed; same groups as the PrometheusRule CR)."""
    import yaml as _yaml

    rules_spec = prometheus_rule_manifest()["spec"]
    prometheus_yml = (
        "global:\n"
        "  scrape_interval: 30s\n"
        "  evaluation_interval: 30s\n"
        "rule_files:\n"
        "  - /etc/prometheus/rules.yml\n"
        # route the generated Foremast* alert rules (metrics/rules.py)
        # to the stack's Alertmanager (alertmanager() below)
        "alerting:\n"
        "  alertmanagers:\n"
        "    - static_configs:\n"
        "        - targets: ['alertmanager-main.monitoring.svc:9093']\n"
        "scrape_configs:\n"
        "  - job_name: kube-state-metrics\n"
        "    static_configs:\n"
        "      - targets: ['kube-state-metrics.monitoring.svc:8080']\n"
        + "\n".join("  " + ln for ln in _scrape_job_yaml().splitlines())
        + "\n"
    )
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": "prometheus-config",
            "namespace": MONITORING_NAMESPACE,
        },
        "data": {
            "prometheus.yml": prometheus_yml,
            "rules.yml": _yaml.safe_dump(rules_spec, sort_keys=False),
        },
    }


def prometheus_deployment() -> list[dict]:
    dep = _deployment(
        "prometheus-k8s",
        {
            "name": "prometheus",
            "image": "prom/prometheus:v2.53.0",
            "args": [
                "--config.file=/etc/prometheus/prometheus.yml",
                "--storage.tsdb.path=/prometheus",
                "--storage.tsdb.retention.time=7d",
            ],
            "ports": [{"containerPort": 9090}],
            "volumeMounts": [
                {"name": "config", "mountPath": "/etc/prometheus"},
                {"name": "data", "mountPath": "/prometheus"},
            ],
            "resources": {
                "requests": {"cpu": "250m", "memory": "512Mi"},
                "limits": {"memory": "2Gi"},
            },
        },
        sa="prometheus",
        namespace=MONITORING_NAMESPACE,
        scrape=False,
    )
    dep["spec"]["template"]["spec"]["volumes"] = [
        {"name": "config", "configMap": {"name": "prometheus-config"}},
        {"name": "data", "emptyDir": {}},
    ]
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": "prometheus-k8s",  # the endpoint every foremast
            "namespace": MONITORING_NAMESPACE,  # component points at
        },
        "spec": {
            "selector": {"app": "prometheus-k8s"},
            "ports": [{"port": 9090, "targetPort": 9090}],
        },
    }
    return [dep, svc]


def kube_state_metrics() -> list[dict]:
    sa = {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {
            "name": "kube-state-metrics",
            "namespace": MONITORING_NAMESPACE,
        },
    }
    role = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "foremast-kube-state-metrics"},
        "rules": [
            {
                "apiGroups": [""],
                "resources": [
                    "pods",
                    "nodes",
                    "namespaces",
                    "services",
                    "endpoints",
                ],
                "verbs": ["list", "watch"],
            },
            {
                "apiGroups": ["apps"],
                "resources": ["deployments", "replicasets", "statefulsets"],
                "verbs": ["list", "watch"],
            },
        ],
    }
    binding = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": "foremast-kube-state-metrics"},
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": "foremast-kube-state-metrics",
        },
        "subjects": [
            {
                "kind": "ServiceAccount",
                "name": "kube-state-metrics",
                "namespace": MONITORING_NAMESPACE,
            }
        ],
    }
    dep = _deployment(
        "kube-state-metrics",
        {
            "name": "kube-state-metrics",
            "image": "registry.k8s.io/kube-state-metrics/kube-state-metrics:v2.12.0",
            "args": ["--metric-labels-allowlist=pods=[app]"],
            "ports": [{"containerPort": 8080}],
            "resources": {
                "requests": {"cpu": "50m", "memory": "64Mi"},
                "limits": {"memory": "256Mi"},
            },
        },
        sa="kube-state-metrics",
        namespace=MONITORING_NAMESPACE,
        scrape=False,
    )
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": "kube-state-metrics",
            "namespace": MONITORING_NAMESPACE,
        },
        "spec": {
            "selector": {"app": "kube-state-metrics"},
            "ports": [{"port": 8080, "targetPort": 8080}],
        },
    }
    return [sa, role, binding, dep, svc]


def alertmanager_config_yaml() -> str:
    """The default route/receiver config, with the reference bundle's
    cadence (`deploy/prometheus-operator/alertmanager-secret.yaml` —
    base64 of: resolve 5m, 30s group_wait / 5m group_interval / 12h
    repeat, one default receiver). Two deliberate divergences: grouping
    keys on ['alertname', 'app'] instead of the reference's ['job']
    because every generated Foremast* alert is app-scoped (one page per
    service, not one per scrape job), and the receiver is a stub the
    operator points at their pager bridge instead of the operator
    bundle's 'null' sink — `kubectl edit configmap alertmanager-config`
    is the whole integration step."""
    return (
        "global:\n"
        "  resolve_timeout: 5m\n"
        "route:\n"
        "  group_by: ['alertname', 'app']\n"
        "  group_wait: 30s\n"
        "  group_interval: 5m\n"
        "  repeat_interval: 12h\n"
        "  receiver: 'default'\n"
        "receivers:\n"
        "  - name: 'default'\n"
        "    # point this at your pager/chat bridge; an unset webhook list\n"
        "    # keeps alerts visible in the Alertmanager UI/API only\n"
    )


def alertmanager() -> list[dict]:
    """Self-contained Alertmanager (role of the reference's
    alertmanager-{alertmanager,service,secret,serviceAccount}.yaml
    operator bundle): the ForemastAnomaly_*/Foremast*Breach_*/
    ForemastEngineDown rules (`metrics/rules.alert_rules`) evaluate in
    Prometheus and ROUTE here — grouping, silences, and receivers
    included; without it the alert rules fire into the void."""
    cm = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": "alertmanager-config",
            "namespace": MONITORING_NAMESPACE,
        },
        "data": {"alertmanager.yml": alertmanager_config_yaml()},
    }
    dep = _deployment(
        "alertmanager-main",
        {
            "name": "alertmanager",
            "image": "prom/alertmanager:v0.27.0",
            "args": [
                "--config.file=/etc/alertmanager/alertmanager.yml",
                "--storage.path=/alertmanager",
            ],
            "ports": [{"containerPort": 9093, "name": "web"}],
            "volumeMounts": [
                {"name": "config", "mountPath": "/etc/alertmanager"},
                {"name": "data", "mountPath": "/alertmanager"},
            ],
            "resources": {
                "requests": {"cpu": "20m", "memory": "64Mi"},
                "limits": {"memory": "256Mi"},
            },
        },
        namespace=MONITORING_NAMESPACE,
        scrape=False,
    )
    dep["spec"]["template"]["spec"]["volumes"] = [
        {"name": "config", "configMap": {"name": "alertmanager-config"}},
        {"name": "data", "emptyDir": {}},
    ]
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            # same service name as the reference bundle
            # (alertmanager-service.yaml) so runbooks port to it directly
            "name": "alertmanager-main",
            "namespace": MONITORING_NAMESPACE,
        },
        "spec": {
            "selector": {"app": "alertmanager-main"},
            "ports": [{"name": "web", "port": 9093, "targetPort": 9093}],
        },
    }
    return [cm, dep, svc]


def node_exporter() -> list[dict]:
    """node-exporter DaemonSet + Service (role of the reference's
    node-exporter-{daemonset,service,serviceAccount}.yaml): host CPU/
    memory feed the cpu/memory metric types of the threshold matrix
    (`foremast-brain.yaml:56-73`). Pods carry the stack's scrape
    annotations, so the existing pod-annotation job collects them — no
    kube-rbac-proxy sidecar (the reference's secure-scrape proxy; this
    self-contained stack scrapes in-cluster HTTP directly)."""
    sa = {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {
            "name": "node-exporter",
            "namespace": MONITORING_NAMESPACE,
        },
    }
    ds = {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {
            "name": "node-exporter",
            "namespace": MONITORING_NAMESPACE,
            "labels": {"app": "node-exporter"},
        },
        "spec": {
            "selector": {"matchLabels": {"app": "node-exporter"}},
            "template": {
                "metadata": {
                    "labels": {"app": "node-exporter"},
                    "annotations": {
                        "prometheus.io/scrape": "true",
                        "prometheus.io/port": "9100",
                    },
                },
                "spec": {
                    "serviceAccountName": "node-exporter",
                    "hostNetwork": True,
                    "hostPID": True,
                    "securityContext": {
                        "runAsNonRoot": True,
                        "runAsUser": 65534,
                    },
                    "tolerations": [
                        {
                            "key": "node-role.kubernetes.io/master",
                            "effect": "NoSchedule",
                        },
                        {
                            "key": "node-role.kubernetes.io/control-plane",
                            "effect": "NoSchedule",
                        },
                    ],
                    "containers": [
                        {
                            "name": "node-exporter",
                            "image": "quay.io/prometheus/node-exporter:v1.8.1",
                            "args": [
                                # same collector surface as the reference
                                # daemonset (node-exporter-daemonset.yaml),
                                # minus the localhost+proxy split
                                "--path.procfs=/host/proc",
                                "--path.sysfs=/host/sys",
                                (
                                    "--collector.filesystem.mount-points-exclude="
                                    "^/(dev|proc|sys|var/lib/docker/.+)($|/)"
                                ),
                            ],
                            "ports": [
                                {"containerPort": 9100, "name": "metrics"}
                            ],
                            "resources": {
                                "requests": {"cpu": "50m", "memory": "64Mi"},
                                "limits": {"memory": "180Mi"},
                            },
                            "volumeMounts": [
                                {
                                    "name": "proc",
                                    "mountPath": "/host/proc",
                                    "readOnly": True,
                                },
                                {
                                    "name": "sys",
                                    "mountPath": "/host/sys",
                                    "readOnly": True,
                                },
                            ],
                        }
                    ],
                    "volumes": [
                        {"name": "proc", "hostPath": {"path": "/proc"}},
                        {"name": "sys", "hostPath": {"path": "/sys"}},
                    ],
                },
            },
        },
    }
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": "node-exporter",
            "namespace": MONITORING_NAMESPACE,
            "labels": {"app": "node-exporter"},
        },
        "spec": {
            "clusterIP": "None",
            "selector": {"app": "node-exporter"},
            "ports": [{"name": "metrics", "port": 9100, "targetPort": 9100}],
        },
    }
    return [sa, ds, svc]


def grafana_dashboard() -> dict:
    """A Grafana dashboard generated from the SAME panel spec the built-in
    UI renders (`ui/metrics.DEFAULT_PANELS`) — base series, model band,
    and anomaly gauge per metric, parameterized by $namespace/$app — so
    the Grafana view can never drift from what the engine publishes."""
    import json as _json

    from foremast_tpu.ui.metrics import DEFAULT_PANELS

    panels = []
    for i, p in enumerate(DEFAULT_PANELS):
        scale = "" if p.scale == 1.0 else f" * {p.scale}"
        targets = []
        for s in p.series("$namespace", "$app"):
            targets.append(
                {
                    "expr": s["query"] + scale,
                    "legendFormat": s["type"],
                    "refId": chr(ord("A") + len(targets)),
                }
            )
        panels.append(
            {
                "id": i + 1,
                "type": "timeseries",
                "title": f"{p.common_name} ({p.unit})",
                "gridPos": {
                    "x": (i % 2) * 12,
                    "y": (i // 2) * 8,
                    "w": 12,
                    "h": 8,
                },
                "datasource": {"type": "prometheus", "uid": "prometheus"},
                "targets": targets,
            }
        )
    dashboard = {
        "uid": "foremast",
        "title": "Foremast — application health",
        "tags": ["foremast"],
        "timezone": "browser",
        "refresh": "15s",  # the reference UI's poll (App.js:20,78)
        "time": {"from": "now-1h", "to": "now"},
        "templating": {
            "list": [
                {
                    "name": "namespace",
                    "type": "query",
                    "datasource": {"type": "prometheus", "uid": "prometheus"},
                    "query": "label_values(namespace_app:pod_count, namespace)",
                    "refresh": 2,
                },
                {
                    "name": "app",
                    "type": "query",
                    "datasource": {"type": "prometheus", "uid": "prometheus"},
                    "query": 'label_values(namespace_app:pod_count{namespace="$namespace"}, app)',
                    "refresh": 2,
                },
            ]
        },
        "panels": panels,
        "schemaVersion": 39,
    }
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": "grafana-dashboard-foremast",
            "namespace": MONITORING_NAMESPACE,
        },
        "data": {"foremast.json": _json.dumps(dashboard, indent=1)},
    }


def grafana() -> list[dict]:
    provider = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": "grafana-dashboard-provider",
            "namespace": MONITORING_NAMESPACE,
        },
        "data": {
            "provider.yaml": (
                "apiVersion: 1\n"
                "providers:\n"
                "  - name: foremast\n"
                "    folder: ''\n"
                "    type: file\n"
                "    options:\n"
                "      path: /var/lib/grafana/dashboards\n"
            )
        },
    }
    datasource = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": "grafana-datasources",
            "namespace": MONITORING_NAMESPACE,
        },
        "data": {
            "datasources.yaml": (
                "apiVersion: 1\n"
                "datasources:\n"
                "  - name: Prometheus\n"
                "    uid: prometheus\n"
                "    type: prometheus\n"
                "    access: proxy\n"
                "    url: http://prometheus-k8s.monitoring.svc:9090\n"
                "    isDefault: true\n"
            )
        },
    }
    dep = _deployment(
        "grafana",
        {
            "name": "grafana",
            "image": "grafana/grafana:11.1.0",
            "ports": [{"containerPort": 3000}],
            "env": [
                {"name": "GF_AUTH_ANONYMOUS_ENABLED", "value": "true"},
                {"name": "GF_AUTH_ANONYMOUS_ORG_ROLE", "value": "Admin"},
            ],
            "volumeMounts": [
                {
                    "name": "datasources",
                    "mountPath": "/etc/grafana/provisioning/datasources",
                },
                {
                    "name": "dashboard-provider",
                    "mountPath": "/etc/grafana/provisioning/dashboards",
                },
                {
                    "name": "dashboards",
                    "mountPath": "/var/lib/grafana/dashboards",
                },
            ],
            "resources": {
                "requests": {"cpu": "50m", "memory": "128Mi"},
                "limits": {"memory": "512Mi"},
            },
        },
        namespace=MONITORING_NAMESPACE,
        scrape=False,
    )
    dep["spec"]["template"]["spec"]["volumes"] = [
        {"name": "datasources", "configMap": {"name": "grafana-datasources"}},
        {
            "name": "dashboard-provider",
            "configMap": {"name": "grafana-dashboard-provider"},
        },
        {
            "name": "dashboards",
            "configMap": {"name": "grafana-dashboard-foremast"},
        },
    ]
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": "grafana", "namespace": MONITORING_NAMESPACE},
        "spec": {
            "selector": {"app": "grafana"},
            "ports": [{"port": 3000, "targetPort": 3000}],
        },
    }
    return [provider, grafana_dashboard(), datasource, dep, svc]


# ---------------------------------------------------------------------------
# Demo workload (reference examples/demo/{rollingUpdate,continuous})
# ---------------------------------------------------------------------------

DEMO_NAMESPACE = "foremast-examples"


def demo_deployment(version: str, args: list[str], continuous: bool = False) -> list[dict]:
    """demo_v1 (healthy) / demo_v2 (error-injecting) manifests.

    v2's args are the fault injector (reference: `-DerrorType=5xx
    -Dfrequency=6` in demo_v2.yaml; here the demo module's flags). The
    rolling-update pair shares one Deployment name so `kubectl apply`ing
    v2 over v1 IS the canary event; the continuous variant carries the
    kubectl-watch toggle instead.
    """
    name = "demo"
    c = {
        "name": name,
        "image": IMAGE,
        "imagePullPolicy": "IfNotPresent",
        "command": ["python", "-m", "foremast_tpu.demo"],
        "args": args,
        "ports": [{"containerPort": 8080, "name": "http"}],
        "resources": {
            "requests": {"cpu": "100m", "memory": "128Mi"},
            "limits": {"cpu": "200m", "memory": "256Mi"},
        },
    }
    dep = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "namespace": DEMO_NAMESPACE,
            "labels": {"app": name, "version": version},
        },
        "spec": {
            "replicas": 2,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {
                    "labels": {"app": name, "version": version},
                    "annotations": {
                        "prometheus.io/scrape": "true",
                        "prometheus.io/port": "8080",
                        "prometheus.io/path": "/metrics",
                    },
                },
                "spec": {"containers": [c]},
            },
        },
    }
    docs: list[dict] = [
        {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": DEMO_NAMESPACE},
        },
        dep,
    ]
    if continuous:
        docs.append(
            {
                "apiVersion": API_VERSION,
                "kind": "DeploymentMonitor",
                "metadata": {"name": name, "namespace": DEMO_NAMESPACE},
                "spec": {
                    "selector": {"app": name},
                    "analyst": {
                        "endpoint": f"http://foremast-service.{NAMESPACE}.svc:8099/v1/healthcheck/"
                    },
                    "continuous": True,
                    "remediation": {"option": "AutoRollback"},
                },
            }
        )
    return docs


# ---------------------------------------------------------------------------
# Shell helpers
# ---------------------------------------------------------------------------

MINIKUBE_SH = """\
#!/bin/sh
# Local demo cluster (reference deploy/minikube.sh footprint: 4 CPU / 6 GB).
minikube start --cpus 4 --memory 6144
minikube addons enable ingress
"""

EXPORT_SERVICE_SH = """\
#!/bin/sh
# Port-forward the job gateway to localhost:8099.
kubectl -n foremast port-forward svc/foremast-service 8099:8099
"""

EXPORT_PROMETHEUS_SH = """\
#!/bin/sh
# Port-forward Prometheus to localhost:9090.
kubectl -n monitoring port-forward svc/prometheus-k8s 9090:9090
"""

EXPORT_UI_SH = """\
#!/bin/sh
# Port-forward the dashboard to localhost:8080.
kubectl -n foremast port-forward svc/foremast-ui 8080:8080
"""

README = """\
# Deploying foremast-tpu on Kubernetes

Generated tree - do not edit by hand; run `python -m foremast_tpu.deploy deploy/`
after changing `foremast_tpu/deploy/manifests.py`.

Install order (numbered dirs, like the reference's deploy/foremast), from
an EMPTY cluster — no out-of-repo prerequisites:

    kubectl apply -f prometheus/00namespace.yaml
    kubectl apply -f prometheus/1_rbac/
    kubectl apply -f prometheus/2_stack/
    kubectl apply -f foremast/00namespace.yaml
    kubectl apply -f foremast/1_crds/
    kubectl apply -f foremast/2_watch/
    kubectl apply -f foremast/3_engine/

`prometheus/` is a minimal self-contained monitoring stack (role of the
reference's `deploy/prometheus-operator/` kube-prometheus bundle):
Prometheus with the pod-annotation scrape job and the generated recording
rules mounted as native rule files, kube-state-metrics (the rules'
`kube_pod_labels` join), Alertmanager on :9093 receiving the generated
`Foremast*` alert rules (edit `alertmanager-config` to point the default
receiver at your pager), node-exporter feeding the cpu/memory metric
types, and Grafana pre-provisioned with the Prometheus datasource on
:3000. If you already run prometheus-operator instead, skip
`prometheus/{00namespace.yaml,1_rbac,2_stack}` and use
`prometheus/additional-scrape-configs.yaml` as an additionalScrapeConfigs
secret plus `foremast/2_watch/metrics-rules.yaml` (the same rules as a
PrometheusRule CR).

The engine Deployment requests a TPU host (GKE v5e 2x4 node selector); edit
`engine_deployment()` for other topologies, or drop the TPU request to score
on CPU. `minikube.sh` bootstraps a local demo cluster; `export/*.sh`
port-forward the service (:8099), Prometheus (:9090), and the UI (:8080).

Demo runbook (the reference's de-facto integration test,
docs/guides/installation.md:84-143): apply `examples/demo/rollingUpdate/
demo_v1.yaml`, wait >= 5 min so history accumulates, apply `demo_v2.yaml`
(error injector) and watch `kubectl -n foremast-examples get
deploymentmonitor demo -w` reach phase Unhealthy followed by automatic
rollback to v1. The `continuous/` variants carry a DeploymentMonitor with
`continuous: true` (what `kubectl watch demo` toggles).
"""


# ---------------------------------------------------------------------------
# Tree assembly
# ---------------------------------------------------------------------------


def tree(cfg: BrainConfig | None = None) -> dict[str, object]:
    """path -> manifest list (YAML docs) or literal string content."""
    rules = prometheus_rule_manifest(namespace=NAMESPACE)
    return {
        "README.md": README,
        "minikube.sh": MINIKUBE_SH,
        "export/export-service.sh": EXPORT_SERVICE_SH,
        "export/export-prometheus.sh": EXPORT_PROMETHEUS_SH,
        "export/export-ui.sh": EXPORT_UI_SH,
        "prometheus/additional-scrape-configs.yaml": [scrape_config_secret()],
        "prometheus/00namespace.yaml": [monitoring_namespace()],
        "prometheus/1_rbac/prometheus-rbac.yaml": prometheus_rbac(),
        "prometheus/2_stack/prometheus-config.yaml": [prometheus_config()],
        "prometheus/2_stack/prometheus.yaml": prometheus_deployment(),
        "prometheus/2_stack/kube-state-metrics.yaml": kube_state_metrics(),
        "prometheus/2_stack/alertmanager.yaml": alertmanager(),
        "prometheus/2_stack/node-exporter.yaml": node_exporter(),
        "prometheus/2_stack/grafana.yaml": grafana(),
        "foremast/00namespace.yaml": [namespace()],
        "foremast/1_crds/deploymentmetadata.yaml": [deployment_metadata_crd()],
        "foremast/1_crds/deploymentmonitor.yaml": [deployment_monitor_crd()],
        "foremast/2_watch/foremast-watch-rbac.yaml": watch_rbac(),
        "foremast/2_watch/foremast-watch.yaml": [watch_deployment()],
        "foremast/2_watch/deployment-metadata-default.yaml": [default_metadata_cr()],
        "foremast/2_watch/metrics-rules.yaml": [rules],
        "foremast/3_engine/es.yaml": elasticsearch(),
        "foremast/3_engine/foremast-service.yaml": service_deployment(),
        "foremast/3_engine/foremast-engine.yaml": engine_deployment(cfg),
        "foremast/3_engine/foremast-ui.yaml": ui_deployment(),
        "examples/demo/rollingUpdate/demo_v1.yaml": demo_deployment("v1", []),
        "examples/demo/rollingUpdate/demo_v2.yaml": demo_deployment(
            "v2", ["--error-type", "5xx", "--frequency", "6"]
        ),
        "examples/demo/continuous/demo_v1.yaml": demo_deployment(
            "v1", [], continuous=True
        ),
        "examples/demo/continuous/demo_v2.yaml": demo_deployment(
            "v2",
            ["--trace", "/app/tests/data/demo_canary_spike.csv"],
            continuous=True,
        ),
    }


def render_file(content: object) -> str:
    import json

    import yaml

    if isinstance(content, str):
        return content
    # JSON round-trip breaks object identity between shared schema fragments
    # so the YAML emitter never writes anchors/aliases.
    return yaml.safe_dump_all(
        json.loads(json.dumps(content)), sort_keys=False, default_flow_style=False
    )


def render(root: str) -> list[str]:
    """Write the tree under `root`; returns the paths written."""
    import os

    written = []
    for rel, content in tree().items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(render_file(content))
        if path.endswith(".sh"):
            os.chmod(path, 0o755)
        written.append(path)
    return written
