"""`python -m foremast_tpu.deploy [root]` — render the deploy/ tree."""

import sys

from foremast_tpu.deploy.manifests import render

if __name__ == "__main__":
    root = sys.argv[1] if len(sys.argv) > 1 else "deploy"
    for path in render(root):
        print(path)
