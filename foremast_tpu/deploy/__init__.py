"""Deploy manifests as code (see manifests.py)."""

from foremast_tpu.deploy.manifests import render, render_file, tree

__all__ = ["render", "render_file", "tree"]
