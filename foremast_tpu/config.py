"""Typed configuration mirroring the reference brain's env-var surface.

The reference configures its ML engine entirely through environment
variables (`foremast-brain/README.md:20-38`; deployed values
`deploy/foremast/3_brain/foremast-brain.yaml:21-81`), including an indexed
per-metric-type override family `metric_type{i}/threshold{i}/bound{i}/
min_lower_bound{i}` (`foremast-brain.yaml:32-73`). This module keeps that
exact surface for drop-in parity (`from_env()`), while exposing typed
dataclasses internally.

TPU-first twist: the per-metric-type table compiles to dense per-window
vectors (`AnomalyConfig.gather`) so thresholds become array operands of a
single jitted scoring program instead of per-job Python branches.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Sequence

import numpy as np

from foremast_tpu.ops.anomaly import BOUND_BOTH, BOUND_LOWER, BOUND_UPPER

# Pairwise algorithm selectors (`foremast-brain/README.md:34`); FRIEDMAN
# is the "Fried manchi square (special case)" of the reference's design
# doc (`docs/guides/design.md:90-93`).
PAIRWISE_ALL = "ALL"
PAIRWISE_ANY = "ANY"
PAIRWISE_MANN_WHITE = "MANN_WHITE"
PAIRWISE_WILCOXON = "WILCOXON"
PAIRWISE_KRUSKAL = "KRUSKAL"
PAIRWISE_FRIEDMAN = "FRIEDMAN"
PAIRWISE_CHOICES = (
    PAIRWISE_ALL,
    PAIRWISE_ANY,
    PAIRWISE_MANN_WHITE,
    PAIRWISE_WILCOXON,
    PAIRWISE_KRUSKAL,
    PAIRWISE_FRIEDMAN,
)

_BOUND_NAMES = {
    "upper": BOUND_UPPER,
    "lower": BOUND_LOWER,
    "both": BOUND_BOTH,
    "1": BOUND_UPPER,
    "2": BOUND_LOWER,
    "3": BOUND_BOTH,
}


def _parse_bound(raw: str | int) -> int:
    if isinstance(raw, int):
        if raw not in (BOUND_UPPER, BOUND_LOWER, BOUND_BOTH):
            raise ValueError(f"bound must be 1/2/3, got {raw}")
        return raw
    key = str(raw).strip().lower()
    if key not in _BOUND_NAMES:
        raise ValueError(f"unknown bound selector {raw!r}")
    return _BOUND_NAMES[key]


@dataclasses.dataclass(frozen=True)
class MetricTypeRule:
    """One row of the per-metric-type override matrix.

    Deployed defaults (`foremast-brain.yaml:32-73`): error5xx(t=2,b=upper),
    error4xx(t=3,b=upper), latency(t=10,b=both), cpu(t=5,b=upper),
    memory(t=5,b=upper).
    """

    metric_type: str
    threshold: float
    bound: int = BOUND_UPPER
    min_lower_bound: float = 0.0


_DEFAULT_RULES = (
    MetricTypeRule("error5xx", 2.0, BOUND_UPPER, 0.0),
    MetricTypeRule("error4xx", 3.0, BOUND_UPPER, 0.0),
    MetricTypeRule("latency", 10.0, BOUND_BOTH, 0.0),
    MetricTypeRule("cpu", 5.0, BOUND_UPPER, 0.0),
    MetricTypeRule("memory", 5.0, BOUND_UPPER, 0.0),
)


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    """Global threshold params + per-metric-type override table."""

    threshold: float = 2.0  # `foremast-brain.yaml:26-27`
    min_lower_bound: float = 0.0  # `foremast-brain.yaml:28-29`
    bound: int = BOUND_UPPER  # `foremast-brain.yaml:30-31`
    rules: tuple[MetricTypeRule, ...] = _DEFAULT_RULES

    def rule_for(self, metric_type: str | None) -> MetricTypeRule:
        for r in self.rules:
            if r.metric_type == metric_type:
                return r
        return MetricTypeRule(
            metric_type or "", self.threshold, self.bound, self.min_lower_bound
        )

    def gather(
        self, metric_types: Sequence[str | None]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dense (threshold[B], bound[B], min_lower_bound[B]) vectors for a
        batch of metric types — the jitted scorer's array operands."""
        rules = [self.rule_for(t) for t in metric_types]
        return (
            np.asarray([r.threshold for r in rules], dtype=np.float32),
            np.asarray([r.bound for r in rules], dtype=np.int32),
            np.asarray([r.min_lower_bound for r in rules], dtype=np.float32),
        )


@dataclasses.dataclass(frozen=True)
class PairwiseConfig:
    """Baseline-vs-current distribution-test selection and gates.

    `ML_PAIRWISE_ALGORITHM` = ALL | ANY | MANN_WHITE | WILCOXON | KRUSKAL
    (`foremast-brain/README.md:34`); min-points gates
    (`foremast-brain.yaml:74-79`).
    """

    algorithm: str = PAIRWISE_ALL
    threshold: float = 0.05  # p-value cutoff, `ML_PAIRWISE_THRESHOLD` README:35
    min_mann_white_points: int = 20
    min_wilcoxon_points: int = 20
    min_kruskal_points: int = 5
    # no reference deployment pins a Friedman minimum; pairs like Wilcoxon
    min_friedman_points: int = 20

    def __post_init__(self):
        if self.algorithm not in PAIRWISE_CHOICES:
            raise ValueError(f"unknown pairwise algorithm {self.algorithm!r}")


@dataclasses.dataclass(frozen=True)
class BrainConfig:
    """Full engine config — env parity with `foremast-brain.yaml:21-81`."""

    algorithm: str = "moving_average_all"  # ML_ALGORITHM, yaml:24-25
    anomaly: AnomalyConfig = AnomalyConfig()
    pairwise: PairwiseConfig = PairwiseConfig()
    # Season length, in time steps, for every seasonal model (fitted
    # Holt-Winters, the trend+Fourier seasonal model, the residual-MVN's
    # HW state, and the auto screen). The deployed default matches the
    # reference's canonical workload: *daily* cycles at the 60 s PromQL
    # step of the 7-day historical window (`metricsquery.go:43,75-77`)
    # = 1440 steps. No reference env var exists (its HW season was an
    # internal constant); ML_SEASON_STEPS is this framework's knob.
    season_steps: int = 1440
    min_historical_points: int = 10  # MIN_HISTORICAL_DATA_POINT_TO_MEASURE README:23
    max_stuck_seconds: float = 90.0  # MAX_STUCK_IN_SECONDS, yaml:80-81
    max_cache_size: int = 1000  # MAX_CACHE_SIZE model cache, README:30
    es_endpoint: str = "http://localhost:9200"  # ES_ENDPOINT, yaml:22-23
    # FOREMAST_TRACE_DIR: directory for Perfetto-loadable span dumps
    # (observe/spans.py); None disables the trace ring buffer entirely —
    # the deployed default pays only the stage histograms.
    trace_dir: str | None = None

    def fingerprint(self) -> str:
        """Stable short hash of the effective JUDGMENT config — exported
        on /debug/state so two workers' configs can be compared at a
        glance (a fleet serving one store with divergent thresholds is a
        misconfiguration the varz plane should make visible).
        Plumbing fields are excluded: turning tracing on for one worker,
        or reaching the SAME store via a sidecar address, must not make
        it look misconfigured."""
        import dataclasses
        import hashlib

        d = dataclasses.asdict(self)
        for plumbing in ("trace_dir", "es_endpoint"):
            d.pop(plumbing, None)
        return hashlib.sha256(repr(d).encode()).hexdigest()[:12]

    @staticmethod
    def from_env(env: Mapping[str, str] | None = None) -> "BrainConfig":
        """Build from the reference's env-var names, including the indexed
        `metric_type{i}` family (`foremast-brain.yaml:32-73`)."""
        e = dict(os.environ if env is None else env)

        def get(name: str, default):
            raw = e.get(name)
            if raw is None or raw == "":
                return default
            if isinstance(default, bool):
                return raw.strip().lower() in ("1", "true", "yes")
            if isinstance(default, int):
                return int(raw)
            if isinstance(default, float):
                return float(raw)
            return raw

        def geti(name: str, i: int, default):
            """Indexed env lookup: `name{i}` falling back to the global
            `name`, then the built-in default; empty strings count as unset
            (same semantics as `get`)."""
            for key in (f"{name}{i}", name):
                raw = e.get(key)
                if raw is not None and raw != "":
                    return raw
            return default

        n_rules = int(e.get("metric_type_threshold_count", "0") or 0)
        rules: list[MetricTypeRule] = []
        for i in range(n_rules):
            mt = e.get(f"metric_type{i}")
            if not mt:
                continue
            rules.append(
                MetricTypeRule(
                    metric_type=mt,
                    threshold=float(geti("threshold", i, 2.0)),
                    bound=_parse_bound(geti("bound", i, 1)),
                    min_lower_bound=float(geti("min_lower_bound", i, 0.0)),
                )
            )
        raw_bound = e.get("ML_BOUND") or e.get("bound") or 1  # "" counts unset
        anomaly = AnomalyConfig(
            threshold=get("ML_THRESHOLD", get("threshold", 2.0)),
            min_lower_bound=get("min_lower_bound", 0.0),
            bound=_parse_bound(raw_bound),
            rules=tuple(rules) if rules else _DEFAULT_RULES,
        )
        pairwise = PairwiseConfig(
            algorithm=get("ML_PAIRWISE_ALGORITHM", PAIRWISE_ALL).upper(),
            threshold=get("ML_PAIRWISE_THRESHOLD", 0.05),
            min_mann_white_points=get("MIN_MANN_WHITE_DATA_POINTS", 20),
            min_wilcoxon_points=get("MIN_WILCOXON_DATA_POINTS", 20),
            min_kruskal_points=get("MIN_KRUSKAL_DATA_POINTS", 5),
            min_friedman_points=get("MIN_FRIEDMAN_DATA_POINTS", 20),
        )
        return BrainConfig(
            algorithm=get("ML_ALGORITHM", "moving_average_all"),
            anomaly=anomaly,
            pairwise=pairwise,
            season_steps=get("ML_SEASON_STEPS", 1440),
            min_historical_points=get("MIN_HISTORICAL_DATA_POINT_TO_MEASURE", 10),
            max_stuck_seconds=get("MAX_STUCK_IN_SECONDS", 90.0),
            max_cache_size=get("MAX_CACHE_SIZE", 1000),
            es_endpoint=get("ES_ENDPOINT", "http://localhost:9200"),
            trace_dir=e.get("FOREMAST_TRACE_DIR") or None,
        )
