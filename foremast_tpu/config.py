"""Typed configuration mirroring the reference brain's env-var surface.

The reference configures its ML engine entirely through environment
variables (`foremast-brain/README.md:20-38`; deployed values
`deploy/foremast/3_brain/foremast-brain.yaml:21-81`), including an indexed
per-metric-type override family `metric_type{i}/threshold{i}/bound{i}/
min_lower_bound{i}` (`foremast-brain.yaml:32-73`). This module keeps that
exact surface for drop-in parity (`from_env()`), while exposing typed
dataclasses internally.

TPU-first twist: the per-metric-type table compiles to dense per-window
vectors (`AnomalyConfig.gather`) so thresholds become array operands of a
single jitted scoring program instead of per-job Python branches.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Sequence

import numpy as np

from foremast_tpu.ops.anomaly import BOUND_BOTH, BOUND_LOWER, BOUND_UPPER

# Pairwise algorithm selectors (`foremast-brain/README.md:34`); FRIEDMAN
# is the "Fried manchi square (special case)" of the reference's design
# doc (`docs/guides/design.md:90-93`).
PAIRWISE_ALL = "ALL"
PAIRWISE_ANY = "ANY"
PAIRWISE_MANN_WHITE = "MANN_WHITE"
PAIRWISE_WILCOXON = "WILCOXON"
PAIRWISE_KRUSKAL = "KRUSKAL"
PAIRWISE_FRIEDMAN = "FRIEDMAN"
PAIRWISE_CHOICES = (
    PAIRWISE_ALL,
    PAIRWISE_ANY,
    PAIRWISE_MANN_WHITE,
    PAIRWISE_WILCOXON,
    PAIRWISE_KRUSKAL,
    PAIRWISE_FRIEDMAN,
)

_BOUND_NAMES = {
    "upper": BOUND_UPPER,
    "lower": BOUND_LOWER,
    "both": BOUND_BOTH,
    "1": BOUND_UPPER,
    "2": BOUND_LOWER,
    "3": BOUND_BOTH,
}


def _parse_bound(raw: str | int) -> int:
    if isinstance(raw, int):
        if raw not in (BOUND_UPPER, BOUND_LOWER, BOUND_BOTH):
            raise ValueError(f"bound must be 1/2/3, got {raw}")
        return raw
    key = str(raw).strip().lower()
    if key not in _BOUND_NAMES:
        raise ValueError(f"unknown bound selector {raw!r}")
    return _BOUND_NAMES[key]


@dataclasses.dataclass(frozen=True)
class MetricTypeRule:
    """One row of the per-metric-type override matrix.

    Deployed defaults (`foremast-brain.yaml:32-73`): error5xx(t=2,b=upper),
    error4xx(t=3,b=upper), latency(t=10,b=both), cpu(t=5,b=upper),
    memory(t=5,b=upper).
    """

    metric_type: str
    threshold: float
    bound: int = BOUND_UPPER
    min_lower_bound: float = 0.0


_DEFAULT_RULES = (
    MetricTypeRule("error5xx", 2.0, BOUND_UPPER, 0.0),
    MetricTypeRule("error4xx", 3.0, BOUND_UPPER, 0.0),
    MetricTypeRule("latency", 10.0, BOUND_BOTH, 0.0),
    MetricTypeRule("cpu", 5.0, BOUND_UPPER, 0.0),
    MetricTypeRule("memory", 5.0, BOUND_UPPER, 0.0),
)


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    """Global threshold params + per-metric-type override table."""

    threshold: float = 2.0  # `foremast-brain.yaml:26-27`
    min_lower_bound: float = 0.0  # `foremast-brain.yaml:28-29`
    bound: int = BOUND_UPPER  # `foremast-brain.yaml:30-31`
    rules: tuple[MetricTypeRule, ...] = _DEFAULT_RULES

    def rule_for(self, metric_type: str | None) -> MetricTypeRule:
        for r in self.rules:
            if r.metric_type == metric_type:
                return r
        return MetricTypeRule(
            metric_type or "", self.threshold, self.bound, self.min_lower_bound
        )

    def gather(
        self, metric_types: Sequence[str | None]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dense (threshold[B], bound[B], min_lower_bound[B]) vectors for a
        batch of metric types — the jitted scorer's array operands."""
        rules = [self.rule_for(t) for t in metric_types]
        return (
            np.asarray([r.threshold for r in rules], dtype=np.float32),
            np.asarray([r.bound for r in rules], dtype=np.int32),
            np.asarray([r.min_lower_bound for r in rules], dtype=np.float32),
        )


@dataclasses.dataclass(frozen=True)
class PairwiseConfig:
    """Baseline-vs-current distribution-test selection and gates.

    `ML_PAIRWISE_ALGORITHM` = ALL | ANY | MANN_WHITE | WILCOXON | KRUSKAL
    (`foremast-brain/README.md:34`); min-points gates
    (`foremast-brain.yaml:74-79`).
    """

    algorithm: str = PAIRWISE_ALL
    threshold: float = 0.05  # p-value cutoff, `ML_PAIRWISE_THRESHOLD` README:35
    min_mann_white_points: int = 20
    min_wilcoxon_points: int = 20
    min_kruskal_points: int = 5
    # no reference deployment pins a Friedman minimum; pairs like Wilcoxon
    min_friedman_points: int = 20

    def __post_init__(self):
        if self.algorithm not in PAIRWISE_CHOICES:
            raise ValueError(f"unknown pairwise algorithm {self.algorithm!r}")


@dataclasses.dataclass(frozen=True)
class BrainConfig:
    """Full engine config — env parity with `foremast-brain.yaml:21-81`."""

    algorithm: str = "moving_average_all"  # ML_ALGORITHM, yaml:24-25
    anomaly: AnomalyConfig = AnomalyConfig()
    pairwise: PairwiseConfig = PairwiseConfig()
    # Season length, in time steps, for every seasonal model (fitted
    # Holt-Winters, the trend+Fourier seasonal model, the residual-MVN's
    # HW state, and the auto screen). The deployed default matches the
    # reference's canonical workload: *daily* cycles at the 60 s PromQL
    # step of the 7-day historical window (`metricsquery.go:43,75-77`)
    # = 1440 steps. No reference env var exists (its HW season was an
    # internal constant); ML_SEASON_STEPS is this framework's knob.
    season_steps: int = 1440
    min_historical_points: int = 10  # MIN_HISTORICAL_DATA_POINT_TO_MEASURE README:23
    max_stuck_seconds: float = 90.0  # MAX_STUCK_IN_SECONDS, yaml:80-81
    max_cache_size: int = 1000  # MAX_CACHE_SIZE model cache, README:30
    es_endpoint: str = "http://localhost:9200"  # ES_ENDPOINT, yaml:22-23
    # FOREMAST_TRACE_DIR: directory for Perfetto-loadable span dumps
    # (observe/spans.py); None disables the trace ring buffer entirely —
    # the deployed default pays only the stage histograms.
    trace_dir: str | None = None

    def fingerprint(self) -> str:
        """Stable short hash of the effective JUDGMENT config — exported
        on /debug/state so two workers' configs can be compared at a
        glance (a fleet serving one store with divergent thresholds is a
        misconfiguration the varz plane should make visible).
        Plumbing fields are excluded: turning tracing on for one worker,
        or reaching the SAME store via a sidecar address, must not make
        it look misconfigured."""
        import dataclasses
        import hashlib

        d = dataclasses.asdict(self)
        for plumbing in ("trace_dir", "es_endpoint"):
            d.pop(plumbing, None)
        return hashlib.sha256(repr(d).encode()).hexdigest()[:12]

    @staticmethod
    def from_env(env: Mapping[str, str] | None = None) -> "BrainConfig":
        """Build from the reference's env-var names, including the indexed
        `metric_type{i}` family (`foremast-brain.yaml:32-73`)."""
        e = dict(os.environ if env is None else env)

        def get(name: str, default):
            raw = e.get(name)
            if raw is None or raw == "":
                return default
            if isinstance(default, bool):
                return raw.strip().lower() in ("1", "true", "yes")
            if isinstance(default, int):
                return int(raw)
            if isinstance(default, float):
                return float(raw)
            return raw

        def geti(name: str, i: int, default):
            """Indexed env lookup: `name{i}` falling back to the global
            `name`, then the built-in default; empty strings count as unset
            (same semantics as `get`)."""
            for key in (f"{name}{i}", name):
                raw = e.get(key)
                if raw is not None and raw != "":
                    return raw
            return default

        n_rules = int(e.get("metric_type_threshold_count", "0") or 0)
        rules: list[MetricTypeRule] = []
        for i in range(n_rules):
            mt = e.get(f"metric_type{i}")
            if not mt:
                continue
            rules.append(
                MetricTypeRule(
                    metric_type=mt,
                    threshold=float(geti("threshold", i, 2.0)),
                    bound=_parse_bound(geti("bound", i, 1)),
                    min_lower_bound=float(geti("min_lower_bound", i, 0.0)),
                )
            )
        raw_bound = e.get("ML_BOUND") or e.get("bound") or 1  # "" counts unset
        anomaly = AnomalyConfig(
            threshold=get("ML_THRESHOLD", get("threshold", 2.0)),
            min_lower_bound=get("min_lower_bound", 0.0),
            bound=_parse_bound(raw_bound),
            rules=tuple(rules) if rules else _DEFAULT_RULES,
        )
        pairwise = PairwiseConfig(
            algorithm=get("ML_PAIRWISE_ALGORITHM", PAIRWISE_ALL).upper(),
            threshold=get("ML_PAIRWISE_THRESHOLD", 0.05),
            min_mann_white_points=get("MIN_MANN_WHITE_DATA_POINTS", 20),
            min_wilcoxon_points=get("MIN_WILCOXON_DATA_POINTS", 20),
            min_kruskal_points=get("MIN_KRUSKAL_DATA_POINTS", 5),
            min_friedman_points=get("MIN_FRIEDMAN_DATA_POINTS", 20),
        )
        return BrainConfig(
            algorithm=get("ML_ALGORITHM", "moving_average_all"),
            anomaly=anomaly,
            pairwise=pairwise,
            season_steps=get("ML_SEASON_STEPS", 1440),
            min_historical_points=get("MIN_HISTORICAL_DATA_POINT_TO_MEASURE", 10),
            max_stuck_seconds=get("MAX_STUCK_IN_SECONDS", 90.0),
            max_cache_size=get("MAX_CACHE_SIZE", 1000),
            es_endpoint=get("ES_ENDPOINT", "http://localhost:9200"),
            trace_dir=e.get("FOREMAST_TRACE_DIR") or None,
        )


# ---------------------------------------------------------------------------
# The env-var registry: the ENTIRE configuration surface, enumerable.
# ---------------------------------------------------------------------------
#
# Every environment variable any foremast_tpu module reads must be
# declared here — the env-contract checker (foremast_tpu/analysis/)
# fails the build on undeclared reads, and the operator docs table in
# docs/operations.md is GENERATED from this registry (`make env-docs`).
# That keeps three things from drifting: the code's actual env surface,
# the docs, and what /debug/state can enumerate (`env_overrides()`).
#
# `name` may be an indexed pattern (`metric_type{i}`) for the
# reference's per-metric-type override family — those are only ever
# read through config.from_env, so the checker never needs to match
# them literally.


@dataclasses.dataclass(frozen=True)
class EnvKnob:
    """One declared environment variable: the unit of config surface."""

    name: str
    default: str | None
    kind: str  # "str" | "int" | "float" | "bool" | "path" | "indexed"
    description: str
    group: str = "framework"  # "engine" | "framework" | "deploy"


ENV_KNOBS: tuple[EnvKnob, ...] = (
    # -- engine (reference parity: foremast-brain.yaml:21-81 + README:20-38)
    EnvKnob(
        "ES_ENDPOINT",
        "http://localhost:9200",
        "str",
        "Elasticsearch job store, engine spelling (in-memory if unset "
        "and no `ELASTIC_URL`)",
        "engine",
    ),
    EnvKnob(
        "ML_ALGORITHM",
        "moving_average_all",
        "str",
        "also: moving_average, ewma, double_exponential_smoothing, "
        "holt_winters, phase_means (pooled per-phase means — the "
        "daily-season workhorse), seasonal, prophet, `auto_univariate` "
        "(per-series structure screen over {mean, HW or phase_means by "
        "season length, Fourier seasonal} — recommended for unknown "
        "metric mixes), `auto`, `bivariate_normal`, `lstm_autoencoder` "
        "(hybrid: AE + seasonal-residual Gaussian)",
        "engine",
    ),
    EnvKnob(
        "ML_THRESHOLD",
        "2.0",
        "float",
        "global sigma multiplier (reference alias: `threshold`)",
        "engine",
    ),
    EnvKnob(
        "threshold",
        "2.0",
        "float",
        "reference spelling of `ML_THRESHOLD`",
        "engine",
    ),
    EnvKnob(
        "ML_BOUND",
        "1",
        "int",
        "1=upper, 2=lower, 3=both (reference alias: `bound`)",
        "engine",
    ),
    EnvKnob("bound", "1", "int", "reference spelling of `ML_BOUND`", "engine"),
    EnvKnob("min_lower_bound", "0", "float", "lower-bound floor", "engine"),
    EnvKnob(
        "metric_type_threshold_count",
        "0",
        "int",
        "row count of the per-metric-type override table",
        "engine",
    ),
    EnvKnob(
        "metric_type{i}",
        None,
        "indexed",
        "with `threshold{i}`/`bound{i}`/`min_lower_bound{i}`: "
        "per-metric-type override rows (deployed defaults: error5xx 2/1, "
        "error4xx 3/1, latency 10/3, cpu 5/1, memory 5/1)",
        "engine",
    ),
    EnvKnob(
        "ML_PAIRWISE_ALGORITHM",
        "ALL",
        "str",
        "ALL | ANY | MANN_WHITE | WILCOXON | KRUSKAL | FRIEDMAN (the "
        "reference design doc's fourth algorithm, two-group special case)",
        "engine",
    ),
    EnvKnob(
        "ML_PAIRWISE_THRESHOLD", "0.05", "float", "pairwise p threshold", "engine"
    ),
    EnvKnob(
        "MIN_MANN_WHITE_DATA_POINTS",
        "20",
        "int",
        "Mann-Whitney min-points gate",
        "engine",
    ),
    EnvKnob(
        "MIN_WILCOXON_DATA_POINTS",
        "20",
        "int",
        "Wilcoxon min-points gate",
        "engine",
    ),
    EnvKnob(
        "MIN_KRUSKAL_DATA_POINTS",
        "5",
        "int",
        "Kruskal-Wallis min-points gate",
        "engine",
    ),
    EnvKnob(
        "MIN_FRIEDMAN_DATA_POINTS",
        "20",
        "int",
        "Friedman min-points gate",
        "engine",
    ),
    EnvKnob(
        "ML_SEASON_STEPS",
        "1440",
        "int",
        "season length in steps for every seasonal model (HW, Fourier "
        "seasonal, residual-MVN, the auto screen); 1440 = daily at the "
        "60 s PromQL step. Histories under 2 cycles keep the mean model "
        "(identifiability guard). Routing note (deliberate): an EXPLICIT "
        "`ML_ALGORITHM=holt_winters` is honored as configured even at "
        "m=1440, where its rolled scan makes cold fits several times "
        "slower than `phase_means` — silently rewriting an operator's "
        "explicit algorithm choice would make config behavior "
        "unpredictable. For daily seasons prefer `auto_univariate` "
        "(which routes long seasons to the pooled phase-means fit "
        "itself) or set `phase_means` directly",
        "engine",
    ),
    EnvKnob(
        "MIN_HISTORICAL_DATA_POINT_TO_MEASURE",
        "10",
        "int",
        "measurability gate",
        "engine",
    ),
    EnvKnob(
        "MAX_STUCK_IN_SECONDS", "90", "float", "work-stealing takeover", "engine"
    ),
    EnvKnob("MAX_CACHE_SIZE", "1000", "int", "fitted-model LRU size", "engine"),
    # -- framework-specific
    EnvKnob(
        "ELASTIC_URL",
        None,
        "str",
        "Elasticsearch job store, service spelling (falls back to "
        "`ES_ENDPOINT`; in-memory when both unset)",
    ),
    EnvKnob(
        "FOREMAST_PALLAS",
        "0",
        "bool",
        "`1` opts into the fused Pallas judgment kernel",
    ),
    EnvKnob(
        "FOREMAST_NATIVE",
        "1",
        "bool",
        "`0` disables the C++ data loader (pure Python)",
    ),
    EnvKnob(
        "FOREMAST_LSTM_STEPS",
        "60",
        "int",
        "LSTM-AE train steps per new model",
    ),
    EnvKnob(
        "FOREMAST_CLAIM_LIMIT",
        "256",
        "int",
        "jobs claimed per tick (`worker --claim-limit`); the whole claim "
        "scores as one batched program",
    ),
    EnvKnob(
        "FOREMAST_JOINT_COLUMNAR",
        "1",
        "bool",
        "default `1`: warm joint (multi-alias bivariate / LSTM-hybrid) "
        "docs ride the columnar fast tick from arena-resident model "
        "state, the same path univariate re-checks use. `0` routes every "
        "joint doc through the per-task object path (the pre-round-7 "
        "behavior — ~10x slower per joint doc at fleet scale)",
    ),
    EnvKnob(
        "FOREMAST_CANARY_COLUMNAR",
        "1",
        "bool",
        "default `1`: warm BASELINE-carrying univariate docs (the "
        "canary/continuous strategies) ride the columnar fast tick as "
        "their own bucket — baseline windows fill a second [B, Tc] "
        "buffer judged by a pairwise-active compiled variant "
        "(Mann-Whitney/Wilcoxon/Kruskal/Friedman batched over the "
        "buffer). `0` routes every baseline-carrying doc through the "
        "per-task object path (the pre-round-16 behavior — ~10k w/s "
        "regardless of device)",
    ),
    EnvKnob(
        "FOREMAST_COLD_CHUNK_DOCS",
        "1024",
        "int",
        "slow-path doc-chunk size: cold claim sets run "
        "fetch→fit→judge→write per chunk, bounding time-to-first-verdict "
        "by one chunk's work (~20 s at fleet scale)",
    ),
    EnvKnob(
        "FOREMAST_PIPELINE_DEPTH",
        "2",
        "int",
        "slow-path tick-pipeline depth: prefetch runs depth-1 chunks "
        "ahead of the device and the write queue holds at most depth "
        "judged chunks (peak residency ~2×depth chunks across the "
        "fetch / judge / write-back stages). `1` = fully serial. "
        "Sources declaring "
        "`concurrent_fetch = False` (pod-mode LeaderSource — its "
        "fetches are ordered collectives — and in-memory sources) "
        "always degrade to serial. Pod mode broadcasts the leader's "
        "value",
    ),
    EnvKnob(
        "FOREMAST_FETCH_WORKERS",
        "16",
        "int",
        "persistent per-worker metric-fetch thread pool size (per-doc "
        "query_range fan-out within a chunk; one pool per worker "
        "process, reused across ticks). Pod mode broadcasts the "
        "leader's value",
    ),
    EnvKnob(
        "FOREMAST_COMPILE_CACHE_DIR",
        None,
        "path",
        "JAX persistent compilation cache directory: the 20-40 s "
        "per-bucket warmup compiles are paid once per binary and "
        "reloaded across process restarts (hit/miss logged at "
        "`worker --warmup`). Unset = in-memory compile cache only",
    ),
    EnvKnob(
        "FOREMAST_ARENA_BYTES",
        "268435456",
        "int",
        "soft HBM budget for the device state arena (default 256 MB; `0` "
        "disables the arena). The arena AUTO-GROWS past this when the "
        "fleet's working set needs more rows — one warning log per "
        "growth — because an LRU arena smaller than the working set "
        "would re-upload the whole fleet's state every tick. Sizing "
        "rule: rows = services × metrics-per-job; bytes/row = 20 + 4 × "
        "`ML_SEASON_STEPS` (daily m=1440 ⇒ ~5.8 KB/row, so a "
        "16k-service × 4-metric daily fleet needs ~378 MB). Pod mode "
        "broadcasts the leader's value (engine.arena.set_arena_budget)",
    ),
    EnvKnob(
        "FOREMAST_ARENA_MAX_BYTES",
        "2147483648",
        "int",
        "hard arena ceiling (default 2 GB ≈ 12% of a v5e chip's HBM). "
        "Batches that cannot fit even here fall back to a per-tick full "
        "state restack — counted in "
        "`foremast_worker_arena_events_total{event=\"fallbacks\"}` and "
        "logged, never silent",
    ),
    EnvKnob(
        "FOREMAST_DEVICE_MESH",
        "auto",
        "str",
        "device mesh the worker's judge partitions over (ISSUE 13): "
        "`auto` (default) = all local devices on the data axis — a "
        "1-device resolution IS the plain single-device judge, so "
        "stock CPU hosts are unaffected; `0`/`off` disables mesh "
        "placement entirely; `N` puts N devices on the data axis; "
        "`NxM` is an explicit (data, model) grid. The warm columnar "
        "paths (univariate + joint from-rows) shard their batch "
        "leading axis over `data` with state-arena ROW SPACE "
        "block-sharded over the same axis by default (ISSUE 19; "
        "aggregate capacity = per-device budget × devices, accounted "
        "on `/debug/state device_mesh`; set FOREMAST_ARENA_SHARDED=0 "
        "to replicate instead). Malformed values warn and fall "
        "back to `auto`. Pod mode (`--sharded`) spans the GLOBAL mesh "
        "instead and ignores this knob",
    ),
    EnvKnob(
        "FOREMAST_ARENA_SHARDED",
        "1",
        "int",
        "shard the device state arenas' row space over the mesh data "
        "axis (default on, ISSUE 19): each device holds only its "
        "block of rows — placement tied to batch position, so warm "
        "gathers stay device-local with zero cross-chip transfer — "
        "and the per-device FOREMAST_ARENA_BYTES budget buys "
        "devices× aggregate rows instead of one replica per chip. "
        "`0` restores the ISSUE-13 replicated layout. Ignored (forced "
        "replicated) on a 1-device judge and in pod mode, where "
        "per-process meshes already partition the fleet",
    ),
    EnvKnob(
        "FOREMAST_DEVICE_MESH_MODEL",
        "1",
        "int",
        "model-axis width for `FOREMAST_DEVICE_MESH=auto`/`N` "
        "spellings (tensor parallelism for the learned detectors; the "
        "`NxM` spelling overrides this). Must stay inside one host's "
        "ICI domain — see parallel/mesh.py make_global_mesh",
    ),
    EnvKnob(
        "FOREMAST_BF16_DELTA",
        "1",
        "bool",
        "default `1`: histories travel/reside as f32 anchor + bf16 "
        "deltas (2 B/point) — 1.95x on the steady-state headline, 2-4x "
        "on cold-tick/churn H2D (moments shortcut for the deployed "
        "default, in-program reconstruction for seasonal fits); verdict "
        "parity, low-CV band geometry, and m=1440 seasonal fidelity are "
        "test-pinned. `0` restores full-f32 handling. Pod mode "
        "broadcasts the leader's value (engine.scoring.set_bf16_delta)",
    ),
    EnvKnob(
        "FOREMAST_FETCH_RETRIES",
        "2",
        "int",
        "transient-failure retries per metric fetch (HTTP 429/5xx and "
        "connection errors), with exponential jittered backoff; `0` "
        "restores fail-on-first-error. A retry budget is per URL, so a "
        "doc's preprocess stage survives one flaky round trip instead "
        "of failing the whole document",
    ),
    EnvKnob(
        "FOREMAST_INGEST",
        "0",
        "bool",
        "`1` mounts the push-based ingest plane (docs/operations.md "
        "\"Ingest plane\"): a remote-write receiver feeding a sharded "
        "in-memory ring TSDB, with the worker's fetches served from "
        "resident series and falling back to Prometheus on cold miss",
    ),
    EnvKnob(
        "FOREMAST_INGEST_PORT",
        "9009",
        "int",
        "ingest receiver port (POST /api/v1/write, JSON remote-write "
        "style); `0` disables the HTTP receiver — the ring then only "
        "fills through backfill and the direct push API",
    ),
    EnvKnob(
        "FOREMAST_INGEST_BUDGET_BYTES",
        "268435456",
        "int",
        "resident-series byte budget for the ring TSDB (default "
        "256 MB), split evenly across shards; past it, "
        "least-recently-used series are evicted whole (they re-warm "
        "via the cold-miss fallback). Sizing rule: 12 B/point at pow2 "
        "capacities — a full 7-day 60 s history rounds to 16,384 "
        "points ≈ 192 KB/series",
    ),
    EnvKnob(
        "FOREMAST_INGEST_SHARDS",
        "8",
        "int",
        "ring TSDB shard count — receiver push threads, tick fetches "
        "and scrapes contend on 1/N of the keyspace per lock",
    ),
    EnvKnob(
        "FOREMAST_INGEST_STALE_SECONDS",
        "300",
        "float",
        "staleness watermark: a fetch is only served from the ring "
        "when the newest resident sample is within this many seconds "
        "of the requested window head — a dead pusher degrades to the "
        "pull path instead of freezing verdicts",
    ),
    EnvKnob(
        "FOREMAST_INGEST_MAX_POINTS",
        "16384",
        "int",
        "per-series ring capacity ceiling (pow2-rounded); older "
        "samples are overwritten past it",
    ),
    EnvKnob(
        "FOREMAST_INGEST_MAX_BODY_BYTES",
        "8388608",
        "int",
        "ingest receiver request-body cap (default 8 MiB): pushes "
        "whose Content-Length exceeds it answer 413 before any byte "
        "is buffered or parsed, so one oversized pusher cannot wedge "
        "a handler thread or balloon the heap",
    ),
    EnvKnob(
        "FOREMAST_ADMIT_MIN_COVERAGE_SECONDS",
        "86400",
        "float",
        "short-history admission floor (docs/operations.md \"Cold "
        "start & churn\"): in PURE-PUSH mode (no fallback source) a "
        "newcomer series whose live ring-coverage span holds at least "
        "this many seconds of fresh data gets a verdict-capable "
        "PROVISIONAL fit from the resident columns in its first tick, "
        "refined toward the full 7-day fit in the background as "
        "coverage grows; below the floor the fetch stays UNKNOWN. "
        "With a fallback configured the floor is inert — an uncovered "
        "window start keeps degrading to the fallback, which may hold "
        "the full history the ring lost. `0` disables partial "
        "admission entirely",
    ),
    EnvKnob(
        "FOREMAST_REFINE_DOCS_PER_TICK",
        "256",
        "int",
        "background-refinement budget: at most this many provisional "
        "fits are upgraded (invalidated for refit from the grown ring "
        "window) per idle or all-warm tick — bounds the next tick's "
        "slow-path refit batch. Refits pace geometrically (~1.5x more "
        "points each), so a fit refines O(log) times on its way from "
        "the admission floor to the full window; `0` parks provisional "
        "fits at their admitted history",
    ),
    EnvKnob(
        "FOREMAST_MICROTICK_SECONDS",
        "0",
        "float",
        "reactive plane pacing (docs/operations.md \"Event-driven "
        "detection\"): > 0 turns the worker's idle wait between full "
        "ticks into the micro-tick drain window — every this-many "
        "seconds the worker claims and judges JUST the documents whose "
        "series the receiver marked dirty since the last drain, so a "
        "pushed anomaly meets its verdict in ~this + judge time "
        "instead of waiting out the poll; full ticks demote to sweeps "
        "on the poll cadence. `0` (default) = tick-paced detection "
        "(the pre-ISSUE-12 behavior). Requires FOREMAST_INGEST=1 "
        "(the receiver is what marks arrivals)",
    ),
    EnvKnob(
        "FOREMAST_MICROTICK_DOCS",
        "256",
        "int",
        "dirty route keys drained per micro-tick: bounds one "
        "micro-tick's claim scope (the claim itself stays bounded by "
        "--claim-limit); keys past the budget wait for the next "
        "micro-tick or sweep",
    ),
    EnvKnob(
        "FOREMAST_SWEEP_SLICE_DOCS",
        "2048",
        "int",
        "sliced, preemptible sweeps (ISSUE 15, docs/operations.md "
        "\"Event-driven detection\"): a full sweep whose claim exceeds "
        "this many docs runs as bounded SLICES through the warm-path "
        "pipeline — the prefetch thread packs slice N+1 while the "
        "device runs slice N and the writer decodes + bulk-writes "
        "slice N-1 — with a dirty-drain preemption point at every "
        "slice boundary, so pushed-anomaly p99 is bounded by one "
        "slice's wall clock instead of the sweep's. `0` = monolithic "
        "ticks (the pre-ISSUE-15 behavior; also forced in pod mode). "
        "Smaller slices tighten the latency bound and cost more "
        "per-dispatch overhead; see the slice-size tuning guidance",
    ),
    EnvKnob(
        "FOREMAST_MICROTICK_DIRTY_MAX",
        "8192",
        "int",
        "dirty-set capacity (route keys): past it the OLDEST pending "
        "arrival drops, counted on "
        "foremast_microtick_dirty_events{event=\"dropped\"} — the full "
        "sweep still judges those documents on its own cadence, so "
        "overflow degrades latency attribution, never correctness "
        "(bounded by construction, never a leak)",
    ),
    EnvKnob(
        "FOREMAST_WATCH_STREAM",
        "0",
        "bool",
        "`1` switches the watch plane's deployment informer to the "
        "streaming `watch=true` long-poll (resourceVersion resume, "
        "410-Gone re-list, stall detection): deployment events "
        "dispatch on ARRIVAL and the 30 s resync demotes to a repair "
        "sweep. `0` keeps the list+diff poll informer",
    ),
    EnvKnob(
        "FOREMAST_SNAPSHOT_DIR",
        None,
        "path",
        "durable data-plane directory (docs/operations.md \"Restarts "
        "and upgrades\"): ring shard snapshots + append logs, "
        "write-through fit journals, and the worker's persistent mesh "
        "identity all live here, so a SIGKILLed or upgraded worker "
        "restarts WARM — next tick ≥ 90% fast-path with zero fallback "
        "HTTP fetches — instead of re-fetching 7-day histories for the "
        "whole fleet. Unset = ephemeral (the pre-ISSUE-7 behavior)",
    ),
    EnvKnob(
        "FOREMAST_SNAPSHOT_INTERVAL_SECONDS",
        "60",
        "float",
        "ring snapshot cadence: a full shard snapshot pass at most this "
        "often (append logs cover the gap between passes, so crash "
        "recency is bounded by log flush — every push — not by this)",
    ),
    EnvKnob(
        "FOREMAST_SNAPSHOT_MAX_AGE_SECONDS",
        "86400",
        "float",
        "restore age cutoff: a restored series whose coverage head is "
        "older than this is discarded (counted on "
        "`foremast_snapshot_discards{reason=\"stale\"}`) and cold-fits "
        "through the fallback instead — yesterday's ring must not "
        "shadow a fleet that moved on",
    ),
    EnvKnob(
        "FOREMAST_SNAPSHOT_LOG_MAX_BYTES",
        "67108864",
        "int",
        "per-shard append-log budget (default 64 MiB): a log past it "
        "forces a snapshot pass (fit journals compact at 8 MiB), "
        "bounding restart replay time",
    ),
    EnvKnob(
        "FOREMAST_CHAOS_PLAN",
        None,
        "str",
        "deterministic fault-injection plan (docs/operations.md "
        "\"Failure modes & degradation\"): inline JSON or `@path` to a "
        "JSON file — seeded rules injecting latency / error-rate / "
        "blackhole / clock-skew faults into each dependency edge "
        "(prometheus, store, kube, receiver, pusher, transfer, clock). "
        "UNSET in "
        "production: every injection seam is then a pass-through "
        "attribute check. Test/soak tooling only",
    ),
    EnvKnob(
        "FOREMAST_BREAKER_FAILURES",
        "5",
        "int",
        "circuit breaker: consecutive transient failures (connection/"
        "timeout errors, HTTP 429/5xx) on one dependency edge before "
        "its breaker opens and further calls fail fast (BreakerOpen) "
        "instead of stalling on timeouts",
    ),
    EnvKnob(
        "FOREMAST_BREAKER_OPEN_SECONDS",
        "10",
        "float",
        "circuit breaker: open-state cooldown before ONE half-open "
        "probe call is allowed through; probe success re-closes, "
        "failure re-opens with a fresh cooldown",
    ),
    EnvKnob(
        "FOREMAST_TICK_BUDGET_SECONDS",
        "0",
        "float",
        "per-tick deadline (0 = unbounded): docs whose fetch/judge "
        "turn comes after the budget are RELEASED un-judged — status "
        "back to preprocess_completed, claimable next tick, counted on "
        "`foremast_degraded_docs{reason=\"deadline_released\"}` — so "
        "one slow dependency bounds tick latency instead of wedging "
        "the whole claim behind it",
    ),
    EnvKnob(
        "FOREMAST_WRITE_BEHIND_DOCS",
        "65536",
        "int",
        "write-behind buffer entry cap: verdicts whose store write "
        "failed transiently buffer locally and replay when the store "
        "heals; past the cap the OLDEST entries drop (counted). "
        "Entries aging past MAX_STUCK_IN_SECONDS always drop — past "
        "the stuck window a peer's claim-CAS takeover owns the doc, "
        "and a late replay would double-write its verdict",
    ),
    EnvKnob(
        "FOREMAST_INGEST_MAX_INFLIGHT",
        "64",
        "int",
        "ingest receiver overload shedding: concurrent push handlers "
        "allowed before a push is answered 429 + Retry-After BEFORE "
        "its body is read (pushers retry-then-buffer client-side); "
        "`0` disables shedding",
    ),
    EnvKnob(
        "FOREMAST_INGEST_DECODE_WORKERS",
        "4",
        "int",
        "pooled decode worker threads on the ingest receiver "
        "(docs/wire-protocol.md): handler threads do socket I/O only "
        "while decompress/decode/apply run on this many pool threads, "
        "bounding decode CPU however many pusher connections pile up; "
        "a full decode queue sheds 429. `0` decodes inline on the "
        "handler thread",
    ),
    EnvKnob(
        "FOREMAST_INGEST_MAX_DECODED_BYTES",
        "33554432",
        "int",
        "decoded-size ceiling for the binary wire path (default "
        "32 MiB): the DECLARED size in the snappy preamble / FMW1 "
        "frame header past it answers 413 before the body is read or "
        "decompressed — the snappy-bomb mirror of "
        "FOREMAST_INGEST_MAX_BODY_BYTES's no-buffering contract",
    ),
    EnvKnob(
        "FOREMAST_ES_CONNECT_DEADLINE_SECONDS",
        "0",
        "float",
        "bound on the Elasticsearch connect-retry loop at startup "
        "(`0` = the reference's forever-retry): past it the worker "
        "exits loudly with the retry state instead of waiting "
        "invisibly; the retry progress is always surfaced on "
        "`/debug/state` `store_connect`",
    ),
    EnvKnob(
        "FOREMAST_KUBE_TIMEOUT_SECONDS",
        "30",
        "float",
        "per-request socket timeout for the in-cluster K8s API client "
        "(HttpKube; applies to connect and read). Transient API-server "
        "failures (429/5xx, connection errors) retry under "
        "FOREMAST_FETCH_RETRIES with jittered backoff; hard 4xx fails "
        "fast",
    ),
    EnvKnob(
        "FOREMAST_MESH",
        "0",
        "bool",
        "`1` joins the worker mesh (docs/operations.md \"Worker "
        "mesh\"): this worker registers a membership lease in the job "
        "store, claims only the fleet partition a consistent-hash "
        "ring assigns it, and (with `FOREMAST_INGEST=1`) answers "
        "pushes for series another member owns with that member's "
        "advertised receiver address",
    ),
    EnvKnob(
        "FOREMAST_MESH_LEASE_SECONDS",
        "15",
        "float",
        "membership lease: renewed every third of this, and a member "
        "whose record is older than this (by the reader's clock) is "
        "treated as dead — the ring heals around it and its in-flight "
        "claims age out via MAX_STUCK_IN_SECONDS takeover. Keep it "
        "comfortably above the tick poll interval and below the stuck "
        "window",
    ),
    EnvKnob(
        "FOREMAST_MESH_REPLICAS",
        "64",
        "int",
        "consistent-hash virtual nodes per unit of member capacity — "
        "higher evens out partition sizes at the cost of ring-build "
        "time on rebalance (64 keeps the largest/smallest partition "
        "spread under ~20% at 4 members)",
    ),
    EnvKnob(
        "FOREMAST_MESH_ROUTE_LABEL",
        "app",
        "str",
        "the series label whose value is the partition identity: a "
        "pushed series carrying it hashes to the SAME member as the "
        "documents of that application (doc route key = appName). "
        "Series without the label hash by whole canonical key and may "
        "land off-worker — their fetches degrade to the cold-miss "
        "fallback, never to wrong answers",
    ),
    EnvKnob(
        "FOREMAST_MESH_ADVERTISE",
        None,
        "str",
        "host (or host:port) peers and pushers should use to reach "
        "this worker's ingest receiver; default advertises the local "
        "hostname with the receiver's actual bound port",
    ),
    EnvKnob(
        "FOREMAST_HANDOFF",
        "1",
        "bool",
        "default `1` (mesh + ingest mode): planned membership changes "
        "move state instead of refitting it (docs/operations.md "
        "\"Elastic scaling\") — a joining worker registers FENCED and "
        "receives its partition's ring series + fit entries from the "
        "current owners before claiming; a draining worker streams its "
        "state to the post-drain owners before leaving; SIGTERM on a "
        "mesh worker drains instead of just leaving. `0` restores the "
        "PR-6 behavior (every partition move cold-refits)",
    ),
    EnvKnob(
        "FOREMAST_HANDOFF_DEADLINE_SECONDS",
        "30",
        "float",
        "planned-handoff fence bound: a joining worker waits at most "
        "this long for the current owners' transfer `done` markers "
        "before activating anyway — a torn, blackholed, or crashed "
        "transfer degrades the moved state to cold refits (counted on "
        "`foremast_handoff_transfers`), never a parked joiner. Also "
        "bounds (2x) how long transferred-in series are protected from "
        "the rebalance eviction pass",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_HIGH_OCCUPANCY",
        "0.8",
        "float",
        "autoscale driver (mesh/autoscale.py): tick occupancy (busy "
        "seconds per wall second) at or above this is a scale-up "
        "breach signal",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_LOW_OCCUPANCY",
        "0.3",
        "float",
        "autoscale driver: occupancy at or below this (with every "
        "other signal quiet) is a scale-down breach signal",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_HIGH_RING_PRESSURE",
        "0.85",
        "float",
        "autoscale driver: resident ring bytes over "
        "FOREMAST_INGEST_BUDGET_BYTES at or above this fraction is a "
        "scale-up breach signal (eviction pressure turns warm fetches "
        "back into fallback fetches)",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_HIGH_WRITE_QUEUE",
        "8",
        "int",
        "autoscale driver: a slow-path write-queue peak at or above "
        "this is a scale-up breach signal",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_BREACH_TICKS",
        "3",
        "int",
        "autoscale driver hysteresis: a signal must breach for this "
        "many CONSECUTIVE observations before a verdict fires",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_COOLDOWN_SECONDS",
        "120",
        "float",
        "autoscale driver hysteresis: no verdict within this window of "
        "the previous one — the rebalance transient a scale event "
        "itself causes must not trigger the next one",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_MIN_WORKERS",
        "1",
        "int",
        "autoscale driver: scale-down floor",
    ),
    EnvKnob(
        "FOREMAST_AUTOSCALE_MAX_WORKERS",
        "64",
        "int",
        "autoscale driver: scale-up ceiling",
    ),
    EnvKnob(
        "FOREMAST_MAX_GAUGE_FAMILIES",
        "512",
        "int",
        "gauge-family cap: past it, publishes for NEW metric names are "
        "dropped (counted on "
        "`foremastbrain_gauge_families_dropped_total`, warned once)",
    ),
    EnvKnob(
        "FOREMAST_TRACE_DIR",
        None,
        "path",
        "directory for Perfetto-loadable span ring-buffer dumps; unset "
        "disables the buffer (stage histograms stay on)",
    ),
    EnvKnob(
        "FOREMAST_PROFILE",
        None,
        "path",
        "dump jax.profiler traces around scoring",
    ),
    EnvKnob(
        "FOREMAST_LOCK_WITNESS",
        None,
        "bool",
        "`1` wraps this package's locks to record real acquisition "
        "order (one list append per acquire) and logs at exit any "
        "edge missing from the committed `analysis_lockgraph.json` — "
        "the static lock-order model's runtime witness "
        "(docs/static-analysis.md)",
    ),
    EnvKnob(
        "FOREMAST_RECOMPILE_WITNESS",
        None,
        "bool",
        "`1` counts actual XLA backend compiles via `jax.monitoring` "
        "and logs the total at exit — a warm fleet whose count keeps "
        "growing has a dispatch cache-key leak; the benches use the "
        "same witness to assert zero warm-phase recompiles in-run "
        "(the static recompile-hazard rule's runtime twin, "
        "docs/static-analysis.md)",
    ),
    EnvKnob(
        "FOREMAST_SERVICE_ENDPOINT",
        "http://localhost:8099",
        "str",
        "browser-reachable job-gateway URL for the UI",
    ),
    EnvKnob(
        "QUERY_SERVICE_ENDPOINT",
        None,
        "str",
        "Prometheus base for the service's query proxy",
    ),
    EnvKnob(
        "FOREMAST_UI_NAMESPACE",
        "foremast-examples",
        "str",
        "dashboard's charted namespace label",
    ),
    EnvKnob("FOREMAST_UI_APP", "demo", "str", "dashboard's charted app label"),
    EnvKnob(
        "FOREMAST_BENCH_ROUND",
        None,
        "int",
        "benchmark-round override for the BENCH_rNN.json summaries "
        "(benchmarks/report.py): set when re-running a bench for an "
        "already-pinned BENCHMARKS.md round; unset, the round is the "
        "highest pinned round + 1",
    ),
    # -- multi-tenant QoS plane (ISSUE 20)
    EnvKnob(
        "FOREMAST_TENANTS",
        None,
        "str",
        "tenant spec map as inline JSON or `@/path/to/file.json` "
        "(FOREMAST_CHAOS_PLAN-style): `{name: {weight, ring_bytes, "
        "arena_rows, ingest_bytes_per_s, burst_bytes}}` (or wrapped "
        "under a top-level `tenants` key); 0/omitted fields mean no "
        "envelope. Unset or a single tenant keeps every scheduling "
        "and eviction path byte-identical to the untenanted worker; "
        ">=2 tenants turns on weighted-fair claim ordering, "
        "per-tenant ingest admission and budget-envelope eviction. "
        "Malformed JSON raises at startup",
    ),
    EnvKnob(
        "FOREMAST_TENANT_LABEL",
        "tenant",
        "str",
        "series/doc label the tenant is resolved from (canonical "
        "selector label on pushed series, URL-encoded matcher in doc "
        "query configs); series without it belong to `default`",
    ),
    EnvKnob(
        "FOREMAST_TENANT_LABEL_MAX",
        "64",
        "int",
        "cardinality cap for the `tenant` metric label: configured "
        "tenants always export under their own name; at most this "
        "many UNCONFIGURED observed values get label slots, the rest "
        "fold into `other` (BrainGauges-style, warned once)",
    ),
    # -- deployment / platform integration
    EnvKnob(
        "NAMESPACE",
        "default",
        "str",
        "fallback gauge namespace label; the watch plane's own namespace "
        "(downward-API parity)",
        "deploy",
    ),
    EnvKnob(
        "JAX_COORDINATOR_ADDRESS",
        None,
        "str",
        "multi-host init (pod mode), with `JAX_NUM_PROCESSES` / "
        "`JAX_PROCESS_ID`",
        "deploy",
    ),
    EnvKnob("JAX_NUM_PROCESSES", None, "int", "multi-host init", "deploy"),
    EnvKnob(
        "FOREMAST_POD_TIMEOUT_SECONDS",
        "300",
        "float",
        "pod-mode collective watchdog: a broadcast that does not "
        "complete within this budget aborts the tick "
        "(PodCollectiveTimeout) so a follower never hangs on a dead "
        "leader — the in-flight claims age out via "
        "MAX_STUCK_IN_SECONDS takeover. `0` disables the watchdog",
        "deploy",
    ),
    EnvKnob("JAX_PROCESS_ID", None, "int", "multi-host init", "deploy"),
    EnvKnob(
        "KUBERNETES_SERVICE_HOST",
        "kubernetes.default.svc",
        "str",
        "in-cluster API server (injected by the kubelet)",
        "deploy",
    ),
    EnvKnob(
        "KUBERNETES_SERVICE_PORT",
        "443",
        "str",
        "in-cluster API server port",
        "deploy",
    ),
    EnvKnob(
        "K8S_METRICS_COMMON_TAGS",
        None,
        "str",
        "instrument starter: comma-separated `key:value` tags stamped on "
        "every emitted metric",
        "deploy",
    ),
    EnvKnob(
        "APP_NAME",
        None,
        "str",
        "instrument starter: fallback `app` tag",
        "deploy",
    ),
)

ENV_KNOB_NAMES = frozenset(k.name for k in ENV_KNOBS)


def env_overrides(env: Mapping[str, str] | None = None) -> dict[str, str]:
    """Registered knobs explicitly set in the process env — the varz
    plane's enumerable answer to "how is this worker configured beyond
    defaults" (non-indexed knobs only; values are raw strings)."""
    e = os.environ if env is None else env
    return {k.name: e[k.name] for k in ENV_KNOBS if k.name in e}
