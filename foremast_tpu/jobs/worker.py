"""The brain worker — claim, fetch, judge (batched), write back.

Reference loop (SURVEY.md section 3.2): poll ES for claimable docs (stuck-job
takeover after MAX_STUCK_IN_SECONDS), mark preprocess_inprogress, HTTP-GET
each query_range URL, run pairwise + historical-model scoring, fail fast to
`completed_unhealth` on any anomaly, else keep re-checking until endTime
then `completed_health`.

TPU re-design: one worker claims MANY jobs per tick and judges every
(job x alias) window in a single batched `HealthJudge.judge` call — jobs
are array rows, not units of work. Horizontal scaling still works exactly
like the reference (shared-nothing workers against the same store, CAS
claims), but each worker saturates a chip instead of a 100m-CPU sliver.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Callable

import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import (
    HEALTHY,
    UNHEALTHY,
    UNKNOWN,
    HealthJudge,
    MetricTask,
    MetricVerdict,
    combine_verdicts,
)
from foremast_tpu.jobs.models import (
    STATUS_COMPLETED_HEALTH,
    STATUS_COMPLETED_UNHEALTH,
    STATUS_COMPLETED_UNKNOWN,
    STATUS_PREPROCESS_COMPLETED,
    STATUS_PREPROCESS_FAILED,
    AnomalyInfo,
    Document,
)
from foremast_tpu.jobs.store import JobStore, parse_time
from foremast_tpu.metrics.promql import decode_config
from foremast_tpu.metrics.source import MetricSource

log = logging.getLogger("foremast_tpu.worker")

# History-cache sizing and admission: entries are whole ~10k-point series
# (~120 KB), so the cap is independent of MAX_CACHE_SIZE (model params);
# a range's `end` must be at least this far in the past before its series
# is treated as immutable (covers the reference's 1-min Prometheus
# ingestion latency with margin, metricsquery.go:53-55).
HIST_CACHE_ENTRIES = 256
HIST_SETTLED_SECONDS = 120.0

_EMPTY_TIMES = np.zeros(0, np.int64)
_EMPTY_VALUES = np.zeros(0, np.float32)


def _hist_end_epoch(url: str) -> float | None:
    """The historical range's end as unix seconds, or None if unknown.

    Handles both datasource URL shapes: Prometheus query_range's `?end=`
    parameter (epoch float or RFC3339 — Prometheus accepts either,
    prometheushelper.go:12-27) and the wavefront stub's
    `<query>&&<start>&&<unit>&&<end>` encoding (wavefronthelper.go:20-29).
    """
    import urllib.parse

    raw: str | None = None
    try:
        q = urllib.parse.parse_qs(urllib.parse.urlparse(url).query)
        raw = q["end"][0]
    except (KeyError, IndexError):
        if "&&" in url:
            parts = url.split("&&")
            if len(parts) >= 4:
                raw = parts[3]
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        ts = parse_time(raw)  # RFC3339 fallback; 0.0 when unparseable
        return ts if ts > 0 else None


def infer_metric_type(alias: str, config: BrainConfig) -> str | None:
    """Map a metric alias onto a per-type threshold row by substring match
    (the reference keys its override matrix by metric *type* names like
    error5xx/latency which appear in the aliases, foremast-brain.yaml:32-73)."""
    low = alias.lower()
    for rule in config.anomaly.rules:
        if rule.metric_type.lower() in low:
            return rule.metric_type
    return None


class BrainWorker:
    """One scoring node. `tick()` processes one claim-fetch-judge-write
    cycle; `run()` loops forever."""

    def __init__(
        self,
        store: JobStore,
        source: MetricSource,
        config: BrainConfig | None = None,
        judge: HealthJudge | None = None,
        worker_id: str | None = None,
        claim_limit: int = 256,
        on_verdict: Callable[[Document, list[MetricVerdict]], None] | None = None,
        metrics=None,  # observe.gauges.WorkerMetrics (optional)
    ):
        self.store = store
        self.source = source
        self.config = config or BrainConfig()
        if judge is None:
            # MultivariateJudge dispatches by metric count (design.md:57-93:
            # 1 -> univariate, 2 -> bivariate normal, 3+ -> LSTM) and
            # delegates univariate jobs to a plain HealthJudge
            from foremast_tpu.engine.multivariate import MultivariateJudge

            judge = MultivariateJudge(self.config)
        self.judge = judge
        self.worker_id = worker_id or f"brain-{uuid.uuid4().hex[:8]}"
        self.claim_limit = claim_limit
        self.on_verdict = on_verdict  # gauge-export hook (observe/)
        # Historical-window cache for the incremental re-check loop
        # (SURVEY "hard part" (d)): a job's historical query_range URL is
        # fixed for the job's lifetime, so a job re-checked every tick
        # until endTime need not re-fetch ~10k-point histories each time.
        # Only ranges whose `end` is safely in the past are cached (see
        # _fetch_hist_cached); sized independently of MAX_CACHE_SIZE —
        # entries are ~120 KB series, not model params.
        from foremast_tpu.models.cache import ModelCache

        self._hist_cache = ModelCache(HIST_CACHE_ENTRIES)
        # Fitted-forecast cache (the reference's MAX_CACHE_SIZE model
        # cache, `foremast-brain/README.md:30`): terminal forecaster state
        # per (algorithm, app|alias|historical-URL), so a re-check tick on
        # an unchanged history skips the 7-day scan and re-runs only the
        # judgment tail. Attached to the univariate judge (the LSTM path
        # has its own ModelCache in MultivariateJudge).
        self._fit_cache = ModelCache(self.config.max_cache_size)
        uni = getattr(self.judge, "univariate", self.judge)
        if isinstance(uni, HealthJudge):
            uni.fit_cache = self._fit_cache
        # the algorithm the univariate judge actually fits/caches under
        # (a multivariate selector rewrites it to its univariate fallback)
        # ... and the season it caches under: BOTH must come from the
        # judge actually doing the caching (an injected judge may carry a
        # different config than the worker's own), or the warm-path probe
        # key would never match and every tick would refetch histories
        eff_cfg = uni.config if isinstance(uni, HealthJudge) else self.config
        self._eff_algo = eff_cfg.algorithm
        self._eff_season = eff_cfg.season_steps
        from foremast_tpu.engine.judge import GAP_SENSITIVE_FITS

        self._gap_sensitive = self._eff_algo in GAP_SENSITIVE_FITS
        # per-document decoded config/endTime metadata (immutable per doc
        # id — see _doc_meta) and per-fit-key gap anchors (step, last
        # hist timestamp) for the history-free warm path
        self._meta_cache = ModelCache(max(4096, 2 * claim_limit))
        self._gap_meta = ModelCache(max(4096, 8 * claim_limit))
        self.metrics = metrics

    # -- preprocess: document -> MetricTasks ----------------------------

    def _doc_meta(self, doc: Document):
        """Per-document decoded metadata, cached by document id.

        A document's id is the HMAC of its app/times/configs
        (`elasticsearchstore.go:29`), so the decoded config strings,
        per-alias metric types, historical end epochs and the parsed
        endTime are immutable per id — decoding them on every re-check
        tick is pure per-tick overhead (3 string splits + N substring
        matches + RFC3339 parses per doc x 10k docs x every tick).
        Entries: (aliases, end_epoch) where aliases is a list of
        (alias, cur_url, metric_type, base_url, hist_url, fit_key,
        hist_end_epoch)."""
        meta = self._meta_cache.get(doc.id)
        if meta is not None:
            return meta
        cur = decode_config(doc.current_config)
        base = decode_config(doc.baseline_config)
        hist = decode_config(doc.historical_config)
        aliases = []
        for alias, cur_url in cur.items():
            hist_url = hist.get(alias)
            aliases.append(
                (
                    alias,
                    cur_url,
                    infer_metric_type(alias, self.config),
                    base.get(alias),
                    hist_url,
                    # immutable history => the fitted model is immutable
                    # too; key it per (app, alias, URL)
                    f"{doc.app_name}|{alias}|{hist_url}" if hist_url else None,
                    _hist_end_epoch(hist_url) if hist_url else None,
                )
            )
        meta = (aliases, parse_time(doc.end_time))
        self._meta_cache.put(doc.id, meta)
        return meta

    def _fetch_tasks(self, doc: Document, now: float) -> list[MetricTask] | None:
        """Fetch every window of every alias; None => preprocess failure."""
        aliases, _ = self._doc_meta(doc)
        if not aliases:
            return None
        tasks = []
        empty_t = _EMPTY_TIMES
        empty_v = _EMPTY_VALUES
        try:
            for alias, cur_url, mtype, base_url, hist_url, key, hist_end in aliases:
                ct, cv = self.source.fetch(cur_url)
                fit_key = None
                step_kw = {}
                if hist_url is not None:
                    settled = (
                        hist_end is not None
                        and hist_end <= now - HIST_SETTLED_SECONDS
                    )
                    if settled:
                        fit_key = key
                        entry = self._fit_cache.get(
                            (self._eff_algo, self._eff_season, key)
                        )
                        gap = (
                            self._gap_meta.get(key)
                            if self._gap_sensitive
                            else None
                        )
                        if entry is not None and (
                            gap is not None or not self._gap_sensitive
                        ):
                            # warm fast path: the fitted state is cached,
                            # so the task needs no history at all — skip
                            # the fetch (no datastore round trip) and
                            # attach the ENTRY itself (race-free: see
                            # MetricTask.fit_entry) plus, for seasonal
                            # fits, the gap anchors
                            ht, hv = empty_t, empty_v
                            step_kw = dict(fit_entry=entry)
                            if gap is not None:
                                step_kw.update(
                                    hist_step=gap[0], hist_last_t=gap[1]
                                )
                        else:
                            ht, hv = self._fetch_hist_cached(hist_url, now)
                            if len(ht) and self._gap_sensitive:
                                from foremast_tpu.engine.judge import infer_step

                                self._gap_meta.put(
                                    key, (infer_step(ht), float(ht[-1]))
                                )
                    else:
                        # mutable range: fetch fresh every tick, never
                        # cache the series or the fit
                        ht, hv = self.source.fetch(hist_url)
                else:
                    ht, hv = ct[:0], cv[:0]
                kw = {}
                if base_url is not None:
                    bt, bv = self.source.fetch(base_url)
                    kw = dict(base_times=bt, base_values=bv)
                tasks.append(
                    MetricTask(
                        job_id=doc.id,
                        alias=alias,
                        metric_type=mtype,
                        hist_times=ht,
                        hist_values=hv,
                        cur_times=ct,
                        cur_values=cv,
                        app=doc.app_name,
                        fit_key=fit_key,
                        **step_kw,
                        **kw,
                    )
                )
        except Exception as e:  # fetch failures fail the preprocess stage
            log.warning("preprocess failed for %s: %s", doc.id, e)
            return None
        return tasks

    def _fetch_hist_cached(self, url: str, now: float):
        """Fetch a settled historical window, memoized by URL.

        Only called for provably immutable ranges (the caller checks the
        range's end against `now` - HIST_SETTLED_SECONDS; the watcher
        builds historical ranges ending at deploy start, but REST clients
        may supply arbitrary params — a range whose end lies in the
        future or too close to `now` for datastore ingestion to have
        settled is fetched fresh every tick and never cached, series or
        fit). `now` is the tick's injectable clock so admission is
        deterministic in tests."""
        cached = self._hist_cache.get(url)
        if cached is not None:
            return cached
        series = self.source.fetch(url)
        self._hist_cache.put(url, series)
        return series

    # -- postprocess: verdicts -> document status -----------------------

    def _write_back(
        self, doc: Document, verdicts: list[MetricVerdict], now: float
    ) -> Document:
        job_verdict = combine_verdicts(verdicts)
        end = self._doc_meta(doc)[1]  # parsed once per doc, not per tick
        # a missing/unparseable endTime must not make the job immortal:
        # finalize on the first judgment instead of re-checking forever
        past_end = end <= 0 or now >= end
        if job_verdict == UNHEALTHY:
            # fail fast (design.md:43)
            doc.status = STATUS_COMPLETED_UNHEALTH
            doc.status_code = "200"
            doc.reason = "anomaly detected"
            doc.anomaly_info = AnomalyInfo(
                tags="",
                values={
                    v.alias: v.anomaly_pairs for v in verdicts if v.anomaly_pairs
                },
            ).to_json()
        elif past_end:
            # window closed with no anomaly: healthy unless nothing measured
            if job_verdict == UNKNOWN:
                doc.status = STATUS_COMPLETED_UNKNOWN
                doc.reason = "insufficient data"
            else:
                doc.status = STATUS_COMPLETED_HEALTH
                doc.reason = ""
            doc.status_code = "200"
        else:
            # keep re-checking until endTime (incremental re-check loop)
            doc.status = STATUS_PREPROCESS_COMPLETED
        return self.store.update(doc)

    def warmup(self, hist_len: int = 10_080, cur_len: int = 30) -> None:
        """Precompile the scoring programs for the canonical shapes.

        XLA compiles one program per (B, Th, Tc) bucket triple, and the
        first compile of the 7-day-history judgment costs 20-40 s on a
        TPU — paid, without this, inside the first PRODUCTION tick. The
        warmup judges synthetic windows through the SHIPPED judge path at
        EVERY power-of-two batch bucket up to the claim-limit bucket
        (real claim sizes vary, so the first tick can land in any of
        them; the sweep's cost is geometric — ~2x the largest bucket
        alone, and the fit sub-batch buckets get covered by the same
        progression) at the reference workload shape (10,080-pt history,
        30-pt current, `metricsquery.go:43,75-77`). When the effective
        univariate algorithm runs through the fit cache, each bucket is
        judged twice so the warm `score_from_state` replay compiles too,
        and the warmup fits are evicted afterwards — they must not
        occupy real cache capacity."""
        import numpy as np

        from foremast_tpu.engine.judge import (
            _MIN_BUCKET,
            EXPENSIVE_FITS,
            HealthJudge,
            bucket_length,
        )

        # the algorithm the UNIVARIATE judge actually caches under — a
        # multivariate selector (auto/bivariate/lstm) rewrites it to its
        # univariate fallback (multivariate.MultivariateJudge.__init__)
        uni = getattr(self.judge, "univariate", self.judge)
        eff_algo = (
            uni.config.algorithm
            if isinstance(uni, HealthJudge)
            else self.config.algorithm
        )
        expensive = eff_algo in EXPENSIVE_FITS
        b_max = bucket_length(max(self.claim_limit, 1))
        rng = np.random.default_rng(0)
        t0 = int(time.time()) - 86_400 * 8
        ht = t0 + 60 * np.arange(hist_len, dtype=np.int64)
        ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
        hv = rng.normal(1.0, 0.1, (b_max, hist_len)).astype(np.float32)
        cv = rng.normal(1.0, 0.1, (b_max, cur_len)).astype(np.float32)
        tasks = [
            MetricTask(
                job_id=f"__warmup__{i}",
                alias="__warmup__",
                metric_type=None,
                hist_times=ht,
                hist_values=hv[i],
                cur_times=ct,
                cur_values=cv[i],
                fit_key=f"__warmup__|{i}",
            )
            for i in range(b_max)
        ]
        t_start = time.perf_counter()
        buckets = []
        rows = _MIN_BUCKET
        while rows <= b_max:
            self.judge.judge(tasks[:rows])
            if expensive:
                self.judge.judge(tasks[:rows])  # warm replay program
            buckets.append(rows)
            rows *= 2
        if expensive:
            for i in range(b_max):
                self._fit_cache.pop(
                    (eff_algo, self.config.season_steps, f"__warmup__|{i}")
                )
            # the warm-replay passes also cached stacked device state for
            # the warmup claim sets (~25 MB each at daily width) — release
            if isinstance(uni, HealthJudge):
                uni._state_stacks.clear()
        log.info(
            "warmup compiled batch buckets %s (Th=%d Tc=%d, algorithm=%s) in %.1fs",
            buckets, hist_len, cur_len, eff_algo, time.perf_counter() - t_start,
        )

    # -- main cycle ------------------------------------------------------

    def tick(self, now: float | None = None) -> int:
        """One claim-fetch-judge-write cycle. Returns #docs processed."""
        t0 = time.perf_counter()
        now = time.time() if now is None else now
        docs = self.store.claim(
            self.worker_id, self.config.max_stuck_seconds, self.claim_limit
        )
        if not docs:
            # idle cycles still did the claim round-trip (real store I/O)
            # and must be visible on the tick histogram
            if self.metrics:
                self.metrics.tick_seconds.observe(time.perf_counter() - t0)
            return 0

        # Fetch every claimed doc's windows concurrently: the fetches are
        # HTTP round trips to Prometheus (latency-bound), and a tick may
        # claim hundreds of jobs; serial fetching would make wall-clock
        # scale with claim count instead of the slowest single fetch.
        all_tasks: list[MetricTask] = []
        failed: list[Document] = []
        ok_docs: list[Document] = []
        # ... but only when the source actually blocks on I/O: in-memory
        # sources (replay/static/tests/benchmarks) declare
        # concurrent_fetch=False, and threading pure-Python dict lookups
        # through a pool is pure GIL overhead on the worker's host core
        if len(docs) > 1 and getattr(self.source, "concurrent_fetch", True):
            from concurrent.futures import ThreadPoolExecutor
            from functools import partial as _partial

            with ThreadPoolExecutor(max_workers=min(16, len(docs))) as pool:
                fetched = list(pool.map(_partial(self._fetch_tasks, now=now), docs))
        else:
            fetched = [self._fetch_tasks(doc, now) for doc in docs]
        for doc, tasks in zip(docs, fetched):
            # claim() already flipped + persisted preprocess_inprogress
            if tasks is None:
                doc.status = STATUS_PREPROCESS_FAILED
                doc.status_code = "500"
                doc.reason = "metric fetch failed"
                self.store.update(doc)
                failed.append(doc)
            else:
                ok_docs.append(doc)
                all_tasks.extend(tasks)

        # ONE batched judgment for every window of every claimed job
        verdicts = self.judge.judge(all_tasks)
        by_job: dict[str, list[MetricVerdict]] = {}
        for v in verdicts:
            by_job.setdefault(v.job_id, []).append(v)

        for doc in ok_docs:
            vs = by_job.get(doc.id, [])
            self._write_back(doc, vs, now)
            if self.metrics:
                self.metrics.observe_doc(doc.status, len(vs))
            if self.on_verdict:
                try:
                    self.on_verdict(doc, vs)
                except Exception:
                    log.exception("on_verdict hook failed for %s", doc.id)
        if self.metrics:
            for doc in failed:
                self.metrics.observe_doc(doc.status, 0)
            self.metrics.tick_seconds.observe(time.perf_counter() - t0)
        return len(docs)

    def run(
        self,
        poll_seconds: float = 5.0,
        stop: Callable[[], bool] | None = None,
        after_tick: Callable[[int], None] | None = None,
    ):
        """Poll forever (the shared-nothing worker loop, design.md:35-43).

        `after_tick(n_processed)` runs after every cycle — the hook for
        periodic model-cache checkpointing and similar housekeeping."""
        while not (stop and stop()):
            n = self.tick()
            if after_tick:
                try:
                    after_tick(n)
                except Exception:
                    log.exception("after_tick hook failed")
            if n == 0:
                time.sleep(poll_seconds)
