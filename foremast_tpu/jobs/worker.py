"""The brain worker — claim, fetch, judge (batched), write back.

Reference loop (SURVEY.md section 3.2): poll ES for claimable docs (stuck-job
takeover after MAX_STUCK_IN_SECONDS), mark preprocess_inprogress, HTTP-GET
each query_range URL, run pairwise + historical-model scoring, fail fast to
`completed_unhealth` on any anomaly, else keep re-checking until endTime
then `completed_health`.

TPU re-design: one worker claims MANY jobs per tick and judges every
(job x alias) window in a single batched `HealthJudge.judge` call — jobs
are array rows, not units of work. Horizontal scaling still works exactly
like the reference (shared-nothing workers against the same store, CAS
claims), but each worker saturates a chip instead of a 100m-CPU sliver.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import uuid
from typing import Callable

import numpy as np

from foremast_tpu.chaos.degrade import (
    REASON_ABORT,
    REASON_DEADLINE,
    REASON_DEMOTED,
    REASON_FETCH,
    REASON_REPLAYED,
    Degradation,
    is_transient_error,
)
from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import (
    HEALTHY,
    UNHEALTHY,
    UNKNOWN,
    HealthJudge,
    MetricTask,
    MetricVerdict,
    combine_verdicts,
)
from foremast_tpu.jobs.models import (
    STATUS_COMPLETED_HEALTH,
    STATUS_COMPLETED_UNHEALTH,
    STATUS_COMPLETED_UNKNOWN,
    STATUS_PREPROCESS_COMPLETED,
    STATUS_PREPROCESS_FAILED,
    TERMINAL_STATUSES,
    AnomalyInfo,
    Document,
)
from foremast_tpu.jobs.store import JobStore, parse_time
from foremast_tpu.mesh.routing import doc_route_key
from foremast_tpu.metrics.promql import decode_config
from foremast_tpu.metrics.source import MetricSource
from foremast_tpu.observe.logs import ctx_log
from foremast_tpu.observe.spans import inherit_span, span

log = logging.getLogger("foremast_tpu.worker")

# History-cache sizing and admission: entries are whole ~10k-point series
# (~120 KB), so the cap is independent of MAX_CACHE_SIZE (model params);
# a range's `end` must be at least this far in the past before its series
# is treated as immutable (covers the reference's 1-min Prometheus
# ingestion latency with margin, metricsquery.go:53-55).
HIST_CACHE_ENTRIES = 256
HIST_SETTLED_SECONDS = 120.0

_EMPTY_TIMES = np.zeros(0, np.int64)
_EMPTY_VALUES = np.zeros(0, np.float32)

# Partial-tick sentinels (ISSUE 9): a doc whose fetch failed
# TRANSIENTLY (dependency down, breaker open) or whose turn came after
# the tick budget is RELEASED — status back to preprocess_completed,
# claimable next tick, counted on foremast_degraded_docs{reason} —
# instead of terminally preprocess_failed (permanent errors keep that
# reference behavior) or wedging the tick. Two sentinels so the
# counters attribute the release to the right cause.
RELEASED = object()  # transient fetch failure
RELEASED_DEADLINE = object()  # tick budget exceeded

# Sliced, preemptible sweeps (ISSUE 15): a full sweep whose claim can
# exceed FOREMAST_SWEEP_SLICE_DOCS (reactive/dirty.py:
# sweep_slice_docs_from_env, default 2048, 0 = monolithic opt-out)
# runs as a SEQUENCE of bounded slices through a warm-path pipeline
# (claim-pool prepare / async columnar dispatch / gather+decode+
# write), with a micro-tick preemption point at every slice boundary —
# pushed-anomaly latency is bounded by one slice's wall clock, not the
# sweep's.


class _TickLedger:
    """One judging cycle's arrival-attribution state (ISSUE 12/15):
    the route keys this cycle owes a push→verdict latency observation
    (``pending``: key → receiver arrival stamp) and the keys already
    observed. A sliced sweep and the micro-ticks that PREEMPT it
    mid-flight each carry their OWN ledger, so a nested cycle can never
    clobber the outer one's attribution (the sweep's writer thread
    reads its ledger while the tick thread runs the nested micro).
    Individual dict/set operations are GIL-atomic; iteration happens
    only after the cycle's pipeline threads are joined."""

    __slots__ = ("path", "pending", "observed")

    def __init__(self, path: str, pending=None):
        self.path = path
        self.pending: dict[str, float] = dict(pending) if pending else {}
        self.observed: set[str] = set()


class _SweepPool:
    """The sliced sweep's claimed-but-unsliced document pool.

    One leaf lock guards the queue, the route-key index, the promoted
    front, and the in-flight key counts — three threads touch it: the
    prefetch thread takes slices, the tick thread promotes dirty route
    keys to the front at preemption points, and the writer thread
    retires written slices. ``promote`` is how a pushed anomaly whose
    document is claimed but NOT yet fetched jumps the queue: its slice
    runs next, fetches post-arrival samples, and the sweep itself
    delivers the verdict inside ~one slice."""

    def __init__(self, docs, tenancy=None):
        self._lock = threading.Lock()
        self._queue = collections.OrderedDict((d.id, d) for d in docs)
        self._keys: dict[str, list[str]] = {}
        for d in docs:
            self._keys.setdefault(doc_route_key(d), []).append(d.id)
        self._front: collections.deque = collections.deque()
        self._inflight: dict[str, int] = {}
        # Tenant-fair slice order (ISSUE 20): with >= 2 tenants
        # configured, take() serves tenants deficit-weighted (promoted
        # docs still jump everything — preemption latency beats
        # fairness), so a whale tenant's 100k-doc claim cannot push a
        # quiet tenant's documents to the sweep's tail. With one (or
        # zero) tenants self._drr stays None and take() is
        # byte-identical to the untenanted queue order (parity pin).
        self._tenancy = (
            tenancy if tenancy is not None and tenancy.fair else None
        )
        self._drr = None
        self._tenant_of: dict[str, str] = {}
        self._tqueues: dict[str, collections.OrderedDict] = {}
        if self._tenancy is not None:
            from foremast_tpu.tenant.fairness import DeficitRoundRobin

            self._drr = DeficitRoundRobin(self._tenancy.weights())
            for d in docs:
                t = self._tenancy.tenant_of_doc(d)
                self._tenant_of[d.id] = t
                self._tqueues.setdefault(
                    t, collections.OrderedDict()
                )[d.id] = d

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def take(self, n: int) -> list:
        """Next slice: promoted docs first, then queue order — or,
        with tenant fairness active, deficit-weighted across tenants
        (claim order preserved within a tenant). Taken docs enter the
        in-flight set until `done` retires them."""
        out = []
        with self._lock:
            while self._front and len(out) < n:
                doc = self._queue.pop(self._front.popleft(), None)
                if doc is not None:
                    out.append(doc)
            if self._drr is None:
                while self._queue and len(out) < n:
                    _, doc = self._queue.popitem(last=False)
                    out.append(doc)
            else:
                # promoted docs leave their tenant queues too
                for doc in out:
                    self._tpop(doc.id)
                need = n - len(out)
                if need > 0 and self._queue:
                    order = self._drr.pick(
                        {t: len(q) for t, q in self._tqueues.items()},
                        need,
                    )
                    for t in order:
                        tq = self._tqueues.get(t)
                        if not tq:
                            continue
                        doc_id, doc = tq.popitem(last=False)
                        if not tq:
                            del self._tqueues[t]
                        self._tenant_of.pop(doc_id, None)
                        self._queue.pop(doc_id, None)
                        out.append(doc)
            for doc in out:
                rk = doc_route_key(doc)
                ids = self._keys.get(rk)
                if ids:
                    try:
                        ids.remove(doc.id)
                    except ValueError:
                        pass
                    if not ids:
                        del self._keys[rk]
                self._inflight[rk] = self._inflight.get(rk, 0) + 1
        return out

    def _tpop(self, doc_id: str) -> None:
        """Drop one doc from its tenant queue. Runs ONLY from take()'s
        `with self._lock:` block — the lock is not reentrant, so the
        guarded accesses carry the suppression instead."""
        # foremast: ignore[lock-discipline] — caller (take) holds _lock
        t = self._tenant_of.pop(doc_id, None)
        if t is None:
            return
        # foremast: ignore[lock-discipline] — caller (take) holds _lock
        tq = self._tqueues.get(t)
        if tq is not None:
            tq.pop(doc_id, None)
            if not tq:
                # foremast: ignore[lock-discipline] — caller holds _lock
                del self._tqueues[t]

    def drain(self) -> list:
        """Everything still pooled (deadline expiry / abort): one bulk
        release instead of judging over budget."""
        with self._lock:
            out = list(self._queue.values())
            self._queue.clear()
            self._keys.clear()
            self._tqueues.clear()
            self._tenant_of.clear()
            return out

    def done(self, docs) -> None:
        """A slice's docs were written (or released): their route keys
        leave the in-flight set, making them fair game for the next
        boundary's micro-tick."""
        with self._lock:
            for doc in docs:
                rk = doc_route_key(doc)
                c = self._inflight.get(rk, 0)
                if c <= 1:
                    self._inflight.pop(rk, None)
                else:
                    self._inflight[rk] = c - 1

    def promote(self, route_key: str) -> bool:
        """Move every pooled doc of `route_key` to the front of the
        slice order; False when none are pooled."""
        with self._lock:
            ids = self._keys.get(route_key)
            if not ids:
                return False
            self._front.extend(ids)
            return True

    def inflight(self, route_key: str) -> bool:
        with self._lock:
            return route_key in self._inflight


class _SlicePrep:
    """One prepared slice: admission split + fetched windows + packed
    columnar buffers, built on the prefetch thread. `release_all` marks
    a deadline-expiry bundle (every doc releases un-judged)."""

    __slots__ = (
        "docs", "claim_mono", "slow", "ok_items", "ok_citems", "ok_joint",
        "failed", "released", "uni_packed", "canary_packed", "release_all",
        "slow_done",
    )

    def __init__(self, docs, claim_mono, release_all=False):
        self.docs = docs
        self.claim_mono = claim_mono
        self.release_all = release_all
        self.slow_done = False
        self.slow = []
        self.ok_items = []
        self.ok_citems = []
        self.ok_joint = []
        self.failed = []
        self.released = []
        self.uni_packed = None
        self.canary_packed = None


class _UniPacked:
    """One packed univariate/canary columnar bucket: the [B, tc]
    buffers plus per-row operands, ready for `judge_columnar_async`.
    `ok_items` is the (possibly canary-split) item list the decode
    walks. Built on whichever thread packs (prefetch under the sliced
    sweep); consumed by dispatch (tick thread) and decode (writer)."""

    __slots__ = (
        "ok_items", "values", "mask", "keys", "entries", "nidx",
        "thr", "bnd", "mlb", "gaps", "tc", "canary",
        "base_vals", "base_m",
    )

    def __init__(
        self, ok_items, values, mask, keys, entries, nidx,
        thr, bnd, mlb, gaps, tc, canary, base_vals, base_m,
    ):
        self.ok_items = ok_items
        self.values = values
        self.mask = mask
        self.keys = keys
        self.entries = entries
        self.nidx = nidx
        self.thr = thr
        self.bnd = bnd
        self.mlb = mlb
        self.gaps = gaps
        self.tc = tc
        self.canary = canary
        self.base_vals = base_vals
        self.base_m = base_m


class _SliceResult:
    """A dispatched slice: pending (ungathered) columnar judgments plus
    the synchronously-judged joint docs. `aborted` marks a StageError
    partial — finish writes what was judged and releases the rest."""

    __slots__ = (
        "prep", "joint_updated", "joint_counts", "uni_pending",
        "canary_pending", "aborted",
    )

    def __init__(self, prep):
        self.prep = prep
        self.joint_updated = []
        self.joint_counts = None
        self.uni_pending = None
        self.canary_pending = None
        self.aborted = False


def _hist_end_epoch(url: str) -> float | None:
    """The historical range's end as unix seconds, or None if unknown.

    Handles both datasource URL shapes: Prometheus query_range's `?end=`
    parameter (epoch float or RFC3339 — Prometheus accepts either,
    prometheushelper.go:12-27) and the wavefront stub's
    `<query>&&<start>&&<unit>&&<end>` encoding (wavefronthelper.go:20-29).
    """
    import urllib.parse

    raw: str | None = None
    try:
        q = urllib.parse.parse_qs(urllib.parse.urlparse(url).query)
        raw = q["end"][0]
    except (KeyError, IndexError):
        if "&&" in url:
            parts = url.split("&&")
            if len(parts) >= 4:
                raw = parts[3]
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        ts = parse_time(raw)  # RFC3339 fallback; 0.0 when unparseable
        return ts if ts > 0 else None


def infer_metric_type(alias: str, config: BrainConfig) -> str | None:
    """Map a metric alias onto a per-type threshold row by substring match
    (the reference keys its override matrix by metric *type* names like
    error5xx/latency which appear in the aliases, foremast-brain.yaml:32-73)."""
    low = alias.lower()
    for rule in config.anomaly.rules:
        if rule.metric_type.lower() in low:
            return rule.metric_type
    return None


class BrainWorker:
    """One scoring node. `tick()` processes one claim-fetch-judge-write
    cycle; `run()` loops forever."""

    def __init__(
        self,
        store: JobStore,
        source: MetricSource,
        config: BrainConfig | None = None,
        judge: HealthJudge | None = None,
        worker_id: str | None = None,
        claim_limit: int = 256,
        on_verdict: Callable[[Document, list[MetricVerdict]], None] | None = None,
        metrics=None,  # observe.gauges.WorkerMetrics (optional)
        band_mode: str = "last",
        tracer=None,  # observe.spans.Tracer (optional)
        mesh=None,  # mesh.node.MeshNode (optional fleet partitioning)
        degrade: Degradation | None = None,
        dirty=None,  # reactive.DirtySet (optional: micro-tick plane)
        device_mesh="env",  # jax.sharding.Mesh | None | "env" (ISSUE 13)
    ):
        """`band_mode` controls how much of the model band each verdict
        carries back from the device: "last" (default — only the final
        band point, what the built-in gauge exporter publishes; ~15x
        fewer D2H bytes per tick) or "full" (whole [Tc] band per metric,
        for custom on_verdict hooks that consume the band shape)."""
        self.store = store
        self.source = source
        self.config = config or BrainConfig()
        if judge is None:
            # MultivariateJudge dispatches by metric count (design.md:57-93:
            # 1 -> univariate, 2 -> bivariate normal, 3+ -> LSTM) and
            # delegates univariate jobs to a HealthJudge. ISSUE 13: that
            # univariate engine spans the worker's DEVICE MESH by default
            # (`device_mesh`: a jax Mesh, None to force single-device, or
            # "env" to resolve FOREMAST_DEVICE_MESH — "auto" = all local
            # devices; a 1-device resolution IS the single-device judge,
            # so stock CPU hosts pay zero placement overhead). Sharding
            # is placement, not semantics: arenas replicate, batches
            # partition their leading axis, every cache/admission/
            # degradation contract is unchanged.
            from foremast_tpu.engine.multivariate import MultivariateJudge

            if device_mesh is None:
                univariate = None
            else:
                from foremast_tpu.parallel.batch import sharded_univariate

                univariate = sharded_univariate(
                    self.config,
                    mesh=None if device_mesh == "env" else device_mesh,
                )
            judge = MultivariateJudge(self.config, univariate=univariate)
        self.judge = judge
        self.worker_id = worker_id or f"brain-{uuid.uuid4().hex[:8]}"
        self.claim_limit = claim_limit
        self.on_verdict = on_verdict  # gauge-export hook (observe/)
        # Historical-window cache for the incremental re-check loop
        # (SURVEY "hard part" (d)): a job's historical query_range URL is
        # fixed for the job's lifetime, so a job re-checked every tick
        # until endTime need not re-fetch ~10k-point histories each time.
        # Only ranges whose `end` is safely in the past are cached (see
        # _fetch_hist_cached); sized independently of MAX_CACHE_SIZE —
        # entries are ~120 KB series, not model params. Constructed at
        # the ring-first decision below, where its size is chosen.
        from foremast_tpu.models.cache import ModelCache
        # Fitted-forecast cache (the reference's MAX_CACHE_SIZE model
        # cache, `foremast-brain/README.md:30`): terminal forecaster state
        # per (algorithm, app|alias|historical-URL), so a re-check tick on
        # an unchanged history skips the 7-day scan and re-runs only the
        # judgment tail. Attached to the univariate judge (the LSTM path
        # has its own ModelCache in MultivariateJudge).
        self._fit_cache = ModelCache(self.config.max_cache_size)
        uni = getattr(self.judge, "univariate", self.judge)
        if isinstance(uni, HealthJudge):
            uni.fit_cache = self._fit_cache
        # the algorithm the univariate judge actually fits/caches under
        # (a multivariate selector rewrites it to its univariate fallback)
        # ... and the season it caches under: BOTH must come from the
        # judge actually doing the caching (an injected judge may carry a
        # different config than the worker's own), or the warm-path probe
        # key would never match and every tick would refetch histories
        eff_cfg = uni.config if isinstance(uni, HealthJudge) else self.config
        self._uni = uni if isinstance(uni, HealthJudge) else None
        if self._uni is not None:
            self._uni.band_mode = band_mode
        self._eff_cfg = eff_cfg
        self._eff_algo = eff_cfg.algorithm
        self._eff_season = eff_cfg.season_steps
        from foremast_tpu.engine.multivariate import (
            MULTIVARIATE_ALGOS,
            MultivariateJudge,
        )

        # multivariate selectors route multi-alias jobs to joint models;
        # single-alias docs take the univariate columnar path, and
        # multi-alias docs take the JOINT columnar path below once their
        # fits are cached (ISSUE 4 tentpole — previously every joint doc
        # fell onto the ~10x-slower per-task object path forever)
        self._mv = self.config.algorithm in MULTIVARIATE_ALGOS
        self._mvj = (
            self.judge if isinstance(self.judge, MultivariateJudge) else None
        )
        # fast-path admission cache: doc.id -> [end_epoch, rowsinfo,
        # ops, token]; token is the (fit, gap) cache-version pair at last
        # validation. A token match trusts the entry wholesale; a
        # mismatch revalidates PER ROW by entry identity (one dict peek +
        # `is` compare each) instead of discarding the whole cache — a
        # churning fleet bumps the version every tick, and the round-4
        # wholesale clear forced a full admission re-walk of the fleet
        # for every single cold fit (VERDICT r4 weak #3 / ask #4).
        self._admit: dict = {}
        from foremast_tpu.engine.judge import GAP_SENSITIVE_FITS

        self._gap_sensitive = self._eff_algo in GAP_SENSITIVE_FITS
        # joint-doc fast-path admission cache: doc.id -> [end_epoch,
        # jinfo, token]; token is the (joint cache, joint meta) version
        # pair, revalidated per entry by IDENTITY on a version bump —
        # same discipline as _admit/_revalidate above
        self._jadmit: dict = {}
        import os as _os0

        self._joint_fast = (
            self._mv
            and self._mvj is not None
            and _os0.environ.get("FOREMAST_JOINT_COLUMNAR", "1") == "1"
        )
        # canary columnar path (ISSUE 14): baseline-carrying univariate
        # docs ride the fast tick as their own bucket — a second
        # [B, tc] baseline buffer through a pairwise-active compiled
        # variant. FOREMAST_CANARY_COLUMNAR=0 opts out (they demote to
        # the object path, the pre-ISSUE-14 behavior).
        self._canary_fast = (
            _os0.environ.get("FOREMAST_CANARY_COLUMNAR", "1") == "1"
        )
        # cumulative columnar-path doc counts per model kind — the
        # per-kind bucket counters /debug/state and WorkerMetrics expose
        # (proof that joint docs actually ride the fast path;
        # "baseline" is the canary bucket — single-alias docs judged
        # WITH their baseline windows through the pairwise-active
        # columnar program)
        self._fast_kinds = {
            "univariate": 0, "bivariate": 0, "lstm": 0, "baseline": 0,
        }
        # per-document decoded config/endTime metadata (immutable per doc
        # id — see _doc_meta) and per-fit-key gap anchors (step, last
        # hist timestamp) for the history-free warm path
        self._meta_cache = ModelCache(max(4096, 2 * claim_limit))
        self._gap_meta = ModelCache(max(4096, 8 * claim_limit))
        # slow-path doc-chunk size (progressive cold admission); an
        # instance attribute so PodWorker can broadcast the leader's
        # value — per-host env skew would desync SPMD judge boundaries
        import os as _os

        self.cold_chunk_docs = int(
            _os.environ.get("FOREMAST_COLD_CHUNK_DOCS", "1024")
        )
        # Slow-path chunk pipeline (jobs/pipeline.py): depth bounds the
        # chunks in flight across fetch/judge/write (1 = serial). Also
        # broadcast by PodWorker — though pod mode degrades to serial
        # anyway (LeaderSource.concurrent_fetch = False), a per-host
        # skew must never be able to shape control flow differently.
        self.pipeline_depth = int(
            _os.environ.get("FOREMAST_PIPELINE_DEPTH", "2")
        )
        # One persistent fetch pool per worker (per-doc query_range
        # fan-out within a chunk), NOT one pool per chunk per tick:
        # constructing/tearing down a ThreadPoolExecutor spawns and
        # joins up to 16 threads each time, paid per chunk at fleet
        # scale. Lazily built so in-memory sources never spawn threads.
        self.fetch_workers = max(
            1, int(_os.environ.get("FOREMAST_FETCH_WORKERS", "16"))
        )
        self._fetch_pool = None
        self._prefetch_pool = None
        self._last_pipeline: dict | None = None
        # Ring-first cold path (ISSUE 10 tentpole): when the source can
        # serve historical ranges from resident ring columns
        # (RingSource.hist_columns — duck-typed like ingest_debug_state;
        # deliberately NOT resolved through a pod-mode LeaderSource's
        # .inner, whose fetches are ordered collectives), cold fits read
        # the ring directly and the worker's own `_hist_cache` is
        # BYPASSED for ring-covered ranges — it would double-buffer
        # ~120 KB histories the ring already owns. The cache shrinks to
        # a sliver serving only fallback-path (HTTP) reads; the decision
        # is exposed on /debug/state (`cold_start.hist_bypass`).
        self._ring_hist = getattr(source, "hist_columns", None)
        self._hist_bypass = self._ring_hist is not None
        self._hist_cache = ModelCache(
            max(8, HIST_CACHE_ENTRIES // 16)
            if self._hist_bypass
            else HIST_CACHE_ENTRIES
        )
        # pure-push: a ring source with no fallback never does HTTP —
        # its unservable reads come back empty and are labeled
        # "unserved", not "http" (operators read the http count as
        # proof a pull path exists)
        self._cold_unserved = (
            self._hist_bypass
            and getattr(source, "fallback", object()) is None
        )
        # Short-history admission + background refinement (ISSUE 10):
        # provisional fits ledger + per-tick upgrade budget. Refinement
        # INVALIDATES a provisional fit when its ring coverage grew
        # enough; the next claim refits it from the ring through the
        # production slow path (band parity by construction).
        from foremast_tpu.jobs.refine import (
            RefineBook,
            refine_docs_per_tick_from_env,
        )

        self.refine_docs_per_tick = refine_docs_per_tick_from_env()
        self._refine_book = RefineBook()
        # cold-path historical-read accounting (fetch-pool threads write
        # here, the varz scrape thread reads — lock-guarded)
        self._cold_lock = threading.Lock()
        self._cold_counts = {
            "ring_full": 0, "ring_partial": 0, "http": 0, "cache": 0,
            "unserved": 0,
        }
        self.metrics = metrics
        # Span tracer (observe/spans.py): tick() opens a root span and
        # every stage — claim, fetch, fit, arena, score, decide, write —
        # parents to it via the ambient-context helper, so the engine
        # and store need no tracer plumbing. None = zero overhead.
        self.tracer = tracer
        # Worker mesh (mesh/node.py): when set, every tick renews this
        # worker's membership lease + refreshes its ownership ring, and
        # the claim only takes documents in this worker's partition
        # (claim-CAS stays the safety net against stale ring views).
        self.mesh = mesh
        # Planned handoff (mesh/handoff.py): a mesh node carrying a
        # handoff manager streams/receives fit-cache entries on planned
        # scale events — register this worker's caches with it so a
        # moved partition arrives with its fits, not just its samples.
        if mesh is not None and getattr(mesh, "handoff", None) is not None:
            self.attach_handoff(mesh.handoff)
        self._last_tick = {"at": 0.0, "docs": 0, "fast": 0, "seconds": 0.0}
        # Durable data plane (ISSUE 7): write-through fit journals
        # (enable_fit_persistence) + the ring snapshotter the CLI
        # attaches so /debug/state can report both from one place.
        self._fit_journals: dict = {}
        self._snapshotter = None
        # last status logged per open job (pruned on terminal): open docs
        # are re-judged every poll, and re-asserting an unchanged status
        # at INFO would flood logs at fleet scale
        self._judged_status: dict[str, str] = {}
        self._JUDGED_STATUS_CAP = 16384
        # Graceful degradation (ISSUE 9): write-behind buffer for store
        # outages, per-tick deadline, breaker registry + shared
        # counters. ALWAYS present — when everything is healthy the
        # machinery costs a try/except per store write and one deadline
        # compare per chunk. The write-behind age cap is wired to the
        # stuck window so a late replay can never double-write a doc a
        # peer's claim-CAS takeover re-judged (the exactly-once net).
        self._degrade = (
            degrade
            if degrade is not None
            else Degradation.from_env(
                max_stuck_seconds=self.config.max_stuck_seconds
            )
        )
        self._tick_deadline: float | None = None
        # the current tick's claim instant (monotonic): write-behind
        # entries are stamped with THIS, not with the write-failure
        # time — the buffer's age cutoff must measure from the claim,
        # because stuck-takeover eligibility runs off the claim's
        # modified_at. Stamping at buffer time would let a slow
        # fetch/judge push the replay window past the takeover boundary
        # and double-write a doc a peer already re-judged.
        self._tick_claim_mono = time.monotonic()
        # one WARNING per degradation episode, not per buffered write
        self._write_degraded = False
        # Reactive plane (ISSUE 12): the receiver-fed dirty-series set.
        # When wired, `micro_tick()` drains it between full ticks —
        # claiming JUST the dirty documents through the same _tick body
        # (columnar fast path for warm docs, slow pipeline for cold) —
        # and full ticks demote to sweeps that drain whatever arrivals
        # the micro-ticks missed. `_pending_arrivals` is the in-flight
        # tick's route-key → receiver-arrival-stamp map; every judged
        # doc whose route key is pending observes the push→verdict
        # latency histogram (foremast_verdict_latency_seconds).
        self.dirty = dirty
        from foremast_tpu.reactive.dirty import (
            microtick_docs_from_env,
            microtick_seconds_from_env,
        )

        self.microtick_seconds = microtick_seconds_from_env()
        self.microtick_docs = microtick_docs_from_env()
        self._ledger = _TickLedger("sweep")
        self._last_micro = {"at": 0.0, "docs": 0, "seconds": 0.0, "runs": 0}
        # Sliced, preemptible sweeps (ISSUE 15): claims above this size
        # run as bounded slices through the warm-path pipeline, with a
        # dirty-drain preemption point between slices. 0 = monolithic
        # (the parity arm). PodWorker forces 0 — slice control flow off
        # local state would desync SPMD collectives, and LeaderSource
        # fetches may not run on a prefetch thread.
        from foremast_tpu.reactive.dirty import sweep_slice_docs_from_env

        self.sweep_slice_docs = sweep_slice_docs_from_env()
        self._last_sweep: dict | None = None
        # True while a sliced sweep is in flight: pins _tick_claim_mono
        # at the sweep's claim instant (see _claim_cycle)
        self._sweep_active = False
        # Tenant QoS plane (ISSUE 20, FOREMAST_TENANTS): tenant
        # resolution for the verdict-latency histogram's bounded
        # `tenant` label, per-tenant claim accounting, and — with >= 2
        # tenants configured — deficit-weighted fair slice ordering in
        # the sweep pool. None keeps every path untenanted and
        # byte-identical (the parity pin).
        from foremast_tpu.tenant.registry import get_tenancy

        self._tenancy = get_tenancy()
        self._tenant_acct = None
        if self._tenancy is not None:
            from foremast_tpu.tenant.accounting import accounting_for
            from foremast_tpu.tenant.collector import register_collector

            self._tenant_acct = accounting_for(self._tenancy)
            # export the ledger on this worker's scrape registry (the
            # receiver shares the same per-tenancy ledger, so its sheds
            # ride along); idempotent across co-registered workers
            if self.metrics is not None:
                register_collector(
                    getattr(self.metrics, "registry", None),
                    self._tenant_acct,
                )

    # -- preprocess: document -> MetricTasks ----------------------------

    def _doc_meta(self, doc: Document):
        """Per-document decoded metadata, cached by document id.

        A document's id is the HMAC of its app/times/configs
        (`elasticsearchstore.go:29`), so the decoded config strings,
        per-alias metric types, historical end epochs and the parsed
        endTime are immutable per id — decoding them on every re-check
        tick is pure per-tick overhead (3 string splits + N substring
        matches + RFC3339 parses per doc x 10k docs x every tick).
        Entries: (aliases, end_epoch) where aliases is a list of
        (alias, cur_url, metric_type, base_url, hist_url, fit_key,
        hist_end_epoch)."""
        meta = self._meta_cache.peek(doc.id)
        if meta is not None:
            return meta
        cur = decode_config(doc.current_config)
        base = decode_config(doc.baseline_config)
        hist = decode_config(doc.historical_config)
        aliases = []
        # [3, n] = (threshold, bound, min_lower_bound) per alias — the
        # fast tick concatenates these per-doc blocks into the batch
        # operand vectors with one call instead of per-row lookups.
        # Rules come from the JUDGE's effective config (eff_cfg), the
        # same source the slow path's _judge_bucket gathers from — an
        # injected judge with divergent anomaly rules must not produce
        # different verdicts on warm vs cold ticks.
        ops = np.empty((3, len(cur)), np.float32)
        for i, (alias, cur_url) in enumerate(cur.items()):
            hist_url = hist.get(alias)
            mtype = infer_metric_type(alias, self._eff_cfg)
            rule = self._eff_cfg.anomaly.rule_for(mtype)
            ops[0, i] = rule.threshold
            ops[1, i] = rule.bound
            ops[2, i] = rule.min_lower_bound
            key = f"{doc.app_name}|{alias}|{hist_url}" if hist_url else None
            aliases.append(
                (
                    alias,
                    cur_url,
                    mtype,
                    base.get(alias),
                    hist_url,
                    # immutable history => the fitted model is immutable
                    # too; key it per (app, alias, URL)
                    key,
                    _hist_end_epoch(hist_url) if hist_url else None,
                    # the full fit-cache key, prebuilt once (the fast
                    # path would otherwise build this tuple per row
                    # per tick)
                    (self._eff_algo, self._eff_season, key)
                    if key
                    else None,
                )
            )
        meta = (aliases, parse_time(doc.end_time), ops)
        self._meta_cache.put(doc.id, meta)
        return meta

    def _fetch_tasks(self, doc: Document, now: float):
        """Fetch every window of every alias; None => preprocess failure
        (permanent), the RELEASED sentinel => transient dependency
        failure, give the doc back un-judged (ISSUE 9)."""
        aliases, _, _ = self._doc_meta(doc)
        if not aliases:
            return None
        tasks = []
        empty_t = _EMPTY_TIMES
        empty_v = _EMPTY_VALUES
        # the history-free warm shortcut only serves the UNIVARIATE
        # judge: joint models (bivariate/LSTM — multi-alias docs under a
        # multivariate selector) align histories across metrics and fit
        # their own state, so an empty-hist task would collapse the
        # joint fit to zero points
        may_skip_hist = not self._mv or len(aliases) == 1
        # aliases whose history came back as a PARTIAL ring slice this
        # fetch (short-history admission) — noted in the refine book
        # after the loop so the fit they produce is tracked provisional
        partials: list[tuple] = []
        try:
            for (
                alias,
                cur_url,
                mtype,
                base_url,
                hist_url,
                key,
                hist_end,
                fullkey,
            ) in aliases:
                ct, cv = self.source.fetch(cur_url)
                fit_key = None
                step_kw = {}
                if hist_url is not None:
                    settled = (
                        hist_end is not None
                        and hist_end <= now - HIST_SETTLED_SECONDS
                    )
                    if settled:
                        fit_key = key
                        entry = (
                            self._fit_cache.get(fullkey)
                            if may_skip_hist
                            else None
                        )
                        gap = (
                            self._gap_meta.get(key)
                            if self._gap_sensitive
                            else None
                        )
                        if entry is not None and (
                            gap is not None or not self._gap_sensitive
                        ):
                            # warm fast path: the fitted state is cached,
                            # so the task needs no history at all — skip
                            # the fetch (no datastore round trip) and
                            # attach the ENTRY itself (race-free: see
                            # MetricTask.fit_entry) plus, for seasonal
                            # fits, the gap anchors
                            ht, hv = empty_t, empty_v
                            step_kw = dict(fit_entry=entry)
                            if gap is not None:
                                step_kw.update(
                                    hist_step=gap[0], hist_last_t=gap[1]
                                )
                        else:
                            ht, hv, prov = self._fetch_hist(hist_url, now)
                            if prov:
                                partials.append(
                                    (fullkey, key, hist_url, len(ht))
                                )
                            if len(ht) and self._gap_sensitive:
                                from foremast_tpu.engine.judge import infer_step

                                self._gap_meta.put(
                                    key, (infer_step(ht), float(ht[-1]))
                                )
                    else:
                        # mutable range: fetch fresh every tick, never
                        # cache the series or the fit
                        ht, hv = self.source.fetch(hist_url)
                else:
                    ht, hv = ct[:0], cv[:0]
                kw = {}
                if base_url is not None:
                    bt, bv = self.source.fetch(base_url)
                    kw = dict(base_times=bt, base_values=bv)
                tasks.append(
                    MetricTask(
                        job_id=doc.id,
                        alias=alias,
                        metric_type=mtype,
                        hist_times=ht,
                        hist_values=hv,
                        cur_times=ct,
                        cur_values=cv,
                        app=doc.app_name,
                        fit_key=fit_key,
                        **step_kw,
                        **kw,
                    )
                )
        except Exception as e:  # fetch failures fail the preprocess stage
            if is_transient_error(e):
                # dependency outage / breaker open: release un-judged
                # (claimable next tick) instead of terminal failure
                log.warning(
                    "preprocess released (transient) for %s: %s", doc.id, e
                )
                return RELEASED
            log.warning("preprocess failed for %s: %s", doc.id, e)
            return None
        if partials:
            if may_skip_hist:
                # univariate fits: one provisional record per fit key
                for fullkey, key, url, n in partials:
                    self._refine_book.note_uni(fullkey, key, url, n)
            else:
                # joint doc: one record for the doc (its joint cache
                # keys resolve through the admission cache — or by app
                # when the doc never warmed — at invalidation time)
                self._refine_book.note_joint(
                    doc.id,
                    doc.app_name,
                    tuple(u for _, _, u, _ in partials),
                    sum(n for _, _, _, n in partials),
                )
        return tasks

    def _count_cold(self, source: str) -> None:
        """One historical-range read on the cold-fit path, by source
        (ring_full / ring_partial / http / cache). Fetch-pool threads
        land here, hence the lock; the metric family mirrors the
        lock-guarded dict so /debug/state and Prometheus agree."""
        with self._cold_lock:
            self._cold_counts[source] += 1
        m = getattr(self.metrics, "cold_hist", None) if self.metrics else None
        if m is not None:
            m.labels(source=source).inc()

    def _cold_snapshot(self) -> dict:
        with self._cold_lock:
            return dict(self._cold_counts)

    def _fetch_hist(self, url: str, now: float):
        """Historical window for a cold fit: ring columns first, HTTP
        fallback second (ISSUE 10 tentpole). Returns (times, values,
        provisional) — provisional True when the window is a PARTIAL
        ring slice under short-history admission whose coverage can
        still grow inside the requested range (the caller notes it in
        the refine book).

        Ring reads bypass `_hist_cache` entirely: the ring IS the
        resident history (one slice copy, no JSON reassembly, no
        double-buffering), and the bf16-delta fit upload packs straight
        off the returned columns. Only the fallback path — ranges the
        ring cannot serve — still memoizes, and a fallback fetch
        through `RingSource.fetch` backfills the ring write-through, so
        the NEXT cold fit of the same series (second doc of the same
        app, or the restart after a PR-7 snapshot) reads resident."""
        if self._ring_hist is not None:
            res = self._ring_hist(url, now)
            if res is not None:
                status, ht, hv, cov, window = res
                if status == "full":
                    self._count_cold("ring_full")
                    return ht, hv, False
                self._count_cold("ring_partial")
                t1 = window[1]
                # provisional iff in-window data can still arrive: the
                # window head is not yet covered. A slice whose head IS
                # covered is terminal — marking it provisional would
                # re-note every finalized refit back into the book and
                # double-count the refinement metrics. (Backward
                # bulk-loads into an already-closed window are the one
                # untracked growth; they self-correct on natural
                # churn.)
                return ht, hv, t1 is None or cov[1] < t1
        series, hit = self._fetch_hist_cached(url, now)
        if hit:
            self._count_cold("cache")
        else:
            self._count_cold(
                "unserved" if self._cold_unserved else "http"
            )
        return series[0], series[1], False

    def _fetch_hist_cached(self, url: str, now: float):
        """Fetch a settled historical window, memoized by URL; returns
        (series, cache_hit) — the hit flag keeps `_count_cold`'s
        cache/fetch split exact under concurrent fetch-pool threads.

        Only called for provably immutable ranges (the caller checks the
        range's end against `now` - HIST_SETTLED_SECONDS; the watcher
        builds historical ranges ending at deploy start, but REST clients
        may supply arbitrary params — a range whose end lies in the
        future or too close to `now` for datastore ingestion to have
        settled is fetched fresh every tick and never cached, series or
        fit). `now` is the tick's injectable clock so admission is
        deterministic in tests."""
        cached = self._hist_cache.get(url)
        if cached is not None:
            return cached, True
        series = self.source.fetch(url)
        # pure-push: an unservable range comes back EMPTY, not fetched —
        # memoizing it would make every later read of the same settled
        # URL count "cache" (a served history, per the family help text)
        # while the doc sits UNKNOWN; leave it uncached so repeats keep
        # counting "unserved" (the re-probe is a resident ring lookup,
        # not HTTP)
        if not (self._cold_unserved and len(series[0]) == 0):
            self._hist_cache.put(url, series)
        return series, False

    # -- postprocess: verdicts -> document status -----------------------

    def _decide_status(
        self,
        doc: Document,
        job_verdict: int,
        anomaly_values: dict,
        now: float,
        end: float,
    ) -> None:
        """Shared status transition for the object and columnar paths —
        one source of truth for the reference's state machine
        (`converter.go:13-26`, fail-fast per `design.md:43`). Mutates the
        doc; the caller persists (per-doc update or batched
        update_many)."""
        # a missing/unparseable endTime must not make the job immortal:
        # finalize on the first judgment instead of re-checking forever
        past_end = end <= 0 or now >= end
        if job_verdict == UNHEALTHY:
            # fail fast (design.md:43)
            doc.status = STATUS_COMPLETED_UNHEALTH
            doc.status_code = "200"
            doc.reason = "anomaly detected"
            doc.anomaly_info = AnomalyInfo(
                tags="", values=anomaly_values
            ).to_json()
        elif past_end:
            # window closed with no anomaly: healthy unless nothing measured
            if job_verdict == UNKNOWN:
                doc.status = STATUS_COMPLETED_UNKNOWN
                doc.reason = "insufficient data"
            else:
                doc.status = STATUS_COMPLETED_HEALTH
                doc.reason = ""
            doc.status_code = "200"
        else:
            # keep re-checking until endTime (incremental re-check loop)
            doc.status = STATUS_PREPROCESS_COMPLETED

    def _write_back(
        self, doc: Document, verdicts: list[MetricVerdict], now: float
    ) -> Document:
        job_verdict = combine_verdicts(verdicts)
        end = self._doc_meta(doc)[1]  # parsed once per doc, not per tick
        values = {}
        if job_verdict == UNHEALTHY:
            values = {
                v.alias: v.anomaly_pairs for v in verdicts if v.anomaly_pairs
            }
        self._decide_status(doc, job_verdict, values, now, end)
        # write-behind stamps fall back to self._tick_claim_mono, which
        # a sliced sweep PINS at its own claim instant for its whole
        # duration (_claim_cycle's _sweep_active guard) — safe for this
        # subclass-overridable seam to stay claim-context-free
        return self._store_update(doc)

    def warmup(self, hist_len: int = 10_080, cur_len: int = 30) -> None:
        """Precompile the scoring programs for the canonical shapes.

        XLA compiles one program per (B, Th, Tc) bucket triple, and the
        first compile of the 7-day-history judgment costs 20-40 s on a
        TPU — paid, without this, inside the first PRODUCTION tick. The
        warmup judges synthetic windows through the SHIPPED judge path at
        EVERY power-of-two batch bucket up to the claim-limit bucket
        (real claim sizes vary, so the first tick can land in any of
        them; the sweep's cost is geometric — ~2x the largest bucket
        alone, and the fit sub-batch buckets get covered by the same
        progression) at the reference workload shape (10,080-pt history,
        30-pt current, `metricsquery.go:43,75-77`). When the effective
        univariate algorithm runs through the fit cache, each bucket is
        judged twice so the warm `score_from_state` replay compiles too,
        and the warmup fits are evicted afterwards — they must not
        occupy real cache capacity."""
        from foremast_tpu.engine.judge import (
            _MIN_BUCKET,
            HealthJudge,
            bucket_length,
        )

        # the algorithm the UNIVARIATE judge actually caches under — a
        # multivariate selector (auto/bivariate/lstm) rewrites it to its
        # univariate fallback (multivariate.MultivariateJudge.__init__)
        uni = getattr(self.judge, "univariate", self.judge)
        eff_algo = self._eff_algo
        b_max = bucket_length(max(self.claim_limit, 1))
        rng = np.random.default_rng(0)
        t0 = int(time.time()) - 86_400 * 8
        ht = t0 + 60 * np.arange(hist_len, dtype=np.int64)
        ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
        hv = rng.normal(1.0, 0.1, (b_max, hist_len)).astype(np.float32)
        cv = rng.normal(1.0, 0.1, (b_max, cur_len)).astype(np.float32)
        tasks = [
            MetricTask(
                job_id=f"__warmup__{i}",
                alias="__warmup__",
                metric_type=None,
                hist_times=ht,
                hist_values=hv[i],
                cur_times=ct,
                cur_values=cv[i],
                fit_key=f"__warmup__|{i}",
            )
            for i in range(b_max)
        ]
        # persistent-compile-cache accounting (FOREMAST_COMPILE_CACHE_DIR,
        # enabled at CLI startup): entry counts before/after the sweep
        # are the honest hit/miss signal — a warm binary adds zero
        # entries and pays only cache loads
        import os as _os

        cache_dir = _os.environ.get("FOREMAST_COMPILE_CACHE_DIR")

        def _cache_entries():
            try:
                return len(_os.listdir(cache_dir))
            except OSError:
                return None

        cache_before = _cache_entries() if cache_dir else None
        t_start = time.perf_counter()
        buckets = []
        rows = _MIN_BUCKET
        while rows <= b_max:
            self.judge.judge(tasks[:rows])
            # every algorithm caches now, so always compile the warm
            # arena-replay program too
            self.judge.judge(tasks[:rows])
            buckets.append(rows)
            rows *= 2
        for i in range(b_max):
            self._fit_cache.pop(
                (eff_algo, self._eff_season, f"__warmup__|{i}")
            )
        # the warm passes also scattered synthetic rows into the device
        # arena — release the HBM; real rows repopulate on the first tick
        if isinstance(uni, HealthJudge):
            uni.clear_device_state()
        log.info(
            "warmup compiled batch buckets %s (Th=%d Tc=%d, algorithm=%s) in %.1fs",
            buckets, hist_len, cur_len, eff_algo, time.perf_counter() - t_start,
        )
        if cache_dir:
            cache_after = _cache_entries()
            if cache_before is None or cache_after is None:
                log.warning(
                    "compile cache %s unreadable; hit/miss unknown",
                    cache_dir,
                )
            elif cache_after > cache_before:
                log.info(
                    "compile cache MISS: %d new entries persisted to %s "
                    "(%d resident) — the next restart pays cache loads, "
                    "not XLA compiles",
                    cache_after - cache_before, cache_dir, cache_after,
                )
            elif cache_before > 0 and cache_after == cache_before:
                log.info(
                    "compile cache HIT: warmup served from the %d "
                    "persisted entries in %s (no new compiles)",
                    cache_after, cache_dir,
                )
            else:
                # 0 entries both sides (persistence gates never fired —
                # e.g. an older jaxlib ignoring the min-compile-time
                # override) or the dir shrank under us: either way the
                # compiles were NOT cached; claiming HIT here would tell
                # the operator the opposite of what happened
                log.warning(
                    "compile cache %s persisted nothing during warmup "
                    "(%d entries before, %d after) — persistence "
                    "inactive or externally pruned; this process paid "
                    "full XLA compiles",
                    cache_dir, cache_before, cache_after,
                )

    # -- persistent thread pools -----------------------------------------

    def _fetch_pool_get(self):
        """The worker's persistent metric-fetch pool (sized by
        `FOREMAST_FETCH_WORKERS`). Tick-thread + prefetch-thread use
        only; lazy so sources with `concurrent_fetch = False` never
        spawn threads."""
        if self._fetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._fetch_pool = ThreadPoolExecutor(
                max_workers=self.fetch_workers,
                thread_name_prefix="foremast-fetch",
            )
        return self._fetch_pool

    def _prefetch_pool_get(self):
        """Chunk-level prefetch pool for the tick pipeline — separate
        executor from the per-doc fetch pool so a chunk job fanning its
        docs over `_fetch_pool` can never deadlock waiting on its own
        pool's slots."""
        if self._prefetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._prefetch_pool = ThreadPoolExecutor(
                max_workers=max(1, self.pipeline_depth - 1),
                thread_name_prefix="foremast-prefetch",
            )
        return self._prefetch_pool

    def close(self) -> None:
        """Shut down the persistent thread pools. Idempotent, and the
        worker stays usable afterwards (pools rebuild lazily)."""
        for attr in ("_fetch_pool", "_prefetch_pool"):
            pool = getattr(self, attr)
            if pool is not None:
                pool.shutdown(wait=True, cancel_futures=True)
                setattr(self, attr, None)
        for journal in self._fit_journals.values():
            journal.close()
        self._fit_journals = {}

    # -- durable fit state (ISSUE 7) -------------------------------------

    def enable_fit_persistence(self, directory: str) -> dict:
        """Mount write-through fit journals under `directory`: restore
        each cache's persisted terminal states (staged for LAZY
        rehydration — the first claim of a document pulls its fits back
        in, so admission passes without an HTTP history re-fetch), then
        attach write-through so every completed fit persists the moment
        the judge caches it. Returns per-journal restore counts.

        Journaled caches: the univariate fit cache and (for seasonal
        algorithms) its gap anchors, plus — when the judge dispatches
        joint models — the joint entry cache and its warm metadata, and
        the provisional-fit refine book (ISSUE 10: the journals restore
        a short-history FIT warm, so the restored doc takes the fast
        path and nothing would ever re-note it — without its own
        persistence the fit would stay parked at the admitted history
        forever instead of refining to the full window).
        NOT journaled: the history cache (re-fetchable), the per-doc
        meta cache (derived from immutable configs), and the device
        arena (it rehydrates row-by-row from the restored fit cache,
        which keeps persisted state bounded to fits, not device
        buffers)."""
        import os as _os

        from foremast_tpu.models.cache import FitJournal

        _os.makedirs(directory, exist_ok=True)
        pairs = [
            ("fits", self._fit_cache),
            ("gaps", self._gap_meta),
            ("refine", self._refine_book),
        ]
        if self._mvj is not None:
            pairs += [
                ("joint", self._mvj.cache),
                ("jmeta", self._mvj.joint_meta),
            ]
        restored = {}
        for name, cache in pairs:
            journal = FitJournal(_os.path.join(directory, f"fit-{name}"))
            items = journal.restore()
            restored[name] = cache.restore_lazy(items)
            journal.attach(cache)
            self._fit_journals[name] = journal
        if any(restored.values()):
            log.info(
                "fit persistence: restored %s from %s (lazy rehydration)",
                restored, directory,
            )
        return restored

    def attach_handoff(self, handoff) -> None:
        """Register this worker's fit caches with the mesh handoff
        plane (ISSUE 11) — the same cache set `enable_fit_persistence`
        journals, because "what must survive a restart" and "what must
        move with a partition" are the same state: the univariate fit
        cache, the seasonal gap anchors, the provisional-fit refine
        book, and (for joint judges) the joint entry cache + its warm
        metadata. The device arena is NOT transferred for the same
        reason it is not snapshotted — it rehydrates row-by-row from
        the transferred fits on the new owner's first claim."""
        pairs = {
            "fits": self._fit_cache,
            "gaps": self._gap_meta,
            "refine": self._refine_book,
        }
        if self._mvj is not None:
            pairs["joint"] = self._mvj.cache
            pairs["jmeta"] = self._mvj.joint_meta
        handoff.register_caches(pairs)

    def attach_ring_snapshotter(self, snapshotter) -> None:
        """Expose an ingest.snapshot.RingSnapshotter on /debug/state
        and fold its cadence into the tick loop (maybe_snapshot runs in
        `_tick_done` next to fit-journal compaction)."""
        self._snapshotter = snapshotter

    def _maybe_persist(self) -> None:
        """Per-tick durability housekeeping: compact any fit journal
        whose log outgrew its budget, and let the ring snapshotter
        decide whether a snapshot pass is due. Failures are logged,
        never allowed to fail a tick that already judged its docs."""
        try:
            for journal in self._fit_journals.values():
                journal.maybe_compact()
            if self._snapshotter is not None:
                self._snapshotter.maybe_snapshot()
        except Exception:  # noqa: BLE001 — durability must not kill ticks
            log.exception("durability housekeeping failed")

    # -- background refinement of provisional fits (ISSUE 10) ------------

    def _count_refine(self, result: str) -> None:
        m = (
            getattr(self.metrics, "refine_docs", None)
            if self.metrics
            else None
        )
        if m is not None:
            m.labels(result=result).inc()

    def _refine_provisional(self, now: float) -> int:
        """Upgrade provisional fits whose ring coverage grew (idle and
        all-warm steady ticks only — a busy slow-path tick already has
        cold fits to pay for). Bounded to `refine_docs_per_tick`
        records per pass; each upgrade is an INVALIDATION — the next
        claim refits the doc from the (larger) ring window through the
        production slow path, so a refined fit is byte-identical to a
        from-scratch fit on the same columns. Returns #invalidated."""
        book = self._refine_book
        if not len(book) or self._ring_hist is None:
            return 0
        if self.mesh is not None and getattr(self.mesh, "draining", False):
            # drain-aware tick (ISSUE 11): a refinement invalidation
            # right now would pop fits this worker is about to STREAM
            # to the new owners — the receiver would inherit a hole it
            # must cold-refit. The records move with the handoff (the
            # refine book is a registered cache), so the new owner
            # resumes the pacing instead.
            return 0
        probe = getattr(self.source, "hist_coverage", None)
        if probe is None:
            return 0
        upgraded = 0
        for bkey, rec in book.take(self.refine_docs_per_tick):
            states = [probe(u, now) for u in rec["urls"]]
            if any(s is None for s in states):
                # unresolvable URL: no series identity to ever pace
                book.drop(bkey, "dropped")
                continue
            if any(s[0] is None for s in states):
                # no serving span RIGHT NOW (pusher pause past the
                # staleness cutoff, mid-rebalance eviction): pacing
                # pauses but the record STAYS — the short-history fit is
                # still warm in the fit cache, so no cold claim will
                # ever re-note it; dropping here would park it at its
                # admitted history forever once the pusher resumes.
                # take() already rotated the record to the back.
                continue
            n_now = sum(s[1] for s in states)
            closed = all(
                s[0] == "full"
                or (s[3][1] is not None and s[2][1] >= s[3][1])
                for s in states
            )
            if closed:
                # the window is fully covered (or its head is past —
                # nothing more can arrive inside it): pay a TERMINAL
                # refit only when the resident data actually grew past
                # the admitted fit; either way the record settles.
                # "finalized" counts only actual terminal refits —
                # a record whose data never grew settles without one
                if n_now > rec["points"]:
                    self._invalidate_provisional(bkey, rec)
                    upgraded += 1
                    book.drop(bkey, "finalized")
                    self._count_refine("finalized")
                else:
                    book.drop(bkey, "settled")
                    self._count_refine("settled")
            elif book.due(rec["points"], n_now):
                self._invalidate_provisional(bkey, rec)
                book.refit(bkey, n_now)
                self._count_refine("refit")
                upgraded += 1
        gauge = (
            getattr(self.metrics, "provisional", None)
            if self.metrics
            else None
        )
        if gauge is not None:
            gauge.set(len(book))
        if upgraded:
            log.info(
                "refinement: invalidated %d provisional fit(s) for "
                "refit from the ring (%d still pending)",
                upgraded, len(book),
            )
        return upgraded

    def _invalidate_provisional(self, bkey: tuple, rec: dict) -> None:
        """Drop a provisional fit's cached state so the next claim
        refits from the ring. Version bumps make the fast-path
        admission caches revalidate and demote the doc to the slow
        path for exactly one refit tick."""
        if rec["kind"] == "uni":
            self._fit_cache.pop(rec["fullkey"])
            if self._gap_sensitive:
                self._gap_meta.pop(rec["gap_key"])
            return
        # joint: resolve the cache keys through the admission cache
        jad = self._jadmit.pop(rec["doc_id"], None)
        if self._mvj is None:
            return
        if jad is not None:
            jinfo = jad[1]
            self._mvj.cache.pop(jinfo[3])
            self._mvj.joint_meta.pop(jinfo[5])
            return
        # never fast-path-admitted (columnar off, or refinement fired
        # before the doc's second claim): the slow path's LSTM cache
        # key carries no history content (multivariate._key), so its
        # short-history fit would be served FOREVER unless popped —
        # invalidate by app. Joint cache keys are (mode, app, ...),
        # meta keys ("jmeta", mode, app, ...); over-matching sibling
        # docs of the same app costs them one extra refit, never a
        # wrong verdict.
        app = rec.get("app")
        if app is None:
            return
        self._mvj.cache.pop_where(
            lambda k: isinstance(k, tuple) and len(k) > 1 and k[1] == app
        )
        self._mvj.joint_meta.pop_where(
            lambda k: isinstance(k, tuple)
            and len(k) > 2
            and k[0] == "jmeta"
            and k[2] == app
        )

    # -- degraded store writes (ISSUE 9) ---------------------------------

    def _store_update(
        self, doc: Document, claim_mono: float | None = None
    ) -> Document:
        """`store.update` with write-behind degradation: a TRANSIENT
        store failure (connection/timeout, 429/5xx, breaker open) parks
        the doc in the bounded buffer for replay instead of failing the
        tick; permanent errors propagate. `claim_mono` is the doc's
        CLAIM instant for the write-behind age stamp — a sliced sweep
        passes each slice's own claim time so a late slice can never
        inherit a fresher stamp from a nested micro-tick's claim
        (see _tick_claim_mono, the monolithic default)."""
        try:
            doc = self.store.update(doc)
            self._write_degraded = False
            return doc
        except Exception as e:
            if not is_transient_error(e):
                raise
            self._note_write_degraded(e)
            # stamped at the CLAIM instant (see _tick_claim_mono)
            self._degrade.write_behind.add(
                [doc],
                now=(
                    self._tick_claim_mono
                    if claim_mono is None
                    else claim_mono
                ),
            )
            return doc

    def _store_update_many(
        self, docs: list[Document], claim_mono: float | None = None
    ) -> None:
        """Batched `_store_update` (the fast tick's write-back path)."""
        if not docs:
            return
        try:
            self.store.update_many(docs)
            self._write_degraded = False
        except Exception as e:
            if not is_transient_error(e):
                raise
            self._note_write_degraded(e)
            self._degrade.write_behind.add(
                docs,
                now=(
                    self._tick_claim_mono
                    if claim_mono is None
                    else claim_mono
                ),
            )

    def _note_write_degraded(self, e: BaseException) -> None:
        if not self._write_degraded:
            log.warning(
                "store write failed transiently (%s: %s); degrading to "
                "write-behind — verdicts buffer locally and replay when "
                "the store heals (docs/operations.md \"Failure modes\")",
                type(e).__name__, e,
            )
            self._write_degraded = True
        self._degrade.stats.count_event("store", "write_error")

    def _flush_write_behind(self) -> None:
        """Replay the write-behind backlog (tick start + idle ticks).
        Entries that aged past the stuck window were dropped by
        `drain` — claim-CAS takeover owns those docs now."""
        buf = self._degrade.write_behind
        if not len(buf):
            return
        # headroom for the replay RPC itself: an entry that passes the
        # age check must also LAND inside the stuck window, so the
        # drain cutoff advances by the store's round-trip bound (capped
        # at a third of the window so tiny test windows keep working)
        margin = min(
            float(getattr(self.store, "timeout", 10.0) or 10.0),
            buf.max_age_seconds / 3.0,
        )
        entries = buf.drain(margin=margin)
        if not entries:
            return
        docs = [d for _, d in entries]
        try:
            self.store.update_many(docs)
        except Exception as e:
            buf.requeue(entries)
            if not is_transient_error(e):
                raise
            return
        self._write_degraded = False
        self._degrade.stats.count_docs(REASON_REPLAYED, len(docs))
        self._degrade.stats.count_event("store", "replay_flush")
        log.info(
            "write-behind replay: %d buffered doc(s) flushed to the "
            "recovered store", len(docs),
        )

    def _release_docs(
        self,
        docs: list[Document],
        reason: str,
        led: _TickLedger | None = None,
        claim_mono: float | None = None,
    ) -> None:
        """Partial-tick semantics: give docs back un-judged (status →
        preprocess_completed, claimable next tick) and count them —
        never wedge a tick behind a slow dependency, never terminally
        fail a doc for a dependency's transient sin."""
        if not docs:
            return
        led = self._ledger if led is None else led
        for doc in docs:
            doc.status = STATUS_PREPROCESS_COMPLETED
        self._store_update_many(docs, claim_mono=claim_mono)
        self._degrade.stats.count_docs(reason, len(docs))
        # reactive: a released doc's pending arrival goes BACK to the
        # dirty set with its ORIGINAL stamp — a brownout mid-micro-tick
        # must not lose the arrival, and the eventual verdict must
        # still measure from the push's receive instant (the latency
        # the operator actually suffered)
        if led.pending and self.dirty is not None:
            for doc in docs:
                rk = doc_route_key(doc)
                stamp = led.pending.pop(rk, None)
                if stamp is not None:
                    self.dirty.mark(rk, stamp, requeue=True)
        log.warning(
            "released %d doc(s) un-judged (%s); they stay claimable "
            "for the next tick", len(docs), reason,
        )

    def _deadline_exceeded(self) -> bool:
        return (
            self._tick_deadline is not None
            and time.perf_counter() > self._tick_deadline
        )

    # -- columnar fast path ---------------------------------------------

    def _revalidate(self, cached, token) -> bool:
        """Per-row admission revalidation after a cache-version bump.

        The cached rowsinfo holds the ENTRY OBJECTS it was admitted
        with; the fit (and gap anchors, for seasonal fits) are still
        current iff the caches hold those same objects — one peek + `is`
        compare per row, no tuple rebuilding. Stamps the entry with the
        new token on success so the next stable tick is free again.
        Stale rows (refit under the same key, or evicted) fail and the
        caller re-walks just this document's admission."""
        peek = self._fit_cache.peek
        gpeek = self._gap_meta.peek if self._gap_sensitive else None
        for r in cached[1]:
            if peek(r[2]) is not r[3]:
                return False
            if gpeek is not None and gpeek(r[2][2]) is not r[4]:
                return False
        cached[3] = token
        return True

    # -- joint (multi-alias) fast path — ISSUE 4 tentpole ----------------

    def _admit_joint(self, doc, aliases, end_epoch, now: float, jtoken):
        """Joint-doc fast-path admission: (doc, end_epoch, jinfo) when
        this multi-alias doc's joint fit + warm metadata are cached and
        every alias clears the same gates the univariate path applies
        (no baseline, settled history); None routes it to the slow path.

        jinfo: (mode, alias names, cur urls, cache_key, entry, meta_key,
        meta) — the entry/meta OBJECTS are carried so revalidation after
        a cache-version bump is one identity compare each, exactly the
        `_revalidate` discipline."""
        if not self._joint_fast:
            return None
        cached = self._jadmit.get(doc.id)
        if cached is not None and (
            cached[2] == jtoken or self._revalidate_joint(cached, jtoken)
        ):
            return (doc, cached[0], cached[1])
        from foremast_tpu.engine.multivariate import select_mode

        mode = select_mode(self.config.algorithm, len(aliases))
        if mode == "univariate":
            # metric-count misfit (e.g. 3 aliases under bivariate_normal):
            # the object path scores these per alias with the univariate
            # fallback — multi-task docs stay off the columnar paths
            return None
        names = []
        urls = []
        hkeys = []
        for (
            alias,
            cur_url,
            _mtype,
            base_url,
            hist_url,
            key,
            hist_end,
            _fullkey,
        ) in aliases:
            if (
                base_url is not None
                or hist_url is None
                or key is None
                or hist_end is None
                or hist_end > now - HIST_SETTLED_SECONDS
            ):
                return None
            names.append(alias)
            urls.append(cur_url)
            hkeys.append(key)
        peek = self._mvj.columnar_joint_peek(
            mode, doc.app_name, tuple(names), tuple(hkeys)
        )
        if peek is None:
            return None
        jinfo = (mode, tuple(names), tuple(urls)) + peek
        self._jadmit[doc.id] = [end_epoch, jinfo, jtoken]
        return (doc, end_epoch, jinfo)

    def _revalidate_joint(self, cached, token) -> bool:
        """Per-doc joint admission revalidation after a version bump:
        the cached jinfo holds the entry/meta OBJECTS it was admitted
        with — still current iff the judge's caches hold those same
        objects."""
        jinfo = cached[1]
        judge = self._mvj
        if judge.cache.peek(jinfo[3]) is not jinfo[4]:
            return False
        if judge.joint_meta.peek(jinfo[5]) is not jinfo[6]:
            return False
        cached[2] = token
        return True

    def _demote_to_slow(self, slow: list, demoted: list, why: str) -> None:
        """Route fast-tick demotions (an admitted doc the columnar
        program can no longer score — e.g. a joint doc whose window
        bucket drifted from the fitted one) back onto the slow path,
        COUNTED on foremast_degraded_docs{reason="fast_demoted"}
        (ISSUE 14 satellite: demotions used to ride the slow leftovers
        silently, so an operator could not see the fast path shedding
        work)."""
        if not demoted:
            return
        slow.extend(demoted)
        self._degrade.stats.count_docs(REASON_DEMOTED, len(demoted))
        log.info(
            "fast path demoted %d doc(s) to the slow path (%s)",
            len(demoted), why,
        )

    def _account_fast_kinds(self, kind_counts: dict) -> None:
        """Fold one tick's columnar doc counts into the cumulative
        per-kind counters (/debug/state) and the WorkerMetrics family."""
        metrics_fast = (
            getattr(self.metrics, "fast_docs", None) if self.metrics else None
        )
        for kind, n in kind_counts.items():
            if not n:
                continue
            self._fast_kinds[kind] += n
            if metrics_fast is not None:
                metrics_fast.labels(kind=kind).inc(n)

    def _judge_joint_fast(self, ok_joint, now: float):
        """Columnar warm judgment of admitted joint docs.

        Aligns each doc's fetched current windows (the cheap all-equal
        timestamp case short-circuits the intersect), groups by (model
        kind, feature count, window bucket), and runs ONE arena-gathered
        program per group (`MultivariateJudge.joint_columnar`). Statuses
        and anomaly pairs replicate the object path's `_emit` exactly;
        docs whose window bucket drifted from the fitted one are DEMOTED
        to the slow path (refit) rather than mis-scored. Returns
        (updated_docs, demoted_docs, per-kind counts)."""
        from foremast_tpu.engine.judge import bucket_length
        from foremast_tpu.engine.multivariate import align_series

        observe = self.metrics.observe_doc if self.metrics else None
        hook = self.on_verdict
        judge = self._mvj
        thr = float(
            np.float32(judge.config.anomaly.rule_for(None).threshold)
        )
        updated: list = []
        demoted: list = []
        counts = {"univariate": 0, "bivariate": 0, "lstm": 0}
        groups: dict = {}
        for (doc, end_epoch, jinfo), series in ok_joint:
            mode = jinfo[0]
            times = [s[0] for s in series]
            vals = [s[1] for s in series]
            t0 = np.asarray(times[0], np.int64)
            # all-equal shortcut requires STRICTLY INCREASING stamps:
            # align_series dedups repeated timestamps (first occurrence)
            # and sorts — a raw trace with duplicates must take the same
            # path so fast and object verdicts cannot diverge
            if (
                len(t0) > 0
                and bool(np.all(np.diff(t0) > 0))
                and all(
                    len(t) == len(t0) and np.array_equal(t, t0)
                    for t in times[1:]
                )
            ):
                ct = t0
                cv = np.stack(
                    [np.asarray(v, np.float32) for v in vals]
                )
            else:
                ct, cv = align_series(times, vals)
            n = len(ct)
            if n == 0:
                # no joint observation: UNKNOWN, object-path parity
                # (`_unknown` — baseline-less pairwise is (1.0, False))
                self._decide_status(doc, UNKNOWN, {}, now, end_epoch)
                self._log_judged(doc)
                updated.append(doc)
                counts[mode] += 1
                if observe:
                    observe(doc.status, len(jinfo[1]))
                if hook:
                    vs = [
                        MetricVerdict(
                            job_id=doc.id,
                            alias=alias,
                            verdict=UNKNOWN,
                            anomaly_pairs=[],
                            upper=np.zeros(len(vals[f_i]), np.float32),
                            lower=np.zeros(len(vals[f_i]), np.float32),
                            p_value=1.0,
                            dist_differs=False,
                        )
                        for f_i, alias in enumerate(jinfo[1])
                    ]
                    try:
                        hook(doc, vs)
                    except Exception:
                        log.exception(
                            "on_verdict hook failed for %s", doc.id
                        )
                continue
            tcb = bucket_length(n)
            if jinfo[0] == "lstm" and tcb != jinfo[6][0]:
                # window bucket drifted from the one the AE was fitted
                # at: the model no longer applies — refit on the slow
                # path instead of scoring through the wrong program
                demoted.append(doc)
                continue
            groups.setdefault((mode, len(jinfo[1])), []).append(
                (doc, end_epoch, jinfo, ct, cv, n)
            )

        for (mode, f), items in groups.items():
            if mode == "lstm":
                # ONE dispatch per (lstm, F) group, padded to the
                # group's widest fitted window bucket (VERDICT r5 #10:
                # per-bucket sub-dispatches serialized refinement
                # sweeps on 2,048-window programs). Exact by
                # construction: the AE scan carries state through
                # masked steps unchanged and the decoder's outputs at
                # step i never depend on later steps, and the MVN
                # d^2 is causal — so SUFFIX padding (each item keeps
                # its own n/mask) cannot change any real point's flag.
                # Admission still pins each item's bucket to its fitted
                # meta (drift demotes to the slow path above); only the
                # dispatch shape is merged, univariate-style.
                subgroups = [
                    (max(it[2][6][0] for it in items), items)
                ]
            else:
                subgroups = [
                    (
                        bucket_length(max(it[5] for it in items)),
                        items,
                    )
                ]
            for tcb, sub in subgroups:
                s = len(sub)
                cur = np.zeros((s, f, tcb), np.float32)
                mask = np.zeros((s, tcb), bool)
                gaps = np.zeros(s, np.int32) if mode == "lstm" else None
                keys, entries, metas = [], [], []
                for i, (doc, end_epoch, jinfo, ct, cv, n) in enumerate(sub):
                    cur[i, :, :n] = cv[:, :n]
                    mask[i, :n] = True
                    keys.append(jinfo[3])
                    entries.append(jinfo[4])
                    metas.append(jinfo[6])
                    if mode == "lstm":
                        meta = jinfo[6]
                        k = int(
                            round(
                                (float(ct[0]) - meta[4])
                                / max(meta[3], 1.0)
                            )
                        )
                        gaps[i] = max(k - 1, 0)
                flags = judge.joint_columnar(
                    mode, keys, entries, metas, cur, mask, gaps
                )
                for i, (doc, end_epoch, jinfo, ct, cv, n) in enumerate(sub):
                    fl = flags[i, :n]
                    jv = UNHEALTHY if fl.any() else HEALTHY
                    values_map = {}
                    if jv == UNHEALTHY:
                        ft = ct[fl]
                        for f_i, alias in enumerate(jinfo[1]):
                            pairs = np.empty(2 * len(ft), np.float64)
                            pairs[0::2] = ft
                            pairs[1::2] = cv[f_i][fl]
                            values_map[alias] = pairs.tolist()
                    self._decide_status(doc, jv, values_map, now, end_epoch)
                    self._log_judged(doc)
                    updated.append(doc)
                    counts[mode] += 1
                    if observe:
                        observe(doc.status, f)
                    if hook:
                        try:
                            hook(
                                doc,
                                self._joint_verdicts(
                                    doc, jinfo, ct, cv, n, fl, jv, thr
                                ),
                            )
                        except Exception:
                            log.exception(
                                "on_verdict hook failed for %s", doc.id
                            )
        return updated, demoted, counts

    def _joint_verdicts(self, doc, jinfo, ct, cv, n, fl, jv, thr):
        """Hook verdicts replicating the object path's `_emit`: per-alias
        marginal bands (mean ± thr·sigma of the aligned history, from
        the cached meta moments), the doc-wide joint verdict, and each
        alias's own values at the flagged timestamps. Baseline-less by
        fast-path admission, so pairwise evidence is (1.0, False)."""
        meta = jinfo[6]
        mu, sd = meta[1], meta[2]
        width = max(n, 1)
        up = np.repeat((mu + thr * sd)[:, None], width, axis=1).astype(
            np.float32
        )
        lo = np.repeat(
            np.maximum(mu - thr * sd, 0.0)[:, None], width, axis=1
        ).astype(np.float32)
        flagged_times = ct[fl]
        out = []
        for f_i, alias in enumerate(jinfo[1]):
            pairs: list[float] = []
            for ts, v in zip(flagged_times, cv[f_i][fl]):
                pairs.extend([float(ts), float(v)])
            out.append(
                MetricVerdict(
                    job_id=doc.id,
                    alias=alias,
                    verdict=jv,
                    anomaly_pairs=pairs,
                    upper=up[f_i],
                    lower=lo[f_i],
                    p_value=1.0,
                    dist_differs=False,
                )
            )
        return out

    def _fast_tick(self, docs, now: float):
        """Columnar processing of the all-warm re-check subset.

        The steady state of the whole system is: a stable fleet of jobs
        re-checked every tick against cached fits, no baselines (the
        continuous/rollingUpdate strategies), new data only in the
        ~30-point current windows. For that subset this path skips every
        per-task object the slow path builds — no MetricTask, no
        MetricVerdict (unless a hook wants them), no ragged packing, no
        per-task cache tuples — writing current windows straight into
        [B, tc] buffers and decoding verdicts with segment reductions.
        Joint (multi-alias) docs ride the fast tick too (ISSUE 4): once
        their bivariate/LSTM-hybrid fits are cached, they are claimed
        here and scored through one arena-gathered joint program per
        model kind (`_judge_joint_fast`) instead of falling onto the
        per-task object path forever. BASELINE-carrying univariate docs
        (the canary/continuous strategies — the reference's headline
        use case) ride it too (ISSUE 14): they form their own bucket
        whose baseline windows fill a second [B, tc] buffer judged by
        the pairwise-active columnar program. Docs that don't qualify
        (unsettled or absent histories, cold fits, multi-alias docs
        with baselines, canary docs under FOREMAST_CANARY_COLUMNAR=0)
        are returned for the slow path. Returns (n_processed,
        slow_docs).

        Admission (which docs qualify, with their entry/gap references)
        is itself cached per doc: a version-stable tick trusts entries
        with one integer compare, and a version bump (churn: cold fits,
        evictions) revalidates per row by entry identity instead of
        discarding the cache — see _revalidate.
        """
        fast, fastc, fastj, slow = self._admit_fast(docs, now)
        if not fast and not fastc and not fastj:
            return 0, slow
        ok_items, ok_citems, ok_joint, failed, released = self._fetch_fast(
            fast, fastc, fastj
        )
        for doc in failed:
            self._store_update(doc)
        self._release_docs(released, REASON_FETCH)
        if self.metrics:
            for doc in failed:
                self.metrics.observe_doc(doc.status, 0)
        if not ok_items and not ok_citems and not ok_joint:
            return len(failed) + len(released), slow
        updated_all: list = []
        n_joint = 0
        kind_counts = {
            "univariate": 0, "bivariate": 0, "lstm": 0, "baseline": 0,
        }
        if ok_joint:
            j_updated, demoted, j_counts = self._judge_joint_fast(
                ok_joint, now
            )
            updated_all.extend(j_updated)
            n_joint = len(j_updated)
            self._demote_to_slow(slow, demoted, "joint window bucket drift")
            for kind, n in j_counts.items():
                kind_counts[kind] += n
        if ok_items:
            updated_all.extend(self._judge_uni_fast(ok_items, now))
            kind_counts["univariate"] += len(ok_items)
        if ok_citems:
            updated_all.extend(
                self._judge_uni_fast(ok_citems, now, canary=True)
            )
            kind_counts["baseline"] += len(ok_citems)
        self._account_fast_kinds(kind_counts)
        with span(
            "worker.write_back", stage="write_back", docs=len(updated_all)
        ):
            self._store_update_many(updated_all)
        self._observe_verdicts(updated_all)
        return (
            len(ok_items)
            + len(ok_citems)
            + n_joint
            + len(failed)
            + len(released),
            slow,
        )

    def _admit_fast(self, docs, now: float):
        """The fast-tick admission walk — shared by the monolithic
        `_fast_tick` and the sliced sweep's prepare stage (prefetch
        thread: per-doc dict operations are GIL-atomic, the ModelCaches
        are lock-guarded, and a sweep's slices and any preempting
        micro-tick operate on DISJOINT claimed docs). Returns (fast,
        fastc, fastj, slow) — the baseline-less, canary, joint, and
        object-path doc groups."""
        fit_cache = self._fit_cache
        gap_sensitive = self._gap_sensitive
        token = (fit_cache.version, self._gap_meta.version)
        admit = self._admit
        if len(admit) > 8 * max(self.claim_limit, 512):
            admit.clear()  # crude bound; repopulates from caches
        jadmit = self._jadmit
        jtoken = None
        if self._joint_fast:
            jtoken = (
                self._mvj.cache.version,
                self._mvj.joint_meta.version,
            )
            if len(jadmit) > 8 * max(self.claim_limit, 512):
                jadmit.clear()
        fast = []  # (doc, end_epoch, rowsinfo, ops) — baseline-less
        fastc = []  # same shape — the canary bucket (>=1 baseline URL)
        fastj = []  # (doc, end_epoch, jinfo) — joint docs, warm
        slow = []
        for doc in docs:
            cached = admit.get(doc.id)
            if cached is not None and (
                cached[3] == token or self._revalidate(cached, token)
            ):
                (fastc if cached[4] else fast).append(
                    (doc, cached[0], cached[1], cached[2])
                )
                continue
            aliases, end_epoch, ops = self._doc_meta(doc)
            if not aliases:
                slow.append(doc)
                continue
            if self._mv and len(aliases) != 1:
                item = self._admit_joint(
                    doc, aliases, end_epoch, now, jtoken
                )
                if item is None:
                    slow.append(doc)
                else:
                    fastj.append(item)
                continue
            rowsinfo = []
            has_base = False
            for (
                alias,
                cur_url,
                mtype,
                base_url,
                hist_url,
                key,
                hist_end,
                fullkey,
            ) in aliases:
                # baseline presence is a BUCKET dimension, not a
                # slow-path demotion (ISSUE 14): a baseline-carrying
                # alias routes its doc to the canary bucket below —
                # unless the canary columnar path is opted out, in
                # which case it keeps the pre-ISSUE-14 object-path
                # routing. The fit gates (settled history, cached
                # entry/gap) are identical for both buckets: the
                # baseline window, like the current window, is fetched
                # fresh every tick and never feeds the fit.
                if (
                    (base_url is not None and not self._canary_fast)
                    or hist_url is None
                    or hist_end is None
                    or hist_end > now - HIST_SETTLED_SECONDS
                ):
                    rowsinfo = None
                    break
                entry = fit_cache.peek(fullkey)
                if entry is None:
                    rowsinfo = None
                    break
                gap = None
                if gap_sensitive:
                    gap = self._gap_meta.peek(key)
                    if gap is None:
                        rowsinfo = None
                        break
                if base_url is not None:
                    has_base = True
                rowsinfo.append(
                    (alias, cur_url, fullkey, entry, gap, base_url)
                )
            if rowsinfo is None:
                slow.append(doc)
            else:
                admit[doc.id] = [end_epoch, rowsinfo, ops, token, has_base]
                (fastc if has_base else fast).append(
                    (doc, end_epoch, rowsinfo, ops)
                )
        return fast, fastc, fastj, slow

    def _fetch_fast(self, fast, fastc, fastj):
        """Fetch current windows for the admitted groups (thread pool
        only for blocking sources): univariate, canary and joint docs
        share one pooled fan-out — a fetch entry is (kind, item, url
        list). Canary docs append their per-row baseline URLs after the
        current URLs (None for a baseline-less alias inside a canary
        doc: it fetches as an empty window, whose all-False mask gates
        every rank test off — the object path's exact semantics for
        that alias). Returns (ok_items, ok_citems, ok_joint, failed,
        released); failed docs carry their terminal marks but are NOT
        persisted here — the CALLER owns store writes (the sliced
        sweep's writer thread, or `_fast_tick` inline)."""
        fetch_items = [
            ("uni", item, [r[1] for r in item[2]]) for item in fast
        ]
        fetch_items += [
            (
                "canary",
                item,
                [r[1] for r in item[2]] + [r[5] for r in item[2]],
            )
            for item in fastc
        ]
        fetch_items += [
            ("joint", item, list(item[2][2])) for item in fastj
        ]

        def fetch_doc(entry):
            _kind, item, urls = entry
            try:
                return [
                    self.source.fetch(u)
                    if u is not None
                    else (_EMPTY_TIMES, _EMPTY_VALUES)
                    for u in urls
                ]
            except Exception as e:
                if is_transient_error(e):
                    # dependency outage (or breaker open): release the
                    # doc un-judged instead of terminally failing it
                    log.warning(
                        "preprocess released (transient) for %s: %s",
                        item[0].id, e,
                    )
                    return RELEASED
                log.warning("preprocess failed for %s: %s", item[0].id, e)
                return None

        with span(
            "worker.fetch", stage="metric_fetch", docs=len(fetch_items)
        ):
            if len(fetch_items) > 1 and getattr(
                self.source, "concurrent_fetch", True
            ):
                series = list(
                    self._fetch_pool_get().map(
                        inherit_span(fetch_doc), fetch_items
                    )
                )
            else:
                series = [fetch_doc(entry) for entry in fetch_items]

        failed = []
        released = []
        ok_items = []
        ok_citems = []
        ok_joint = []
        for (kind, item, _urls), s in zip(fetch_items, series):
            if s is None:
                doc = item[0]
                doc.status = STATUS_PREPROCESS_FAILED
                doc.status_code = "500"
                doc.reason = "metric fetch failed"
                failed.append(doc)
            elif s is RELEASED:
                released.append(item[0])
            elif kind == "uni":
                ok_items.append((item, s))
            elif kind == "canary":
                ok_citems.append((item, s))
            else:
                ok_joint.append((item, s))
        return ok_items, ok_citems, ok_joint, failed, released

    def _judge_uni_fast(self, ok_items, now: float, canary: bool = False) -> list:
        """Columnar warm judgment of admitted univariate rows: one
        [B, tc] buffer pair, one `judge_columnar` call, segment-reduction
        decode (the `_judge_joint_fast` counterpart for single-alias
        rows). `canary=True` is the baseline-carrying bucket (ISSUE 14):
        each item's fetched series carry the baseline windows AFTER the
        current windows (the `_fast_tick` fetch layout), which fill a
        second [B, tc] buffer pair judged by the pairwise-active
        compiled variant — hook verdicts then carry the REAL device
        (p, differs) instead of the baseline-less constants. Returns
        the decided docs; the caller persists.

        Pack → dispatch → gather+decode are separate helpers so the
        sliced sweep (ISSUE 15) can run them on different pipeline
        stages; this monolithic wrapper composes the same pack and
        decode around `judge_columnar` — itself the async dispatch +
        wait pair — which is what pins sliced-vs-monolithic byte
        parity by construction (and keeps `judge_columnar` the one
        instrumentable judgment seam)."""
        packed = self._pack_uni(ok_items, canary)
        res = self._uni.judge_columnar(
            packed.values,
            packed.mask,
            packed.keys,
            packed.entries,
            packed.nidx,
            packed.thr,
            packed.bnd,
            packed.mlb,
            gap_steps=packed.gaps,
            with_bands=self.on_verdict is not None,
            base_values=packed.base_vals,
            base_mask=packed.base_m,
        )
        return self._decode_uni(packed, res, now)

    def _pack_uni(self, ok_items, canary: bool):
        """The host-side packing half (prefetch-thread-safe: pure numpy
        + per-row reads of immutable admission tuples): fill the
        [B, tc] buffer pair (plus the canary bucket's baseline pair),
        gather per-row operands, keys, entries and gap steps. Returns a
        `_UniPacked`."""
        gap_sensitive = self._gap_sensitive
        # columnar fill: one [B, tc] buffer pair, no per-row objects
        from foremast_tpu.engine.judge import bucket_length

        bv_flat = None
        if canary:
            # split each item's series back into (current, baseline)
            # halves; the decode below must only ever see the currents
            split = []
            bv_flat = []
            for item, s in ok_items:
                rows = len(item[2])
                split.append((item, s[:rows]))
                bv_flat.extend(s[rows:])
            ok_items = split
        cv_flat = [cv for _, s in ok_items for _, cv in s]
        n_rows = len(cv_flat)
        lens = np.fromiter((len(cv) for cv in cv_flat), np.int64, count=n_rows)
        n_max = int(lens.max(initial=1))
        if canary:
            # the shared window bucket covers the baseline windows too —
            # the object path's per-task rule is bucket_length(max(cur,
            # base)) (judge.judge), so the canary bucket's shape follows
            # the same maximum
            n_max = max(
                n_max, max((len(bv) for _, bv in bv_flat), default=1)
            )
        tc = bucket_length(max(n_max, 1))
        nidx = np.maximum(lens - 1, 0).astype(np.int32)
        values = np.zeros((n_rows, tc), np.float32)
        maskarr = np.zeros((n_rows, tc), bool)
        n_min = int(lens.min(initial=0))
        if n_min == n_max and n_min > 0:
            # uniform window length (the common steady state): ONE
            # C-level stack instead of a per-row assignment loop
            values[:, :n_max] = np.stack(cv_flat)
            maskarr[:, :n_max] = True
        else:
            for i, cv in enumerate(cv_flat):
                n = min(len(cv), tc)
                if n:
                    values[i, :n] = cv[:n]
                    maskarr[i, :n] = True
        base_vals = base_m = None
        if canary:
            # second [B, tc] buffer: baseline windows, left-packed like
            # the currents; a baseline-less alias inside a canary doc
            # fetched empty, so its all-False mask row gates every rank
            # test off (the object path's exact outcome for it)
            base_vals = np.zeros((n_rows, tc), np.float32)
            base_m = np.zeros((n_rows, tc), bool)
            blens = np.fromiter(
                (len(bv) for _, bv in bv_flat), np.int64, count=n_rows
            )
            b_min, b_max = int(blens.min(initial=0)), int(blens.max(initial=0))
            if b_min == b_max and b_min > 0:
                # uniform baseline length (the steady state): one
                # C-level stack, same as the currents above
                base_vals[:, :b_max] = np.stack(
                    [bv for _, bv in bv_flat]
                )
                base_m[:, :b_max] = True
            else:
                for i, (_, bv) in enumerate(bv_flat):
                    nb = min(len(bv), tc)
                    if nb:
                        base_vals[i, :nb] = np.asarray(bv, np.float32)[:nb]
                        base_m[i, :nb] = True
        opcat = np.concatenate([item[3] for item, _ in ok_items], axis=1)
        thr = opcat[0]
        bnd = opcat[1].astype(np.int32)
        mlb = opcat[2]
        keys = [r[2] for item, s in ok_items for r in item[2]]
        entries = [r[3] for item, s in ok_items for r in item[2]]
        gaps = None
        if gap_sensitive:
            gaps = np.zeros(n_rows, np.int32)
            i = 0
            for item, s in ok_items:
                for r, (ct, cv) in zip(item[2], s):
                    gap = r[4]
                    if gap is not None and len(ct):
                        k = int(
                            round((float(ct[0]) - gap[1]) / max(gap[0], 1.0))
                        )
                        gaps[i] = max(k - 1, 0)
                    i += 1

        return _UniPacked(
            ok_items, values, maskarr, keys, entries, nidx,
            thr, bnd, mlb, gaps, tc, canary, base_vals, base_m,
        )

    def _dispatch_uni(self, packed: "_UniPacked"):
        """The device-dispatch half (tick thread ONLY — arena
        assignment order is load-bearing): one async columnar program,
        returns the un-gathered `ColumnarPending`."""
        return self._uni.judge_columnar_async(
            packed.values,
            packed.mask,
            packed.keys,
            packed.entries,
            packed.nidx,
            packed.thr,
            packed.bnd,
            packed.mlb,
            gap_steps=packed.gaps,
            with_bands=self.on_verdict is not None,
            base_values=packed.base_vals,
            base_mask=packed.base_m,
        )

    # The uni fast path's designated decode stage: consumes the gathered
    # columnar result tuple; everything it hands on (verdicts, decided
    # docs) is host.
    # foremast: device-boundary
    def _decode_uni(self, packed: "_UniPacked", res, now: float) -> list:
        """The decode half (any single consumer thread — the sliced
        sweep runs it on the writer after `ColumnarPending.wait()`):
        segment-reduce per-doc verdicts and decide statuses off the
        gathered result tuple. Returns the decided docs; the caller
        persists."""
        ok_items = packed.ok_items
        tc = packed.tc
        v8, anoms, ub, lb, ps, difs = res

        # decode: segment reductions over per-doc row ranges
        counts = np.fromiter(
            (len(s) for _, s in ok_items), np.int64, count=len(ok_items)
        )
        starts = np.zeros(len(ok_items), np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        is_unh = v8 == UNHEALTHY
        seg_unh = np.maximum.reduceat(is_unh, starts)
        seg_min = np.minimum.reduceat(v8, starts)
        nz_r, nz_c = np.nonzero(anoms)

        def pairs_for(r, s_local, k2):
            lo_i = np.searchsorted(nz_r, r)
            hi_i = np.searchsorted(nz_r, r, side="right")
            cols = nz_c[lo_i:hi_i]
            if not len(cols):
                return []
            ct, cv = s_local[k2]
            flat = np.empty(2 * len(cols), np.float64)
            flat[0::2] = np.asarray(ct)[cols]
            flat[1::2] = np.asarray(cv)[cols]
            return flat.tolist()

        with span("worker.decide", stage="decide", docs=len(ok_items)):
            return self._decide_fast(
                ok_items, v8, seg_unh, seg_min, starts, pairs_for,
                ub, lb, tc, now, ps, difs,
            )

    def _decide_fast(
        self, ok_items, v8, seg_unh, seg_min, starts, pairs_for,
        ub, lb, tc, now, ps=None, difs=None,
    ):
        """Fast-path status decisions + hook dispatch (split from
        _fast_tick so the decide stage is one guarded span). `ps`/`difs`
        are the canary bucket's per-row device pairwise outcomes (None
        on the baseline-less bucket, whose hook verdicts carry the
        hardwired constants)."""
        hook = self.on_verdict
        updated = []
        observe = self.metrics.observe_doc if self.metrics else None
        for j, ((doc, end_epoch, rowsinfo, _), s) in enumerate(ok_items):
            if seg_unh[j]:
                jv = UNHEALTHY
            elif seg_min[j] == UNKNOWN:
                jv = UNKNOWN
            else:
                jv = HEALTHY
            a = int(starts[j])
            values_map = {}
            if jv == UNHEALTHY:
                for k2 in range(len(s)):
                    p = pairs_for(a + k2, s, k2)
                    if p:
                        values_map[rowsinfo[k2][0]] = p
            self._decide_status(doc, jv, values_map, now, end_epoch)
            self._log_judged(doc)
            updated.append(doc)
            if observe:
                observe(doc.status, len(s))
            if hook:
                vs = []
                full_bands = ub is not None and ub.ndim == 2
                for k2, (row, (ct, cv)) in enumerate(
                    zip(rowsinfo, s)
                ):
                    alias = row[0]
                    r = a + k2
                    n = min(len(cv), tc)
                    if full_bands:
                        # band_mode="full": whole [n] band per metric,
                        # same shape the slow path's hooks receive
                        up = ub[r, :n] if n else _EMPTY_VALUES
                        lo = lb[r, :n] if n else _EMPTY_VALUES
                    else:
                        up = ub[r : r + 1] if n else _EMPTY_VALUES
                        lo = lb[r : r + 1] if n else _EMPTY_VALUES
                    vs.append(
                        MetricVerdict(
                            job_id=doc.id,
                            alias=alias,
                            verdict=int(v8[r]),
                            anomaly_pairs=pairs_for(r, s, k2),
                            upper=up,
                            lower=lo,
                            # baseline-less bucket: the pairwise
                            # decision is the all-gates-failed
                            # constant; the canary bucket carries the
                            # REAL device outcomes (object-path _emit
                            # parity)
                            p_value=float(ps[r]) if ps is not None else 1.0,
                            dist_differs=(
                                bool(difs[r]) if difs is not None else False
                            ),
                        )
                    )
                try:
                    hook(doc, vs)
                except Exception:
                    log.exception("on_verdict hook failed for %s", doc.id)
        return updated


    # -- reactive plane: micro-ticks + verdict latency (ISSUE 12) --------

    def micro_tick(self, now: float | None = None) -> int:
        """Drain up to `FOREMAST_MICROTICK_DOCS` dirty route keys
        through ONE claim-fetch-judge-write cycle restricted to their
        documents. The body is `_tick` itself — warm docs ride the
        columnar fast path (the sub-second case this plane exists
        for), cold docs take the slow pipeline, every degradation
        contract (write-behind, transient release, breakers) applies
        unchanged — so a micro-tick-judged doc's status is
        byte-identical to the same doc judged by a full tick, by
        construction and pinned by test. Housekeeping (refinement,
        snapshots) stays with the sweeps. Returns #docs processed."""
        dirty = self.dirty
        if dirty is None:
            return 0
        entries = dirty.take(self.microtick_docs)
        if not entries:
            return 0
        if self.tracer is None:
            return self._tick(now, micro=entries)
        with self.tracer.span("worker.microtick", worker=self.worker_id):
            return self._tick(now, micro=entries)

    def _begin_pending(self, micro) -> _TickLedger:
        """Set up this cycle's arrival-attribution ledger: a micro-tick
        owns exactly the entries it took; a full sweep drains the WHOLE
        dirty set (the catch-all — arrivals the micro-ticks missed,
        dropped keys' documents, non-push docs attribute nothing).
        Returns the ledger; `self._ledger` tracks the INNERMOST live
        cycle (a sweep's preemption point save/restores it around the
        nested micro-tick)."""
        if micro is not None:
            led = _TickLedger("micro", micro)
        elif self.dirty is not None:
            led = _TickLedger("sweep", self.dirty.take_all())
        else:
            led = _TickLedger("sweep")
        self._ledger = led
        return led

    def _requeue_pending(self, led: _TickLedger) -> None:
        """Give every un-attributed arrival back to the dirty set with
        its original stamp (claim brownout: nothing was claimed, the
        docs stay claimable, the arrivals must survive)."""
        if led.pending and self.dirty is not None:
            for rk, stamp in led.pending.items():
                self.dirty.mark(rk, stamp, requeue=True)
        led.pending = {}

    def _finish_pending(self, led: _TickLedger) -> None:
        """Close out arrival attribution: pending keys no judged doc
        matched (terminal docs, apps claimed by a peer, sweep claims
        past the limit) are DROPPED and counted — never re-queued,
        because a key with no claimable doc would cycle forever."""
        pending = led.pending
        if pending:
            missed = sum(1 for k in pending if k not in led.observed)
            if missed and self.dirty is not None:
                self.dirty.count("unattributed", missed)
        led.pending = {}
        led.observed = set()

    def _observe_verdicts(
        self, docs, led: _TickLedger | None = None
    ) -> None:
        """Per-verdict latency: every just-written doc whose route key
        carries a pending arrival observes (now - receiver arrival
        stamp) on `foremast_verdict_latency_seconds{path}` — the
        push→verdict SLO. Called at the write-back points of both tick
        paths; a write-behind-buffered verdict observes too (the
        verdict exists; its persistence is the buffer's contract)."""
        led = self._ledger if led is None else led
        pending = led.pending
        if not pending or not docs:
            return
        hist = (
            getattr(self.metrics, "verdict_latency", None)
            if self.metrics
            else None
        )
        observed = led.observed
        path = led.path
        now = time.time()
        tenancy = self._tenancy
        for doc in docs:
            rk = doc_route_key(doc)
            stamp = pending.get(rk)
            if stamp is None:
                continue
            observed.add(rk)
            if hist is not None:
                # bounded-cardinality tenant attribution (ISSUE 20):
                # configured tenants + up to FOREMAST_TENANT_LABEL_MAX
                # observed values get their own label, the rest fold
                # into `other`; untenanted workers export one constant
                # `default` series per path
                tenant = (
                    tenancy.metric_tenant(tenancy.tenant_of_doc(doc))
                    if tenancy is not None
                    else "default"
                )
                hist.labels(path=path, tenant=tenant).observe(
                    max(0.0, now - stamp)
                )

    def _micro_claim_filter(self, base, led: _TickLedger):
        """The micro-tick's claim restriction: only documents whose
        route key is in this tick's pending set, composed with the
        mesh partition filter (dirty routing respects partition
        ownership — a stale dirty key for a moved app can never steal
        a foreign doc; claim-CAS stays the net beneath both)."""
        keys = led.pending

        def claim_filter(doc) -> bool:
            if base is not None and not base(doc):
                return False
            return doc_route_key(doc) in keys

        return claim_filter

    # -- main cycle ------------------------------------------------------

    def tick(self, now: float | None = None) -> int:
        """One claim-fetch-judge-write cycle. Returns #docs processed.

        Sweeps whose claim can exceed one slice run SLICED (ISSUE 15,
        `_sweep_sliced`): bounded slices through the warm-path
        pipeline with a dirty-drain preemption point between slices,
        so a pushed anomaly's latency is bounded by slice wall clock.
        Everything else — and `FOREMAST_SWEEP_SLICE_DOCS=0` — keeps
        the monolithic body (`_tick`), the byte-parity arm."""
        if self.tracer is None:
            return self._cycle(now)
        # the root span mints the tick's trace ID: every stage span
        # below (and the engine/store spans nested inside them) shares
        # it, as do JSON log records emitted while the tick is open
        with self.tracer.span("worker.tick", worker=self.worker_id):
            return self._cycle(now)

    def _cycle(self, now: float | None) -> int:
        if self._sweep_sliceable():
            return self._sweep_sliced(now)
        return self._tick(now)

    def _sweep_sliceable(self) -> bool:
        """Sliced sweeps engage when a claim can outgrow one slice
        (FOREMAST_SWEEP_SLICE_DOCS > 0 and < claim_limit), the columnar
        fast path exists, and `_fast_tick` has not been replaced on the
        instance (tests/benches forcing the object path get exactly the
        monolithic body they stubbed). PodWorker forces the knob to 0."""
        return (
            self.sweep_slice_docs > 0
            and self.claim_limit > self.sweep_slice_docs
            and self._uni is not None
            and "_fast_tick" not in self.__dict__
        )

    # -- sliced, preemptible sweeps (ISSUE 15) ---------------------------

    def _sweep_sliced(self, now: float | None = None) -> int:
        """One full sweep as a sequence of bounded slices through a
        warm-path pipeline: the prefetch thread CLAIM-POOL-takes and
        packs slice N+1 while the tick thread async-dispatches slice
        N's columnar programs and the writer thread gathers, decodes
        and bulk-writes slice N−1 — steady-state wall clock approaches
        max(prepare, dispatch, finish) per slice instead of their sum
        (the round-15 roofline's host-plane fix). At every slice
        boundary the reactive drain gets a PREEMPTION POINT
        (`_preempt_between_slices`), so pushed-anomaly latency is
        bounded by one slice's wall clock, not the sweep's.

        Contract preservation: the claim is ONE store round trip (same
        claim/lease semantics as the monolithic tick — per-slice
        re-claiming would re-take re-check docs this sweep already
        judged); each slice's write-behind stamps carry the sweep's
        claim instant; the tick budget is checked per slice with the
        still-pooled remainder released in one bulk write on expiry;
        per-doc judgment is byte-identical to the monolithic tick
        because both compose the same pack/dispatch/decode helpers."""
        t0 = time.perf_counter()
        self._tick_deadline = self._degrade.deadline(t0)
        now = time.time() if now is None else now
        self._flush_write_behind()
        led = self._begin_pending(None)
        docs = self._claim_cycle(led, None)
        claim_mono = self._tick_claim_mono
        if docs and self._deadline_exceeded():
            self._release_docs(docs, REASON_DEADLINE, led, claim_mono)
            docs = []
        if not docs:
            # idle sweep: same housekeeping as the monolithic idle tick
            self._finish_pending(led)
            self._refine_provisional(now)
            self._maybe_persist()
            if self.metrics:
                self.metrics.tick_seconds.observe(time.perf_counter() - t0)
            return 0

        import itertools

        from foremast_tpu.jobs import pipeline as _pl

        pool = _SweepPool(docs, tenancy=self._tenancy)
        counters = {
            "slices": 0, "slow_docs": 0, "promoted": 0,
            "inflight_requeued": 0, "preempt_microticks": 0,
            "preempt_docs": 0,
        }
        totals = {"docs": 0, "fast": 0}
        # the SWEEP's deadline, captured like claim_mono: prepare runs
        # on the prefetch thread CONCURRENTLY with the boundary hook,
        # and a nested preemption micro-tick temporarily points
        # self._tick_deadline at its own (fresher) deadline — reading
        # the instance attr there could let a budget-expired sweep
        # keep taking slices through a micro's unexpired window
        sweep_deadline = self._tick_deadline

        def past_deadline() -> bool:
            return (
                sweep_deadline is not None
                and time.perf_counter() > sweep_deadline
            )

        def prepare(_i):
            # prefetch thread: deadline check FIRST — an expired sweep
            # releases its pooled remainder in one bulk write instead
            # of fetching work it may not judge (per-slice budget
            # accounting, chaos/degrade.py)
            if past_deadline():
                rest = pool.drain()
                if not rest:
                    return _pl.END
                return _SlicePrep(rest, claim_mono, release_all=True)
            batch = pool.take(self.sweep_slice_docs)
            if not batch:
                return _pl.END
            return self._prepare_slice(batch, now, claim_mono)

        def judge(_i, prep):
            if not prep.release_all:
                # release bundles judge nothing: counting them would
                # overstate foremast_sweep_slices_total and the varz
                counters["slices"] += 1
                counters["slow_docs"] += len(prep.slow)
            return self._dispatch_slice(prep, now, led)

        def write(_i, res):
            n_docs, n_fast = self._finish_slice(res, now, led, pool)
            totals["docs"] += n_docs
            totals["fast"] += n_fast

        def boundary():
            self._preempt_between_slices(pool, led, now, counters)

        # _sweep_active pins _tick_claim_mono for nested micro-ticks
        # (see _claim_cycle); flipped back in the SAME finally that
        # releases the pool, so no setup failure (pool materialization,
        # a KeyboardInterrupt) can leave it stuck True — that would
        # freeze write-behind claim stamps for every later tick
        self._sweep_active = True
        pipe = None
        try:
            use_threads = self.pipeline_depth > 1
            if use_threads:
                # materialize both pools on the tick thread (see the
                # slow pipeline's rationale)
                self._fetch_pool_get()
            pipe = _pl.ChunkPipeline(
                inherit_span(prepare),
                judge,
                inherit_span(write),
                depth=self.pipeline_depth,
                prefetch_pool=(
                    self._prefetch_pool_get() if use_threads else None
                ),
                boundary=boundary,
                on_drained=lambda _i, prep: self._abort_slice(prep, led),
            )
            pipe.run(itertools.count())
        finally:
            self._sweep_active = False
            rest = pool.drain()
            if rest:
                # abort path: claimed docs whose slice never ran go
                # back un-judged instead of waiting out stuck takeover
                try:
                    self._release_docs(rest, REASON_ABORT, led, claim_mono)
                except Exception:  # noqa: BLE001 — the primary error propagates
                    log.exception(
                        "failed to release %d pooled doc(s) after sweep "
                        "abort; stuck-claim takeover will net them",
                        len(rest),
                    )
            stats = pipe.last_stats if pipe is not None else None
            pipe_state = stats.as_dict() if stats else None
            if pipe_state is not None:
                # chunk specs are opaque slice indices; the honest doc
                # count is what the writer actually retired
                pipe_state["docs"] = totals["docs"]
            self._last_sweep = {
                "slice_docs": self.sweep_slice_docs,
                **counters,
                "pipeline": pipe_state,
            }
            if self.metrics and hasattr(self.metrics, "observe_sweep"):
                self.metrics.observe_sweep(stats, counters)
        if counters["slow_docs"] == 0:
            # all-warm sweep: the cheap moment to upgrade provisional
            # fits, exactly the monolithic tick's rule
            self._refine_provisional(now)
        if self.metrics:
            if hasattr(self.metrics, "observe_arena"):
                self.metrics.observe_arena(
                    self._uni.device_state_counters()
                )
            self.metrics.tick_seconds.observe(time.perf_counter() - t0)
        self._tick_done(totals["docs"], totals["fast"], t0, led=led)
        return totals["docs"]

    def _prepare_slice(
        self, docs, now: float, claim_mono: float
    ) -> _SlicePrep:
        """Pipeline stage 1 (prefetch thread): admission split, window
        fetch, and columnar packing for one slice. No store writes and
        no device work — those belong to the writer and tick threads."""
        prep = _SlicePrep(docs, claim_mono)
        fast, fastc, fastj, prep.slow = self._admit_fast(docs, now)
        if fast or fastc or fastj:
            (
                prep.ok_items,
                prep.ok_citems,
                prep.ok_joint,
                prep.failed,
                prep.released,
            ) = self._fetch_fast(fast, fastc, fastj)
            if prep.ok_items:
                prep.uni_packed = self._pack_uni(prep.ok_items, False)
            if prep.ok_citems:
                prep.canary_packed = self._pack_uni(prep.ok_citems, True)
        return prep

    def _dispatch_slice(
        self, prep: _SlicePrep, now: float, led: _TickLedger
    ) -> _SliceResult:
        """Pipeline stage 2 (tick thread, strict slice order — arena
        assignment and device dispatch order are load-bearing): judge
        the joint group synchronously (a minority; its own dispatch
        merges internally), async-dispatch the univariate and canary
        columnar programs, then run this slice's slow leftovers through
        the existing chunk pipeline. A dispatch failure raises
        StageError carrying the partial result so already-judged work
        still persists through the writer."""
        res = _SliceResult(prep)
        if prep.release_all:
            return res
        from foremast_tpu.jobs.pipeline import StageError

        try:
            if prep.ok_joint:
                j_updated, demoted, j_counts = self._judge_joint_fast(
                    prep.ok_joint, now
                )
                res.joint_updated = j_updated
                res.joint_counts = j_counts
                self._demote_to_slow(
                    prep.slow, demoted, "joint window bucket drift"
                )
            if prep.uni_packed is not None:
                res.uni_pending = self._dispatch_uni(prep.uni_packed)
            if prep.canary_packed is not None:
                res.canary_pending = self._dispatch_uni(prep.canary_packed)
        except BaseException as e:  # noqa: BLE001 — re-raised post-drain
            res.aborted = True
            raise StageError(e, res) from e
        if prep.slow:
            try:
                self._run_slow_chunks(
                    prep.slow, now, led, prep.claim_mono
                )
                prep.slow_done = True
            except BaseException as e:  # noqa: BLE001 — re-raised post-drain
                # the warm dispatches above still owe their writes:
                # ship them through the writer before the error
                # propagates (the slow pipeline released/persisted its
                # own partial work already)
                prep.slow_done = True
                raise StageError(e, res) from e
        return res

    def _finish_slice(
        self, res: _SliceResult, now: float, led: _TickLedger, pool
    ) -> tuple[int, int]:
        """Pipeline stage 3 (writer thread): gather + decode the
        pending columnar judgments, persist everything, observe the
        verdict latencies, and retire the slice's route keys from the
        in-flight set. Returns (docs_processed, fast_docs)."""
        prep = res.prep
        try:
            if prep.release_all:
                self._release_docs(
                    prep.docs, REASON_DEADLINE, led, prep.claim_mono
                )
                return len(prep.docs), 0
            for doc in prep.failed:
                self._store_update(doc, claim_mono=prep.claim_mono)
                if self.metrics:
                    self.metrics.observe_doc(doc.status, 0)
            if prep.released:
                self._release_docs(
                    prep.released, REASON_FETCH, led, prep.claim_mono
                )
            updated = list(res.joint_updated)
            kind_counts = {
                "univariate": 0, "bivariate": 0, "lstm": 0, "baseline": 0,
            }
            if res.joint_counts:
                for kind, n in res.joint_counts.items():
                    kind_counts[kind] += n
            drop: list = []
            if res.uni_pending is not None:
                updated += self._decode_uni(
                    prep.uni_packed, res.uni_pending.wait(), now
                )
                kind_counts["univariate"] += len(prep.uni_packed.ok_items)
            elif res.aborted and prep.uni_packed is not None:
                drop += [it[0] for it, _ in prep.uni_packed.ok_items]
            if res.canary_pending is not None:
                updated += self._decode_uni(
                    prep.canary_packed, res.canary_pending.wait(), now
                )
                kind_counts["baseline"] += len(
                    prep.canary_packed.ok_items
                )
            elif res.aborted and prep.canary_packed is not None:
                drop += [it[0] for it, _ in prep.canary_packed.ok_items]
            if res.aborted:
                if not res.joint_updated and prep.ok_joint:
                    drop += [it[0] for it, _ in prep.ok_joint]
                if not prep.slow_done:
                    drop += list(prep.slow)
                self._release_docs(
                    drop, REASON_ABORT, led, prep.claim_mono
                )
            self._account_fast_kinds(kind_counts)
            if updated:
                with span(
                    "worker.write_back",
                    stage="write_back",
                    docs=len(updated),
                ):
                    self._store_update_many(
                        updated, claim_mono=prep.claim_mono
                    )
            self._observe_verdicts(updated, led)
            n_fast = len(updated) + len(prep.failed) + len(prep.released)
            return n_fast + len(prep.slow), n_fast
        finally:
            pool.done(prep.docs)

    def _abort_slice(self, prep: _SlicePrep, led: _TickLedger) -> None:
        """A prepared slice whose judgment never ran (pipeline abort):
        persist the fetch-failure marks, give every other claimed doc
        back un-judged. Best-effort — a store that is itself the abort
        cause leaves the docs to stuck-claim takeover."""
        try:
            if prep.release_all:
                self._release_docs(
                    prep.docs, REASON_DEADLINE, led, prep.claim_mono
                )
                return
            for doc in prep.failed:
                self._store_update(doc, claim_mono=prep.claim_mono)
            # fetch-released docs keep their honest reason — an abort
            # coinciding with a dependency brownout must not hide the
            # brownout from the fetch_released counter
            self._release_docs(
                list(prep.released), REASON_FETCH, led, prep.claim_mono
            )
            docs = [it[0] for it, _ in prep.ok_items]
            docs += [it[0] for it, _ in prep.ok_citems]
            docs += [it[0] for it, _ in prep.ok_joint]
            docs += list(prep.slow)
            self._release_docs(docs, REASON_ABORT, led, prep.claim_mono)
        except Exception:  # noqa: BLE001 — the primary error propagates
            log.exception(
                "failed to release an unjudged slice after sweep abort; "
                "stuck-claim takeover will net its docs"
            )

    def _preempt_between_slices(
        self, pool, led: _TickLedger, now: float, counters: dict
    ) -> None:
        """The slice-boundary preemption point (ISSUE 15 tentpole).

        Pending dirty arrivals are triaged against the sweep itself:

          * key matches POOLED docs (claimed, not yet fetched) — the
            docs are PROMOTED to the front of the slice order; their
            slice fetches post-arrival samples, so the sweep's own
            write delivers the verdict within ~one slice, attributed
            through the sweep ledger (earliest stamp wins).
          * key matches an IN-FLIGHT slice (fetched or fetching — its
            windows may predate the arrival) — requeued at the front
            of the dirty set with the ORIGINAL stamp; once the slice's
            write releases the doc, the next boundary claims it.
          * anything else (docs outside this sweep's claim: new jobs,
            already-written re-check docs) — a NESTED micro-tick runs
            between slices, the unchanged `_tick` body on its own
            ledger, every degradation contract intact.
        """
        dirty = self.dirty
        if dirty is None or not len(dirty):
            return
        entries = dirty.take(self.microtick_docs)
        if not entries:
            return
        micro_entries = []
        for rk, stamp in entries:
            if pool.promote(rk):
                cur = led.pending.get(rk)
                if cur is None or stamp < cur:
                    led.pending[rk] = stamp
                counters["promoted"] += 1
                dirty.count("promoted")
            elif pool.inflight(rk):
                dirty.mark(rk, stamp, requeue=True)
                counters["inflight_requeued"] += 1
                dirty.count("inflight_requeued")
            else:
                micro_entries.append((rk, stamp))
        if not micro_entries:
            return
        counters["preempt_microticks"] += 1
        # the nested cycle swaps the innermost-ledger pointer and the
        # tick deadline; restore both so the sweep's remaining slices
        # keep their budget and attribution. It takes a FRESH clock
        # (micro_tick's contract), NOT the sweep's start `now`: a
        # late-sweep preemption judging with a clock stale by the
        # sweep's whole duration would miss endTimes that elapsed
        # mid-sweep and treat just-settled histories as unsettled —
        # demoting the latency-critical arrival to the slow path
        saved_deadline = self._tick_deadline
        saved_ledger = self._ledger
        try:
            counters["preempt_docs"] += self._tick(
                None, micro=micro_entries
            )
        finally:
            self._tick_deadline = saved_deadline
            self._ledger = saved_ledger

    def _claim_cycle(self, led: _TickLedger, micro) -> list[Document]:
        """Shared cycle head for the monolithic tick and the sliced
        sweep: renew the mesh lease, compose the claim filter (mesh
        partition, plus the dirty-key restriction for micro-ticks),
        stamp the claim instant, and claim — degrading a transient
        store failure to an empty cycle with the pending arrivals
        requeued un-spent."""
        claim_kw = {}
        if self.mesh is not None:
            # idle ticks renew too — the lease must outlive quiet
            # fleets (lease/refresh timing runs on the mesh's own
            # injectable clocks, not this tick's possibly-simulated now)
            self.mesh.on_tick()
            claim_kw["claim_filter"] = self.mesh.claim_filter
        if micro is not None:
            claim_kw["claim_filter"] = self._micro_claim_filter(
                claim_kw.get("claim_filter"), led
            )
        # Write-behind age stamps measure from this instant. A sliced
        # sweep PINS it at its own claim time (`_sweep_active`): a
        # nested preemption micro-tick must never move it FORWARD,
        # because the sweep's writer threads stamp concurrently — a
        # fresher stamp on an older claim would stretch the replay
        # window past the stuck-takeover boundary (the exactly-once
        # net). The micro's own entries getting the sweep's OLDER
        # stamp is conservative: they age out earlier, never later.
        if not self._sweep_active:
            self._tick_claim_mono = time.monotonic()
        with span("worker.claim", stage="claim", limit=self.claim_limit):
            try:
                docs = self.store.claim(
                    self.worker_id,
                    self.config.max_stuck_seconds,
                    self.claim_limit,
                    **claim_kw,
                )
                if self._tenant_acct is not None and docs:
                    # per-tenant claim attribution (ISSUE 20): counted
                    # at THE claim, so sweep, sliced-sweep and
                    # micro-tick paths all charge through one seam
                    by_tenant: dict[str, int] = {}
                    for d in docs:
                        t = self._tenancy.tenant_of_doc(d)
                        by_tenant[t] = by_tenant.get(t, 0) + 1
                    for t, c in by_tenant.items():
                        self._tenant_acct.count_claims(t, c)
                return docs
            except Exception as e:
                # a store outage must degrade to an idle tick, not kill
                # the worker loop: nothing was claimed, nothing is owed
                # — and the pending arrivals go back to the dirty set
                # un-spent (the docs stay claimable; the push→verdict
                # clock keeps running from the original stamps)
                if not is_transient_error(e):
                    raise
                self._degrade.stats.count_event("store", "claim_error")
                log.warning(
                    "claim degraded to empty tick (store transient "
                    "error: %s)", e,
                )
                self._requeue_pending(led)
                return []

    # An unexpected exception mid-judgment deliberately leaves this
    # cycle's claims to the stuck-claim takeover (the window is a
    # first-class claim parameter — `store.claim(..., max_stuck_seconds,
    # ...)` in _claim_cycle). A blanket release edge here would be WRONG:
    # it could reset docs whose terminal status the chunk pipeline's
    # writer already persisted, breaking the exactly-once ledger. The
    # detectable failures all have protected edges already (claim
    # brownout -> empty cycle, deadline -> _release_docs, pipeline abort
    # -> _abort_slice, judge error -> _judge_chunk's failure write).
    # foremast: ignore[status-machine]
    def _tick(self, now: float | None = None, micro=None) -> int:
        t0 = time.perf_counter()
        self._tick_deadline = self._degrade.deadline(t0)
        now = time.time() if now is None else now
        # replay any write-behind backlog FIRST: the store may have
        # healed, and re-check docs buffered as preprocess_completed
        # must become claimable before this tick's claim
        self._flush_write_behind()
        # reactive (ISSUE 12): a micro-tick owns the dirty entries it
        # took; a full sweep drains the rest as its catch-all
        led = self._begin_pending(micro)
        docs = self._claim_cycle(led, micro)
        if docs and self._deadline_exceeded():
            # the claim alone blew the tick budget (store brownout):
            # give everything back un-judged rather than start a fetch/
            # judge pass that is already over budget
            self._release_docs(docs, REASON_DEADLINE, led)
            docs = []
        if not docs:
            # idle cycles still did the claim round-trip (real store I/O)
            # and must be visible on the tick histogram; an idle WORKER
            # is not an idle RING (receiver threads keep pushing), so
            # snapshot cadence and provisional-fit refinement run here
            # (sweeps only — micro-ticks stay lean)
            self._finish_pending(led)
            if micro is not None:
                self._tick_done(0, 0, t0, micro=True, led=led)
                return 0
            self._refine_provisional(now)
            self._maybe_persist()
            if self.metrics:
                self.metrics.tick_seconds.observe(time.perf_counter() - t0)
            return 0

        # the all-warm re-check subset takes the columnar fast path;
        # whatever it returns (cold fits, baselines, joint models,
        # unsettled histories) flows through the object path below
        n_fast = 0
        if self._uni is not None:
            n_fast, docs = self._fast_tick(docs, now)
            if not docs:
                # all-warm steady tick: the cheap moment to upgrade
                # provisional fits — invalidations land their refits on
                # the NEXT tick's slow path, in bounded batches
                # (sweeps only; micro-ticks leave housekeeping alone)
                if micro is None:
                    self._refine_provisional(now)
                if self.metrics:
                    if hasattr(self.metrics, "observe_arena"):
                        self.metrics.observe_arena(
                            self._uni.device_state_counters()
                        )
                    if micro is None:
                        self.metrics.tick_seconds.observe(
                            time.perf_counter() - t0
                        )
                self._tick_done(
                    n_fast, n_fast, t0, micro=micro is not None, led=led
                )
                return n_fast

        self._run_slow_chunks(docs, now, led, self._tick_claim_mono)
        if self.metrics:
            if self._uni is not None and hasattr(
                self.metrics, "observe_arena"
            ):
                self.metrics.observe_arena(self._uni.device_state_counters())
            if micro is None:
                self.metrics.tick_seconds.observe(time.perf_counter() - t0)
        self._tick_done(
            n_fast + len(docs), n_fast, t0, micro=micro is not None, led=led
        )
        return n_fast + len(docs)

    def _run_slow_chunks(
        self, docs, now: float, led: _TickLedger, claim_mono: float
    ) -> None:
        """Progressive admission (VERDICT r4 #7): the slow path — cold
        fits, baselines, joint models — processes the claim set in
        bounded DOC CHUNKS, bounding time-to-first-verdict by one
        chunk's work (and bounding peak host memory for the packed
        histories the same way _FIT_CHUNK bounds device memory). The
        chunks run through a bounded-depth pipeline (jobs/pipeline.py,
        FOREMAST_PIPELINE_DEPTH): chunk N+1's windows are prefetched
        while chunk N's judgment is in flight on the device and chunk
        N-1's verdicts drain to the store on a writer thread, so a
        fleet-cold tick approaches max(fetch, judge, write) per chunk
        instead of their sum. Warm steady state is unaffected: the
        columnar fast path already consumed the all-warm subset, so
        `docs` here is usually tiny (a single serial chunk). Under a
        sliced sweep (ISSUE 15) each slice's leftovers run through
        their own bounded pass, so cold docs persist within their own
        slice's lifetime."""
        chunk_docs = self.cold_chunk_docs
        # Pool/pipeline only when the source actually blocks on I/O:
        # in-memory sources declare concurrent_fetch=False (threading
        # pure-Python dict lookups is pure GIL overhead), and pod-mode
        # LeaderSource fetches are ordered broadcast collectives that a
        # prefetch thread would interleave into a deadlock — both
        # degrade to the depth-1 serial loop.
        use_pool = len(docs) > 1 and getattr(
            self.source, "concurrent_fetch", True
        )
        chunks = [
            docs[c0 : c0 + chunk_docs]
            for c0 in range(0, len(docs), chunk_docs)
        ]
        from functools import partial as _partial

        from foremast_tpu.jobs.pipeline import ChunkPipeline

        depth = self.pipeline_depth if use_pool else 1
        if use_pool:
            # materialize the fetch pool on the tick thread: lazy
            # creation from concurrent prefetch threads (depth > 2)
            # could race into two executors, leaking one
            self._fetch_pool_get()
        pipe = ChunkPipeline(
            # fetch/write run on pipeline threads: inherit_span re-seats
            # the tick's ambient span so their stage spans and log
            # records keep the tick's trace ID
            inherit_span(_partial(self._fetch_chunk, now=now, use_pool=use_pool)),
            self._judge_chunk,
            inherit_span(
                _partial(
                    self._write_chunk,
                    now=now,
                    led=led,
                    claim_mono=claim_mono,
                )
            ),
            depth=depth,
            prefetch_pool=(
                self._prefetch_pool_get()
                if depth > 1 and len(chunks) > 1
                else None
            ),
        )
        try:
            pipe.run(chunks)
        finally:
            # surface occupancy on the ABORT path too: an operator
            # debugging a dead tick must not read the previous healthy
            # tick's stats from /debug/state (completed=False marks the
            # partial snapshot)
            stats = pipe.last_stats
            self._last_pipeline = stats.as_dict()
            if self.metrics and hasattr(self.metrics, "observe_pipeline"):
                self.metrics.observe_pipeline(stats)

    # -- slow-path pipeline stages (jobs/pipeline.py) --------------------

    def _fetch_chunk(self, chunk, now: float, use_pool: bool):
        """Pipeline stage 1: every window of every doc in the chunk.
        Runs on a prefetch thread when the pipeline is engaged; per-doc
        failures come back as None entries (fail-fast isolation) or the
        RELEASED sentinel (transient — released un-judged), never
        exceptions. The fetches are HTTP round trips to Prometheus
        (latency-bound), fanned over the persistent fetch pool so chunk
        wall-clock scales with the slowest fetch, not the claim count.
        A chunk whose turn comes after the tick deadline skips its
        fetches entirely — every doc releases (partial-tick
        semantics)."""
        if self._deadline_exceeded():
            return [RELEASED_DEADLINE] * len(chunk)
        with span("worker.fetch", stage="metric_fetch", docs=len(chunk)):
            if use_pool:
                from functools import partial as _partial

                return list(
                    self._fetch_pool_get().map(
                        inherit_span(_partial(self._fetch_tasks, now=now)),
                        chunk,
                    )
                )
            return [self._fetch_tasks(doc, now) for doc in chunk]

    def _judge_chunk(self, chunk, fetched):
        """Pipeline stage 2 (tick thread, strict chunk order): ONE
        batched judgment for every window of the chunk's jobs. Returns
        (ok_docs, failed_docs, verdicts by job id, released (doc,
        reason) pairs); store writes belong to stage 3. A judge
        exception becomes a StageError carrying the failed/released
        partial result: the chunk's fetch-failure markings must still
        reach the store (the pre-pipeline loop persisted them before
        judging), only the writer thread may touch the store, and no
        further chunk may be dispatched to the broken judge —
        StageError is exactly that contract. A chunk reaching the judge
        after the tick deadline releases every fetched doc un-judged
        (partial-tick semantics) instead of running over budget."""
        all_tasks: list[MetricTask] = []
        failed: list[Document] = []
        ok_docs: list[Document] = []
        released: list[tuple[Document, str]] = []
        past_deadline = self._deadline_exceeded()
        for doc, tasks in zip(chunk, fetched):
            # claim() already flipped + persisted preprocess_inprogress
            if tasks is None:
                doc.status = STATUS_PREPROCESS_FAILED
                doc.status_code = "500"
                doc.reason = "metric fetch failed"
                failed.append(doc)
            elif tasks is RELEASED:
                released.append((doc, REASON_FETCH))
            elif tasks is RELEASED_DEADLINE or past_deadline:
                released.append((doc, REASON_DEADLINE))
            else:
                ok_docs.append(doc)
                all_tasks.extend(tasks)
        try:
            verdicts = self.judge.judge(all_tasks)
        except BaseException as e:  # noqa: BLE001 — re-raised post-drain
            from foremast_tpu.jobs.pipeline import StageError

            raise StageError(e, ([], failed, {}, released)) from e
        by_job: dict[str, list[MetricVerdict]] = {}
        for v in verdicts:
            by_job.setdefault(v.job_id, []).append(v)
        return ok_docs, failed, by_job, released

    def _write_chunk(
        self,
        chunk,
        result,
        now: float,
        led: _TickLedger | None = None,
        claim_mono: float | None = None,
    ) -> None:
        """Pipeline stage 3 (single writer thread, FIFO): status
        transitions + per-doc persistence + hooks. `_write_back` keeps
        decide + store.update together so subclass overrides stay
        valid; the store is only ever called from one thread at a time
        during the slow path (the writer), preserving the serial loop's
        write sequence one chunk behind the judgment."""
        ok_docs, failed, by_job, released = result
        if released:
            # one bulk write per reason group, not a round trip per doc
            # (a blackholed Prometheus releases WHOLE chunks — exactly
            # when the tick can least afford per-doc write latency)
            by_reason: dict[str, list[Document]] = {}
            for doc, reason in released:
                by_reason.setdefault(reason, []).append(doc)
            for reason, docs_r in by_reason.items():
                self._release_docs(
                    docs_r, reason, led, claim_mono=claim_mono
                )
        for doc in failed:
            self._store_update(doc, claim_mono=claim_mono)
            if self.metrics:
                self.metrics.observe_doc(doc.status, 0)
        with span("worker.decide", stage="decide", docs=len(ok_docs)):
            for doc in ok_docs:
                vs = by_job.get(doc.id, [])
                self._write_back(doc, vs, now)
                self._log_judged(doc)
                if self.metrics:
                    self.metrics.observe_doc(doc.status, len(vs))
                if self.on_verdict:
                    try:
                        self.on_verdict(doc, vs)
                    except Exception:
                        log.exception(
                            "on_verdict hook failed for %s", doc.id
                        )
        self._observe_verdicts(ok_docs, led)

    def _log_judged(self, doc) -> None:
        """One correlatable line per service-created judgment: emitted
        inside the tick span, so the record carries the tick's
        trace/span IDs AND the request trace ID the service stamped on
        the document (`job_trace_id`) — grep either ID to find the
        other. Docs without a stamped ID (direct store writes) stay
        silent. INFO only on the first judgment or a status CHANGE
        (mirroring the controller's transitions counter); a re-judged
        open doc whose status held re-asserts at DEBUG, else a fleet of
        open jobs emits thousands of identical lines per poll."""
        if doc.trace_id:
            prev = self._judged_status.get(doc.id)
            level = logging.INFO if doc.status != prev else logging.DEBUG
            if doc.status in TERMINAL_STATUSES:
                self._judged_status.pop(doc.id, None)
            else:
                self._judged_status[doc.id] = doc.status
                # bound the map: a peer worker may land a job's terminal
                # judgment, leaving our entry orphaned forever. Evict
                # oldest-inserted past the cap — a still-open evictee
                # merely re-logs one INFO line on its next judgment.
                while len(self._judged_status) > self._JUDGED_STATUS_CAP:
                    self._judged_status.pop(
                        next(iter(self._judged_status))
                    )
            ctx_log(
                log,
                level,
                "judgment",
                job_id=doc.id,
                status=doc.status,
                job_trace_id=doc.trace_id,
            )

    def _tick_done(
        self,
        n_docs: int,
        n_fast: int,
        t0: float,
        micro: bool = False,
        led: _TickLedger | None = None,
    ) -> None:
        """Record the finished busy tick for /debug/state and emit one
        correlatable completion log (the tick's trace ID rides on the
        JSON record when a tracer is wired). Micro-ticks keep their own
        ledger + counter and skip durability housekeeping (snapshot
        cadence and journal compaction belong to the sweeps — a
        sub-second judgment path must never absorb a snapshot pass)."""
        self._finish_pending(self._ledger if led is None else led)
        seconds = time.perf_counter() - t0
        if micro:
            self._last_micro = {
                "at": time.time(),
                "docs": n_docs,
                "seconds": seconds,
                "runs": self._last_micro.get("runs", 0) + 1,
            }
            m = (
                getattr(self.metrics, "microtick_docs", None)
                if self.metrics
                else None
            )
            if m is not None and n_docs:
                m.inc(n_docs)
            if n_docs:
                ctx_log(
                    log,
                    logging.DEBUG,
                    "micro-tick complete",
                    docs=n_docs,
                    seconds=round(seconds, 4),
                )
            return
        self._maybe_persist()
        if self.metrics is not None and hasattr(
            self.metrics, "observe_device_mesh"
        ):
            dm = self._device_mesh_state()
            if dm is not None:
                self.metrics.observe_device_mesh(dm)
        self._last_tick = {
            "at": time.time(),
            "docs": n_docs,
            "fast": n_fast,
            "seconds": seconds,
        }
        ctx_log(
            log,
            logging.INFO,
            "tick complete",
            docs=n_docs,
            fast_path=n_fast,
            seconds=round(seconds, 4),
        )

    def _columnar_pad_state(self) -> dict | None:
        """Padded-row accounting across the univariate AND joint
        columnar dispatches — meaningful on every judge (pow2 bucketing
        pads with or without a device mesh). None when no columnar
        dispatch has run."""
        rows = pads = 0
        if self._uni is not None:
            rows += self._uni.batch_rows_total
            pads += self._uni.pad_rows_total
        if self._mvj is not None:
            rows += self._mvj.batch_rows_total
            pads += self._mvj.pad_rows_total
        if not rows:
            return None
        return {
            "batch_rows_total": rows,
            "pad_rows_total": pads,
            "padded_row_fraction": round(pads / rows, 4),
        }

    def _device_mesh_state(self) -> dict | None:
        """The /debug/state `device_mesh` section (ISSUE 13, arena
        accounting resharded by ISSUE 19): mesh shape, padded-row
        fraction across the univariate AND joint columnar dispatches,
        arena HBM accounting, and the H2D/gather roofline counters.
        None when the judge is single-device.

        `arena_replica_bytes` is PER-DEVICE arena bytes in either
        layout (one replica when replicated; one row-space block when
        sharded — RowArena.device_bytes divides by the shard count), so
        `arena_total_device_bytes` = per-device x device count is the
        fleet-wide HBM bill in both: the replication tax when
        FOREMAST_ARENA_SHARDED=0, the SHARD-SUM (= one logical copy,
        the capacity win) by default. `arena_layout` says which is in
        force; `arena_capacity_rows` is the aggregate row capacity
        across all arenas."""
        uni = self._uni
        if uni is None or not hasattr(uni, "mesh_debug"):
            return None
        out = uni.mesh_debug()
        if self._mvj is not None:
            rows = out["batch_rows_total"] + self._mvj.batch_rows_total
            pads = out["pad_rows_total"] + self._mvj.pad_rows_total
            out["batch_rows_total"] = rows
            out["pad_rows_total"] = pads
            out["padded_row_fraction"] = (
                round(pads / rows, 4) if rows else None
            )
        arenas = list(uni._arenas.values())
        if self._mvj is not None:
            arenas += list(self._mvj._joint_arenas.values())
        replica = sum(a.device_bytes() for a in arenas)
        shards = getattr(uni, "_arena_shards", lambda: 1)()
        out["arena_layout"] = "sharded" if shards > 1 else "replicated"
        out["arena_capacity_rows"] = sum(a.cap for a in arenas)
        out["arena_replica_bytes"] = replica
        out["arena_total_device_bytes"] = replica * out["devices"]
        return out

    def debug_state(self) -> dict:
        """The /debug/state varz payload (observe.start_observe_server):
        queue depth, cache occupancy, arena counters with hit rate, the
        latest tick's stage breakdown, and config identity."""
        from foremast_tpu import __version__

        try:
            queue_depth: int | None = self.store.count_open()
            store_ok = True
        except Exception:  # noqa: BLE001 - varz must not depend on ES health
            queue_depth, store_ok = None, False
        arena = None
        if self._uni is not None:
            arena = self._uni.device_state_counters()
            looked = arena.get("hits", 0) + arena.get("misses", 0)
            arena["hit_rate"] = (
                round(arena.get("hits", 0) / looked, 4) if looked else None
            )
        joint_arena = None
        if self._mvj is not None:
            joint_arena = self._mvj.joint_state_counters()
        # push-based ingest plane (duck-typed: any source exposing
        # ingest_debug_state — RingSource directly, or wrapped inside a
        # pod-mode LeaderSource via .inner)
        ingest_fn = getattr(self.source, "ingest_debug_state", None)
        if ingest_fn is None:
            ingest_fn = getattr(
                getattr(self.source, "inner", None),
                "ingest_debug_state",
                None,
            )
        try:
            ingest = ingest_fn() if ingest_fn is not None else None
        except Exception:  # noqa: BLE001 - varz must not depend on ingest
            ingest = None
        state = {
            "worker_id": self.worker_id,
            "version": __version__,
            "config_fingerprint": self.config.fingerprint(),
            "claim_limit": self.claim_limit,
            "queue_depth": queue_depth,
            "store_ok": store_ok,
            # ES connect-retry progress (jobs/store.py wait_ready): a
            # worker stuck dialing the store reads as "retrying", not
            # as a hang; None for stores without the loop (in-memory)
            "store_connect": getattr(self.store, "connect_state", None),
            "model_cache": {
                "fit_entries": len(self._fit_cache),
                "fit_capacity": self.config.max_cache_size,
                "hist_entries": len(self._hist_cache),
                "admission_entries": len(self._admit),
            },
            # ring-first cold path (ISSUE 10): whether the worker's
            # host-side history cache is bypassed in favor of resident
            # ring columns (and shrunk — the freed RAM decision made
            # observable), where cold-fit histories actually came from,
            # and the provisional-fit refinement ledger
            "cold_start": {
                "hist_bypass": self._hist_bypass,
                "hist_cache_cap": self._hist_cache.max_size,
                "hist_reads": self._cold_snapshot(),
                "admit_floor_seconds": getattr(
                    self.source, "admit_floor", None
                ),
                "refine_docs_per_tick": self.refine_docs_per_tick,
                "refine": self._refine_book.debug_state(),
            },
            "arena": arena,
            # joint-model device arena (TreeArena rows: bivariate fits,
            # LSTM-AE params + residual-MVN state); None when the judge
            # has no joint dispatch
            "joint_arena": joint_arena,
            # device mesh (ISSUE 13/19, FOREMAST_DEVICE_MESH): mesh
            # shape, padded-row fraction, arena layout + HBM accounting
            # (per-device bytes x device count = shard-sum when sharded,
            # replication tax when FOREMAST_ARENA_SHARDED=0), H2D/gather
            # roofline counters; None when the judge runs single-device
            "device_mesh": self._device_mesh_state(),
            # push-based ingest plane (FOREMAST_INGEST=1): series
            # resident, bytes, evictions, hit ratio, receiver lag,
            # subscriptions; None when the worker runs pure-pull
            "ingest": ingest,
            # worker mesh (FOREMAST_MESH=1): live members with their
            # advertised addresses/ports, rebalance + redirect counters,
            # claim partition traffic; None when unsharded
            "mesh": (
                self.mesh.debug_state() if self.mesh is not None else None
            ),
            # cumulative columnar-path docs per model kind — joint kinds
            # > 0 is the observable proof multi-alias docs ride the fast
            # path (ISSUE 4 acceptance)
            "fast_path_docs": dict(self._fast_kinds),
            # columnar batch-padding accounting for SINGLE-device
            # judges too (the pow2 bucket pads regardless of sharding;
            # sharded judges report the same counters with the mesh
            # roofline under `device_mesh`) — the <2% padded-row bar is
            # observable on stock hosts, not assumed
            "columnar_pad": self._columnar_pad_state(),
            "last_tick": dict(self._last_tick),
            # occupancy of the latest slow-path chunk pipeline run:
            # device_idle_seconds (judge waited on fetch), write_queue
            # peak, overlap_ratio (0 = serial; →2/3 at perfect 3-stage
            # overlap). None until a tick exercises the slow path.
            "pipeline": (
                dict(self._last_pipeline) if self._last_pipeline else None
            ),
            # sliced, preemptible sweeps (ISSUE 15,
            # FOREMAST_SWEEP_SLICE_DOCS): whether sweeps run sliced,
            # and the latest sliced sweep's ledger — slice count, slow
            # docs, promoted/requeued/micro-ticked preemptions, and the
            # WARM-path pipeline occupancy (the slow path's twin above)
            "sweep": {
                "slice_docs": self.sweep_slice_docs,
                "sliced": self._sweep_sliceable(),
                "last": (
                    dict(self._last_sweep) if self._last_sweep else None
                ),
            },
            # durable data plane (FOREMAST_SNAPSHOT_DIR): per-journal
            # fit persistence counters + ring snapshot cadence/restore
            # stats; None when the worker runs ephemeral
            "durability": (
                {
                    "fit_journals": {
                        name: j.debug_state()
                        for name, j in self._fit_journals.items()
                    },
                    "ring": (
                        self._snapshotter.debug_state()
                        if self._snapshotter is not None
                        else None
                    ),
                }
                if self._fit_journals or self._snapshotter is not None
                else None
            ),
            # reactive plane (ISSUE 12): dirty-set occupancy/counters,
            # micro-tick budget + pacing, and the latest micro-tick's
            # ledger; None when the worker is pure tick-paced
            "reactive": (
                {
                    "dirty": self.dirty.debug_state(),
                    "microtick_seconds": self.microtick_seconds,
                    "microtick_docs_budget": self.microtick_docs,
                    "last_micro": dict(self._last_micro),
                }
                if self.dirty is not None
                else None
            ),
            # chaos plane + graceful degradation (ISSUE 9): write-behind
            # occupancy, tick budget, per-edge breaker states, released/
            # buffered/replayed doc counters, active chaos plan (tests/
            # soaks only — None in production)
            "degradation": self._degrade.debug_state(),
            # tenant QoS plane (ISSUE 20, FOREMAST_TENANTS): envelope
            # config + the per-tenant shed/eviction/claim/ring-byte
            # attribution ledger; None when the worker runs untenanted
            "tenants": self._debug_tenants(),
        }
        # registered knobs explicitly set in this process's env — with
        # the config fingerprint, the enumerable answer to "why do two
        # workers behave differently" (config.ENV_KNOBS is the registry
        # the env-contract checker enforces)
        from foremast_tpu.config import env_overrides

        state["env_overrides"] = env_overrides()
        if self.tracer is not None:
            state["trace"] = self.tracer.debug_state()
        return state

    def _debug_tenants(self) -> dict | None:
        if self._tenancy is None:
            return None
        from foremast_tpu.tenant.collector import debug_tenants

        return debug_tenants(self._tenancy, self._tenant_acct)

    def run(
        self,
        poll_seconds: float = 5.0,
        stop: Callable[[], bool] | None = None,
        after_tick: Callable[[int], None] | None = None,
    ):
        """Poll forever (the shared-nothing worker loop, design.md:35-43).

        `after_tick(n_processed)` runs after every cycle — the hook for
        periodic model-cache checkpointing and similar housekeeping.

        Reactive mode (a `dirty` set wired AND
        ``FOREMAST_MICROTICK_SECONDS`` > 0): the idle wait between full
        ticks becomes the micro-tick drain window — every
        `microtick_seconds` the worker claims and judges just the
        dirty documents, so a pushed anomaly meets its verdict in
        ~`microtick_seconds` + judge time instead of waiting out the
        poll. Full ticks keep the poll cadence as SWEEPS; a saturated
        claim (n == claim_limit — more work is surely waiting) still
        re-sweeps immediately, exactly the pre-reactive busy loop."""
        def hook(n: int) -> None:
            if after_tick:
                try:
                    after_tick(n)
                except Exception:
                    log.exception("after_tick hook failed")

        def micro_drain() -> None:
            # one bounded micro drain, with the hook only when work
            # happened (sweeps keep the run-every-cycle contract the
            # idle-checkpoint logic relies on)
            if len(self.dirty):
                n_micro = self.micro_tick()
                if n_micro:
                    hook(n_micro)

        reactive = self.dirty is not None and self.microtick_seconds > 0
        while not (stop and stop()):
            n = self.tick()
            hook(n)
            if not reactive:
                if n == 0:
                    time.sleep(poll_seconds)
                continue
            if n >= self.claim_limit:
                # saturated sweep (backlog exceeds one claim): keep the
                # pre-reactive busy loop's drain rate, but ALTERNATE one
                # micro drain between sweeps — a pushed anomaly's
                # latency stays bounded by one sweep, not by the whole
                # backlog's drain time
                micro_drain()
                continue
            deadline = time.monotonic() + poll_seconds
            while not (stop and stop()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                micro_drain()
                time.sleep(min(self.microtick_seconds, max(remaining, 0.0)))
